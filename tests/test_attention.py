"""Splash-attention parity vs the naive segment-masked reference.

Runs the Pallas kernels in interpret mode on the virtual 8-device CPU mesh
(tests can't see real chips; scripts/tpu_splash_parity.py is the
on-hardware twin).  Covers the packed-segment mask semantics, GQA grouping,
sliding windows, gradients, and the shard_map path with a sequence-sharded
query (the Ulysses-regime long-context configuration, VERDICT.md #1/#5).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.ops import attention as attn_mod
from areal_tpu.ops.attention import (
    make_attention_mask,
    naive_attention,
    segment_attention,
)
from areal_tpu.parallel import build_mesh


@pytest.fixture(autouse=True)
def _interpret_mode():
    attn_mod.INTERPRET = True
    yield
    attn_mod.INTERPRET = False


def _packed_inputs(rng, B, T, Hq, Hkv, hd, n_segs=3):
    q = jnp.asarray(rng.normal(size=(B, T, Hq, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, Hkv, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, Hkv, hd)), jnp.float32)
    seg = np.full((B, T), -1, np.int32)
    pos = np.zeros((B, T), np.int32)
    for b in range(B):
        bounds = sorted(rng.choice(np.arange(32, T - 32), n_segs - 1, replace=False))
        start = 0
        for s, end in enumerate(list(bounds) + [T - 16]):  # leave tail padding
            seg[b, start:end] = s
            pos[b, start:end] = np.arange(end - start)
            start = end
    return q, k, v, jnp.asarray(seg), jnp.asarray(pos)


def _naive(q, k, v, seg, pos, window=None, softcap=None):
    mask = make_attention_mask(seg, pos, window)
    return naive_attention(q, k, v, mask, softcap)


def test_splash_matches_naive_packed_segments():
    rng = np.random.default_rng(0)
    q, k, v, seg, pos = _packed_inputs(rng, B=2, T=256, Hq=4, Hkv=2, hd=128)
    out = segment_attention(q, k, v, seg, pos, impl="splash")
    ref = _naive(q, k, v, seg, pos)
    valid = np.asarray(seg) >= 0
    err = np.abs(np.asarray(out) - np.asarray(ref))[valid].max()
    assert err < 1e-4


def test_splash_non_pow2_extent_picks_dividing_block():
    """A 768-token packed row is 128-aligned but NOT divisible by the
    default 512 query block; the kernel builder must step down to 384
    instead of crashing (regression: heterogeneous-length GRPO rollouts
    quantized to 768-token rows killed the train step)."""
    rng = np.random.default_rng(3)
    q, k, v, seg, pos = _packed_inputs(rng, B=1, T=768, Hq=2, Hkv=1, hd=128)
    out = segment_attention(q, k, v, seg, pos, impl="splash")
    ref = _naive(q, k, v, seg, pos)
    valid = np.asarray(seg) >= 0
    err = np.abs(np.asarray(out) - np.asarray(ref))[valid].max()
    assert err < 1e-4


def test_splash_sliding_window():
    rng = np.random.default_rng(1)
    q, k, v, seg, pos = _packed_inputs(rng, B=1, T=256, Hq=2, Hkv=1, hd=128, n_segs=2)
    out = segment_attention(q, k, v, seg, pos, sliding_window=64, impl="splash")
    ref = _naive(q, k, v, seg, pos, window=64)
    valid = np.asarray(seg) >= 0
    err = np.abs(np.asarray(out) - np.asarray(ref))[valid].max()
    assert err < 1e-4


def test_splash_gradients_match():
    rng = np.random.default_rng(2)
    q, k, v, seg, pos = _packed_inputs(rng, B=1, T=256, Hq=4, Hkv=2, hd=128)
    w = jnp.asarray((np.asarray(seg) >= 0)[..., None, None], jnp.float32)

    def loss(impl):
        def f(q, k, v):
            o = segment_attention(q, k, v, seg, pos, impl=impl)
            return ((o * w) ** 2).sum()

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    gs = loss("splash")
    gn = loss("naive")
    for a, b in zip(gs, gn):
        denom = np.abs(np.asarray(b)).max() + 1e-9
        assert np.abs(np.asarray(a) - np.asarray(b)).max() / denom < 1e-3


def test_sharded_splash_matches_naive():
    """dp2 x sp2 x tp2 mesh: q-sequence sharded, kv whole, kv heads over tp."""
    mesh = build_mesh(dp=2, fsdp=1, sp=2, tp=2)
    rng = np.random.default_rng(3)
    q, k, v, seg, pos = _packed_inputs(rng, B=4, T=256, Hq=4, Hkv=2, hd=128)

    @jax.jit
    def sharded(q, k, v, seg, pos):
        return segment_attention(q, k, v, seg, pos, impl="splash", mesh=mesh)

    with mesh:
        out = sharded(q, k, v, seg, pos)
    ref = _naive(q, k, v, seg, pos)
    valid = np.asarray(seg) >= 0
    err = np.abs(np.asarray(out) - np.asarray(ref))[valid].max()
    assert err < 1e-4


def test_auto_impl_cpu_is_naive():
    attn_mod.INTERPRET = False
    rng = np.random.default_rng(4)
    q, k, v, seg, pos = _packed_inputs(rng, B=1, T=256, Hq=2, Hkv=2, hd=128)
    out = segment_attention(q, k, v, seg, pos, impl="auto")
    ref = _naive(q, k, v, seg, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6)


def test_ring_matches_naive():
    """dp2 x sp4 mesh: K/V sequence-sharded and rotated via ppermute; the
    online-softmax accumulation matches the full naive oracle on packed
    segments with padding."""
    mesh = build_mesh(dp=2, fsdp=1, sp=4, tp=1)
    rng = np.random.default_rng(5)
    q, k, v, seg, pos = _packed_inputs(rng, B=2, T=256, Hq=4, Hkv=2, hd=32)

    @jax.jit
    def ring(q, k, v, seg, pos):
        return segment_attention(q, k, v, seg, pos, impl="ring", mesh=mesh)

    with mesh:
        out = ring(q, k, v, seg, pos)
    ref = _naive(q, k, v, seg, pos)
    valid = np.asarray(seg) >= 0
    err = np.abs(np.asarray(out) - np.asarray(ref))[valid].max()
    assert err < 1e-4
    # padding rows produce exact zeros (no valid key anywhere)
    assert np.abs(np.asarray(out)[~valid]).max() == 0.0


def test_ring_sliding_window_and_tp():
    mesh = build_mesh(dp=1, fsdp=2, sp=2, tp=2)
    rng = np.random.default_rng(6)
    q, k, v, seg, pos = _packed_inputs(rng, B=2, T=128, Hq=4, Hkv=2, hd=16)

    @jax.jit
    def ring(q, k, v, seg, pos):
        return segment_attention(
            q, k, v, seg, pos, impl="ring", mesh=mesh, sliding_window=24
        )

    with mesh:
        out = ring(q, k, v, seg, pos)
    ref = _naive(q, k, v, seg, pos, window=24)
    valid = np.asarray(seg) >= 0
    err = np.abs(np.asarray(out) - np.asarray(ref))[valid].max()
    assert err < 1e-4


def test_ring_gradients_match_naive():
    mesh = build_mesh(dp=1, fsdp=1, sp=4, tp=2)
    rng = np.random.default_rng(7)
    q, k, v, seg, pos = _packed_inputs(rng, B=1, T=128, Hq=4, Hkv=2, hd=16)
    # cotangent only on valid positions: the naive oracle's padding rows
    # attend uniformly (softmax over an all-MASK_VALUE row) while ring
    # emits exact zeros there — a deliberate behavioural difference
    valid = (np.asarray(seg) >= 0)[..., None, None]
    ct = jnp.asarray(rng.normal(size=q.shape) * valid, jnp.float32)

    def loss_ring(q, k, v):
        out = segment_attention(q, k, v, seg, pos, impl="ring", mesh=mesh)
        return jnp.sum(out * ct)

    def loss_naive(q, k, v):
        return jnp.sum(_naive(q, k, v, seg, pos) * ct)

    with mesh:
        gs = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gn):
        denom = np.abs(np.asarray(b)).max() + 1e-9
        assert np.abs(np.asarray(a) - np.asarray(b)).max() / denom < 1e-3


def test_ring_without_sp_falls_back():
    rng = np.random.default_rng(8)
    q, k, v, seg, pos = _packed_inputs(rng, B=1, T=128, Hq=2, Hkv=2, hd=16)
    out = segment_attention(q, k, v, seg, pos, impl="ring", mesh=None)
    ref = _naive(q, k, v, seg, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)
