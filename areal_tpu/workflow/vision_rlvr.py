"""Vision RLVR rollout workflow.

Behavioral counterpart of the reference's `VisionRLVRWorkflow`
(areal/workflow/vision_rlvr.py): episodes whose data carries `images` +
`messages`; an HF AutoProcessor turns (images, text) into input_ids with
image-placeholder tokens, the images travel to the inference server as
base64 in `ModelRequest.image_data`, and rewards are computed from the
decoded completion as in text RLVR (episode loop shared with RLVRWorkflow
via the request/reward hooks).

Serving note: the in-repo JAX generation engine is text-only today — this
workflow targets inference backends that accept image_data (the backend
protocol field is plumbed end-to-end); multimodal towers are the remaining
model-side work.
"""

import uuid
from typing import Any, Callable, Dict, Optional

from areal_tpu.api.config import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.utils.image import image2base64, load_images
from areal_tpu.workflow.rlvr import RLVRWorkflow


class VisionRLVRWorkflow(RLVRWorkflow):
    def __init__(
        self,
        reward_fn: Callable[..., float],
        gconfig: GenerationHyperparameters,
        tokenizer=None,
        processor=None,
        enable_thinking: bool = False,
        rollout_stat_scope: str = "rollout",
        dump_dir: Optional[str] = None,
        image_token_id: Optional[int] = None,
        spatial_merge_size: int = 2,
    ):
        super().__init__(
            reward_fn,
            gconfig,
            tokenizer=tokenizer,
            enable_thinking=enable_thinking,
            rollout_stat_scope=rollout_stat_scope,
            dump_dir=dump_dir,
        )
        self.processor = processor
        # needed to build trainer-side mrope positions; fall back to the
        # processor's advertised id when not given explicitly
        self.image_token_id = (
            image_token_id
            if image_token_id is not None
            else getattr(processor, "image_token_id", None)
        )
        self.spatial_merge_size = spatial_merge_size

    def _build_request(self, data: Dict[str, Any]) -> ModelRequest:
        images = load_images(data["images"]) if "images" in data else None
        pixel_values = data.get("pixel_values")
        image_grid_thw = data.get("image_grid_thw")
        if "input_ids" in data:
            input_ids = list(data["input_ids"])
        else:
            if self.processor is None:
                raise ValueError(
                    "need an AutoProcessor or pre-tokenized input_ids"
                )
            processed = self.processor(
                images=images, text=data["messages"], padding=False
            )
            ids = processed["input_ids"]
            input_ids = list(ids[0] if hasattr(ids[0], "__len__") else ids)
            # the processor's patchified pixels feed the native VLM server
            # directly (gen/server.py pixel_values_b64 wire field); stash
            # them on the episode data so trajectory augmentation reuses
            # them for the train batch
            if pixel_values is None and "pixel_values" in processed:
                pixel_values = processed["pixel_values"]
                image_grid_thw = processed.get("image_grid_thw")
                data["pixel_values"] = pixel_values
                data["image_grid_thw"] = image_grid_thw
        return ModelRequest(
            rid=str(uuid.uuid4()),
            input_ids=input_ids,
            image_data=image2base64(images) if images is not None else None,
            pixel_values=pixel_values,
            image_grid_thw=image_grid_thw,
            gconfig=self.gconfig.new(n_samples=1),
            tokenizer=self.tokenizer,
            processor=self.processor,
        )

    def _reward_kwargs(self, data: Dict[str, Any]) -> Dict[str, Any]:
        # drop image payloads and internal caches (underscore keys): reward
        # fns have fixed signatures and run in a pickle-boundary pool
        return {
            k: v
            for k, v in data.items()
            if k not in ("images", "pixel_values", "image_grid_thw")
            and not k.startswith("_")
        }

    # --- trainer payload: mrope positions + pixels -----------------------

    def _augment_result(self, result, data, resp):
        """Per-sample (t, h, w) rope positions [T, 3]: the prompt part from
        the image grids, generated tokens continuing linearly past the
        compressed extent (models/vision.py mrope scheme)."""
        if "pixel_values" not in data:
            # image_data-only mode (external multimodal backend serves the
            # images; the trainer sees text rows).  Datasets must not MIX
            # pixel and non-pixel episodes — the executor's concat rejects
            # inconsistent keys loudly if they do.
            return result
        if self.image_token_id is None:
            raise ValueError(
                "VisionRLVRWorkflow needs image_token_id (pass it or use a "
                "processor that exposes one) — training without mrope while "
                "serving decodes with it would silently mismatch positions"
            )
        import numpy as np

        from areal_tpu.models.vision import mrope_position_ids

        mpos = data.get("_mrope_prompt_cache")
        if mpos is None:
            # identical for every sample of the episode (same prompt/grids)
            grid = np.asarray(data["image_grid_thw"], np.int64).reshape(-1, 3)
            prompt = np.asarray(resp.input_tokens, np.int64)
            mpos = mrope_position_ids(
                prompt, grid, self.image_token_id,
                spatial_merge_size=self.spatial_merge_size,
            )  # [3, P]
            data["_mrope_prompt_cache"] = mpos
        T = len(result["input_ids"])
        P = mpos.shape[1]
        full = np.zeros((3, T), np.int32)
        full[:, :P] = mpos
        nxt = int(mpos.max()) + 1
        tail = np.arange(T - P, dtype=np.int32) + nxt
        full[:, P:] = tail[None, :]
        result["mrope_positions"] = full.T  # [T, 3] for per-token padding
        return result

    def _augment_batch(self, batch, data, n_samples: int):
        """Batch-level pixels: every sample row shares the episode's
        image(s), so patches repeat per row — in row order, with per-row
        image ids (concat across episodes renumbers them globally)."""
        data.pop("_mrope_prompt_cache", None)  # episode-scoped
        if "pixel_values" not in data:
            return batch  # image_data-only mode: text-style training rows
        import numpy as np

        from areal_tpu.models.vision import patch_arrays_for_rows

        pv = np.asarray(data["pixel_values"], np.float32)
        grid = np.asarray(data["image_grid_thw"], np.int64).reshape(-1, 3)
        batch["pixel_values"] = np.tile(pv, (n_samples, 1))
        # every sample row repeats the episode's image(s): one grid per row
        ids, pos_hw, spans = patch_arrays_for_rows(
            [grid] * n_samples, self.spatial_merge_size
        )
        batch["patch_img_ids"] = ids
        batch["patch_pos_hw"] = pos_hw
        batch["patches_per_row"] = spans
        return batch
