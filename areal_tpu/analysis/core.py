"""areal-lint core: findings, suppressions, and the source-file model.

The project-specific static-analysis pass (ISSUE 3).  Every advisor round
so far found the same failure classes by hand — guarded state mutated
outside its lock, host syncs / recompile hazards on the hot serving path,
event-loop stalls from blocking calls in `async def`, and modules shipped
with zero importers.  This package encodes those invariants as four AST
checkers (see the sibling modules) so they are enforced in tier-1 instead
of living in reviewer memory:

- C1 `unlocked-field`   — lock_discipline.py
- C2 `host-sync` / `host-item` / `unbucketed-shape` — host_sync.py
- C3 `async-blocking`   — async_blocking.py
- C4 `dead-module`      — dead_modules.py

Annotation surface (documented in docs/lint.md):

- per-class ``_GUARDED_FIELDS = {"_field": "_lock", ...}`` registry, or a
  ``# guarded-by: _lock`` comment on (or above) the field's ``__init__``
  assignment;
- ``# holds: _lock`` on a method that is only ever called with the lock
  already held;
- ``# areal-lint: hot-path`` marks a file for the C2 host-sync rules;
- ``# areal-lint: disable=<rule>[,<rule>] <reason>`` suppresses findings
  on that line (or the line below it); the reason is MANDATORY — a bare
  disable is itself a finding (`bad-suppression`), so every intentional
  exception stays visible and counted.
"""

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

KNOWN_RULES = frozenset(
    {
        "unlocked-field",
        "guard-syntax",
        "host-sync",
        "host-item",
        "host-upload",
        "unbucketed-shape",
        "async-blocking",
        "dead-module",
        "bad-suppression",
        # v2 interprocedural checkers (ISSUE 9)
        "lock-order",
        "blocking-under-lock",
        "atomicity-split",
        "off-ladder-static",
        "signature-budget-stale",
        "slot-double-free",
        "slot-lifecycle",
        "retained-unversioned",
        # v3 cross-process wire-contract checkers (ISSUE 18)
        "payload-contract",
        "payload-silent-default",
        "metric-contract",
        "event-contract",
        "config-plumbing",
        "wire-registry-stale",
    }
)

_SUPPRESS_RE = re.compile(
    r"#\s*areal-lint:\s*disable=([A-Za-z0-9_,\-]+)\s*(.*?)\s*$"
)
_HOT_RE = re.compile(r"#\s*areal-lint:\s*hot-path\b")
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z_]\w*)")


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    def render(self) -> str:
        tag = f" (suppressed: {self.suppress_reason})" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


@dataclass
class Suppression:
    line: int
    rules: List[str]
    reason: str
    used: bool = False


class SourceFile:
    """One parsed source file: AST + per-line comments + suppressions."""

    def __init__(self, path: str, text: str, rel: Optional[str] = None):
        self.path = path
        self.rel = rel or path
        self.text = text
        self.error: Optional[str] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(text)
        except SyntaxError as e:
            self.tree = None
            self.error = f"syntax error: {e}"
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except (tokenize.TokenizeError, IndentationError, SyntaxError):
            # comment extraction is best-effort; the AST parse above is
            # what decides whether the file is analyzable at all
            pass
        self.suppressions: Dict[int, Suppression] = {}
        for line, comment in self.comments.items():
            m = _SUPPRESS_RE.search(comment)
            if m:
                rules = [r for r in m.group(1).split(",") if r]
                self.suppressions[line] = Suppression(
                    line=line, rules=rules, reason=m.group(2).strip()
                )
        self.hot = any(_HOT_RE.search(c) for c in self.comments.values())

    @classmethod
    def from_path(cls, path: str, rel: Optional[str] = None) -> "SourceFile":
        with open(path, encoding="utf-8") as f:
            return cls(path, f.read(), rel=rel)

    # -- annotation helpers shared by the checkers ----------------------

    def comment_near(self, line: int) -> str:
        """Comment on `line`, falling back to the line above (annotations
        may sit on their own line when the code line is long)."""
        return self.comments.get(line) or self.comments.get(line - 1) or ""

    def guarded_by(self, line: int) -> Optional[str]:
        m = _GUARDED_BY_RE.search(self.comment_near(line))
        return m.group(1) if m else None

    def holds_between(self, start: int, end: int) -> List[str]:
        """All `# holds: <lock>` annotations on lines [start, end]."""
        out = []
        for ln in range(start, end + 1):
            m = _HOLDS_RE.search(self.comments.get(ln, ""))
            if m:
                out.append(m.group(1))
        return out

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        """Suppression covering `rule` at `line`: same line or the line
        directly above the flagged one."""
        for ln in (line, line - 1):
            sup = self.suppressions.get(ln)
            if sup is not None and rule in sup.rules:
                return sup
        return None

    def file_suppression_for(self, rule: str) -> Optional[Suppression]:
        """File-scope suppression (used by dead-module findings, which are
        about the module as a whole): any disable of `rule` in the file."""
        for sup in self.suppressions.values():
            if rule in sup.rules:
                return sup
        return None


def apply_suppression(sf: SourceFile, finding: Finding) -> Finding:
    """Mark `finding` suppressed if an inline disable covers it."""
    sup = sf.suppression_for(finding.rule, finding.line)
    if sup is not None:
        sup.used = True
        finding.suppressed = True
        finding.suppress_reason = sup.reason or "(no reason)"
    return finding


def suppression_hygiene(sf: SourceFile) -> List[Finding]:
    """Every suppression must carry a reason and name known rules."""
    out = []
    for sup in sf.suppressions.values():
        unknown = [r for r in sup.rules if r not in KNOWN_RULES]
        if unknown:
            out.append(
                Finding(
                    "bad-suppression",
                    sf.rel,
                    sup.line,
                    f"disable names unknown rule(s) {unknown}; known rules: "
                    f"{sorted(KNOWN_RULES)}",
                )
            )
        if not sup.reason:
            out.append(
                Finding(
                    "bad-suppression",
                    sf.rel,
                    sup.line,
                    "suppression without a reason string — every intentional "
                    "exception must say why (# areal-lint: disable=<rule> "
                    "<reason>)",
                )
            )
    return out


DEFAULT_EXCLUDE = ("tests", "__pycache__", ".git")


def iter_python_files(
    root: str, exclude: Iterable[str] = DEFAULT_EXCLUDE
) -> List[str]:
    """Repo-relative paths of every scanned .py file: the package tree,
    scripts/, examples/, and top-level modules — everything except tests
    (fixtures under tests/data/lint would otherwise lint themselves)."""
    exclude = set(exclude)
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        parts = [] if rel_dir == "." else rel_dir.split(os.sep)
        if parts and (parts[0] in exclude or parts[0].startswith(".")):
            dirnames[:] = []
            continue
        dirnames[:] = [
            d for d in dirnames if d not in exclude and not d.startswith(".")
        ]
        for fn in filenames:
            if fn.endswith(".py"):
                rel = os.path.normpath(os.path.join(rel_dir, fn))
                out.append(rel if not rel.startswith("./") else rel[2:])
    return sorted(out)


def load_files(
    root: str, exclude: Iterable[str] = DEFAULT_EXCLUDE
) -> Dict[str, SourceFile]:
    files: Dict[str, SourceFile] = {}
    for rel in iter_python_files(root, exclude):
        try:
            files[rel] = SourceFile.from_path(
                os.path.join(root, rel), rel=rel
            )
        except (OSError, UnicodeDecodeError):
            continue
    return files


def run_suite(root: str, package: str = "areal_tpu") -> List[Finding]:
    """Run all checkers (C1–C7) plus suppression hygiene over the tree."""
    from areal_tpu.analysis.async_blocking import check_async_blocking
    from areal_tpu.analysis.dead_modules import check_dead_modules
    from areal_tpu.analysis.host_sync import check_host_sync
    from areal_tpu.analysis.jit_signatures import check_jit_signatures
    from areal_tpu.analysis.lock_discipline import check_lock_discipline
    from areal_tpu.analysis.lock_order import check_lock_order
    from areal_tpu.analysis.typestate import check_typestate
    from areal_tpu.analysis.wire_contracts import check_wire_contracts

    files = load_files(root)
    findings: List[Finding] = []
    for sf in files.values():
        if sf.error is not None:
            continue  # unparseable files are not lintable (none in-tree)
        findings.extend(check_lock_discipline(sf))
        findings.extend(check_host_sync(sf))
        findings.extend(check_async_blocking(sf))
        findings.extend(suppression_hygiene(sf))
    findings.extend(check_dead_modules(root, files, package=package))
    # set-level interprocedural checkers (shared call graph per checker)
    findings.extend(check_lock_order(files))
    findings.extend(check_typestate(files))
    findings.extend(check_jit_signatures(files, root))
    findings.extend(check_wire_contracts(files, root))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def unsuppressed(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if not f.suppressed]
