"""Offline eval harness: run the real CLI against a tiny checkpoint and a
tiny gsm8k jsonl (reference: evaluation/ offline benchmark eval)."""

import json
import os
import subprocess
import sys

import pytest

from tests.fixtures import make_gsm8k_jsonl, make_tiny_ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_eval_cli_end_to_end(tmp_path):
    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    data = make_gsm8k_jsonl(str(tmp_path / "test.jsonl"), n=6)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [
            sys.executable, "-m", "areal_tpu.evaluation.run_eval",
            "--ckpt", str(ckpt),
            "--dataset", data,
            "--k", "2",
            "--max-new-tokens", "16",
            "--max-seq-len", "256",
            "--limit", "4",
            "--type", "gsm8k",
        ],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    metrics = json.loads(proc.stdout.strip().splitlines()[-1])
    assert metrics["n_problems"] == 4 and metrics["k"] == 2
    for key in ("pass@1", "pass@2", "majority"):
        assert 0.0 <= metrics[key] <= 1.0
    assert metrics["gen_tokens"] > 0


def test_evaluate_checkpoint_api(tmp_path):
    from areal_tpu.evaluation import evaluate_checkpoint

    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    data = make_gsm8k_jsonl(str(tmp_path / "t.jsonl"), n=3)
    result = evaluate_checkpoint(
        ckpt=str(ckpt),
        dataset=data,
        dataset_type="gsm8k",
        k=1,
        max_new_tokens=8,
        max_seq_len=128,
        n_slots=4,
        limit=2,
    )
    assert result["n_problems"] == 2
    assert "pass@1" in result
