# areal-lint: disable=dead-module experimental namespace for user-facing surfaces (reference parity: areal/experimental)
