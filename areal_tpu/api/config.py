"""Structured configuration for every subsystem.

Capability counterpart of the reference's `areal/api/cli_args.py` (1314 LoC of
dataclasses + OmegaConf/Hydra loading).  Re-designed without OmegaConf: a plain
dataclass tree plus a small recursive YAML/dot-list merge (`load_expr_config`),
which covers the reference's `cli_args.py:1247-1310` behavior (YAML file +
`a.b.c=value` command-line overrides).
"""

import argparse
import dataclasses
import os
from dataclasses import dataclass, field, fields, is_dataclass
from typing import Any, Dict, List, Optional, Tuple, Type, TypeVar, Union, get_args, get_origin

import yaml

T = TypeVar("T")


# ---------------------------------------------------------------------------
# Generation
# ---------------------------------------------------------------------------


@dataclass
class GenerationHyperparameters:
    """Per-request sampling config (reference: cli_args.py GenerationHyperparameters)."""

    n_samples: int = 1
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    greedy: bool = False
    stop_token_ids: List[int] = field(default_factory=list)
    stop: List[str] = field(default_factory=list)
    frequency_penalty: float = 0.0

    def new(self, **kwargs) -> "GenerationHyperparameters":
        return dataclasses.replace(self, **kwargs)


# ---------------------------------------------------------------------------
# Optimizer / train engine
# ---------------------------------------------------------------------------


@dataclass
class OptimizerConfig:
    type: str = "adamw"
    lr: float = 2e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-8
    min_lr_ratio: float = 0.0
    lr_scheduler_type: str = "constant"  # constant | linear | cosine
    warmup_steps_proportion: float = 0.001
    gradient_clipping: float = 1.0
    # Offload optimizer state to host memory between steps (TPU HBM relief).
    offload: bool = False


@dataclass
class MeshConfig:
    """How a train engine lays its chips out as a jax.sharding.Mesh.

    Normally derived from the allocation expression; explicit here for tests
    and single-engine runs.
    """

    data_parallel_size: int = 1
    fsdp_parallel_size: int = 1
    sequence_parallel_size: int = 1
    tensor_parallel_size: int = 1
    expert_parallel_size: int = 1

    @property
    def world_size(self) -> int:
        return (
            self.data_parallel_size
            * self.fsdp_parallel_size
            * self.sequence_parallel_size
            * self.tensor_parallel_size
        )


@dataclass
class TrainEngineConfig:
    experiment_name: str = ""
    trial_name: str = ""
    path: str = ""  # HF model path or name
    init_from_scratch: bool = False
    dtype: str = "bfloat16"
    param_dtype: str = "float32"  # master copy / optimizer dtype
    disable_dropout: bool = True
    gradient_checkpointing: bool = True
    # "full" recomputes layers in backward (min HBM); "dots" keeps matmul
    # outputs (faster when HBM allows — v5p-class chips); "save_attn"/
    # "save_mlp" keep only the tagged attention/MLP outputs;
    # "carry_offload" keeps both tags but parks them in pinned host memory
    # (models/model_config.py TransformerConfig.remat_policy)
    remat_policy: str = "full"
    # two-level layer scan (models/transformer.py): the outer scan runs
    # num_layers/G steps, each an unrolled chain of G layers behind ONE
    # remat boundary — saved carries shrink ~G×.  Must divide the model
    # depth (rejected loudly); 1 = the classic per-layer scan
    layer_group_size: int = 1
    # outer-scan unroll: >1 cuts per-step scan overhead (~2% throughput at
    # 4 on v5e 1.5B) for more compile time/live buffers; must divide the
    # outer scan length (num_layers / layer_group_size) — non-divisors
    # warn loudly and fall back to 1; the effective value rides train stats
    scan_unroll: int = 1
    # fused LM-head vocab chunk width (ops/fused_xent.py), rounded up to a
    # multiple of 128; 0 = the AREAL_LM_HEAD_CHUNK env default (8192).
    # Plumbed through the loss partial so the bench ladder can sweep it
    lm_head_chunk: int = 0
    mb_spec: "MicroBatchSpec" = field(default_factory=lambda: MicroBatchSpec())
    optimizer: Optional[OptimizerConfig] = field(default_factory=OptimizerConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    pad_to_maximum: bool = False
    # Sequence-length bucketing for packed batches: powers-of-two multiples of
    # this quantum; avoids XLA recompilation storms on variable-length data.
    pack_length_quantum: int = 512
    max_pack_length: int = 32768
    # forwarded onto the model config at initialize: "auto" picks the
    # splash kernel when shapes allow; "ring" turns an sp>1 mesh axis
    # (alloc `s`/`c` dims) into ring attention — K/V sequence-sharded
    # context parallelism (ops/attention.py ring_attention)
    attn_impl: str = "auto"  # auto | splash | naive | ring
    # Defer the per-step stats fetch so consecutive train steps pipeline on
    # the device (the fetch otherwise serialises the trainer on dispatch
    # latency — large on tunneled TPU runtimes).  train_batch then returns a
    # PendingTrainStats mapping that materialises on first read; per-step
    # step_time/tflops/mfu keys are omitted (no sync point to measure them).
    async_stats: bool = False
    lora: "LoRAConfig" = field(default_factory=lambda: LoRAConfig())


@dataclass
class LoRAConfig:
    enabled: bool = False
    rank: int = 8
    alpha: float = 16.0
    target_modules: List[str] = field(
        default_factory=lambda: ["q_proj", "k_proj", "v_proj", "o_proj"]
    )


@dataclass
class MicroBatchSpec:
    """Micro-batch splitting spec (reference: cli_args.py MicroBatchSpec)."""

    n_mbs: int = 1
    max_tokens_per_mb: int = 0  # 0 = unlimited; else balanced FFD packing
    granularity: int = 1


# ---------------------------------------------------------------------------
# PPO / algorithm configs
# ---------------------------------------------------------------------------


@dataclass
class NormConfig:
    mean_level: Optional[str] = "group"  # batch | group | none/null
    std_level: Optional[str] = "group"
    group_size: int = 1
    eps: float = 1e-5


@dataclass
class PPOActorConfig(TrainEngineConfig):
    group_size: int = 1  # answers per prompt (GRPO group)
    ppo_n_minibatches: int = 4
    eps_clip: float = 0.2
    eps_clip_higher: Optional[float] = None  # asymmetric clipping (DAPO)
    c_clip: Optional[float] = None  # dual clip
    temperature: float = 1.0
    # rewards
    group_reward_norm: bool = False
    # full-control reward normalization (lite_ppo group-mean/batch-std,
    # dr.grpo group-mean/no-std); overrides group_reward_norm when set
    reward_norm: Optional[NormConfig] = None
    reward_scaling: float = 1.0
    reward_bias: float = 0.0
    reward_clip: float = 20.0
    overlong_reward_penalty: bool = False
    overlong_tokens: int = 0
    overlong_penalty_factor: float = 0.0
    # generation budget the overlong penalty is measured against (DAPO);
    # must equal the rollout's gconfig.max_new_tokens
    max_new_tokens: int = 0
    mask_no_eos_with_zero: bool = False
    # KL & advantages
    kl_ctl: float = 0.0
    kl_estimator: str = "k1"  # k1 | k2 | k3
    discount: float = 1.0
    gae_lambda: float = 1.0
    adv_norm: Optional[NormConfig] = field(default_factory=NormConfig)
    # decoupled PPO
    recompute_logprob: bool = True
    use_decoupled_loss: bool = True
    behav_imp_weight_cap: Optional[float] = None
    # dynamic sampling (reject groups with identical rewards)
    dynamic_sampling: bool = False
    log_agent_stats: bool = False
    log_agent_stats_keys: List[str] = field(default_factory=list)


@dataclass
class PPOCriticConfig(TrainEngineConfig):
    value_eps_clip: float = 0.2
    ppo_n_minibatches: int = 4
    mask_no_eos_with_zero: bool = False


# ---------------------------------------------------------------------------
# Inference engine / rollout
# ---------------------------------------------------------------------------


@dataclass
class InferenceEngineConfig:
    experiment_name: str = ""
    trial_name: str = ""
    max_concurrent_rollouts: Optional[int] = None
    queue_size: Optional[int] = None
    consumer_batch_size: int = 1
    max_head_offpolicyness: int = 0  # max staleness η
    enable_rollout_tracing: bool = False
    check_trajectory_format: bool = False
    schedule_policy: str = "round_robin"  # round_robin | least_requests
    setup_timeout: float = 120.0
    request_timeout: float = 3600.0
    request_retries: int = 3
    pause_grace_period: float = 0.0
    cleanup_timeout: float = 120.0
    # trajectory failover (ISSUE 11): how many times one trajectory may be
    # resubmitted to a different server after a backend failure before it
    # is declared lost, and how long a failed server is excluded from
    # re-placement
    failover_retries: int = 3
    failover_cooldown: float = 30.0


@dataclass
class GenServerConfig:
    """Config for the JAX generation server (counterpart of SGLangConfig)."""

    model_path: str = ""
    dtype: str = "bfloat16"
    max_seqs: int = 64  # continuous-batching slots
    prefill_chunk: int = 512
    max_context_len: int = 8192
    page_size: int = 128
    mesh: MeshConfig = field(default_factory=MeshConfig)
    host: str = "127.0.0.1"
    port: int = 0  # 0 = pick free port
    enable_metrics: bool = True
    random_seed: int = 1
    # KV cache dtype; bf16 default, fp8-style int8 quantization optional later.
    kv_dtype: str = "bfloat16"
    # Tiered decode (ISSUE 5): decode attention reads a bucketed key window
    # over the occupied span instead of the full max_context_len ceiling.
    decode_window: bool = True
    # Number of length-cohort slot tiers (1 = single cohort).  >1 splits the
    # slot grid into contiguous blocks with ascending length ceilings so a
    # long rollout does not inflate the short cohort's attended window;
    # explicit layouts override via decode_tier_lens/decode_tier_slots
    # (parallel lists: per-tier length ceiling / slot count).
    decode_tiers: int = 1
    decode_tier_lens: List[int] = field(default_factory=list)
    decode_tier_slots: List[int] = field(default_factory=list)
    # Self-speculative decoding (ISSUE 12): prompt-lookup drafts verified in
    # one dispatch per tier; the emitted streams are bit-identical to plain
    # decode at any temperature (counter-keyed sampling), so this is purely
    # a throughput knob.  spec_ladder lists the static draft-length rungs
    # (must match the checked-in signature budget's spec_rungs accounting);
    # spec_draft_len > 0 pins D instead of adapting.
    spec_decode: bool = False
    spec_ladder: List[int] = field(default_factory=list)
    spec_draft_len: int = 0
    # Disaggregated-fleet role (ISSUE 17): prefill | decode | both.  The
    # launcher must plumb this through --role or every server comes up
    # colocated and the router's role pools stay empty.
    role: str = "both"
    # Host-DRAM overflow tier for evicted retained prefixes (ISSUE 16);
    # --role decode implies it server-side, but launchers should set it
    # explicitly so the capacity flag below is honored.
    host_offload: bool = False
    host_cache_mb: int = 64
    # Ragged paged-decode attention (ISSUE 19): one fused Pallas kernel
    # dispatch covers the whole slot grid (per-slot page spans through the
    # KV page table), collapsing the per-tier decode/verify fan-out while
    # keeping output streams bit-identical to the dense path.  The server
    # auto-falls back to dense when the per-slot window exceeds the
    # kernel's VMEM budget.
    ragged_attn: bool = False

    @staticmethod
    def build_cmd(
        config: "GenServerConfig",
        host: str,
        port: int,
        dist_init_addr: Optional[str] = None,
    ) -> str:
        """Shell command launching a generation server (reference:
        SGLangConfig.build_cmd); flags match gen/server.py's argparse —
        launchers must use this instead of hand-building the command."""
        import sys

        args = [
            sys.executable, "-m", "areal_tpu.gen.server",
            f"--model-path={config.model_path}",
            f"--n-slots={config.max_seqs}",
            f"--max-seq-len={config.max_context_len}",
            f"--tp={max(1, config.mesh.tensor_parallel_size)}",
            f"--ep={max(1, config.mesh.expert_parallel_size)}",
        ]
        if config.role != "both":
            args.append(f"--role={config.role}")
        if config.host_offload:
            args.append("--host-offload")
            args.append(f"--host-cache-mb={config.host_cache_mb}")
        if not config.decode_window:
            args.append("--no-decode-window")
        if config.decode_tiers > 1:
            args.append(f"--decode-tiers={config.decode_tiers}")
        if config.decode_tier_lens:
            args.append(
                "--decode-tier-lens="
                + ",".join(str(x) for x in config.decode_tier_lens)
            )
            args.append(
                "--decode-tier-slots="
                + ",".join(str(x) for x in config.decode_tier_slots)
            )
        if config.spec_decode:
            args.append("--spec-decode")
            if config.spec_ladder:
                args.append(
                    "--spec-ladder="
                    + ",".join(str(x) for x in config.spec_ladder)
                )
            if config.spec_draft_len:
                args.append(f"--spec-draft-len={config.spec_draft_len}")
        if config.ragged_attn:
            args.append("--ragged-attn")
        if port:
            args.append(f"--port={port}")
        return " ".join(args)


# ---------------------------------------------------------------------------
# Infra: saver / evaluator / recover / stats / name_resolve / launcher
# ---------------------------------------------------------------------------


@dataclass
class TimerConfig:
    freq_epochs: Optional[int] = None
    freq_steps: Optional[int] = None
    freq_secs: Optional[int] = None


@dataclass
class SaverConfig(TimerConfig):
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = ""


@dataclass
class EvaluatorConfig(TimerConfig):
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = ""


@dataclass
class RecoverConfig(TimerConfig):
    mode: str = "disabled"  # disabled | auto | fault | resume
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = ""
    retries: int = 3


@dataclass
class StatsLoggerConfig:
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = ""
    wandb: Dict[str, Any] = field(default_factory=dict)
    tensorboard_dir: Optional[str] = None


@dataclass
class NameResolveConfig:
    # http = first-party TTL'd KV service (utils/kv_store.py), the
    # distributed-fleet backend (etcd3-lease semantics without etcd)
    type: str = "memory"  # memory | nfs | http
    nfs_record_root: str = "/tmp/areal_tpu/name_resolve"
    http_addr: str = "localhost:18999"
    etcd3_addr: str = "localhost:2379"  # legacy field; etcd3 -> use http


@dataclass
class ClusterSpecConfig:
    name_resolve: NameResolveConfig = field(default_factory=NameResolveConfig)
    cluster_name: str = "local"
    fileroot: str = "/tmp/areal_tpu/experiments"
    n_nodes: int = 1
    n_accelerators_per_node: int = 8


@dataclass
class LauncherConfig:
    inference_server_cpus_per_accelerator: int = 4
    inference_server_mem_per_accelerator: int = 32768
    trainer_cpus_per_accelerator: int = 4
    trainer_mem_per_accelerator: int = 32768
    inference_server_env_vars: str = ""
    trainer_env_vars: str = ""
    trainer_port: int = 27009


@dataclass
class DatasetConfig:
    path: str = ""
    type: str = ""
    batch_size: int = 1
    shuffle: bool = True
    pin_memory: bool = False
    num_workers: int = 2
    drop_last: bool = True
    max_length: Optional[int] = None


# ---------------------------------------------------------------------------
# Experiment-level configs
# ---------------------------------------------------------------------------


@dataclass
class BaseExperimentConfig:
    experiment_name: str = "my-exp"
    trial_name: str = "my-trial"
    cluster: ClusterSpecConfig = field(default_factory=ClusterSpecConfig)
    allocation_mode: str = ""
    seed: int = 1
    total_train_epochs: int = 1
    total_train_steps: Optional[int] = None
    tokenizer_path: str = ""
    train_dataset: DatasetConfig = field(default_factory=DatasetConfig)
    valid_dataset: Optional[DatasetConfig] = None
    saver: SaverConfig = field(default_factory=SaverConfig)
    checkpointer: SaverConfig = field(default_factory=SaverConfig)
    evaluator: EvaluatorConfig = field(default_factory=EvaluatorConfig)
    recover: RecoverConfig = field(default_factory=RecoverConfig)
    stats_logger: StatsLoggerConfig = field(default_factory=StatsLoggerConfig)
    launcher: LauncherConfig = field(default_factory=LauncherConfig)


@dataclass
class SFTConfig(BaseExperimentConfig):
    model: TrainEngineConfig = field(default_factory=TrainEngineConfig)


@dataclass
class RWConfig(BaseExperimentConfig):
    model: TrainEngineConfig = field(default_factory=TrainEngineConfig)


@dataclass
class GRPOConfig(BaseExperimentConfig):
    async_training: bool = True
    # trainer -> inference weight sync: "disk" (shared-fs snapshot, the
    # simple correct default) or "transfer" (HTTP chunk streaming straight
    # into server memory — no shared filesystem, lower latency at scale)
    weight_update_mode: str = "disk"
    # transfer mode only: commit staged weights WITHOUT aborting in-flight
    # generation (swap_weights_live — requests keep decoding across the
    # publish, per-token versions record the transition).  Default ON: the
    # measured abort-and-resume choreography sinks async below sync
    # (E2E_GRPO_BENCH_r04 publish_mode_interrupt 0.736x) while the live
    # commit keeps the pipeline saturated; set False to reproduce the
    # reference's abort-only behavior (SGLang cannot hot-swap mid-request)
    weight_update_live_commit: bool = True
    # (n_sequences, seq_len) pack signatures to AOT-compile before step 0
    # (PPOActor.warm_shapes): varying rollout lengths otherwise trigger XLA
    # compiles INSIDE the training loop the first time each signature lands
    warm_pack_shapes: List[List[int]] = field(default_factory=list)
    gconfig: GenerationHyperparameters = field(
        default_factory=GenerationHyperparameters
    )
    rollout: InferenceEngineConfig = field(default_factory=InferenceEngineConfig)
    gen_server: GenServerConfig = field(default_factory=GenServerConfig)
    actor: PPOActorConfig = field(default_factory=PPOActorConfig)
    ref: Optional[TrainEngineConfig] = None
    # rollout episode pattern: "rlvr" (single-turn) or "multi_turn"
    # (retry-with-feedback, reference workflow/multi_turn.py)
    workflow: str = "rlvr"
    max_turns: int = 3
    turn_discount: float = 0.9


@dataclass
class PPOConfig(GRPOConfig):
    critic: PPOCriticConfig = field(default_factory=PPOCriticConfig)


# ---------------------------------------------------------------------------
# Loading: YAML + dot-list overrides (no OmegaConf)
# ---------------------------------------------------------------------------


def _from_dict(
    cls: Type[T],
    data: Dict[str, Any],
    path: str = "",
    ignore_unknown_top: bool = False,
) -> T:
    if data is None:
        data = {}
    if not isinstance(data, dict):
        raise ValueError(f"config node {path or '<root>'} must be a mapping")
    kwargs = {}
    fld_map = {f.name: f for f in fields(cls)}
    for key, value in data.items():
        if key not in fld_map:
            if ignore_unknown_top and not path:
                # launchers parse experiment configs only for THEIR fields
                # (gen_server, allocation_mode, ...); example-specific
                # top-level sections (e.g. PPOConfig's `critic`) must not
                # fail the launch — the entry point re-parses strictly
                continue
            raise ValueError(f"unknown config key {path + key!r} for {cls.__name__}")
        kwargs[key] = _coerce(fld_map[key].type, value, path + key + ".")
    return cls(**kwargs)


def _unwrap_optional(tp):
    origin = get_origin(tp)
    if origin is Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0], True
    return tp, False


def _coerce(tp, value, path):
    if isinstance(tp, str):
        # string annotations from `from __future__` or forward refs
        tp = _resolve_annotation(tp)
    tp, optional = _unwrap_optional(tp)
    if value is None:
        return None
    if is_dataclass(tp) and isinstance(value, dict):
        return _from_dict(tp, value, path)
    if is_dataclass(tp) and isinstance(value, tp):
        return value
    origin = get_origin(tp)
    if origin in (list, List):
        (etp,) = get_args(tp) or (Any,)
        return [_coerce(etp, v, path) for v in value]
    if origin in (dict, Dict):
        return dict(value)
    if tp is bool and isinstance(value, str):
        return value.lower() in ("1", "true", "yes", "on")
    if tp in (int, float, str) and not isinstance(value, tp):
        return tp(value)
    return value


_ANNOT_CACHE: Dict[str, Any] = {}


def _resolve_annotation(name: str):
    if name in _ANNOT_CACHE:
        return _ANNOT_CACHE[name]
    ns = dict(globals())
    import typing

    ns.update(vars(typing))
    try:
        tp = eval(name, ns)  # noqa: S307 — annotations from this module only
    except Exception:
        tp = Any
    _ANNOT_CACHE[name] = tp
    return tp


def to_dict(cfg) -> Dict[str, Any]:
    if is_dataclass(cfg):
        return {f.name: to_dict(getattr(cfg, f.name)) for f in fields(cfg)}
    if isinstance(cfg, list):
        return [to_dict(v) for v in cfg]
    if isinstance(cfg, dict):
        return {k: to_dict(v) for k, v in cfg.items()}
    return cfg


def _apply_dotlist(data: Dict[str, Any], overrides: List[str]):
    for item in overrides:
        if "=" not in item:
            raise ValueError(f"override {item!r} must look like a.b.c=value")
        key, _, raw = item.partition("=")
        node = data
        parts = key.strip().split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
            if not isinstance(node, dict):
                raise ValueError(f"cannot override through non-mapping at {p!r}")
        node[parts[-1]] = yaml.safe_load(raw) if raw != "" else None


def load_expr_config(
    argv: List[str],
    config_cls: Type[T],
    ignore_unknown_top: bool = False,
) -> Tuple[T, str]:
    """Parse `--config path.yaml key=value ...` into a config dataclass.

    Counterpart of the reference's `load_expr_config` (cli_args.py:1280).
    Returns (config, config_file_path).  `ignore_unknown_top` skips unknown
    TOP-LEVEL yaml sections (for launchers, which parse experiment configs
    only for the fields they own); nested typos still fail loudly.
    """
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=str, default=None)
    args, overrides = parser.parse_known_args(argv)
    bad = [o for o in overrides if o.startswith("--")]
    if bad:
        raise ValueError(
            f"unrecognized flags {bad}; overrides use dotted form a.b.c=value"
        )
    data: Dict[str, Any] = {}
    if args.config:
        with open(args.config) as f:
            data = yaml.safe_load(f) or {}
    _apply_dotlist(data, overrides)
    cfg = _from_dict(config_cls, data, ignore_unknown_top=ignore_unknown_top)
    # propagate experiment/trial names into nested configs that carry them
    for f in fields(cfg):
        sub = getattr(cfg, f.name)
        if is_dataclass(sub) and hasattr(sub, "experiment_name"):
            if getattr(sub, "experiment_name", None) in ("", None):
                sub.experiment_name = cfg.experiment_name
            if getattr(sub, "trial_name", None) in ("", None):
                sub.trial_name = cfg.trial_name
        if is_dataclass(sub) and hasattr(sub, "fileroot"):
            if getattr(sub, "fileroot", None) in ("", None):
                sub.fileroot = cfg.cluster.fileroot
    # select the name_resolve backend for this process: the env override
    # (set by multi-host launchers for every spawned process) wins over the
    # config; both route through utils.name_resolve.reconfigure
    if hasattr(cfg, "cluster"):
        from areal_tpu.utils import name_resolve as _nr

        _nr.reconfigure_from_env(cfg.cluster.name_resolve)
    return cfg, args.config or ""


def save_config(cfg, path: str):
    if os.path.dirname(path):
        os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(to_dict(cfg), f, sort_keys=False)
