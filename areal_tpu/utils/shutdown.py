"""Preemption-aware shutdown for train loops (ISSUE 15).

Cloud schedulers and cluster managers announce eviction with SIGTERM (and
operators with Ctrl-C / SIGINT) some grace period before the SIGKILL.
`PreemptionGuard` converts that signal into a flag the train loop polls at
its step boundary; `preempt_exit` then performs the orderly retreat:

    pause rollout submission -> interrupt in-flight generation ->
    force-dump a recover generation -> exit(RESUME_EXIT_CODE)

`RESUME_EXIT_CODE` (75, EX_TEMPFAIL: "temporary failure, retry") is the
contract with the launchers' relaunch loop (launcher/local.py,
launcher/multihost.py): a trainer exiting with it is relaunched
immediately with the next ``AREAL_RUN_ID`` — it does not consume a
crash-retry and does not wait out the crash backoff, because the dump is
known-good rather than whatever a dying process left behind.

The guard flips a flag instead of raising from the handler on purpose:
a signal raised mid-XLA-dispatch or mid-checkpoint would tear exactly the
state the dump is about to protect.  The second signal is left on the
default disposition, so a stuck dump can still be interrupted.
"""

import signal
import sys
import threading
from typing import Optional

from areal_tpu.utils import logging

logger = logging.getLogger("shutdown")

# os.EX_TEMPFAIL — distinct from both success (0) and crash (anything
# else): the launcher relaunches it immediately without burning a retry
RESUME_EXIT_CODE = 75


class PreemptionGuard:
    """SIGTERM/SIGINT -> a step-boundary flag.

    Usage::

        guard = PreemptionGuard().install()
        for step in range(start, total):
            ...train one step...
            if guard.requested:
                preempt_exit(recover, engine, step_info, ...)
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = signals
        self._flag = threading.Event()
        self.signum: Optional[int] = None
        self._prev = {}

    def install(self) -> "PreemptionGuard":
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def _on_signal(self, signum, frame):
        self.signum = signum
        self._flag.set()
        # restore default disposition: a second signal kills for real
        # instead of being swallowed while the dump runs
        signal.signal(signum, signal.SIG_DFL)
        logger.warning(
            f"received signal {signum}; will dump + exit at the next "
            f"step boundary (send again to kill immediately)"
        )

    @property
    def requested(self) -> bool:
        return self._flag.is_set()


def preempt_exit(
    recover,
    engine,
    step_info,
    *,
    rollout_engines=(),
    dump_kwargs=None,
) -> None:
    """Orderly preemption retreat; does not return.

    `rollout_engines` are paused (no new submissions) and their in-flight
    generation interrupted (best-effort — the fleet may already be dying
    with us) before the force-dump, so the dumped staleness ledger is
    quiescent.  `dump_kwargs` are forwarded to `recover.dump` (saver,
    dataloader, tokenizer, extra_engines, inference_engine, ...).
    """
    for r in rollout_engines:
        try:
            r.pause()
        except Exception as e:  # noqa: BLE001 — retreat must not crash
            logger.warning(f"pause on preemption failed: {e!r}")
        try:
            r.pause_generation()
        except Exception as e:  # noqa: BLE001
            logger.warning(f"generation interrupt on preemption failed: {e!r}")
    path = recover.dump(engine, step_info, **(dump_kwargs or {}))
    logger.warning(
        f"preemption dump complete ({path}); exiting with resume code "
        f"{RESUME_EXIT_CODE}"
    )
    sys.exit(RESUME_EXIT_CODE)
