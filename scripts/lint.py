"""areal-lint CLI: run the project static-analysis suite (ISSUE 3/9).

    python scripts/lint.py                  # report all findings
    python scripts/lint.py --check          # exit 1 on unsuppressed findings
                                            # (the tier-1 gate semantics)
    python scripts/lint.py --suppressed     # also list suppressed findings
    python scripts/lint.py --format json    # machine-readable output
    python scripts/lint.py --format sarif   # SARIF 2.1.0 (CI diff annotation)
    python scripts/lint.py --write-baseline lint-baseline.json
    python scripts/lint.py --baseline lint-baseline.json --check
                                            # only NEW findings fail
    python scripts/lint.py --write-budget   # regenerate the C6 signature
                                            # budget (analysis/signature_budget.json)
    python scripts/lint.py --explain C8     # print a wire-contract checker's
                                            # catalog entry (C8|C9|C10)

Baseline fingerprints are (path, rule, message) hashes — stable across
unrelated line drift, invalidated when the finding itself changes.

Checker catalog, annotation syntax (`_GUARDED_FIELDS`, `# guarded-by:`,
`# holds:`, `# lock-order:`, `_SLOT_TYPESTATE`, `# areal-lint: hot-path`)
and the suppression format (`# areal-lint: disable=<rule> <reason>`):
docs/lint.md.
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.analysis import run_suite, unsuppressed  # noqa: E402
from areal_tpu.analysis.jit_signatures import (  # noqa: E402
    BUDGET_PATH,
    render_budget_doc,
)

# The engine configs the jit-cache soak tests run with; the budgets derived
# from these are what the soak tests assert observed program counts against.
REFERENCE_CONFIGS = {
    "tiered_decode_soak": {
        "n_slots": 4,
        "max_seq_len": 256,
        "prompt_bucket": 16,
        "decode_tiers": 2,
    },
    "group_fanout_soak": {
        "n_slots": 8,
        "max_seq_len": 256,
        "prompt_bucket": 16,
        "decode_tiers": 1,
    },
    # ISSUE 12: spec decode on — verify programs ride (tier, K, D) with D
    # from the nonzero rungs of the default {0, 3, 7} ladder
    "spec_decode_soak": {
        "n_slots": 4,
        "max_seq_len": 256,
        "prompt_bucket": 16,
        "decode_tiers": 2,
        "spec_rungs": 2,
    },
    # ISSUE 19: ragged kernel on — the collapsed grid-wide dispatch
    # budgets one decode program per K bucket and one verify program per
    # (K bucket, nonzero D rung); the tier factor is gone by design
    "ragged_decode_soak": {
        "n_slots": 4,
        "max_seq_len": 256,
        "prompt_bucket": 16,
        "decode_tiers": 2,
        "spec_rungs": 2,
        "ragged": 1,
    },
    # ISSUE 20: the two-level layer-grouped train scan — the soak drives 3
    # distinct (row_len, padded_len) batch signatures twice through one
    # engine; grouping/remat/unroll are engine-lifetime config and must
    # mint no signatures of their own
    "train_scan_soak": {
        "train_shapes": 3,
    },
}


# --explain catalog: one entry per wire-contract checker (the full C1–C10
# catalog with worked examples lives in docs/lint.md).
EXPLAIN = {
    "C8": """\
C8 — cross-process payload contracts
rules: payload-contract, payload-silent-default
registry: areal_tpu/analysis/wire_contracts.json (endpoints/apps/
          client_targets/post_helpers/bindings)

Per HTTP endpoint, the checker extracts producer key-sets (dict literals
and payload["k"] writes flowing into session.post/HttpRequest/helper
calls in core/remote.py, gen/router.py, scripts/bench_replay.py,
tests/fake_server.py) and consumer key-sets (body["k"] / body.get("k", d)
reads in gen/server.py + gen/router.py handlers, and response-field reads
back in the clients).  Findings:
  - a hard read (body["k"]) of a key no producer writes      -> error
  - a producer writing a key the contract does not declare   -> error
  - a closed producer literal omitting a required key        -> error
  - .get with a silent constant/empty-literal default on a key every
    producer writes (the silent-0 class)      -> payload-silent-default
  - a contract key nothing produces/reads     -> wire-registry-stale
Response bodies are checked in the reverse direction.  Suppress inline
with `# areal-lint: disable=payload-contract <reason>`; registry-anchored
findings are fixed by editing wire_contracts.json, not suppressed.""",
    "C9": """\
C9 — telemetry name contracts (bidirectional)
rules: metric-contract, event-contract
registry: wire_contracts.json (events.names, metrics.dynamic_sites/
          dynamic_patterns/unpinned) + tests/data/metrics_schema.json

Every Counter/Gauge/Histogram constructed anywhere must resolve to a name
pinned in tests/data/metrics_schema.json, and every pinned name must be
constructed by code (no orphans in either direction; dynamically-named
constructions are only allowed in metrics.dynamic_sites files and are
covered by metrics.dynamic_patterns on the reverse pass).  Every event
name passed to telemetry.emit must be declared in events.names AND
consumed by obs/trace.py's parser, and vice versa — emitted-but-never-
parsed spans and parsed-but-never-emitted ghosts are both findings.
Exemptions live in the registry (emit_exempt / consume_exempt, each with
a reason).""",
    "C10": """\
C10 — config plumbing
rule: config-plumbing
registry: wire_contracts.json (config_chains.files / config_chains.chains)

Each chain pins one knob end-to-end:
  GenServerConfig field -> build_cmd emission -> gen/server.py argparse
  flag -> GenEngine kwarg (direct or via a **splat dict).
Findings: a chained field/flag/kwarg missing at any hop; build_cmd
emitting a flag argparse rejects (launched servers crash); any argparse
flag, config field, or build_cmd flag not covered by a chain (add a
chain, or a config_only/server_only entry with a reason).  This is the
--role/--host-cache-mb drift class PRs 16-17 maintained by hand.""",
}


def fingerprint(f) -> str:
    """Line-drift-stable identity of a finding for baseline mode."""
    h = hashlib.sha256(
        f"{f.path}\x00{f.rule}\x00{f.message}".encode("utf-8")
    )
    return h.hexdigest()[:16]


def to_sarif(findings) -> dict:
    """Minimal SARIF 2.1.0 payload (github/codeql-action/upload-sarif)."""
    rules = sorted({f.rule for f in findings})
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "areal-lint",
                        "informationUri": "docs/lint.md",
                        "rules": [{"id": r} for r in rules],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "error",
                        "message": {"text": f.message},
                        "partialFingerprints": {
                            "arealLint/v1": fingerprint(f)
                        },
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.path},
                                    "region": {
                                        "startLine": max(1, f.line)
                                    },
                                }
                            }
                        ],
                    }
                    for f in findings
                ],
            }
        ],
    }


def write_budget(root: str) -> str:
    path = os.path.join(root, BUDGET_PATH)
    doc = render_budget_doc(REFERENCE_CONFIGS)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="project root to scan (default: this repo)",
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="exit nonzero when any unsuppressed (non-baselined) finding "
        "exists",
    )
    p.add_argument(
        "--suppressed",
        action="store_true",
        help="also print suppressed findings (they are always counted)",
    )
    p.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default="text",
        help="output format (sarif feeds CI diff annotation)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="deprecated alias for --format json",
    )
    p.add_argument(
        "--baseline",
        metavar="FILE",
        help="suppress findings whose fingerprint appears in FILE; only "
        "new findings are reported / fail --check",
    )
    p.add_argument(
        "--write-baseline",
        metavar="FILE",
        help="write the current unsuppressed findings' fingerprints to "
        "FILE and exit",
    )
    p.add_argument(
        "--write-budget",
        action="store_true",
        help="regenerate areal_tpu/analysis/signature_budget.json from the "
        "reference soak configs and exit",
    )
    p.add_argument(
        "--explain",
        metavar="CHECKER",
        choices=tuple(EXPLAIN),
        help="print the catalog entry for a wire-contract checker "
        f"({', '.join(EXPLAIN)}) and exit",
    )
    args = p.parse_args(argv)

    if args.explain:
        print(EXPLAIN[args.explain])
        return 0

    if args.write_budget:
        path = write_budget(args.root)
        print(f"wrote {path}")
        return 0

    findings = run_suite(args.root)
    active = unsuppressed(findings)
    suppressed = [f for f in findings if f.suppressed]

    if args.write_baseline:
        payload = {
            "comment": "areal-lint baseline: fingerprints of accepted "
            "pre-existing findings; new findings still fail --check",
            "fingerprints": sorted({fingerprint(f) for f in active}),
        }
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        print(f"wrote {len(payload['fingerprints'])} fingerprint(s)")
        return 0

    baselined = []
    if args.baseline:
        with open(args.baseline, encoding="utf-8") as f:
            known = set(json.load(f).get("fingerprints", []))
        baselined = [f for f in active if fingerprint(f) in known]
        active = [f for f in active if fingerprint(f) not in known]

    fmt = "json" if args.json else args.format
    if fmt == "json":
        print(
            json.dumps(
                {
                    "findings": [vars(f) for f in active],
                    "suppressed": [vars(f) for f in suppressed],
                    "baselined": [vars(f) for f in baselined],
                }
            )
        )
    elif fmt == "sarif":
        print(json.dumps(to_sarif(active), indent=2))
    else:
        for f in active:
            print(f.render())
        if args.suppressed:
            for f in suppressed:
                print(f.render())
        tail = f", {len(baselined)} baselined" if args.baseline else ""
        print(
            f"areal-lint: {len(active)} finding(s), "
            f"{len(suppressed)} suppressed{tail}"
        )
    if args.check and active:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
