"""Crash-safe checkpoint/resume for interrupted experiments (ISSUE 15).

Behavioral counterpart of the reference's `RecoverHandler`
(areal/utils/recover.py:139), hardened so the trainer can die at ANY
instant — SIGKILL mid-dump included — and the relaunch resumes from the
last-known-good state losing at most one step:

- `dump` stages the whole checkpoint into ``recover/.tmp-<step>``, fsyncs
  every file, writes a JSON manifest (step, weight version, config
  fingerprint, per-file digests, async rollout state), then atomically
  renames the staging dir to ``recover/gen-<step>``.  The previous
  generation is retained until the new one is durable, so there is never
  a moment without an intact checkpoint on disk.
- `load` walks generations newest-first, validates each manifest (parse,
  per-file size + blake2b digest), and falls back to the previous
  generation on a torn or tampered one.  A config-fingerprint mismatch is
  refused outright (`RecoverConfigMismatch`) — silently resuming under a
  different config corrupts the run worse than starting over.
- Async state rides in the manifest: the staleness ledger snapshot, the
  seeding base, and the fleet weight version.  On load the weight upload
  is replayed with the version PINNED so rejoining gen servers serve the
  recovered policy (not a newer snapshot that survived the crash), and
  in-flight-at-crash trajectories are settled as rejected — the ledger
  invariant holds and the loss is counted in telemetry.

`check_if_recover` (reference :373) decides whether a launch resumes;
mode ``resume`` now *raises* when no checkpoint exists instead of
silently starting fresh.
"""

import hashlib
import json
import os
import pickle
import re
import shutil
import time
from dataclasses import asdict, dataclass, field, replace
from typing import Any, Dict, List, Optional

from areal_tpu.api.config import RecoverConfig
from areal_tpu.api.io_struct import (
    RolloutStat,
    SaveLoadMeta,
    StepInfo,
    WeightUpdateMeta,
)
from areal_tpu.utils import logging, telemetry
from areal_tpu.utils.faults import fault_point

logger = logging.getLogger("recover")

MANIFEST_SCHEMA = "areal-recover/v1"
# generations kept on disk: the live one + the fallback
KEEP_GENERATIONS = 2

_GEN_RE = re.compile(r"gen-(\d{8})")


class RecoverCorruptError(RuntimeError):
    """A generation failed manifest validation (torn rename, tampered or
    truncated file).  `load` falls back to the previous generation."""


class RecoverConfigMismatch(RuntimeError):
    """The checkpoint was written under a different config fingerprint.
    Refused, never fallen back from — resuming a run under different
    hyperparameters silently corrupts it."""


def config_fingerprint(obj: Any) -> str:
    """Stable fingerprint of a config: blake2b over the canonical JSON of
    its dict form.  Non-serializable leaves degrade to repr() so the
    fingerprint stays total over dataclass trees."""
    blob = json.dumps(obj, sort_keys=True, default=repr).encode("utf-8")
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def _file_digest(path: str) -> str:
    h = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_path(path: str) -> None:
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0) \
        if os.path.isdir(path) else os.O_RDONLY
    try:
        fd = os.open(path, flags)
    except OSError:  # e.g. O_DIRECTORY unsupported — durability best-effort
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_tree(root: str) -> None:
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            _fsync_path(os.path.join(dirpath, fn))
        _fsync_path(dirpath)


@dataclass
class RecoverInfo:
    """(reference: recover.py RecoverInfo:29)"""

    recover_start: StepInfo
    last_step_info: StepInfo
    saver_info: Dict[str, Any] = field(default_factory=dict)
    checkpointer_info: Dict[str, Any] = field(default_factory=dict)
    evaluator_info: Dict[str, Any] = field(default_factory=dict)
    stats_logger_info: Dict[str, Any] = field(default_factory=dict)
    dataloader_info: Dict[str, Any] = field(default_factory=dict)
    hash_vals_to_ignore: list = field(default_factory=list)


class RecoverHandler:
    def __init__(self, config: RecoverConfig, ft_spec=None,
                 fingerprint: Optional[str] = None):
        self.config = config
        self.ft_spec = ft_spec
        # config fingerprint stamped into every manifest; load refuses a
        # generation written under a different one
        self.fingerprint = fingerprint

    def recover_root(self) -> str:
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "recover",
        )

    # ------------------------------------------------------------------
    # generation discovery
    # ------------------------------------------------------------------

    def generations(self) -> List[str]:
        """Completed generation dirs, oldest-first.  Staging dirs
        (``.tmp-*``) are invisible here by construction: only the atomic
        rename makes a generation discoverable."""
        root = self.recover_root()
        if not os.path.isdir(root):
            return []
        out = []
        for d in os.listdir(root):
            m = _GEN_RE.fullmatch(d)
            if m:
                out.append((int(m.group(1)), os.path.join(root, d)))
        return [p for _, p in sorted(out)]

    # ------------------------------------------------------------------
    # dump
    # ------------------------------------------------------------------

    def dump(
        self,
        engine,
        step_info: StepInfo,
        saver=None,
        evaluator=None,
        stats_logger=None,
        dataloader=None,
        tokenizer=None,
        extra_engines=None,  # {"critic": engine, ...} — saved beside the main one
        inference_engine=None,  # snapshot its staleness ledger + fleet version
    ) -> str:
        root = self.recover_root()
        os.makedirs(root, exist_ok=True)
        step = step_info.global_step
        staging = os.path.join(root, f".tmp-{step:08d}")
        final = os.path.join(root, f"gen-{step:08d}")
        for stale in (staging, final):
            if os.path.isdir(stale):
                shutil.rmtree(stale)

        ckpt = os.path.join(staging, "checkpoint")
        os.makedirs(ckpt, exist_ok=True)
        engine.save(SaveLoadMeta(path=ckpt, with_optim=True, tokenizer=tokenizer))
        for name, eng in (extra_engines or {}).items():
            sub = os.path.join(staging, f"checkpoint_{name}")
            os.makedirs(sub, exist_ok=True)
            eng.save(SaveLoadMeta(path=sub, with_optim=True, tokenizer=tokenizer))

        info = RecoverInfo(
            recover_start=StepInfo(
                epoch=step_info.epoch,
                epoch_step=step_info.epoch_step + 1,
                global_step=step_info.global_step + 1,
                steps_per_epoch=step_info.steps_per_epoch,
            ),
            last_step_info=step_info,
            saver_info=saver.state_dict() if saver else {},
            evaluator_info=evaluator.state_dict() if evaluator else {},
            stats_logger_info=stats_logger.state_dict() if stats_logger else {},
            dataloader_info=dataloader.state_dict() if dataloader else {},
        )
        # state dicts may hold non-JSON leaves (rng state, tensors) — they
        # stay pickled; everything human-relevant lives in the manifest
        with open(os.path.join(staging, "recover_state.pkl"), "wb") as f:
            pickle.dump(info, f)

        manifest = {
            "schema": MANIFEST_SCHEMA,
            "recover_start": asdict(info.recover_start),
            "last_step_info": asdict(info.last_step_info),
            "weight_version": self._maybe_version(engine),
            "run_id": int(os.environ.get("AREAL_RUN_ID", 0)),
            "created_ts": time.time(),
            "config_fingerprint": self.fingerprint,
            "extra_engines": sorted((extra_engines or {}).keys()),
            "async_state": self._async_state(inference_engine),
            "files": {},
        }
        for dirpath, _dn, filenames in os.walk(staging):
            for fn in filenames:
                p = os.path.join(dirpath, fn)
                rel = os.path.relpath(p, staging)
                manifest["files"][rel] = {
                    "size": os.path.getsize(p),
                    "blake2b": _file_digest(p),
                }
        with open(os.path.join(staging, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
        _fsync_tree(staging)

        # chaos hook: a SIGKILL here leaves only a .tmp-* dir behind —
        # invisible to load(), which keeps serving the previous generation
        fault_point("recover_mid_dump")

        os.rename(staging, final)  # the commit point: atomic on one FS
        _fsync_path(root)
        self._prune()
        self._write_sidecar(manifest, final)  # after prune: reflects disk
        logger.info(f"dumped recover generation @ step {step} -> {final}")
        return final

    @staticmethod
    def _maybe_version(engine) -> Optional[int]:
        try:
            return int(engine.get_version())
        except (AttributeError, TypeError):
            return None

    @staticmethod
    def _async_state(inference_engine) -> Dict[str, Any]:
        """Snapshot of the async side: staleness ledger, seed base, fleet
        weight version.  All best-effort — a dump must never fail because
        the rollout side is degraded."""
        state: Dict[str, Any] = {
            "rollout_stat": None,
            "seed": None,
            "fleet_weight_version": None,
        }
        from areal_tpu.utils import seeding

        try:
            state["seed"] = seeding.get_seed()
        except RuntimeError:
            pass
        if inference_engine is None:
            return state
        executor = getattr(inference_engine, "executor", None)
        if executor is not None:
            state["rollout_stat"] = asdict(
                executor.staleness_manager.get_stats()
            )
        try:
            state["fleet_weight_version"] = int(inference_engine.get_version())
        except (AttributeError, TypeError):
            pass
        return state

    def _write_sidecar(self, manifest: Dict[str, Any], latest: str) -> None:
        """Human-readable ``recover_info.json`` beside the generations:
        the full manifest summary, not just last_step_info (ISSUE 15
        satellite).  Written tmp+rename so it is itself crash-safe."""
        root = self.recover_root()
        gens = self.generations()
        doc = {
            "schema": MANIFEST_SCHEMA,
            "experiment_name": self.config.experiment_name,
            "trial_name": self.config.trial_name,
            "run_id": manifest["run_id"],
            "last_step_info": manifest["last_step_info"],
            "recover_start": manifest["recover_start"],
            "weight_version": manifest["weight_version"],
            "config_fingerprint": manifest["config_fingerprint"],
            "updated_ts": time.time(),
            "latest": latest,
            "generations": gens,
        }
        tmp = os.path.join(root, ".recover_info.json.tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, os.path.join(root, "recover_info.json"))

    def _prune(self) -> None:
        gens = self.generations()
        for old in gens[:-KEEP_GENERATIONS]:
            shutil.rmtree(old, ignore_errors=True)
        # staging leftovers from crashed dumps are dead weight
        root = self.recover_root()
        for d in os.listdir(root):
            if d.startswith(".tmp-"):
                shutil.rmtree(os.path.join(root, d), ignore_errors=True)

    # ------------------------------------------------------------------
    # load
    # ------------------------------------------------------------------

    def _validate_generation(self, gen_dir: str) -> Dict[str, Any]:
        mpath = os.path.join(gen_dir, "manifest.json")
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise RecoverCorruptError(f"{gen_dir}: unreadable manifest: {e}")
        if manifest.get("schema") != MANIFEST_SCHEMA:
            raise RecoverCorruptError(
                f"{gen_dir}: unknown manifest schema "
                f"{manifest.get('schema')!r}"
            )
        for rel, spec in manifest.get("files", {}).items():
            p = os.path.join(gen_dir, rel)
            if not os.path.isfile(p):
                raise RecoverCorruptError(f"{gen_dir}: missing file {rel}")
            if os.path.getsize(p) != spec["size"]:
                raise RecoverCorruptError(
                    f"{gen_dir}: size mismatch for {rel}"
                )
            if _file_digest(p) != spec["blake2b"]:
                raise RecoverCorruptError(
                    f"{gen_dir}: digest mismatch for {rel}"
                )
        fp = manifest.get("config_fingerprint")
        if self.fingerprint is not None and fp is not None \
                and fp != self.fingerprint:
            raise RecoverConfigMismatch(
                f"{gen_dir} was written under config fingerprint {fp}, "
                f"this run has {self.fingerprint}; refusing to resume — "
                f"move the recover dir aside or fix the config"
            )
        return manifest

    def load(
        self,
        engine,
        saver=None,
        evaluator=None,
        stats_logger=None,
        dataloader=None,
        inference_engine=None,
        weight_update_meta: Optional[WeightUpdateMeta] = None,
        extra_engines=None,  # same mapping as dump(); loaded when present
    ) -> Optional[RecoverInfo]:
        """Restore from the newest INTACT generation; torn/corrupt ones are
        skipped with a warning.  If an inference engine is given, the
        weight upload is replayed with the recovered version pinned so
        fresh servers serve the recovered policy, and the staleness ledger
        is restored with in-flight-at-crash trajectories settled as
        rejected."""
        manifest = None
        gen_dir = None
        for cand in reversed(self.generations()):
            try:
                manifest = self._validate_generation(cand)
                gen_dir = cand
                break
            except RecoverCorruptError as e:
                logger.warning(f"skipping corrupt recover generation: {e}")
        if gen_dir is None:
            return None

        with open(os.path.join(gen_dir, "recover_state.pkl"), "rb") as f:
            info: RecoverInfo = pickle.load(f)
        ckpt = os.path.join(gen_dir, "checkpoint")
        engine.load(SaveLoadMeta(path=ckpt, with_optim=True))
        for name, eng in (extra_engines or {}).items():
            sub = os.path.join(gen_dir, f"checkpoint_{name}")
            if os.path.isdir(sub):
                eng.load(SaveLoadMeta(path=sub, with_optim=True))
            else:
                logger.warning(
                    "recover checkpoint has no %s engine state (%s); it "
                    "resumes from its initial weights", name, sub,
                )
        if saver is not None and info.saver_info:
            saver.load_state_dict(info.saver_info)
        if evaluator is not None and info.evaluator_info:
            evaluator.load_state_dict(info.evaluator_info)
        if stats_logger is not None and info.stats_logger_info:
            stats_logger.load_state_dict(info.stats_logger_info)
        if dataloader is not None and info.dataloader_info:
            dataloader.load_state_dict(info.dataloader_info)
        version = info.last_step_info.global_step + 1
        engine.set_version(version)
        settled = 0
        if inference_engine is not None and weight_update_meta is not None:
            # pin the version: gen servers must be force-reloaded to the
            # RECOVERED policy, not whatever newer snapshot survived the
            # crash on disk (see WeightUpdateMeta.version)
            pinned = replace(weight_update_meta, version=version) \
                if weight_update_meta.type == "disk" else weight_update_meta
            engine.update_weights(pinned)
            inference_engine.update_weights(pinned)
            inference_engine.set_version(version)
        if inference_engine is not None:
            settled = self._restore_async_state(inference_engine, manifest)
        telemetry.TRAIN_RECOVER.inc()
        telemetry.emit(
            "run_restart",
            run_id=int(os.environ.get("AREAL_RUN_ID", 0)),
            recovered_step=info.last_step_info.global_step,
            resume_step=info.recover_start.global_step,
            weight_version=version,
            settled_inflight=settled,
            generation=gen_dir,
        )
        logger.info(
            f"recovered from step {info.last_step_info.global_step} "
            f"({gen_dir}); resuming at {info.recover_start.global_step}"
        )
        return info

    @staticmethod
    def _restore_async_state(inference_engine, manifest: Dict[str, Any]) -> int:
        executor = getattr(inference_engine, "executor", None)
        stat = (manifest.get("async_state") or {}).get("rollout_stat")
        if executor is None or stat is None:
            return 0
        settled = executor.restore_staleness(RolloutStat(**stat))
        if settled:
            logger.warning(
                f"settled {settled} in-flight-at-crash trajectories as "
                f"rejected (counted in lost_trajectories)"
            )
        return settled


def check_if_recover(config: RecoverConfig, run_id: int = 0) -> bool:
    """Should this launch resume from a recover checkpoint?
    (reference: recover.py:373)

    - ``disabled``: never.
    - ``auto``: resume iff an intact-looking generation exists.
    - ``resume``: the user EXPLICITLY asked to continue — a missing
      checkpoint is an error, not a silent fresh start.
    - ``fault``: resume only on a relaunch (run_id > 0), the launcher's
      crash-recovery loop.
    """
    if config.mode == "disabled":
        return False
    root = os.path.join(
        config.fileroot, config.experiment_name, config.trial_name, "recover"
    )
    exists = False
    if os.path.isdir(root):
        exists = any(
            _GEN_RE.fullmatch(d)
            and os.path.isfile(os.path.join(root, d, "manifest.json"))
            for d in os.listdir(root)
        )
    if config.mode == "resume":
        if not exists:
            raise FileNotFoundError(
                f"recover.mode='resume' but no recover generation exists "
                f"under {root}"
            )
        return True
    if config.mode == "auto":
        return exists
    if config.mode == "fault":
        # only recover on relaunch (run_id > 0), not on a fresh submit
        return exists and run_id > 0
    return False
