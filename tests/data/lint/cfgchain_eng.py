"""C10 fixture: the engine side — __init__ params the chains target."""


class TinyEngine:
    def __init__(self, depth=1, width=2):
        self.depth = depth
        self.width = width
