"""Dataset loader tests (reference: areal/dataset/ — gsm8k/clevr covered
elsewhere; here hhrlhf preference pairs, geometry3k vision manifests, and
torl math rows + the registry dispatch)."""

import json
import os

import numpy as np
import pytest

from areal_tpu.dataset import get_custom_dataset
from tests.fixtures import make_tiny_tokenizer


@pytest.fixture(scope="module")
def tok(tmp_path_factory):
    d = tmp_path_factory.mktemp("tok")
    return make_tiny_tokenizer(str(d))


def test_hhrlhf_pairs(tok, tmp_path):
    rows = [
        {"chosen": "good answer number one", "rejected": "bad"},
        {"chosen": "ok", "rejected": "a much longer rejected response " * 10},
    ]
    p = tmp_path / "pairs.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    ds = get_custom_dataset(str(p), type="hhrlhf", tokenizer=tok)
    assert len(ds) == 2
    assert all(len(x["chosen_ids"]) > 0 and len(x["rejected_ids"]) > 0 for x in ds)

    # max_length filters out the row with the long rejected side
    n_tok_row0 = max(len(ds[0]["chosen_ids"]), len(ds[0]["rejected_ids"]))
    short = get_custom_dataset(
        str(p), type="hhrlhf", tokenizer=tok, max_length=n_tok_row0
    )
    assert len(short) == 1


def test_geometry3k_manifest(tmp_path):
    img = tmp_path / "diagram.png"
    try:
        from PIL import Image

        Image.new("RGB", (40, 20), (255, 0, 0)).save(img)
    except ImportError:
        pytest.skip("PIL unavailable")
    manifest = tmp_path / "train.jsonl"
    manifest.write_text(
        json.dumps(
            {"image": "diagram.png", "problem": "find angle x", "answer": "42"}
        )
    )
    ds = get_custom_dataset(str(tmp_path), type="geometry3k", split="train")
    assert len(ds) == 1
    sample = ds[0]
    assert os.path.isabs(sample["images"][0])
    assert sample["answer"] == "42"
    assert sample["messages"] == "find angle x"

    from areal_tpu.dataset.geometry3k import pad_to_square

    from PIL import Image

    sq = pad_to_square(Image.open(img))
    assert sq.size == (40, 40)


def test_torl_rows(tok, tmp_path):
    rows = [
        {
            "prompt": [{"role": "user", "content": "compute 2+2"}],
            "reward_model": {"ground_truth": "4"},
            "data_source": "torl",
            "ability": "math",
            "extra_info": {},
        }
    ]
    p = tmp_path / "torl.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    ds = get_custom_dataset(str(p), type="torl")
    assert len(ds) == 1
    assert ds[0]["answer"] == "\\boxed{4}"
    assert ds[0]["messages"][0]["content"] == "compute 2+2"

    # pre-converted shape works too
    p2 = tmp_path / "conv.jsonl"
    p2.write_text(json.dumps({"messages": "solve it", "answer": "7"}))
    ds2 = get_custom_dataset(str(p2), type="torl")
    assert ds2[0]["answer"] == "\\boxed{7}"


def test_registry_dispatch_unknown():
    with pytest.raises(ValueError, match="unknown dataset"):
        get_custom_dataset("nope", type="definitely-not-registered")


# ---------------------------------------------------------------------------
# gsm8k_synth (VERDICT r5): the synthetic GSM8K generator + closed-vocab
# tokenizer must round-trip through the REAL math reward — the module's
# whole reason to exist is that GRPO against gsm8k_reward_fn can move
# accuracy on it
# ---------------------------------------------------------------------------


def test_gsm8k_synth_tokenizer_round_trip():
    from areal_tpu.dataset.gsm8k_synth import WordTokenizer, generate_problems

    tok = WordTokenizer()
    for item in generate_problems(64, seed=3):
        # the solution must survive encode->decode verbatim enough that
        # the \boxed{N} syntax is literally reproduced (no <unk> holes)
        ids = tok.encode(item["solution"])
        assert tok.unk_token_id not in ids, item["solution"]
        assert f"\\boxed{{{item['answer']}}}" in tok.decode(ids)
        # prompts round-trip too (chat template -> ids -> text)
        pids = tok.apply_chat_template(item["messages"])
        assert tok.unk_token_id not in pids
        assert "User:" in tok.decode(pids)


def test_gsm8k_synth_reward_fn_compatibility():
    """The generator's solutions score 1.0 under gsm8k_reward_fn AFTER a
    tokenizer round trip (the exact path RLVRWorkflow runs: completion
    ids -> decode -> extract_answer -> math_equal), and corrupted answers
    score 0.0."""
    from areal_tpu.dataset.gsm8k_synth import WordTokenizer, generate_problems
    from areal_tpu.reward.math_parser import gsm8k_reward_fn

    tok = WordTokenizer()
    for item in generate_problems(32, seed=7):
        completion_ids = tok.encode(item["solution"])
        completion = tok.decode(completion_ids)
        assert gsm8k_reward_fn(
            "", completion, [], completion_ids, item["answer"]
        ) == 1.0, (item, completion)
        wrong = str(int(item["answer"]) + 1)
        assert gsm8k_reward_fn(
            "", completion, [], completion_ids, wrong
        ) == 0.0


def test_gsm8k_synth_sft_example_masks_prompt():
    from areal_tpu.dataset.gsm8k_synth import (
        WordTokenizer,
        generate_problems,
        sft_example,
    )

    tok = WordTokenizer()
    item = generate_problems(1, seed=11)[0]
    ex = sft_example(tok, item)
    n_prompt = len(tok.apply_chat_template(item["messages"]))
    assert ex["input_ids"].shape == ex["loss_mask"].shape
    assert ex["loss_mask"][:n_prompt].sum() == 0  # no loss on the prompt
    assert ex["loss_mask"][n_prompt:].all()  # full loss on the solution
    assert ex["input_ids"][-1] == tok.eos_token_id
