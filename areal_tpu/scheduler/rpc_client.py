"""Client side of the engine RPC layer (reference: RPCClient,
areal/scheduler/rpc/rpc_client.py:17).  Synchronous by design — the
controller's train loop is sequential; concurrency across workers comes from
`TrainController` issuing calls on a thread pool."""

import json
import urllib.error
import urllib.request
from dataclasses import asdict
from typing import Any, Dict, Optional

import numpy as np

from areal_tpu.api.io_struct import SaveLoadMeta, WeightUpdateMeta
from areal_tpu.controller.batch import DistributedBatch
from areal_tpu.scheduler.wire import encode_frame


class RPCError(RuntimeError):
    pass


class RPCEngineClient:
    def __init__(self, addr: str, timeout: float = 3600.0):
        self.addr = addr
        self.timeout = timeout

    # ------------------------------ transport ---------------------------

    def call(
        self,
        method: str,
        batch: Optional[Dict[str, Any]] = None,
        return_batch: bool = False,
        **kwargs,
    ):
        for k, v in list(kwargs.items()):
            if isinstance(v, (WeightUpdateMeta, SaveLoadMeta)):
                d = asdict(v)
                # drop non-wire fields (tokenizer objects, alloc modes)
                d.pop("tokenizer", None)
                d.pop("processor", None)
                d.pop("alloc_mode", None)
                kwargs[k] = d
        frame = encode_frame(
            {"__method__": method, "return_batch": return_batch, **kwargs},
            DistributedBatch(batch).to_bytes() if batch is not None else b"",
        )
        req = urllib.request.Request(
            f"http://{self.addr}/call", data=frame, method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                ctype = resp.headers.get("Content-Type", "")
                body = resp.read()
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:  # noqa: BLE001
                detail = ""
            raise RPCError(f"{method} -> HTTP {e.code}: {detail}") from e
        if "octet-stream" in ctype:
            out = DistributedBatch.from_bytes(body)
            d = out.to_dict()
            if set(d) == {"result"}:
                return d["result"]
            return d
        return json.loads(body).get("result")

    def health(self) -> Dict[str, Any]:
        with urllib.request.urlopen(
            f"http://{self.addr}/health", timeout=30
        ) as resp:
            return json.loads(resp.read())

    # ------------------------- engine-shaped sugar ----------------------

    def compute_logp(self, batch) -> np.ndarray:
        return self.call("compute_logp", batch)

    def compute_advantages(self, batch) -> Dict[str, np.ndarray]:
        """Returns the batch with advantage columns added (server-side
        mutation shipped back)."""
        return self.call("compute_advantages", batch, return_batch=True)

    def ppo_update(self, batch):
        return self.call("ppo_update", batch)

    def update_weights(self, meta: WeightUpdateMeta):
        return self.call("update_weights", meta=meta)

    def save(self, meta: SaveLoadMeta):
        return self.call("save", meta=meta)

    def load(self, meta: SaveLoadMeta):
        return self.call("load", meta=meta)

    def set_version(self, version: int):
        return self.call("set_version", version=version)

    def get_version(self) -> int:
        return self.call("get_version")

    def step_lr_scheduler(self):
        return self.call("step_lr_scheduler")
