"""CLEVR-count visual RL dataset (reference: areal/dataset/clevr_count_70k.py
get_clevr_count_70k_rl_dataset) + counting reward.

Loads a jsonl manifest (offline-friendly — this environment has no network
egress) or an HF dataset dir; each sample carries image paths/arrays, a
counting question, and the integer answer.  Samples feed
`VisionRLVRWorkflow` (workflow/vision_rlvr.py).
"""

import json
import os
from typing import Optional

from areal_tpu.dataset import register_dataset
from areal_tpu.reward.math_parser import extract_answer


@register_dataset("clevr")
def get_clevr_count_dataset(
    path: str,
    split: str = "train",
    tokenizer=None,
    processor=None,
    max_length: Optional[int] = None,
    **kwargs,
):
    """jsonl manifest rows: {"images": [path...] | "image": path,
    "messages": str | chat list, "answer": int}.  Image paths resolve
    relative to the manifest; images load lazily in the workflow's
    processor call."""
    manifest = path
    if os.path.isdir(path):
        manifest = os.path.join(path, f"{split}.jsonl")
    samples = []
    base = os.path.dirname(os.path.abspath(manifest))
    with open(manifest) as f:
        for i, line in enumerate(f):
            if not line.strip():
                continue
            row = json.loads(line)
            images = row.get("images") or (
                [row["image"]] if "image" in row else []
            )
            images = [
                img if not isinstance(img, str) or os.path.isabs(img)
                else os.path.join(base, img)
                for img in images
            ]
            sample = {
                "images": images,
                "messages": row["messages"],
                "answer": str(row["answer"]),
                "query_id": str(row.get("query_id", i)),
            }
            if "input_ids" in row:
                sample["input_ids"] = row["input_ids"]
                if max_length and len(sample["input_ids"]) > max_length:
                    continue
            # pre-patchified manifests (offline processing, no AutoProcessor
            # at train time): inline pixel patches + image grids ride along
            if "pixel_values" in row:
                import numpy as np

                sample["pixel_values"] = np.asarray(
                    row["pixel_values"], np.float32
                )
                sample["image_grid_thw"] = np.asarray(
                    row["image_grid_thw"], np.int64
                )
                del sample["images"]
            samples.append(sample)
    return samples


def clevr_count_reward(prompt, completions, prompt_ids, completion_ids,
                       answer=None, **kw):
    """1.0 iff the completion's explicitly-marked answer equals the count
    (strict extraction: emitting stray digits earns nothing)."""
    pred = extract_answer(completions, strict=True)
    if pred is None or answer is None:
        return 0.0
    try:
        return float(int(float(pred)) == int(float(str(answer))))
    except ValueError:
        return 0.0
