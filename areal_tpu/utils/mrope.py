"""Multimodal rotary position ids (qwen2-VL mrope convention).

Counterpart of the position-id preparation the reference does per batch for
vision models (areal/engine/base_hf_engine.py:261-287, delegating to
Qwen2VL's get_rope_index): three position channels (temporal, height,
width).  Text tokens advance all three channels together; each image's
tokens get (t, h, w) grid coordinates offset from the current position, and
text resumes after the largest extent of the grid.
"""

from typing import List, Sequence, Tuple

import numpy as np


def mrope_position_ids(
    input_ids: Sequence[int],
    image_token_id: int,
    image_grid_thw: List[Tuple[int, int, int]],
) -> np.ndarray:
    """-> int32 [3, L] (temporal, height, width) position channels.

    `image_grid_thw` lists each image's (t, h, w) token grid in the order
    the images' placeholder runs appear in `input_ids`; the i-th contiguous
    run of `image_token_id` must have length t*h*w.
    """
    ids = np.asarray(input_ids)
    L = len(ids)
    out = np.zeros((3, L), np.int32)
    pos = 0
    img_idx = 0
    i = 0
    while i < L:
        if ids[i] == image_token_id:
            if img_idx >= len(image_grid_thw):
                raise ValueError("more image-token runs than image grids")
            t, h, w = image_grid_thw[img_idx]
            n = t * h * w
            if i + n > L or not np.all(ids[i : i + n] == image_token_id):
                raise ValueError(
                    f"image-token run {img_idx} shorter than grid {t}x{h}x{w}"
                )
            grid_t, grid_h, grid_w = np.meshgrid(
                np.arange(t), np.arange(h), np.arange(w), indexing="ij"
            )
            out[0, i : i + n] = pos + grid_t.reshape(-1)
            out[1, i : i + n] = pos + grid_h.reshape(-1)
            out[2, i : i + n] = pos + grid_w.reshape(-1)
            pos += int(max(t, h, w))
            i += n
            img_idx += 1
        else:
            out[:, i] = pos
            pos += 1
            i += 1
    if img_idx != len(image_grid_thw):
        raise ValueError("fewer image-token runs than image grids")
    return out
