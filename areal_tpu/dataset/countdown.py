"""Countdown arithmetic-game dataset.

Capability counterpart of the reference's countdown example data
(examples/countdown/countdown.py — synthetic (numbers, target) puzzles
with a formula-verification reward).  Rows feed `AgentWorkflow` +
`CountdownEnv` (agent/countdown_env.py) via the `workflow=countdown`
entry-point branch.

Two sources:
- a jsonl manifest: {"numbers": [...], "target": N, "query_id"?: ...}
- "synthetic[:N]" — N generated puzzles (default 256), each guaranteed
  solvable by construction (the target is built from the numbers).
"""

import json
import os
import random
from typing import Optional

from areal_tpu.dataset import register_dataset

PROMPT = (
    "Using the numbers {numbers}, create an arithmetic expression that "
    "equals {target}. You may use +, -, *, / and each number at most "
    "once. Show your reasoning, then give the final expression inside "
    "\\boxed{{}}."
)


def _synthesize(n: int, seed: int, n_numbers: int = 4, lo: int = 1, hi: int = 25):
    rng = random.Random(seed)
    ops = [
        ("+", lambda a, b: a + b),
        ("-", lambda a, b: a - b),
        ("*", lambda a, b: a * b),
    ]
    rows = []
    for i in range(n):
        numbers = [rng.randint(lo, hi) for _ in range(n_numbers)]
        # build the target from a random expression over the numbers, so
        # every puzzle is solvable
        value = numbers[0]
        for x in numbers[1:]:
            _, fn = rng.choice(ops)
            value = fn(value, x)
        rows.append({"numbers": numbers, "target": value, "query_id": str(i)})
    return rows


@register_dataset("countdown")
def get_countdown_dataset(
    path: str,
    split: str = "train",
    tokenizer=None,
    max_length: Optional[int] = None,
    **kwargs,
):
    if path.startswith("synthetic"):
        n = int(path.split(":", 1)[1]) if ":" in path else 256
        rows = _synthesize(n, seed=0 if split == "train" else 1)
    else:
        manifest = path
        if os.path.isdir(path):
            manifest = os.path.join(path, f"{split}.jsonl")
        rows = []
        with open(manifest) as f:
            for i, line in enumerate(f):
                if line.strip():
                    row = json.loads(line)
                    row.setdefault("query_id", str(i))
                    rows.append(row)
    samples = []
    for row in rows:
        prompt = PROMPT.format(
            numbers=list(row["numbers"]), target=row["target"]
        )
        sample = {
            "messages": [{"role": "user", "content": prompt}],
            "numbers": list(row["numbers"]),
            "target": row["target"],
            "query_id": str(row["query_id"]),
        }
        if "input_ids" in row:
            sample["input_ids"] = row["input_ids"]
        elif tokenizer is not None and not hasattr(
            tokenizer, "apply_chat_template"
        ):
            sample["input_ids"] = tokenizer.encode(prompt)
        if max_length and "input_ids" in sample and len(sample["input_ids"]) > max_length:
            continue
        samples.append(sample)
    return samples
