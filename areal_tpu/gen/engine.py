"""Continuous-batching generation engine on a fixed slot grid.

The TPU-native replacement for the SGLang/vLLM servers the reference wraps
(areal/launcher/sglang_server.py:117, realhf generation servers) and for the
legacy native decode loop (realhf/impl/model/nn/real_llm_generate.py).
Design for XLA's static shapes:

- `n_slots` concurrent sequences in a preallocated KV cache
  [L, S, M, Hkv, hd]; admission assigns a free slot, completion frees it —
  continuous batching without shape changes.
- TWO compiled programs: `forward_prefill` per (rows, prompt-bucket) pair
  (both power-of-two padded) and ONE `forward_decode` step advancing every
  slot; idle slots decode garbage that is never read (cheaper than
  recompiling for occupancy).
- **Batched admission**: every free slot is filled from the pending queue in
  ONE prefill call (rows padded to a power of two, dummy rows target a
  scratch cache slot) — a burst of N prompts costs O(log N) device
  round-trips, not N.
- **Model-parallel serving**: with `tp > 1` the engine owns a
  (dp=1, fsdp=1, sp=1, tp) mesh; params shard with the same
  `param_partition_specs` the trainer uses (megatron column/row layout) and
  the KV cache shards its kv-head axis, so a 7B model serves across chips
  the way the reference serves via SGLang's server-side tp
  (areal/api/alloc_mode.py:377 inference d x t x p).
- Cache and rng are donated; steady-state decode allocates nothing.
- Weight reload (`load_weights`) aborts in-flight requests with
  stop_reason="abort" — the client's interruption loop resubmits with
  accumulated tokens (reference behavior: remote_inf_engine.py:428-478) —
  then bumps `version`; per-token versions let decoupled PPO weight stale
  spans correctly.
- **KV prefix reuse** (VERDICT r3 #3): freed slots retain their cache and
  token history; admission matches each prompt against retained prefixes
  (longest common prefix) and prefills only the suffix via
  `forward_prefill_cached` — so an interruption resume or a multi-turn
  agentic turn pays O(new tokens), not O(context).  This is the in-engine
  counterpart of the radix-cache reuse the reference inherits from SGLang
  (areal/core/remote_inf_engine.py:404-413 rid->server affinity exists to
  exploit it; our router preserves the same affinity).  Reuse across a
  weight reload keeps old-policy KV behind new-policy decoding — exactly
  the mixed-version trajectory regime decoupled PPO + per-token versions
  are built for; set `retain_kv_on_reload=False` for strict recompute.
- **Abort-storm discipline** (VERDICT r4 #3): admission drains a window of
  the pending queue and prefix-matches it against every free slot globally
  (highest lcp first) before fresh prompts get slots, and abort-freed
  slots carry a short reservation (`abort_reserve_s`) that withholds them
  from fresh prompts until their aborted owner has had an RTT to
  resubmit — so a publish that aborts N in-flight requests over few slots
  no longer hands the retained prefixes to whoever arrives first.
- **Group fan-out prefill** (ISSUE 2): GRPO samples every group as
  `group_size` requests over the SAME prompt, and per-slot retained reuse
  can serve at most one of them — the other G-1 used to pay a full
  redundant prefill.  Admission now clusters its window by longest common
  prefix (explicit `group_id` groups first, content-discovered clusters
  second), prefills ONE representative per cluster, fans the computed
  prefix K/V out to sibling slots with a batched device-side cache copy
  (ops/kv_copy.py — bucketed lengths, no new compile signatures in steady
  state), and suffix-prefills only each sibling's remainder.  When a free
  slot's retained cache already covers the cluster prefix (multi-turn),
  the representative rides THAT via suffix prefill and nobody recomputes
  the prefix at all.  `seq_tokens`/`kv_version` bookkeeping make shared
  prefixes compose with the live weight swap exactly like retained ones
  (strict mode zeroes both).  This is the in-engine counterpart of
  SGLang's RadixAttention / vLLM's shared PagedAttention blocks.
- **Tiered decode** (ISSUE 5): decode used to attend over the full
  `max_seq_len` cache width for every slot on every step, so steady-state
  cost scaled with the configured ceiling, not with what slots hold.  Now
  every decode dispatch carries a STATIC bucketed `key_window` K (the
  same pow2 ladder as prompt buckets — zero new XLA signatures in steady
  state) bounding attention reads, masks, and the cache write to the
  occupied span.  Because one long slot would inflate K for the whole
  grid, the slot grid partitions into **length-cohort tiers** — static
  contiguous slot blocks, `decode_tiers`/`decode_tier_lens`/
  `decode_tier_slots` — and `step()` runs one decode dispatch per
  non-empty tier with that tier's own K.  Admission places requests by
  prompt + `max_new_tokens` budget; a slot that outgrows its cohort
  mid-generation migrates to a roomier tier via a device-side cache-row
  copy (ops/kv_copy.py) or, when nothing is free, simply grows its own
  tier's K bucket (ceilings are placement hints, never correctness).
  Decode sampling is counter-keyed per slot (fold(decode_key, stream_id,
  position)) so the token streams are bit-identical however the grid is
  partitioned — the tiered-vs-untiered parity contract.  `lengths`,
  `rope_pos`, `last_tokens` and the sampling params live device-resident
  between chunks (host mirrors kept for bookkeeping; re-synced only when
  admission/free/migration dirties them).  This is the slot-grid analogue
  of vLLM's block-granular PagedAttention and Sarathi-Serve's principle
  that steady-state serving cost should track occupied context.
- **Unified radix/paged KV pool** (ISSUE 16, gen/kv_pool.py): the prefix
  mechanisms above used to keep separate lookup state; now ONE structure
  fronts them all.  A page table maps logical slots to physical cache
  rows and every compiled decode/verify program reads the cache THROUGH
  it (models/transformer.py `rows=`), so a tier migration is an O(1)
  host-side row remap — the old device-side migration copy is gone, and
  the displaced retained prefix survives at the vacated logical slot
  instead of being overwritten.  A compressed radix tree indexes every
  resident prefix (device-retained and host-spilled alike); admission
  matching, fan-out representatives, and failover resubmits all hit
  through one exact-lcp walk.  An optional LRU host-DRAM overflow tier
  (`host_offload`) spills about-to-be-overwritten prefixes via bucketed
  device->host gathers (ops/kv_copy.py) and swaps them back on a radix
  hit — bit-identical round trip, so token streams are invariant to
  spill scheduling.  Lookups stay host-side and block shapes ride the
  existing bucket ladders: steady state still mints zero XLA programs.
"""

# areal-lint: hot-path
import functools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from areal_tpu.analysis.lockcheck import lock_guarded

from areal_tpu.gen.sampling import sample_tokens, sample_tokens_keyed
from areal_tpu.gen.spec import (
    DEFAULT_SPEC_LADDER,
    SpecController,
    propose_draft,
)
from areal_tpu.gen.kv_pool import KVPool, lcp_ids
from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.ops.kv_copy import gather_kv_prefix, scatter_kv_prefix
from areal_tpu.ops.ragged_decode import ragged_supported
from areal_tpu.models.transformer import (
    forward_decode,
    forward_prefill,
    forward_prefill_cached,
    forward_verify,
    init_kv_cache,
    init_params,
    param_partition_specs,
)
from areal_tpu.models.hf import load_hf_params
from areal_tpu.parallel import build_mesh, shard_pytree
from areal_tpu.utils import logging, telemetry
from areal_tpu.utils.datapack import round_up_to_bucket

logger = logging.getLogger("gen.engine")


def plan_decode_tiers(
    n_slots: int,
    max_seq_len: int,
    n_tiers: int,
    quantum: int = 128,
) -> tuple:
    """Default length-cohort layout: (tier ceilings, slots per tier).

    Ceilings double up to `max_seq_len` (each at least 2 x quantum so the
    lowest cohort still spans a few buckets); slot counts halve away from
    tier 0 — the short cohort is where most rollouts live — with the last
    two tiers equal so the counts sum exactly:
        n_slots=64, n_tiers=3, max=16384 -> lens (4096, 8192, 16384),
        slots (32, 16, 16).
    """
    if n_tiers <= 1:
        return [max_seq_len], [n_slots]
    if n_slots >> (n_tiers - 1) < 1:
        raise ValueError(
            f"decode_tiers={n_tiers} needs n_slots >= {1 << (n_tiers - 1)}"
        )
    slots = [n_slots >> (i + 1) for i in range(n_tiers - 1)]
    slots.append(n_slots - sum(slots))  # tier 0 largest block
    lens = [
        max(2 * quantum, max_seq_len >> (n_tiers - 1 - i))
        for i in range(n_tiers)
    ]
    lens[-1] = max_seq_len
    return lens, slots


@dataclass
class GenRequest:
    rid: str
    input_ids: List[int]
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    stop_token_ids: List[int] = field(default_factory=list)
    # vision inputs (VLM serving): pre-patchified pixels in image order and
    # the per-image (t, h, w) patch grids — the AutoProcessor wire format
    pixel_values: Optional["np.ndarray"] = None  # [N, patch_dim]
    image_grid_thw: Optional["np.ndarray"] = None  # [n_img, 3]
    # group fan-out: siblings sampling the same prompt (a GRPO group) carry
    # a shared affinity key + the expected group size, so admission can
    # hold for the full group, cluster it in one window, and the router can
    # keep the members on one replica (the KV prefix is only shareable
    # within one engine's cache)
    group_id: str = ""
    group_n: int = 0
    # telemetry (utils/telemetry.py): trajectory trace id carried from the
    # wire + the submit() timestamp backing the admission-wait histogram;
    # first_token_ts/finish_ts complete the per-request latency triple
    # (TTFT / end-to-end / inter-token) on the same perf_counter clock
    trace_id: str = ""
    submit_ts: float = 0.0
    first_token_ts: float = 0.0
    finish_ts: float = 0.0
    # filled by the engine
    output_tokens: List[int] = field(default_factory=list)
    output_logprobs: List[float] = field(default_factory=list)
    output_versions: List[int] = field(default_factory=list)
    stop_reason: str = ""
    # prompt tokens inherited from the unified prefix cache at admission
    # (retained reuse, fan-out share, or host swap-in) — surfaced on the
    # wire so a failover resubmit can prove its radix warm start
    cache_hit_tokens: int = 0
    # sampler stream override (disaggregated handoff): 0 means "allocate a
    # fresh stream at admission" (the normal path); nonzero pins the
    # counter-keyed sampler stream so a decode-role server continues a
    # prefill-role server's token stream bit-identically — the per-token
    # key is fold(fold(decode_key, stream_id), position), a pure function
    # of data that rides the wire
    stream_id: int = 0
    on_done: Optional[Callable[["GenRequest"], None]] = None

    def finish(self, reason: str):
        self.stop_reason = reason
        self.finish_ts = time.perf_counter()
        if self.on_done is not None:
            self.on_done(self)


@lock_guarded
class GenEngine:
    # lock-discipline contract (areal-lint C1; runtime-validated under
    # AREAL_DEBUG_LOCKS=1): the worker thread and control threads (abort,
    # weight publish) hand requests across these fields, so every touch
    # must hold _lock.  The tiered-decode state joined the contract in
    # ISSUE 9: _dev_state/_state_dirty are the device mirror handoff
    # (abort/free/admission dirties them from control threads while the
    # decode loop consumes them) and _next_stream is the stream-id
    # allocator shared by all admission paths.  Slot arrays (slot_req,
    # lengths, retained_len, ...) are worker-owned between the documented
    # lock sections and stay outside the contract.
    _GUARDED_FIELDS = {
        "_holdback": "_lock",
        "_abort_gen": "_lock",
        "_state_dirty": "_lock",
        "_dev_state": "_lock",
        "_next_stream": "_lock",
    }

    # slot lifecycle automaton (areal-lint C7): slot s is owned iff
    # slot_req[s] is not None; an acquire must settle every per-slot
    # array below for the same index in the same block (or via a helper
    # whose transitive write set covers it); a release must settle the
    # retained prefix length; _reserved_until/kv_version/_slot_vlm remain
    # writable on freed slots (abort reservations, migration sources).
    _SLOT_TYPESTATE = {
        "owner": "slot_req",
        "acquire_writes": [
            "lengths",
            "rope_pos",
            "last_tokens",
            "temperature",
            "top_p",
            "top_k",
            "retained_len",
            "_reserved_until",
            "kv_version",
            "stream_ids",
        ],
        "release_writes": ["_reserved_until", "kv_version", "_slot_vlm"],
        "version_field": "kv_version",
        "retained_field": "retained_len",
    }

    def __init__(
        self,
        model_config: TransformerConfig,
        params=None,
        model_path: Optional[str] = None,
        n_slots: int = 8,
        max_seq_len: int = 2048,
        prompt_bucket: int = 128,
        kv_dtype: str = "bfloat16",
        seed: int = 0,
        decode_chunk: int = 8,
        tp: int = 1,
        ep: int = 1,
        devices=None,
        kv_reuse: bool = True,
        reuse_min_tokens: int = 16,
        retain_kv_on_reload: bool = True,
        abort_reserve_s: float = 1.0,
        admission_window: Optional[int] = None,
        share_prefix: bool = True,
        share_min_tokens: Optional[int] = None,
        group_hold_s: float = 0.05,
        match_window: Optional[int] = None,
        decode_window: bool = True,
        decode_tiers: int = 1,
        decode_tier_lens: Optional[List[int]] = None,
        decode_tier_slots: Optional[List[int]] = None,
        spec_decode: bool = False,
        spec_ladder: Optional[List[int]] = None,
        spec_draft_len: Optional[int] = None,
        spec_ngram_max: int = 3,
        spec_ngram_min: int = 1,
        spec_probe_every: int = 8,
        spec_accept_hi: float = 0.5,
        spec_accept_lo: float = 0.2,
        host_offload: bool = False,
        host_cache_mb: int = 64,
        host_min_tokens: int = 32,
        ragged_attn: bool = False,
    ):
        self.model_config = model_config.replace(remat=False)
        if params is None:
            if model_path:
                host, mc = load_hf_params(model_path, model_config, dtype="bfloat16")
                self.model_config = mc.replace(
                    dtype=model_config.dtype, param_dtype="bfloat16", remat=False
                )
                params = host
            else:
                params = init_params(self.model_config, jax.random.PRNGKey(seed))
        self.tp = tp
        self.ep = ep
        if tp > 1 and self.model_config.num_kv_heads % tp != 0:
            raise ValueError(
                f"tp={tp} must divide num_kv_heads="
                f"{self.model_config.num_kv_heads} (kv-head-sharded cache)"
            )
        if ep > 1 and (
            self.model_config.num_experts == 0
            or self.model_config.num_experts % ep != 0
        ):
            raise ValueError(
                f"ep={ep} needs a MoE model with num_experts divisible by it "
                f"(num_experts={self.model_config.num_experts})"
            )
        # serving mesh: tensor + expert parallel — dp across servers is the
        # client's job (core/remote.py multi-server routing), so the mesh
        # reuses the trainer's partition specs with dp=fsdp=sp=1.  ep>1
        # shards the [E, ., .] expert leaves (the reference's inference-side
        # expert dims, alloc_mode.py:80-117); without it a large MoE's
        # experts are replicated per server and don't fit.
        self.mesh = build_mesh(dp=1, fsdp=1, sp=1, tp=tp, ep=ep, devices=devices)
        self._pspecs = param_partition_specs(self.model_config, tp=tp)
        if self.model_config.vision is not None:
            # VLM: materialise a scratch tower if the checkpoint lacks one
            # (mirrors JaxVLMEngine.initialize) and replicate it — the tower
            # is small relative to the decoder
            from areal_tpu.models.vision import init_vision_params

            params = dict(params)
            if "vision" not in params:
                logger.warning(
                    "VLM config but the checkpoint has no visual.* weights; "
                    "initialising a RANDOM vision tower — image-conditioned "
                    "outputs will be noise until real weights are loaded"
                )
                params["vision"] = init_vision_params(
                    self.model_config.vision, jax.random.PRNGKey(seed + 1)
                )
            self._pspecs = dict(self._pspecs)
            self._pspecs["vision"] = jax.tree_util.tree_map(
                lambda _: P(), params["vision"]
            )
        self.params = shard_pytree(self.mesh, params, self._pspecs)
        self.n_slots = n_slots
        if (
            self.model_config.pos_emb == "learned"
            and max_seq_len > self.model_config.max_position_embeddings
        ):
            # jnp.take clamps out-of-range rows, so positions past the table
            # would silently reuse the last embedding — fail loudly instead
            raise ValueError(
                f"max_seq_len {max_seq_len} exceeds the learned position "
                f"table ({self.model_config.max_position_embeddings}); "
                "gpt2-family models cannot extrapolate positions"
            )
        self.max_seq_len = max_seq_len
        self.prompt_bucket = prompt_bucket
        self.kv_dtype = kv_dtype
        # slot n_slots is the scratch row: dummy admission rows (power-of-two
        # padding) prefill into it, and decode advances it harmlessly
        self._cache_spec = P(None, None, None, "tp", None)
        cache = init_kv_cache(self.model_config, n_slots + 1, max_seq_len, kv_dtype)
        self.cache = {
            k: jax.device_put(v, NamedSharding(self.mesh, self._cache_spec))
            for k, v in cache.items()
        }
        self.rng = jax.random.PRNGKey(seed)
        self.version = 0
        self._standby = None  # (sharded tree, version) pre-staged weights
        self.last_pause_s = 0.0  # achieved generation-idle window
        # >0 while inside a compound pause entry point (load_weights /
        # commit_staged): the nested swap tail must not double-record its
        # sub-window into the pause histogram
        self._pause_depth = 0

        # host-side slot state (scratch slot included, never assigned)
        S = n_slots + 1
        self.slot_req: List[Optional[GenRequest]] = [None] * S
        self.lengths = np.zeros(S, np.int32)
        # logical rope position per slot; equals lengths for text slots,
        # trails it for VLM slots (mrope compresses image placeholder runs)
        self.rope_pos = np.zeros(S, np.int32)
        self.last_tokens = np.zeros(S, np.int32)
        self.temperature = np.ones(S, np.float32)
        self.top_p = np.ones(S, np.float32)
        self.top_k = np.zeros(S, np.int32)
        self.pending: "queue.Queue[GenRequest]" = queue.Queue()
        self._lock = threading.Lock()

        # KV prefix reuse: freed slots keep their cache; seq_tokens mirrors
        # each slot's cache content (prompt + generated, the pending
        # last_token included) so admission can prefix-match against it
        self.kv_reuse = kv_reuse
        self.reuse_min_tokens = reuse_min_tokens
        self.retain_kv_on_reload = retain_kv_on_reload
        self.seq_tokens = np.zeros((S, max_seq_len), np.int32)
        self.retained_len = np.zeros(S, np.int32)  # cache-valid prefix (free slots)
        # abort-storm protection (VERDICT r4 #3): slots freed by an abort
        # keep a short reservation so a fresh prompt arriving before the
        # aborted request's resubmission cannot overwrite its retained
        # prefix; admission also scans a WINDOW of the pending queue and
        # prefix-matches globally before handing any slot to a fresh prompt
        self.abort_reserve_s = abort_reserve_s
        self.admission_window = admission_window or max(64, 4 * n_slots)
        # the lcp scan is O(window x slots x prefix); cap how much of the
        # drain window it touches independently of the drain size so large
        # slot grids do not pay the full quadratic host cost per pass
        self.match_window = match_window or max(64, 2 * n_slots)
        # cross-slot prefix sharing (group fan-out prefill)
        self.share_prefix = share_prefix
        self.share_min_tokens = (
            share_min_tokens if share_min_tokens is not None
            else reuse_min_tokens
        )
        self.group_hold_s = group_hold_s
        self._group_first_seen: Dict[str, float] = {}
        # bumped by abort_all so an _admit pass that raced it can tell its
        # drained-but-unadmitted requests were already terminally finished
        self._abort_gen = 0
        self._reserved_until = np.zeros(S, np.float64)
        self._holdback: List[GenRequest] = []  # drained but not yet admitted
        # no-progress guard: a pass that parked everything records the slot
        # set + earliest reservation expiry so subsequent steps skip the
        # O(window x slots) rescan until something can actually change
        self._parked_free: Optional[frozenset] = None
        self._parked_until: float = 0.0
        self._slot_vlm = np.zeros(S, bool)  # VLM slots never reuse (mrope)
        # --- unified radix/paged KV pool (ISSUE 16) --------------------
        # page-table indirection (logical slot -> physical cache row, read
        # by every decode/verify dispatch), a radix tree over all resident
        # prefixes (one exact-lcp match serves retained reuse, fan-out,
        # and failover resubmits), and the optional LRU host-DRAM
        # overflow tier.  Prefixes shorter than host_min_tokens are not
        # worth a device<->host round trip and just evict.
        self.host_min_tokens = host_min_tokens
        self.pool = KVPool(
            n_slots,
            host_bytes=(int(host_cache_mb) << 20) if host_offload else 0,
        )
        # --- tiered decode (ISSUE 5) -----------------------------------
        # length-cohort tiers: contiguous slot blocks [tier_start[t],
        # tier_start[t] + tier_size[t]) with ascending ceilings
        # tier_bounds[t] (the last always max_seq_len).  Ceilings steer
        # admission placement and migration; correctness never depends on
        # them — a cohort outlier just grows its own tier's K bucket.
        self.decode_window = decode_window
        if decode_tier_lens is not None or decode_tier_slots is not None:
            if not (decode_tier_lens and decode_tier_slots):
                raise ValueError(
                    "decode_tier_lens and decode_tier_slots come together"
                )
            if len(decode_tier_lens) != len(decode_tier_slots):
                raise ValueError("tier lens/slots length mismatch")
        else:
            decode_tier_lens, decode_tier_slots = plan_decode_tiers(
                n_slots, max_seq_len, max(1, decode_tiers), prompt_bucket
            )
        if sum(decode_tier_slots) != n_slots:
            raise ValueError(
                f"decode_tier_slots {decode_tier_slots} must sum to "
                f"n_slots={n_slots}"
            )
        if list(decode_tier_lens) != sorted(decode_tier_lens):
            raise ValueError("decode_tier_lens must ascend")
        self.tier_bounds = [
            min(int(b), max_seq_len) for b in decode_tier_lens
        ]
        self.tier_bounds[-1] = max_seq_len
        self.tier_size = [int(c) for c in decode_tier_slots]
        self.tier_start = list(np.cumsum([0] + self.tier_size[:-1]))
        self.n_tiers = len(self.tier_size)
        self.slot_tier = np.zeros(S, np.int32)
        for t in range(self.n_tiers):
            lo = self.tier_start[t]
            self.slot_tier[lo : lo + self.tier_size[t]] = t
        self.slot_tier[n_slots] = self.n_tiers - 1  # scratch: never decoded
        # decode sampling is counter-keyed: key = fold(fold(_decode_key,
        # stream_id), cache position).  stream_ids are assigned at
        # admission in arrival order — identical however the grid is
        # tiered — so token streams are partition-invariant AND fresh per
        # request (no gumbel-noise reuse across requests in one slot).
        self._decode_key = jax.random.fold_in(jax.random.PRNGKey(seed), 0xD)
        self.stream_ids = np.zeros(S, np.int32)
        self._next_stream = 1
        # device-resident decode state (tokens/lengths/rope_pos/active/
        # sampling params): uploaded only when host bookkeeping diverges
        # (admission, free, migration, abort) — steady-state chunks flow
        # device->device with zero uploads
        self._dev_state: Optional[Dict[str, jax.Array]] = None
        self._state_dirty = True
        # --- self-speculative decode (ISSUE 12) ------------------------
        # Prompt-lookup drafting + one-dispatch verification.  D rides a
        # small STATIC ladder (each nonzero rung is its own verify program
        # per (tier, K) — budgeted in analysis/signature_budget.json);
        # spec_draft_len pins D for benches/tests, otherwise the
        # per-tier acceptance-rate controller adapts along the ladder.
        # Correctness never depends on any of this: verification samples
        # every position under the SAME counter-keyed PRNG plain decode
        # would use, so the output stream is bit-identical for any D.
        self.spec_decode = spec_decode
        if spec_draft_len is not None:
            if spec_draft_len <= 0:
                raise ValueError("spec_draft_len must be positive")
            self.spec_ladder = (0, int(spec_draft_len))
        else:
            self.spec_ladder = tuple(
                sorted(set(int(d) for d in (spec_ladder or DEFAULT_SPEC_LADDER)))
            )
        self.spec_draft_len = spec_draft_len
        self.spec_ngram_max = spec_ngram_max
        self.spec_ngram_min = spec_ngram_min
        self._spec = SpecController(
            ladder=self.spec_ladder,
            accept_hi=spec_accept_hi,
            accept_lo=spec_accept_lo,
            probe_every=spec_probe_every,
        )
        self._spec_max_d = max(self.spec_ladder)
        # per-tier D chosen for the CURRENT step — a self attr so the
        # dispatch site's static arg is provably on the configured ladder
        # (areal-lint C6 value lattice: self.<attr> is engine config)
        self._spec_tier_d: Dict[int, int] = {}
        # --- ragged paged-decode attention (ISSUE 19) -------------------
        # When enabled AND the per-slot K/V working set fits the Pallas
        # kernel's VMEM budget, every decode/verify step collapses to ONE
        # grid-wide dispatch: the kernel gathers each slot's true page
        # span through the page table, so tiers stop buying attended-cost
        # separation and remain only as admission/migration placement
        # policy.  The gate is evaluated ONCE here (worst case: the full
        # max_seq_len window) so the dispatch site's static flag is an
        # engine-lifetime attribute (areal-lint C6 value lattice).
        self.ragged_attn = bool(ragged_attn)
        self._ragged_ok = bool(
            self.ragged_attn
            and ragged_supported(
                max_seq_len,
                self.model_config.num_kv_heads,
                self.model_config.head_dim_,
                jnp.dtype(kv_dtype).itemsize,
                tp=tp,
            )
        )
        if self.ragged_attn and not self._ragged_ok:
            logger.warning(
                "ragged_attn requested but the %d-column K/V window "
                "exceeds the kernel VMEM budget; falling back to the "
                "dense tiered decode path",
                max_seq_len,
            )
        # grid-wide D chosen for the CURRENT collapsed verify step — a
        # self attr for the same C6 reason as _spec_tier_d
        self._spec_grid_d = 0
        # weight version of the OLDEST K/V in each slot's valid prefix:
        # retained and shared prefixes propagate it, so strict-version
        # audits can prove no pre-swap KV seeds post-swap decoding
        self.kv_version = np.zeros(S, np.int64)
        self.stats = {
            "prefill_calls": 0,
            "prefill_tokens": 0,  # real prompt tokens through fresh prefill
            "suffix_calls": 0,
            "suffix_tokens": 0,  # real tokens through suffix prefill
            "reused_tokens": 0,  # retained-prefix tokens NOT recomputed
            "shared_tokens": 0,  # cluster-prefix tokens fanned out, not recomputed
            "copy_calls": 0,  # device-side cross-slot prefix copies
            "decode_calls": 0,
            # abort reservations whose TTL expired before the aborted
            # owner resubmitted — makes the abort_reserve_s assumption
            # observable (VERDICT r6 #10): a storm that reclaims in time
            # keeps this at 0; a rising count means the TTL is too short
            # (or clients stopped resubmitting)
            "reservations_lapsed": 0,
            # tiered decode (ISSUE 5): cohort migrations (device-side
            # cache-row copies to a roomier tier) and the attended-span
            # accounting — attended/ceiling column-steps, whose ratio is
            # decode_attended_fraction (1.0 = decode pays the full
            # max_seq_len ceiling; the window's whole point is << 1)
            "tier_migrations": 0,
            "decode_attended_cols": 0,
            "decode_ceiling_cols": 0,
            # host->device re-uploads of the decode state (dirtied by
            # admission/free/migration); steady state adds none
            "state_syncs": 0,
            # speculative decode (ISSUE 12): draft tokens proposed /
            # accepted (their ratio is the acceptance rate steering the
            # per-tier D ladder) and verify dispatches issued.  The server
            # telemetry mirror exports these as
            # areal_gen_spec_drafted_total / areal_gen_spec_accepted_total.
            "spec_drafted": 0,
            "spec_accepted": 0,
            "verify_calls": 0,
            # unified prefix cache (ISSUE 16): admission outcomes through
            # the radix/paged pool.  hits = admitted rows that inherited a
            # resident prefix (retained reuse, fan-out siblings, host
            # swap-ins); misses = cold/VLM admissions; evictions =
            # resident prefixes overwritten or LRU-dropped before any hit
            # consumed them; host_swaps = device<->host prefix transfers
            # (spills + swap-ins).  The server mirrors all four as
            # areal_gen_prefix_cache_*_total and derives the global
            # hit-rate gauge from hits / (hits + misses).
            "prefix_cache_hits": 0,
            "prefix_cache_misses": 0,
            "prefix_cache_evictions": 0,
            "prefix_cache_host_swaps": 0,
            # page-granular sub-prefix sharing (ISSUE 17 satellite): hits
            # whose inherited span is a page-rounded PARTIAL prefix copied
            # from a donor slot that a longer match claimed — counted
            # inside prefix_cache_hits too, this key is the breakdown
            "prefix_cache_partial_hits": 0,
            # disaggregated handoff (ISSUE 17): cross-server KV page
            # streaming.  exports/imports count /kv_export gathers and
            # /kv_import host-tier installs; bytes is the wire KV payload
            # both ways; failures are export misses (prefix no longer
            # resident) or imports refused (host tier disabled).  The
            # server mirrors them as areal_gen_kv_handoff_*_total.
            "kv_handoff_exports": 0,
            "kv_handoff_imports": 0,
            "kv_handoff_bytes": 0,
            "kv_handoff_failures": 0,
            # ragged paged-decode attention (ISSUE 19): collapsed
            # grid-wide kernel dispatches, and the page-granular read
            # accounting (pages the kernel actually gathered, summed over
            # slots x steps).  The server mirrors both as
            # areal_gen_ragged_*_total and derives the pages-per-dispatch
            # gauge from their ratio.
            "ragged_dispatches": 0,
            "ragged_attended_pages": 0,
        }

        # decode_chunk: tokens generated per host round-trip.  The decode scan
        # runs this many fused forward+sample steps on device before the host
        # sees anything — the host applies stop conditions in arrears and
        # discards overshoot (slots that stopped mid-chunk decode garbage that
        # is never delivered).  Chunking amortises host<->device latency,
        # which dominates when the chip is reached over a network tunnel.
        self.decode_chunk = max(1, decode_chunk)
        cfg = self.model_config
        # ragged kernel closure constants: page granularity rides the SAME
        # prompt-bucket ladder the key_window buckets on (so page-count
        # buckets and K buckets are 1:1 — no extra signature axis), and
        # tp>1 wraps the kernel in shard_map over the kv-head axis
        _kernel_page = prompt_bucket
        _kernel_mesh = self.mesh if tp > 1 else None

        def _stream_keys(decode_key, streams, pos):
            # counter-keyed sampling shared by every text prefill path:
            # key = fold(fold(decode_key, stream), position) — the SAME
            # scheme decode chunks use, with `pos` the index of the last
            # WRITTEN token (one before the first decode key), so the
            # whole token stream is a pure function of (stream_id,
            # position).  That makes the stream invariant to placement:
            # a fresh prefill here, a suffix resume after failover, or a
            # cross-server handoff import all sample identical tokens.
            return jax.vmap(
                lambda s, p: jax.random.fold_in(
                    jax.random.fold_in(decode_key, s), p
                )
            )(streams, pos)

        def _prefill(
            params, cache, ids, plen, slot_ids, streams, decode_key,
            temp, tp, tk,
        ):
            logits, cache = forward_prefill(params, cfg, ids, plen, cache, slot_ids)
            keys = _stream_keys(decode_key, streams, plen - 1)
            tok, logp = sample_tokens_keyed(
                logits.astype(jnp.float32), keys, temp, tk, tp
            )
            return tok, logp, cache

        def _suffix_prefill(
            params, cache, ids, starts, slens, slot_ids, copy_src,
            streams, decode_key, temp, tp, tk, copy_block, key_window,
        ):
            logits, cache = forward_prefill_cached(
                params, cfg, ids, starts, slens, cache, slot_ids,
                copy_src=copy_src, copy_block=copy_block,
                key_window=key_window,
            )
            keys = _stream_keys(decode_key, streams, starts + slens - 1)
            tok, logp = sample_tokens_keyed(
                logits.astype(jnp.float32), keys, temp, tk, tp
            )
            return tok, logp, cache

        def _decode_chunk(
            params, cache, tokens, lengths, rope_pos, streams, active,
            temp, tp, tk, decode_key, rows, n, base, size, key_window,
            ragged,
        ):
            """Advance ONE length-cohort tier — the `size` slots at
            logical positions [base, base+size) — by `n` fused
            decode+sample steps.  `tokens`/`lengths`/`rope_pos` are the
            FULL device-resident state arrays (donated; returned with the
            block advanced), so consecutive tier dispatches chain
            device->device with no host upload.  `key_window` statically
            bounds the attended span (bucket ladder); `active` drops idle
            slots' cache writes.  `rows` is the page table (traced data):
            each logical slot reads/writes its KV through its physical
            row, so a migration remap costs zero new programs."""
            rows_b = jax.lax.slice_in_dim(rows, base, base + size)
            tok_b = jax.lax.slice_in_dim(tokens, base, base + size)
            len_b = jax.lax.slice_in_dim(lengths, base, base + size)
            rp_b = jax.lax.slice_in_dim(rope_pos, base, base + size)
            act_b = jax.lax.slice_in_dim(active, base, base + size)
            temp_b = jax.lax.slice_in_dim(temp, base, base + size)
            tp_b = jax.lax.slice_in_dim(tp, base, base + size)
            tk_b = jax.lax.slice_in_dim(tk, base, base + size)
            st_b = jax.lax.slice_in_dim(streams, base, base + size)
            slot_keys = jax.vmap(
                lambda s: jax.random.fold_in(decode_key, s)
            )(st_b)

            def body(carry, _):
                cache, tok_b, len_b, rp_b = carry
                logits, cache = forward_decode(
                    params, cfg, tok_b, len_b, cache,
                    rope_positions=rp_b, key_window=key_window,
                    slot_base=base, active=act_b, rows=rows_b,
                    ragged=ragged, page_size=_kernel_page,
                    mesh=_kernel_mesh,
                )
                # counter-based keys: (stream, cache position) — unique
                # per generated token, independent of how the grid is
                # partitioned into dispatches
                keys = jax.vmap(jax.random.fold_in)(slot_keys, len_b)
                tok, logp = sample_tokens_keyed(
                    logits.astype(jnp.float32), keys, temp_b, tk_b, tp_b
                )
                return (cache, tok, len_b + 1, rp_b + 1), (tok, logp)

            (cache, tok_b, len_b, rp_b), (toks, logps) = jax.lax.scan(
                body, (cache, tok_b, len_b, rp_b), None, length=n
            )
            tokens = jax.lax.dynamic_update_slice_in_dim(tokens, tok_b, base, 0)
            lengths = jax.lax.dynamic_update_slice_in_dim(lengths, len_b, base, 0)
            rope_pos = jax.lax.dynamic_update_slice_in_dim(rope_pos, rp_b, base, 0)
            # one fused download: tokens are exactly representable in f32
            out = jnp.stack([toks.astype(jnp.float32), logps])  # [2, n, size]
            return out, cache, tokens, lengths, rope_pos

        def _verify_chunk(
            params, cache, tokens, lengths, rope_pos, streams, active,
            temp, tp, tk, decode_key, rows, drafts, draft_lens,
            base, size, key_window, d_max, ragged,
        ):
            """Speculative step for ONE tier: score the pending token plus
            up to `d_max` prompt-lookup drafts per slot in a single
            `forward_verify` dispatch, sample every position under the
            SAME counter-keyed PRNG plain decode would use, and accept the
            leading run of drafts that match what the sampler emits — so
            the delivered stream is bit-identical to non-speculative
            decode at any temperature.  Per-slot state (lengths/rope/last
            token) advances by the accepted count ON DEVICE; rejected
            draft positions get their freshly-written K/V zeroed before
            the dispatch returns, so no rejected write outlives it."""
            Dp1 = d_max + 1
            rows_b = jax.lax.slice_in_dim(rows, base, base + size)
            tok_b = jax.lax.slice_in_dim(tokens, base, base + size)
            len_b = jax.lax.slice_in_dim(lengths, base, base + size)
            rp_b = jax.lax.slice_in_dim(rope_pos, base, base + size)
            act_b = jax.lax.slice_in_dim(active, base, base + size)
            temp_b = jax.lax.slice_in_dim(temp, base, base + size)
            tp_b = jax.lax.slice_in_dim(tp, base, base + size)
            tk_b = jax.lax.slice_in_dim(tk, base, base + size)
            st_b = jax.lax.slice_in_dim(streams, base, base + size)
            inputs = jnp.concatenate([tok_b[:, None], drafts], axis=1)
            n_write = draft_lens + 1  # pending token + real draft positions
            logits, cache = forward_verify(
                params, cfg, inputs, len_b, cache,
                rope_positions=rp_b, key_window=key_window,
                slot_base=base, active=act_b, n_write=n_write,
                rows=rows_b, ragged=ragged, page_size=_kernel_page,
                mesh=_kernel_mesh,
            )  # [size, Dp1, V]
            # position-keyed sampling: logits[:, j] is the distribution at
            # sequence position len + j, exactly the row a plain decode
            # step would sample with key fold(fold(decode_key, stream),
            # len + j) — flattening to [size*Dp1] preserves per-row
            # determinism (sample_tokens_keyed is fully row-vmapped)
            slot_keys = jax.vmap(
                lambda s: jax.random.fold_in(decode_key, s)
            )(st_b)
            offs = jnp.arange(Dp1, dtype=jnp.int32)
            pos = len_b[:, None] + offs[None, :]  # [size, Dp1]
            keys = jax.vmap(
                jax.vmap(jax.random.fold_in, in_axes=(None, 0))
            )(slot_keys, pos)
            V = logits.shape[-1]
            tok_f, logp_f = sample_tokens_keyed(
                logits.astype(jnp.float32).reshape(size * Dp1, V),
                keys.reshape(size * Dp1, *keys.shape[2:]),
                jnp.repeat(temp_b, Dp1),
                jnp.repeat(tk_b, Dp1),
                jnp.repeat(tp_b, Dp1),
            )
            sampled = tok_f.reshape(size, Dp1)
            logp = logp_f.reshape(size, Dp1)
            # accept the leading run where the draft IS what the sampler
            # emitted; the first mismatch position already carries the
            # correct (non-speculative) token, so a+1 tokens always emit
            ok = (sampled[:, :d_max] == drafts) & (
                offs[None, :d_max] < draft_lens[:, None]
            )
            a = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
            n_emit = jnp.where(act_b, a + 1, 0)
            # rejected-draft K/V must not outlive this dispatch: zero the
            # cache rows at positions above the accepted frontier (index M
            # scatter-drops everything else) — the decode-side analogue of
            # the idle-slot write clamp, made auditable by tests
            M_cache = cache["k"].shape[2]
            rej = (offs[None, :] >= n_emit[:, None]) & (
                offs[None, :] < n_write[:, None]
            ) & act_b[:, None]
            rej_idx = jnp.where(rej, pos, M_cache)
            slots = rows_b  # zero the PHYSICAL rows the writes landed in
            cache = {
                "k": cache["k"].at[:, slots[:, None], rej_idx].set(
                    0, mode="drop"
                ),
                "v": cache["v"].at[:, slots[:, None], rej_idx].set(
                    0, mode="drop"
                ),
            }
            # advance the device-resident state by the accepted count
            new_tok = jnp.where(
                act_b, jnp.take_along_axis(sampled, a[:, None], 1)[:, 0],
                tok_b,
            )
            tokens = jax.lax.dynamic_update_slice_in_dim(
                tokens, new_tok, base, 0
            )
            lengths = jax.lax.dynamic_update_slice_in_dim(
                lengths, len_b + n_emit, base, 0
            )
            rope_pos = jax.lax.dynamic_update_slice_in_dim(
                rope_pos, rp_b + n_emit, base, 0
            )
            # decode-layout download: [2, Dp1, size] + per-slot emit count
            out = jnp.stack([sampled.T.astype(jnp.float32), logp.T])
            return out, n_emit, cache, tokens, lengths, rope_pos

        # ONE cache aval family for every program (ISSUE 17): each
        # cache-producing program pins its cache output to the SAME
        # NamedSharding device_put installed at init (kv-head axis on
        # "tp"), so device_put-fresh, prefill-, decode-, and
        # scatter-produced caches are signature-identical.  Without the
        # pin XLA infers PartitionSpec() for program outputs, splitting
        # every downstream jit into a cold (device_put) and a resident
        # family — the PR 16 cold-start re-mint — and silently degrading
        # the kv-head sharding under tp>1.
        rep = NamedSharding(self.mesh, P())
        # _sync_device_state commits its uploads to this same replicated
        # sharding: a bare jnp.asarray upload is an UNCOMMITTED
        # SingleDeviceSharding, while chained chunk outputs carry `rep` —
        # mixing the two mints a second executable per (static args)
        # signature (the PR 16 cold-start re-mint class, caught again by
        # the ragged soak's exact program accounting)
        self._rep_sharding = rep
        cache_sh = {
            k: NamedSharding(self.mesh, self._cache_spec)
            for k in self.cache
        }
        self._prefill_fn = jax.jit(
            _prefill, donate_argnums=(1,),
            out_shardings=(rep, rep, cache_sh),
        )
        # the suffix program carries the cross-slot prefix fan-out fused in
        # (ops/kv_copy.py gather/scatter before the layer scan): copy_block
        # is static and always from the prompt-bucket ladder, so compile
        # count stays O(log^2 buckets x log slots), same family as
        # admission — and a grouped pass costs no extra dispatch
        self._suffix_prefill_fn = jax.jit(
            _suffix_prefill, static_argnums=(12, 13), donate_argnums=(1,),
            out_shardings=(rep, rep, cache_sh),
        )
        # signature family: (tier block, chunk, K bucket) — tiers and
        # chunk are fixed per engine, K rides the pow2 prompt-bucket
        # ladder, so steady state compiles O(tiers x log(M/quantum))
        # programs and then mints none (pinned by test); the page-table
        # rows arg is traced data and adds no signatures
        self._decode_fn = jax.jit(
            _decode_chunk, static_argnums=(12, 13, 14, 15, 16),
            donate_argnums=(1, 2, 3, 4),
            out_shardings=(rep, cache_sh, rep, rep, rep),
        )
        # verify signature family: (tier block, K bucket, D rung) — D
        # rides the small static spec ladder (D=0 reuses the decode
        # program outright), so spec decode adds
        # tiers x ladder x |nonzero rungs| programs at most, budgeted in
        # analysis/signature_budget.json ("verify") and pinned by the
        # jit-cache soak tests
        self._verify_fn = jax.jit(
            _verify_chunk, static_argnums=(14, 15, 16, 17, 18),
            donate_argnums=(1, 2, 3, 4),
            out_shardings=(rep, rep, cache_sh, rep, rep, rep),
        )
        # host-DRAM overflow tier (ISSUE 16): spill gathers one physical
        # row's bucketed prefix (block static on the prompt ladder — one
        # program per bucket); swap-in scatters it back shape-keyed (same
        # ladder bound), with the cache donated so the restore is in-place
        self._host_gather_fn = jax.jit(gather_kv_prefix, static_argnums=(2,))
        # out_shardings pins the scatter-produced cache to the SAME layout
        # device_put installed at init (kv-head axis on "tp"), so a swap-in
        # or handoff import never changes the cache aval the decode family
        # compiled against — the PR 16 cold-start re-mint is gone, and tp>1
        # swap-ins keep the sharded layout instead of silently gathering
        self._host_scatter_fn = jax.jit(
            scatter_kv_prefix, donate_argnums=(0,),
            out_shardings=NamedSharding(self.mesh, self._cache_spec),
        )
        self._init_vlm()
        self._warmup_host_tier()

    def _init_vlm(self) -> None:
        """Compile the vision tower + image-conditioned prefill when the
        model is a VLM (cfg.vision set and the checkpoint carries a tower);
        text-only engines skip all of it."""
        cfg = self.model_config
        self._vlm = (
            cfg.vision is not None
            and cfg.image_token_id is not None
            and cfg.mrope_section is not None
            and isinstance(self.params, dict)
            and "vision" in self.params
        )
        if not self._vlm:
            return
        from areal_tpu.models.vision import (
            merge_image_embeds,
            mrope_cos_sin,
            vision_forward,
        )

        vcfg = cfg.vision

        def _embed_images(vparams, pv, img_ids, pos_hw):
            return vision_forward(
                vparams, vcfg, pv, img_ids, patch_pos_hw=pos_hw
            )

        def _vlm_prefill(
            params, cache, ids, mpos, image_embeds, plen, slot_ids,
            rng, temp, tp, tk,
        ):
            dtype = jnp.dtype(cfg.dtype)
            text = jnp.take(params["embedding"].astype(dtype), ids, axis=0)
            x = merge_image_embeds(text, ids, image_embeds, cfg.image_token_id)
            rope = mrope_cos_sin(
                mpos, cfg.head_dim_, cfg.rope_theta, cfg.mrope_section
            )
            logits, cache = forward_prefill(
                params, cfg, ids, plen, cache, slot_ids,
                inputs_embeds=x, rope=rope,
            )
            tok, logp = sample_tokens(
                logits.astype(jnp.float32), rng, temp, tk, tp
            )
            return tok, logp, cache

        self._embed_images_fn = jax.jit(_embed_images)
        # same single cache aval family as the text programs
        rep = NamedSharding(self.mesh, P())
        cache_sh = {
            k: NamedSharding(self.mesh, self._cache_spec)
            for k in self.cache
        }
        self._vlm_prefill_fn = jax.jit(
            _vlm_prefill, donate_argnums=(1,),
            out_shardings=(rep, rep, cache_sh),
        )

    # ------------------------------------------------------------------
    # submission / weights
    # ------------------------------------------------------------------

    def submit(self, req: GenRequest) -> None:
        if len(req.input_ids) + 1 >= self.max_seq_len:
            req.finish("length")
            return
        # one clock read per request: backs the admission-queue-wait
        # histogram without any conditional on the hot submit path
        req.submit_ts = time.perf_counter()
        self.pending.put(req)

    def submit_batch(self, reqs: List[GenRequest]) -> None:
        """Enqueue a whole group contiguously, so one admission window sees
        every member and the cluster fan-out can share their prefix; the
        group hold (`group_hold_s`) covers members that still straggle in
        through separate submits."""
        for req in reqs:
            self.submit(req)

    def active_count(self) -> int:
        with self._lock:
            return (
                sum(r is not None for r in self.slot_req)
                + self.pending.qsize()
                + len(self._holdback)
            )

    def abort_all(self, reason: str = "abort") -> int:
        """Finish every in-flight request immediately (weight update /
        shutdown). Returns how many were aborted.

        Each abort-freed slot gets a short reservation
        (`abort_reserve_s`): the aborted client WILL resubmit with the
        same prompt + accumulated tokens within an RTT, and handing the
        slot to a fresh prompt first would overwrite the retained prefix
        exactly when it is most valuable (the r4 abort-storm thrash)."""
        deadline = time.monotonic() + self.abort_reserve_s
        version_before = self.version
        # finish() runs user on_done callbacks and wakes waiters; calling
        # it under _lock deadlocks any callback that re-enters the engine
        # (areal-lint C5 blocking-under-lock) — collect under the lock,
        # call after release
        to_finish: List[GenRequest] = []
        n_in_slot = 0
        with self._lock:
            self._abort_gen += 1  # a racing _admit must drop its leftovers
            for s, req in enumerate(self.slot_req):
                if req is not None:
                    to_finish.append(req)
                    self.slot_req[s] = None
                    # retained prefix makes the client's resubmission (same
                    # prompt + accumulated tokens) a suffix-only prefill
                    self.retained_len[s] = (
                        0 if self._slot_vlm[s] else self.lengths[s]
                    )
                    # reserve only prefixes the owner's resubmission can
                    # actually claim: its lcp is capped below len(ids) by
                    # the admission match, so at retained_len ==
                    # reuse_min_tokens the slot would sit
                    # reserved-yet-unclaimable for the whole TTL — the
                    # threshold must be STRICTLY greater (ADVICE r5)
                    if (
                        self.kv_reuse
                        and self.retained_len[s] > self.reuse_min_tokens
                    ):
                        self._reserved_until[s] = deadline
                    self.pool.note_free(
                        s, self.seq_tokens[s], int(self.retained_len[s])
                    )
            self._state_dirty = True
            n_in_slot = len(to_finish)
            to_finish.extend(self._holdback)
            self._holdback = []
            while True:
                try:
                    to_finish.append(self.pending.get_nowait())
                except queue.Empty:
                    break
        if telemetry.is_enabled():
            # only slot-holding requests were mid-decode: those are the
            # interrupt spans the resume events pair with (queued/held-back
            # requests just bounce through the client's resubmit loop)
            for req in to_finish[:n_in_slot]:
                telemetry.emit(
                    "interrupt", trace_id=req.trace_id or req.rid,
                    reason=reason, version_before=version_before,
                    generated=len(req.output_tokens),
                )
        for req in to_finish:
            req.finish(reason)
        return len(to_finish)

    def load_weights(
        self, path: Optional[str] = None, params=None, version: Optional[int] = None
    ) -> int:
        """Swap weights; aborts in-flight generation first (interruptible
        generation: clients resubmit and the new prefill recomputes under the
        new policy). Returns the new version."""
        t0 = time.perf_counter()
        version_before = self.version
        self._pause_depth += 1
        try:
            aborted = self.abort_all("abort")
            if aborted:
                logger.info(f"aborted {aborted} requests for weight update")
            if params is None:
                import os

                assert path is not None
                pinned = os.path.join(path, f"v{int(version)}") \
                    if version is not None else None
                if pinned is not None and os.path.isdir(pinned):
                    # recovery replays pin the version: load exactly that
                    # snapshot, not the newest — a later, never-trained-on
                    # v{N} may have survived the crash on disk
                    path = pinned
                else:
                    path, dir_version = self._resolve_ckpt_dir(path)
                    if version is None:
                        # adopt the trainer's version from the v{N} dir name
                        # — a fresh server must not restart its version
                        # counter at 1 while the trainer is at N (staleness
                        # gates compare them)
                        version = dir_version
                params, _ = load_hf_params(path, self.model_config, dtype="bfloat16")
            self.swap_weights_live(params, version=version)
        finally:
            self._pause_depth -= 1
        # achieved generation-idle window for the unstaged ABORT path spans
        # the abort + checkpoint load + host->device placement, not just the
        # swap tail (staged swaps record theirs in commit_staged)
        self.last_pause_s = time.perf_counter() - t0
        self._record_pause(self.last_pause_s, "reload_abort", version_before)
        return self.version

    def swap_weights_live(self, params, version: Optional[int] = None) -> int:
        """Non-aborting weight swap — the colocated in-memory publish.

        In-flight requests keep their slots and KV and continue decoding
        under the NEW policy from the next chunk on; per-token
        `output_versions` record the transition, which is exactly the
        mixed-version trajectory the decoupled loss's behavior weight is
        built to consume (reference interruptible generation,
        blog/AReaL_v0_3.md:203-207, achieves the same semantics by
        abort+resume because SGLang cannot hot-swap mid-request — here the
        params tree is one pointer read per dispatch, so nothing needs to
        die).  KV computed under the old weights stays, matching the radix
        cache the reference leans on (remote_inf_engine.py:404-413).

        Callers must not race a swap against an in-flight `step()` if they
        care about exact version stamping (ColocatedEngine parks the
        stepper first); the swap itself is atomic either way.

        `load_weights` (the aborting path) delegates here for the shared
        publish tail, so every swap invariant lives in one place.
        """
        if self.model_config.vision is not None and "vision" not in params:
            # text-only update for a VLM: keep the current tower (already
            # sharded on device; device_put under the same spec is a no-op)
            params = dict(params)
            params["vision"] = self.params["vision"]
        t0 = time.perf_counter()
        version_before = self.version
        self.params = shard_pytree(self.mesh, params, self._pspecs)
        self.version = version if version is not None else self.version + 1
        if not self.retain_kv_on_reload:
            # strict mode applies to EVERY weight-swap path: retained
            # prefixes hold old-policy KV and must not seed suffix
            # prefills.  Shared (fan-out) prefixes are zeroed exactly the
            # same way — once a sibling's slot frees, its copied prefix IS
            # a retained prefix, and kv_version tracks its true origin.
            self.retained_len[:] = 0
            self._reserved_until[:] = 0.0  # nothing left to reserve
            self.kv_version[:] = self.version  # no pre-swap KV survives
            # the host tier is old-policy KV too: strict mode drops every
            # resident prefix from the pool, spilled ones included
            self.pool.clear()
        if getattr(self, "_standby", None) is not None:
            staged_v = self._standby[1]
            if staged_v is None or staged_v <= self.version:
                # staged_v <= version: committing later would ROLL BACK the
                # version.  staged_v None: its ordering vs this publish is
                # unknowable, and a later commit would install the OLDER
                # staged weights under a version bump (+1) — poisoning the
                # staleness accounting that trusts versions to order
                # policies.  Either way the standby must die (it also pins
                # a full bf16 param copy of HBM); the commit's 409 tells
                # the staging client to re-push.
                logger.warning(
                    "weight publish discarding superseded standby (staged "
                    f"v{staged_v}, now v{self.version})"
                )
                self._standby = None
            # a STRICTLY NEWER standby (e.g. v6 staged via prepare while a
            # v5 disk publish lands) stays valid for its pending commit
        self.last_pause_s = time.perf_counter() - t0
        if self._pause_depth == 0:
            # top-level live publish; nested calls (load_weights /
            # commit_staged) record their full window themselves
            self._record_pause(self.last_pause_s, "swap_live", version_before)
        return self.version

    def stage_params(self, params, version: Optional[int] = None) -> bool:
        """Pre-place fresh weights on device while generation KEEPS RUNNING
        (VERDICT r3 weak #2: the staged-transfer commit's ~30s was dominated
        by host->device placement *inside* the pause window).  The standby
        tree costs a second bf16 param copy of HBM; if that does not fit,
        returns False and the caller falls back to commit-time placement."""
        if self.model_config.vision is not None and "vision" not in params:
            params = dict(params)
            params["vision"] = self.params["vision"]
        try:
            # no block_until_ready: allocation (and OOM) is synchronous but
            # the copy streams asynchronously, so the worker thread gets
            # back to decoding while DMA proceeds; the first program under
            # the new params waits for any transfer still in flight
            standby = shard_pytree(self.mesh, params, self._pspecs)
        except Exception as e:  # noqa: BLE001 — OOM => unstaged fallback
            logger.warning(f"weight staging failed ({str(e)[:120]}); "
                           "commit will place from host")
            self._standby = None
            return False
        self._standby = (standby, version)
        return True

    @property
    def staged_version(self) -> Optional[int]:
        """Version of the pre-staged standby weights, or None when nothing
        is staged (public surface for gen/server.py and tests)."""
        return self._standby[1] if self._standby is not None else None

    @property
    def has_standby(self) -> bool:
        return self._standby is not None

    def commit_staged(self, live: bool = False) -> int:
        """Swap pre-staged weights in.  Default: abort in-flight + pointer
        swap — the whole pause is O(abort), not O(model bytes).  `live=True`
        skips the abort entirely (swap_weights_live semantics: in-flight
        requests keep decoding, per-token versions record the transition).
        Returns the version."""
        if getattr(self, "_standby", None) is None:
            raise RuntimeError("commit_staged without stage_params")
        t0 = time.perf_counter()
        version_before = self.version
        self._pause_depth += 1
        try:
            if not live:
                aborted = self.abort_all("abort")
                if aborted:
                    logger.info(
                        f"aborted {aborted} requests for staged weight swap"
                    )
            standby, version = self._standby
            self._standby = None
            # shared swap tail (device_put of the already-sharded standby
            # under the same spec is a no-op, so this stays a pointer swap)
            self.swap_weights_live(standby, version=version)
        finally:
            self._pause_depth -= 1
        self.last_pause_s = time.perf_counter() - t0
        self._record_pause(
            self.last_pause_s,
            "commit_live" if live else "commit_abort",
            version_before,
        )
        return self.version

    def _record_pause(
        self, dur: float, kind: str, version_before: int
    ) -> None:
        """Every weight-publish pause window lands in the evidence
        histogram (cold path — the swap itself dwarfs the observe); the
        event stream additionally records the version transition when
        telemetry is on."""
        telemetry.PAUSE_WINDOW.observe(dur)
        if telemetry.is_enabled():
            telemetry.emit(
                "pause", kind=kind, dur_s=dur,
                version_before=version_before, version_after=self.version,
            )

    def release_memory(self, drop_params: bool = True) -> None:
        """Colocated time-share (alloc `a|b`, VERDICT r3 weak #4): free the
        HBM this engine holds so a trainer can use the same chips.  Aborts
        in-flight requests (clients resume later via the retained-prefix
        machinery being rebuilt fresh), drops the KV cache, and with
        `drop_params` the bf16 serving weights too — a VLM's small vision
        tower is kept so an in-memory text-weight handoff can restage."""
        self.abort_all("abort")
        self.cache = None
        self._standby = None
        with self._lock:
            self._dev_state = None  # rebuilt from host mirrors at restage
            self._state_dirty = True
        self.retained_len[:] = 0  # cache is gone; no prefix survives
        self._reserved_until[:] = 0.0
        self.kv_version[:] = self.version
        self.pool.clear()  # radix entries and host spills die with it
        if drop_params:
            if isinstance(self.params, dict) and "vision" in self.params:
                self.params = {"vision": self.params["vision"]}
            else:
                self.params = None

    def restage(self, params=None, version: Optional[int] = None) -> None:
        """Re-arm serving after release_memory: shard fresh weights (an
        IN-MEMORY handoff from a colocated trainer — no disk snapshot or
        chunk stream inside the pause) and reallocate the KV cache."""
        if params is not None:
            if (
                self.model_config.vision is not None
                and "vision" not in params
                and isinstance(self.params, dict)
                and "vision" in self.params
            ):
                params = dict(params)
                params["vision"] = self.params["vision"]
            self.params = shard_pytree(self.mesh, params, self._pspecs)
            if version is not None:
                self.version = version
        elif self.params is None or (
            isinstance(self.params, dict) and "embedding" not in self.params
        ):
            # None (text model released) or a vision-only remnant (VLM
            # released): either way the text weights are gone
            raise RuntimeError("restage() needs params after release_memory")
        if self.cache is None:
            cache = init_kv_cache(
                self.model_config, self.n_slots + 1, self.max_seq_len,
                self.kv_dtype,
            )
            self.cache = {
                k: jax.device_put(v, NamedSharding(self.mesh, self._cache_spec))
                for k, v in cache.items()
            }
            # fresh physical rows: the identity page table is correct again
            self.pool.reset()

    @staticmethod
    def _resolve_ckpt_dir(path: str):
        """Trainers publish atomic per-version snapshots `root/v{N}`
        (jax_train.py _update_weights_disk); pick the newest and return
        (dir, version).  A plain checkpoint dir (config.json present) is
        used as-is with version None."""
        import os
        import re

        if os.path.exists(os.path.join(path, "config.json")):
            return path, None
        vs = sorted(
            (int(m.group(1)), os.path.join(path, d))
            for d in (os.listdir(path) if os.path.isdir(path) else [])
            if (m := re.fullmatch(r"v(\d+)", d))
        )
        if not vs:
            raise FileNotFoundError(f"no checkpoint under {path}")
        return vs[-1][1], vs[-1][0]

    # ------------------------------------------------------------------
    # stepping
    # ------------------------------------------------------------------

    def _maybe_spill(self, slots: List[int]) -> None:
        """LRU-spill retained prefixes about to be overwritten into the
        host-DRAM overflow tier (no-op when `host_offload` is off).  The
        gather is one bucketed program per block; the download rides the
        admission boundary where the engine already syncs its planning
        state.  Prefixes below `host_min_tokens` are not worth the round
        trip and simply evict."""
        if self.pool.host is None or self.cache is None:
            return
        for s in slots:
            vlen = int(self.retained_len[s])
            toks = self.pool.device_tokens(s)
            if toks is None or vlen < self.host_min_tokens:
                continue
            if len(toks) != vlen:
                continue  # stale index entry: never spill mismatched KV
            block = round_up_to_bucket(
                vlen, self.prompt_bucket, self.max_seq_len
            )
            kv_dev = self._host_gather_fn(
                self.cache, jnp.asarray(self.pool.row(s), jnp.int32), block
            )
            # areal-lint: disable=host-sync delivery point: spill download at the admission boundary (one bucketed row gather per eviction)
            kv = {k: np.asarray(v) for k, v in kv_dev.items()}
            evicted = self.pool.host_put(
                self.seq_tokens[s], vlen, int(self.kv_version[s]), block, kv
            )
            self.pool.drop_device(s)
            self.stats["prefix_cache_host_swaps"] += 1
            self.stats["prefix_cache_evictions"] += evicted

    def _swap_in_host_hits(
        self,
        entries: List[tuple],
        matched: set,
        free_set: set,
        slot_of_entry: Dict[int, tuple],
        reuse_admitted: List[tuple],
    ) -> None:
        """Admission stage for the host overflow tier: requests the device
        match left cold probe the radix over HOST-spilled prefixes; a hit
        scatters the spilled KV back into a free row (bit-identical bytes
        — the spill kept the raw cache dtype) and the request then rides
        the ordinary suffix-prefill path as if the prefix had never left
        HBM.  The landing slot's own retained prefix spills first when
        eligible, so a swap-in never silently destroys resident state."""
        now = time.monotonic()
        for i, (req, is_vlm) in enumerate(entries[: self.match_window]):
            if is_vlm or i in matched or not free_set:
                continue
            host_m = self.pool.match_host(req.input_ids)
            if not host_m:
                continue
            limit = len(req.input_ids) - 1
            best_hid, best_l = None, 0
            for hid, l in host_m.items():
                ent = self.pool.host_entry(hid)
                if ent is None:
                    continue
                l = min(int(l), ent.valid_len, limit)
                if l >= self.reuse_min_tokens and l > best_l:
                    best_hid, best_l = hid, l
            if best_hid is None:
                continue
            open_slots = [
                s for s in free_set
                if not self._slot_vlm[s] and self._reserved_until[s] <= now
            ]
            if not open_slots:
                return  # nothing can land anywhere this pass
            # overwrite the least valuable retained cache, spilling it
            # onward when it is itself worth keeping
            s = min(open_slots, key=lambda u: int(self.retained_len[u]))
            self._maybe_spill([s])
            ent = self.pool.host_take(best_hid)
            if ent is None:
                continue
            self.cache = self._host_scatter_fn(
                self.cache,
                {k: jnp.asarray(v) for k, v in ent.kv.items()},
                jnp.asarray(self.pool.row(s), jnp.int32),
            )
            vlen = ent.valid_len
            with self._lock:
                if self.pool.drop_device(s):
                    self.stats["prefix_cache_evictions"] += 1
                self.seq_tokens[s, :vlen] = ent.tokens
                self.retained_len[s] = vlen
                self.kv_version[s] = ent.version
                self._slot_vlm[s] = False
                self._reserved_until[s] = 0.0
                self.pool.note_free(s, self.seq_tokens[s], vlen)
            self.stats["prefix_cache_host_swaps"] += 1
            matched.add(i)
            free_set.remove(s)
            slot_of_entry[i] = (s, best_l)
            reuse_admitted.append((s, req, best_l, s, False))

    def _warmup_host_tier(self) -> None:
        """Pre-compile the host-tier transfer family from COLD (the PR 16
        cold-start caveat, ISSUE 17 satellite): one gather -> host ->
        scatter round trip of the scratch row per block bucket, run at
        init before any serving dispatch.  Afterwards every gather/scatter
        rung is compiled AND the cache is already scatter-produced (with
        `out_shardings` keeping its aval identical to the device_put one),
        so the first real spill, swap-in, or handoff import mid-serving
        mints nothing — the signature soak asserts this starting cold."""
        if self.pool.host is None or self.cache is None:
            return
        row = jnp.asarray(self.pool.row(self.n_slots), jnp.int32)
        v = 1
        while True:
            b = round_up_to_bucket(v, self.prompt_bucket, self.max_seq_len)
            kv_dev = self._host_gather_fn(self.cache, row, b)
            # areal-lint: disable=host-sync warmup-only: one scratch-row round trip per block bucket before serving starts
            kv = {k: np.asarray(a) for k, a in kv_dev.items()}
            self.cache = self._host_scatter_fn(
                self.cache, {k: jnp.asarray(a) for k, a in kv.items()}, row
            )
            if b >= self.max_seq_len:
                break
            v = b + 1

    # ------------------------------------------------------------------
    # disaggregated handoff (ISSUE 17): cross-server KV page streaming
    # ------------------------------------------------------------------

    def export_request_kv(self, input_ids: List[int]) -> Optional[dict]:
        """Serialize the resident KV prefix covering `input_ids` for a
        cross-server handoff (/kv_export).  Walks the radix for the best
        device-retained match first (normally the just-finished leg's own
        slot), then the host tier; gathers the covered span on the bucket
        ladder — the SAME host_gather program family the spill path uses,
        zero new steady-state signatures — and returns a host-tier-format
        entry {tokens, valid_len, version, block, kv} the importing
        engine installs verbatim.  Non-destructive: the donor prefix
        stays resident here, so a failed import loses nothing.  Returns
        None (counting a failure) when nothing covering at least
        reuse_min_tokens is resident; the router then continues the
        stream colocated, which the counter-keyed sampler keeps
        bit-identical anyway.

        Thread contract: worker thread only (the server's handoff
        mailbox) — radix walks and the donated cache ref are
        worker-owned."""
        limit = len(input_ids) - 1
        best_slot, best_l = None, 0
        if self.cache is not None:
            for s, l in self.pool.match_device(input_ids).items():
                toks = self.pool.device_tokens(s)
                if toks is None or len(toks) != int(self.retained_len[s]):
                    continue
                l = min(int(l), limit)
                if l > best_l:
                    best_slot, best_l = s, l
        if best_slot is not None and best_l >= self.reuse_min_tokens:
            block = round_up_to_bucket(
                best_l, self.prompt_bucket, self.max_seq_len
            )
            kv_dev = self._host_gather_fn(
                self.cache,
                jnp.asarray(self.pool.row(best_slot), jnp.int32),
                block,
            )
            # areal-lint: disable=host-sync delivery point: handoff export download — one bucketed row gather per /kv_export
            kv = {k: np.asarray(a) for k, a in kv_dev.items()}
            entry = {
                "tokens": np.asarray(
                    self.pool.device_tokens(best_slot)[:best_l], np.int64
                ),
                "valid_len": int(best_l),
                "version": int(self.kv_version[best_slot]),
                "block": int(block),
                "kv": kv,
            }
        else:
            best_hid, best_hl = None, 0
            if self.pool.host is not None:
                for hid, l in self.pool.match_host(input_ids).items():
                    ent = self.pool.host_entry(hid)
                    if ent is None:
                        continue
                    l = min(int(l), ent.valid_len, limit)
                    if l > best_hl:
                        best_hid, best_hl = hid, l
            if best_hid is None or best_hl < self.reuse_min_tokens:
                self.stats["kv_handoff_failures"] += 1
                return None
            ent = self.pool.host_entry(best_hid)
            self.pool.host.touch(best_hid)
            # a partial host match exports the entry's full block; the
            # importer attends nothing past valid_len, so the extra
            # positions are dead weight, never wrong bytes
            entry = {
                "tokens": np.asarray(ent.tokens[:best_hl], np.int64),
                "valid_len": int(best_hl),
                "version": int(ent.version),
                "block": int(ent.block),
                "kv": ent.kv,
            }
        self.stats["kv_handoff_exports"] += 1
        self.stats["kv_handoff_bytes"] += sum(
            int(a.nbytes) for a in entry["kv"].values()
        )
        return entry

    def import_request_kv(self, entry: dict) -> bool:
        """Install an exported prefix (/kv_import) as a host-tier entry;
        the request that follows admits through the ordinary radix match
        + swap-in path as a warm-cache hit, re-scattering the pages on
        the same bucket ladder — a bit-identical round trip, exactly like
        a local spill.  Returns False (counting a failure) when the host
        tier is disabled; decode-role servers always enable it (--role
        decode forces host_offload).  Worker thread only, like export."""
        if self.pool.host is None:
            self.stats["kv_handoff_failures"] += 1
            return False
        tokens = np.asarray(entry["tokens"], np.int64)
        vlen = int(entry["valid_len"])
        kv = {k: np.asarray(a) for k, a in entry["kv"].items()}
        evicted = self.pool.host_put(
            tokens, vlen, int(entry["version"]), int(entry["block"]), kv
        )
        self.stats["prefix_cache_evictions"] += evicted
        self.stats["kv_handoff_imports"] += 1
        self.stats["kv_handoff_bytes"] += sum(
            int(a.nbytes) for a in kv.values()
        )
        return True

    def _apply_group_hold(self, entries: List[tuple]):
        """Park members of a declared group (`group_id` + `group_n`) until
        the whole group shares one admission window — the cluster fan-out
        can only share a prefix among co-resident requests.  The hold TTL
        (`group_hold_s`) bounds the wait: a sibling that already finished
        never resubmits, so partial groups must eventually admit.
        Returns (entries, held, hold_deadlines)."""
        if self.group_hold_s <= 0 or not any(
            r.group_id and r.group_n > 1 and not v for r, v in entries
        ):
            return entries, [], []
        now = time.monotonic()
        counts: Dict[str, int] = {}
        need: Dict[str, int] = {}
        for req, is_vlm in entries:
            if req.group_id and req.group_n > 1 and not is_vlm:
                counts[req.group_id] = counts.get(req.group_id, 0) + 1
                need[req.group_id] = max(
                    need.get(req.group_id, 0), req.group_n
                )
        hold: set = set()
        deadlines: List[float] = []
        for gid, cnt in counts.items():
            if cnt >= need[gid]:
                self._group_first_seen.pop(gid, None)
                continue
            first = self._group_first_seen.setdefault(gid, now)
            if now - first < self.group_hold_s:
                hold.add(gid)
                deadlines.append(first + self.group_hold_s)
            else:  # TTL lapsed: admit the partial group
                self._group_first_seen.pop(gid, None)
        if not hold:
            return entries, [], []
        held = [r for r, v in entries if not v and r.group_id in hold]
        entries = [
            (r, v) for r, v in entries if v or r.group_id not in hold
        ]
        return entries, held, deadlines

    def _plan_clusters(
        self, entries: List[tuple], matched: set
    ) -> List[dict]:
        """Cluster the admission window by shared prompt prefix ->
        [{"members": [entry idx], "share": tokens}].

        Explicit groups (GRPO siblings carrying group_id) cluster by key in
        O(window); the rest cluster content-based — sorted by a bounded
        prefix key, then adjacent-lcp runs (lcp is an ultrametric, so the
        min over any chain through a set equals the set's lcp).  The
        shared span is capped at min(len) - 1 so every sibling still
        suffix-prefills at least one token (its last-position logits seed
        sampling); clusters whose span misses `share_min_tokens` dissolve.

        Entries already matched to a retained slot never become siblings
        (their own retained prefix is at least as long) but do serve as
        representatives — the fallback path where the cluster prefix is
        never recomputed at all."""
        cand = [
            i for i, (req, is_vlm) in enumerate(entries)
            if not is_vlm and len(req.input_ids) > self.share_min_tokens
        ]
        if len(cand) < 2:
            return []
        by_gid: Dict[str, List[int]] = {}
        rest: List[int] = []
        for i in cand:
            gid = entries[i][0].group_id
            (by_gid.setdefault(gid, []) if gid else rest).append(i)
        raw = [m for m in by_gid.values() if len(m) >= 2]
        rest.extend(i for m in by_gid.values() if len(m) == 1 for i in m)
        rest = rest[: self.match_window]  # bound the host-side sort/scan
        if len(rest) >= 2:
            rest.sort(key=lambda i: tuple(entries[i][0].input_ids[:64]))
            run = [rest[0]]
            run_share: Optional[int] = None
            for prev, cur in zip(rest, rest[1:]):
                l = lcp_ids(
                    entries[prev][0].input_ids, entries[cur][0].input_ids
                )
                tentative = l if run_share is None else min(run_share, l)
                if tentative >= self.share_min_tokens:
                    run.append(cur)
                    run_share = tentative
                else:
                    if len(run) >= 2:
                        raw.append(run)
                    run = [cur]
                    run_share = None
            if len(run) >= 2:
                raw.append(run)
        clusters: List[dict] = []
        for members in raw:
            ids0 = entries[members[0]][0].input_ids
            share = min(
                lcp_ids(ids0, entries[i][0].input_ids)
                for i in members[1:]
            )
            share = min(
                share,
                min(len(entries[i][0].input_ids) for i in members) - 1,
            )
            # a cluster of only retained-matched members has nothing to fan
            # out; require at least one potential sibling
            if share >= self.share_min_tokens and any(
                i not in matched for i in members
            ):
                clusters.append({"members": sorted(members), "share": share})
        return clusters

    def _admit(self) -> None:
        """Fill every free slot from the pending queue in ONE bucketed
        prefill call.  Rows are padded to a power of two; padding rows
        prefill a single token into the scratch slot (index n_slots), so
        compiled-program count stays O(log n_slots x log buckets) and a
        burst of N prompts no longer pays N sequential device round-trips
        (round-1 review weak #2).

        With kv_reuse, prompts whose prefix matches a freed slot's retained
        cache go through a SUFFIX prefill instead (forward_prefill_cached):
        multi-turn turns and interruption resumes pay O(new tokens).

        Abort-storm discipline (VERDICT r4 #3): a WINDOW of the pending
        queue is drained and prefix-matched against every free slot
        GLOBALLY (highest lcp wins) before any slot is handed to a fresh
        prompt, and abort-reserved slots are withheld from fresh prompts
        until their reservation lapses — so when N aborted clients race
        back over few slots, the retained prefixes go to the requests that
        can actually reuse them instead of to whoever arrived first.

        Group fan-out (ISSUE 2): remaining requests cluster by longest
        common prefix; each cluster prefills one representative, fans its
        prefix K/V out to sibling slots with a device-side cache copy, and
        the siblings suffix-prefill only their remainder — a GRPO group of
        G pays ~1/G of the old grouped prefill FLOPs.  Reservations keep
        applying per SLOT to the abort-resubmission flow: each aborted
        sibling reclaims its own retained slot through the global matching
        above (its retained prefix is strictly longer than the cluster's),
        so a storm never collapses a cluster onto one reserved slot."""
        free = [s for s in range(self.n_slots) if self.slot_req[s] is None]
        if not free:
            return
        if self._parked_free is not None:
            # a previous pass admitted nothing; until a reservation expires,
            # a group hold lapses, a slot frees, or a new request arrives,
            # rescanning would produce the same nothing
            if (
                not self.pending.qsize()
                and time.monotonic() < self._parked_until
                and frozenset(free) == self._parked_free
            ):
                return
            self._parked_free = None
        # intake: held-back requests first (FIFO across admission passes),
        # then drain fresh submissions up to the scan window.  The holdback
        # swap runs under the lock (ADVICE r5): a concurrent abort_all
        # either sees these requests in _holdback and finishes them, or the
        # generation counter tells this pass to drop its leftovers — never
        # a resurrection after their terminal 'abort' callback.
        with self._lock:
            abort_gen = self._abort_gen
            intake = self._holdback
            self._holdback = []
        while len(intake) < self.admission_window:
            try:
                intake.append(self.pending.get_nowait())
            except queue.Empty:
                break
        if not intake:
            return
        entries: List[tuple] = []  # (req, is_vlm) in arrival order
        for req in intake:
            if req.pixel_values is not None:
                if not self._vlm:
                    # "length" terminates the client's interruption loop;
                    # "abort" would make it resubmit the same request forever
                    req.finish("length")
                    logger.error(
                        f"request {req.rid} carries pixels but the model is "
                        "text-only; returned empty (config mismatch)"
                    )
                    continue
                err = self._validate_vlm_request(req)
                if err:
                    req.finish("length")
                    logger.error(f"rejecting VLM request {req.rid}: {err}")
                    continue
                entries.append((req, True))
            else:
                entries.append((req, False))
        held: List[GenRequest] = []
        group_deadlines: List[float] = []
        if self.share_prefix:
            entries, held, group_deadlines = self._apply_group_hold(entries)

        admitted: List[tuple] = []  # (slot, req)
        # suffix rows: (slot, req, start, kv_src, shared) — retained reuse
        # and cluster fan-out ride ONE bucketed call (the fan-out copy is
        # fused into the suffix program)
        reuse_admitted: List[tuple] = []
        vlm_admitted: List[tuple] = []
        shared_admitted: List[tuple] = []
        free_set = set(free)
        matched: set = set()
        slot_of_entry: Dict[int, tuple] = {}  # entry idx -> (slot, lcp)
        cands: List[tuple] = []  # (-lcp, entry idx, slot), sorted
        dev_claimed: set = set()  # slots won by a device-retained match
        if self.kv_reuse:
            # global matching through the radix index: ONE tree walk per
            # request returns the exact lcp against every resident prefix
            # (identical numbers to the old per-slot seq_tokens scan — the
            # entries mirror seq_tokens[:retained_len] by construction,
            # re-validated against the live retained_len so a stale entry
            # can cost a hit but never fabricate one).  All (request,
            # slot) pairs then assign greedily, best lcp first, ties by
            # arrival order; the scanned window stays capped at
            # match_window independently of the drain window.
            cand_set = {
                s for s in free
                if not self._slot_vlm[s]
                and self.retained_len[s] >= self.reuse_min_tokens
            }
            if cand_set:
                for i, (req, is_vlm) in enumerate(
                    entries[: self.match_window]
                ):
                    if is_vlm:
                        continue
                    # capped at len(ids) - 1 so at least one suffix token
                    # runs through prefill (its logits seed sampling)
                    limit = len(req.input_ids) - 1
                    for s, l in self.pool.match_device(
                        req.input_ids
                    ).items():
                        if s not in cand_set:
                            continue
                        toks = self.pool.device_tokens(s)
                        if toks is None or len(toks) != int(
                            self.retained_len[s]
                        ):
                            continue
                        l = min(int(l), limit)
                        if l >= self.reuse_min_tokens:
                            # ties broken by arrival order (i ascending)
                            cands.append((-l, i, s))
                cands.sort()
                for negl, i, s in cands:
                    if i in matched or s not in free_set:
                        continue
                    matched.add(i)
                    free_set.remove(s)
                    dev_claimed.add(s)
                    slot_of_entry[i] = (s, -negl)
                    reuse_admitted.append((s, entries[i][0], -negl, s, False))
        if self.kv_reuse and self.pool.host is not None and free_set:
            self._swap_in_host_hits(
                entries, matched, free_set, slot_of_entry, reuse_admitted
            )

        # page-granular sub-prefix sharing (ISSUE 17 satellite): a request
        # whose best device match LOST its donor slot to a longer match
        # can still inherit the donor's prefix up to a page
        # (prompt-bucket) boundary — the fused fan-out copy duplicates
        # rows [0, span) of the donor's physical row into the loser's own
        # slot before the layer scan, and the loser suffix-prefills from
        # span on.  Safe by construction: the donor is CLAIMED this pass
        # (never handed to a fresh prompt, so its retained K/V survives
        # until the suffix dispatch) and its winner writes only from its
        # own lcp >= the loser's lcp >= span, so the copy reads settled
        # K/V even inside the one shared dispatch.  Exact-lcp IN-PLACE
        # partial hits (the greedy winners above) are untouched — page
        # rounding applies only to this new copy-based share path.
        partial_of: Dict[int, tuple] = {}  # entry idx -> (donor slot, span)
        if self.share_prefix and dev_claimed:
            page = self.prompt_bucket
            for negl, i, s in cands:  # still sorted: longest span first
                if i in matched or i in partial_of or s not in dev_claimed:
                    continue
                span = ((-negl) // page) * page
                if span >= self.share_min_tokens:
                    partial_of[i] = (s, span)

        clusters: List[dict] = (
            self._plan_clusters(entries, matched) if self.share_prefix else []
        )
        cluster_of: Dict[int, int] = {}
        for cid, cl in enumerate(clusters):
            for i in cl["members"]:
                cluster_of[i] = cid
                # a retained-matched member is the preferred representative
                # — the fallback path where NOBODY recomputes the cluster
                # prefix (multi-turn branch points).  The share is capped
                # at its retained lcp: that span is valid in its row BEFORE
                # the suffix batch runs, so the fused fan-out copy and the
                # representative's own suffix can share one dispatch.
                if "rep_slot" not in cl and i in slot_of_entry:
                    s, lcp = slot_of_entry[i]
                    cl["rep_slot"] = s
                    cl["share"] = min(cl["share"], lcp)

        # fresh prompts take the remaining UNRESERVED slots, least-valuable
        # retained cache first; reserved slots stay parked for their
        # aborted owner's resubmission until the TTL lapses
        now = time.monotonic()
        for s in free_set:
            # owner never came back: the reservation lapses here (counted
            # once — the slot re-enters the open pool below) rather than
            # silently evaporating
            if 0.0 < self._reserved_until[s] <= now:
                self._reserved_until[s] = 0.0
                self.stats["reservations_lapsed"] += 1
        # open slots grouped by length-cohort tier, least-valuable retained
        # cache first within each tier; a request lands in the smallest
        # tier whose ceiling covers its prompt + max_new_tokens budget,
        # falling UP to roomier tiers when its cohort is full and DOWN
        # (optimistic placement, migration may follow) only as a last
        # resort — admission capacity is unchanged: a request is parked
        # only when NO open slot exists anywhere
        open_by_tier: List[List[int]] = [[] for _ in range(self.n_tiers)]
        for s in sorted(
            (s for s in free_set if self._reserved_until[s] <= now),
            key=lambda s: int(self.retained_len[s]),
        ):
            open_by_tier[int(self.slot_tier[s])].append(s)
        n_open = sum(len(t) for t in open_by_tier)

        def _pick_slot(req: GenRequest) -> Optional[int]:
            budget = len(req.input_ids) + req.max_new_tokens + 1
            pref = next(
                (t for t, b in enumerate(self.tier_bounds) if b >= budget),
                self.n_tiers - 1,
            )
            for t in list(range(pref, self.n_tiers)) + list(
                range(pref - 1, -1, -1)
            ):
                if open_by_tier[t]:
                    return open_by_tier[t].pop(0)
            return None

        leftover: List[GenRequest] = list(held)
        for i, (req, is_vlm) in enumerate(entries):
            if i in matched:
                continue
            if not n_open:
                leftover.append(req)
                if req.group_id:
                    # the group already had its co-resident window; a later
                    # pass must admit the leftover members immediately (they
                    # still content-cluster among themselves) instead of
                    # re-parking them for the hold TTL
                    self._group_first_seen[req.group_id] = 0.0
                continue
            s = _pick_slot(req)
            n_open -= 1
            cid = cluster_of.get(i)
            if cid is not None and clusters[cid].get("rep_slot") is not None:
                shared_admitted.append(
                    (s, req, clusters[cid]["share"],
                     clusters[cid]["rep_slot"], True)
                )
            elif is_vlm:
                vlm_admitted.append((s, req))
            elif i in partial_of:
                # partial rows never become cluster representatives: their
                # copied span settles only inside the suffix dispatch, too
                # late for a sibling's fused copy to read
                donor, span = partial_of[i]
                self.stats["prefix_cache_partial_hits"] += 1
                shared_admitted.append((s, req, span, donor, True))
            else:
                admitted.append((s, req))
                if cid is not None:
                    # first member to land a slot becomes the cluster's
                    # representative; later members fan out from it
                    clusters[cid]["rep_slot"] = s
        finish_aborted: List[GenRequest] = []
        with self._lock:
            if self._abort_gen != abort_gen:
                # an abort_all landed mid-pass and already finished every
                # request it could see; the ones we drained would otherwise
                # be resurrected behind their terminal callback.  finish()
                # runs user callbacks — defer it past the lock (C5)
                finish_aborted = leftover
                leftover = []
            else:
                # merge, don't overwrite: a concurrent submit may have
                # repopulated _holdback since the intake swap (C5
                # atomicity-split on the guarded field)
                self._holdback = leftover + self._holdback
        for req in finish_aborted:
            req.finish("abort")
        if leftover and not (
            admitted or reuse_admitted or vlm_admitted or shared_admitted
        ):
            # everything parked behind reservations or a group hold: arm
            # the no-progress guard until the earliest one expires
            expiries = [
                float(self._reserved_until[s])
                for s in free
                if self._reserved_until[s] > now
            ] + group_deadlines
            self._parked_free = frozenset(free)
            self._parked_until = min(expiries) if expiries else now + 0.05
        # prefix-cache accounting: every admitted row is a hit (inherited
        # a resident prefix) or a miss (cold/VLM prefill); retained
        # prefixes about to be overwritten spill to the host tier BEFORE
        # any prefill dispatch can clobber their rows
        self.stats["prefix_cache_hits"] += (
            len(reuse_admitted) + len(shared_admitted)
        )
        self.stats["prefix_cache_misses"] += (
            len(admitted) + len(vlm_admitted)
        )
        overwrite = (
            [s for s, _ in admitted]
            + [s for s, _ in vlm_admitted]
            + [s for s, *_ in shared_admitted]
        )
        if overwrite:
            self._maybe_spill(overwrite)
        if telemetry.is_enabled():
            # emitted before the prefill dispatches so the admission event
            # always precedes the request's first decode/finish in the log
            now_pc = time.perf_counter()
            for s, req in admitted:
                self._emit_admission(req, s, "fresh", 0, now_pc)
            for s, req in vlm_admitted:
                self._emit_admission(req, s, "vlm", 0, now_pc)
            for s, req, start, _, shared in reuse_admitted + shared_admitted:
                self._emit_admission(
                    req, s, "shared" if shared else "reuse", start, now_pc
                )
        if vlm_admitted:
            self._admit_vlm_batch(vlm_admitted)
        if admitted:
            self._admit_fresh_batch(admitted)
        if reuse_admitted or shared_admitted:
            # one suffix call for retained reuse AND cluster siblings: by
            # now every copy source row holds its cluster prefix (fresh
            # representatives prefilled above; retained representatives'
            # shares were capped at their already-valid lcp), so the fused
            # fan-out copy inside the program reads only settled K/V
            self._admit_suffix_batch(reuse_admitted + shared_admitted)

    def _emit_admission(
        self, req: GenRequest, slot: int, kind: str, inherited: int,
        now_pc: float,
    ) -> None:
        """Admission + prefill lifecycle events for one admitted request:
        queue wait (submit -> slot grant, covering holdback/group-hold)
        and the cold/inherited prefill token split (`kind` says whether
        the inherited span came from a retained prefix or a fan-out
        share).  Only called when telemetry is enabled."""
        wait = max(0.0, now_pc - req.submit_ts) if req.submit_ts else 0.0
        telemetry.ADMISSION_WAIT.observe(wait)
        tid = req.trace_id or req.rid
        telemetry.emit(
            "admission", trace_id=tid, kind=kind, slot=int(slot),
            tier=int(self.slot_tier[slot]), queue_wait_s=wait,
        )
        total = len(req.input_ids)
        telemetry.emit(
            "prefill", trace_id=tid, kind=kind, total_tokens=total,
            inherited_tokens=int(inherited),
            cold_tokens=total - int(inherited),
        )

    def _assign_streams(
        self, reqs: List[GenRequest], n_rows: int
    ) -> np.ndarray:
        """Counter-keyed sampler streams for one admission batch, assigned
        BEFORE the prefill dispatch (the batch's first sampled token is
        already stream-keyed).  Fresh requests draw from the shared
        allocator in batch (arrival) order — the partition-invariance
        contract — while a nonzero req.stream_id (a disaggregated handoff
        continuing another server's stream) is honored verbatim.
        Allocated ids are written back to req.stream_id so a prefill-role
        server can hand its stream over the wire.  Pad rows keep stream 0
        (never allocated; their samples land in the scratch slot and are
        discarded)."""
        streams = np.zeros(n_rows, np.int32)
        with self._lock:
            for i, req in enumerate(reqs):
                if req.stream_id:
                    streams[i] = req.stream_id
                else:
                    streams[i] = self._next_stream
                    self._next_stream += 1
                    req.stream_id = int(streams[i])
        return streams

    def _admit_fresh_batch(self, admitted: List[tuple]) -> None:
        """Full prefill for prompts with no reusable prefix anywhere: ONE
        bucketed forward_prefill call (pow2 rows, scratch-slot padding)."""
        bucket = round_up_to_bucket(
            max(max(len(r.input_ids) for _, r in admitted), 1),
            self.prompt_bucket,
            self.max_seq_len,
        )
        S = 1 << (len(admitted) - 1).bit_length()  # power-of-two rows
        ids = np.zeros((S, bucket), np.int32)
        plens = np.ones(S, np.int32)
        slot_ids = np.full(S, self.n_slots, np.int32)  # default: scratch
        temp = np.ones(S, np.float32)
        top_p = np.ones(S, np.float32)
        top_k = np.zeros(S, np.int32)
        for i, (s, req) in enumerate(admitted):
            n = len(req.input_ids)
            ids[i, :n] = req.input_ids
            plens[i] = n
            slot_ids[i] = self.pool.row(s)  # write through the page table
            temp[i] = req.temperature
            top_p[i] = req.top_p
            top_k[i] = req.top_k
        streams = self._assign_streams([r for _, r in admitted], S)
        toks, logps, self.cache = self._prefill_fn(
            self.params,
            self.cache,
            ids,
            jnp.asarray(plens),
            jnp.asarray(slot_ids),
            jnp.asarray(streams),
            self._decode_key,
            jnp.asarray(temp),
            jnp.asarray(top_p),
            jnp.asarray(top_k),
        )
        # areal-lint: disable=host-sync delivery point: one batched fetch per admission pass hands sampled tokens to the host scheduler
        toks, logps = np.asarray(toks), np.asarray(logps)
        self.stats["prefill_calls"] += 1
        self.stats["prefill_tokens"] += int(plens[: len(admitted)].sum())
        with self._lock:
            for i, (s, req) in enumerate(admitted):
                # a retained prefix that neither matched nor spilled is
                # evicted by this overwrite
                if self.pool.drop_device(s):
                    self.stats["prefix_cache_evictions"] += 1
                self.slot_req[s] = req
                self.lengths[s] = plens[i]
                self.rope_pos[s] = plens[i]
                self.last_tokens[s] = int(toks[i])
                self.temperature[s] = req.temperature
                self.top_p[s] = req.top_p
                self.top_k[s] = req.top_k
                self.retained_len[s] = 0
                self._reserved_until[s] = 0.0
                self._slot_vlm[s] = False
                self.kv_version[s] = self.version
                # decode-key stream: assigned in batch (arrival) order by
                # _assign_streams so sampled streams are identical however
                # slots are tiered (or pinned by a handoff's stream_id)
                self.stream_ids[s] = streams[i]
                n = len(req.input_ids)
                self.seq_tokens[s, :n] = req.input_ids
            self._state_dirty = True
        for i, (s, req) in enumerate(admitted):
            self._record_token(s, int(toks[i]), float(logps[i]))

    def _admit_suffix_batch(self, batch: List[tuple]) -> None:
        """Suffix-only prefill into slots whose cache (about to) hold the
        prompt's prefix: ONE bucketed forward_prefill_cached call, same
        O(log) compiled-program discipline as fresh admission.

        `batch` rows are (slot, req, start, kv_src, shared): `start` counts
        prompt tokens the row inherits rather than recomputes — the
        retained lcp, or the cluster's shared span — and `kv_src` is the
        slot whose cache computed them (the slot itself for retained
        reuse, the cluster representative for fan-out siblings).  Shared
        rows get their prefix K/V via the copy FUSED into the suffix
        program (ops/kv_copy.py; retained rows self-copy as identity), so
        retained reuse and group fan-out cost one dispatch together.
        kv_src's kv_version propagates so strict-version audits stay
        exact; `shared` picks the stat bucket for the skipped tokens."""
        bucket = round_up_to_bucket(
            max(len(r.input_ids) - start for _, r, start, _, _ in batch),
            self.prompt_bucket,
            self.max_seq_len,
        )
        S = 1 << (len(batch) - 1).bit_length()
        ids = np.zeros((S, bucket), np.int32)
        starts = np.zeros(S, np.int32)
        slens = np.ones(S, np.int32)
        slot_ids = np.full(S, self.n_slots, np.int32)
        copy_src = np.full(S, self.n_slots, np.int32)  # pad: scratch
        temp = np.ones(S, np.float32)
        top_p = np.ones(S, np.float32)
        top_k = np.zeros(S, np.int32)
        max_shared = 0
        for i, (s, req, start, kv_src, shared) in enumerate(batch):
            suffix = req.input_ids[start:]
            n = len(suffix)
            ids[i, :n] = suffix
            starts[i] = start
            slens[i] = n
            slot_ids[i] = self.pool.row(s)  # physical rows: page table
            copy_src[i] = self.pool.row(kv_src)
            temp[i] = req.temperature
            top_p[i] = req.top_p
            top_k[i] = req.top_k
            if shared:
                max_shared = max(max_shared, start)
        # bucketed fan-out span; 0 (no shared rows) skips the copy and
        # compiles the same retained-only program as before
        copy_block = (
            round_up_to_bucket(max_shared, self.prompt_bucket,
                               self.max_seq_len)
            if max_shared else 0
        )
        # bucketed attended span: attention reads O(P x key_window), not
        # O(P x max_seq_len) — short sequences in a deep cache stop paying
        # for the whole row
        key_window = round_up_to_bucket(
            int((starts[: len(batch)] + slens[: len(batch)]).max()),
            self.prompt_bucket,
            self.max_seq_len,
        )
        streams = self._assign_streams([r for _, r, *_ in batch], S)
        toks, logps, self.cache = self._suffix_prefill_fn(
            self.params,
            self.cache,
            ids,
            jnp.asarray(starts),
            jnp.asarray(slens),
            jnp.asarray(slot_ids),
            jnp.asarray(copy_src),
            jnp.asarray(streams),
            self._decode_key,
            jnp.asarray(temp),
            jnp.asarray(top_p),
            jnp.asarray(top_k),
            copy_block,
            key_window,
        )
        # areal-lint: disable=host-sync delivery point: one batched fetch per suffix-admission pass (retained reuse + fan-out share it)
        toks, logps = np.asarray(toks), np.asarray(logps)
        self.stats["suffix_calls"] += 1
        if copy_block:
            self.stats["copy_calls"] += 1
        self.stats["suffix_tokens"] += int(slens[: len(batch)].sum())
        for i, (_, _, start, _, shared) in enumerate(batch):
            self.stats["shared_tokens" if shared else "reused_tokens"] += (
                int(start)
            )
        with self._lock:
            for i, (s, req, start, kv_src, shared) in enumerate(batch):
                n_total = len(req.input_ids)
                # the slot's index entry retires: consumed by its own hit
                # (retained reuse — not an eviction) or clobbered by a
                # fan-out sibling landing on it (counted)
                if self.pool.drop_device(s) and shared:
                    self.stats["prefix_cache_evictions"] += 1
                req.cache_hit_tokens = int(start)
                self.slot_req[s] = req
                self.lengths[s] = n_total
                self.rope_pos[s] = n_total
                self.last_tokens[s] = int(toks[i])
                self.temperature[s] = req.temperature
                self.top_p[s] = req.top_p
                self.top_k[s] = req.top_k
                self.retained_len[s] = 0
                self._reserved_until[s] = 0.0
                # oldest KV in the slot: the inherited prefix's version
                # (suffix tokens are current-version by construction)
                self.kv_version[s] = min(
                    int(self.kv_version[kv_src]), self.version
                )
                self.stream_ids[s] = streams[i]
                self.seq_tokens[s, :n_total] = req.input_ids
            self._state_dirty = True
        for i, (s, req, _, _, _) in enumerate(batch):
            self._record_token(s, int(toks[i]), float(logps[i]))

    def _validate_vlm_request(self, req: GenRequest) -> Optional[str]:
        """Reject malformed wire inputs BEFORE they reach the decode worker:
        a bad grid must not hang or abort-storm the whole server."""
        cfg = self.model_config
        m = cfg.vision.spatial_merge_size
        try:
            grid = np.asarray(req.image_grid_thw, np.int64).reshape(-1, 3)
            pv = np.asarray(req.pixel_values)
        except (ValueError, TypeError) as e:
            return f"malformed pixel inputs: {e}"
        if pv.ndim != 2 or pv.shape[1] != cfg.vision.patch_dim:
            return (
                f"pixel_values shape {pv.shape} != [N, {cfg.vision.patch_dim}]"
            )
        if (grid <= 0).any():
            return f"non-positive grid entries: {grid.tolist()}"
        if ((grid[:, 1] % m) != 0).any() or ((grid[:, 2] % m) != 0).any():
            return f"grid h/w must divide merge size {m}: {grid.tolist()}"
        n_patches = int((grid[:, 0] * grid[:, 1] * grid[:, 2]).sum())
        if n_patches != pv.shape[0]:
            return f"grid implies {n_patches} patches, got {pv.shape[0]}"
        n_placeholders = int(
            np.sum(np.asarray(req.input_ids) == cfg.image_token_id)
        )
        expected = int(
            (grid[:, 0] * (grid[:, 1] // m) * (grid[:, 2] // m)).sum()
        )
        if n_placeholders != expected:
            return (
                f"{n_placeholders} image placeholders but grids imply "
                f"{expected} merged embeddings"
            )
        return None

    def _admit_vlm_batch(self, vlm_admitted: List[tuple]) -> None:
        """Image-conditioned prefill for a batch of requests: ONE vision
        tower call over all patches and ONE bucketed prefill (the same
        O(log)-programs admission discipline as the text path).  Merged
        embeddings concatenate in request order, which matches the
        flattened row order the in-prefill scatter consumes; each slot's
        logical rope position continues past its images' compressed extent
        while the cache index tracks real tokens."""
        from areal_tpu.models.vision import mrope_position_ids

        cfg = self.model_config
        m2 = cfg.vision.spatial_merge_size ** 2
        bucket = round_up_to_bucket(
            max(len(r.input_ids) for _, r in vlm_admitted),
            self.prompt_bucket,
            self.max_seq_len,
        )
        S = 1 << (len(vlm_admitted) - 1).bit_length()
        ids = np.zeros((S, bucket), np.int32)
        mpos = np.zeros((3, S, bucket), np.int32)
        plens = np.ones(S, np.int32)
        slot_ids = np.full(S, self.n_slots, np.int32)
        temp = np.ones(S, np.float32)
        top_p = np.ones(S, np.float32)
        top_k = np.zeros(S, np.int32)
        rope_next = np.zeros(S, np.int32)
        pv_parts, grids = [], []
        for i, (s, req) in enumerate(vlm_admitted):
            r_ids = np.asarray(req.input_ids, np.int32)
            n = len(r_ids)
            ids[i, :n] = r_ids
            plens[i] = n
            slot_ids[i] = self.pool.row(s)
            temp[i] = req.temperature
            top_p[i] = req.top_p
            top_k[i] = req.top_k
            grid = np.asarray(req.image_grid_thw, np.int64).reshape(-1, 3)
            r_mpos = mrope_position_ids(
                r_ids, grid, cfg.image_token_id,
                spatial_merge_size=cfg.vision.spatial_merge_size,
            )
            mpos[:, i, :n] = r_mpos
            rope_next[i] = int(r_mpos.max()) + 1
            pv_parts.append(np.asarray(req.pixel_values, np.float32))
            grids.append(grid)

        pv_all = np.concatenate(pv_parts, axis=0)
        n_patches = pv_all.shape[0]
        # bucket the patch count (pow2 multiples of the merge group) so the
        # vision jit compiles O(log) variants; pad patches carry img id -1
        n_pad = m2 * (
            1 << max(0, (max(1, (n_patches + m2 - 1) // m2) - 1).bit_length())
        )
        pv_pad = np.zeros((n_pad, pv_all.shape[1]), np.float32)
        pv_pad[:n_patches] = pv_all
        img_ids = np.full(n_pad, -1, np.int32)
        ofs = gid = 0
        for grid in grids:
            for t, h, w in grid:
                n = int(t * h * w)
                img_ids[ofs : ofs + n] = gid
                ofs += n
                gid += 1
        from areal_tpu.models.vision import vision_rot_pos_ids

        pos_hw = np.zeros((n_pad, 2), np.int32)
        real_pos = vision_rot_pos_ids(
            np.concatenate(grids), cfg.vision.spatial_merge_size
        )
        pos_hw[: real_pos.shape[0]] = real_pos
        embeds = self._embed_images_fn(
            self.params["vision"],
            jnp.asarray(pv_pad, jnp.dtype(cfg.dtype)),
            jnp.asarray(img_ids),
            jnp.asarray(pos_hw),
        )
        self.rng, sub = jax.random.split(self.rng)
        toks, logps, self.cache = self._vlm_prefill_fn(
            self.params,
            self.cache,
            ids,
            jnp.asarray(mpos),
            embeds,
            jnp.asarray(plens),
            jnp.asarray(slot_ids),
            sub,
            jnp.asarray(temp),
            jnp.asarray(top_p),
            jnp.asarray(top_k),
        )
        # areal-lint: disable=host-sync delivery point: one batched fetch per VLM admission pass
        toks, logps = np.asarray(toks), np.asarray(logps)
        with self._lock:
            for i, (s, req) in enumerate(vlm_admitted):
                if self.pool.drop_device(s):
                    self.stats["prefix_cache_evictions"] += 1
                self.slot_req[s] = req
                self.lengths[s] = plens[i]
                self.rope_pos[s] = rope_next[i]
                self.last_tokens[s] = int(toks[i])
                self.temperature[s] = req.temperature
                self.top_p[s] = req.top_p
                self.top_k[s] = req.top_k
                # mrope decouples rope from cache index: prefix reuse would
                # need the image context too — VLM slots never retain
                self._slot_vlm[s] = True
                self.retained_len[s] = 0
                self._reserved_until[s] = 0.0
                self.kv_version[s] = self.version
                self.stream_ids[s] = self._next_stream
                self._next_stream += 1
            self._state_dirty = True
        for i, (s, req) in enumerate(vlm_admitted):
            self._record_token(s, int(toks[i]), float(logps[i]))

    def _record_token(self, s: int, tok: int, logp: float) -> None:
        req = self.slot_req[s]
        if req is None:  # aborted between decode and delivery
            return
        req.output_tokens.append(tok)
        req.output_logprobs.append(logp)
        req.output_versions.append(self.version)
        if req.first_token_ts == 0.0:
            req.first_token_ts = time.perf_counter()
        # the sampled token's K/V lands at cache position lengths[s] on the
        # next decode step; mirror it for prefix matching
        self.seq_tokens[s, min(int(self.lengths[s]), self.max_seq_len - 1)] = tok
        n_out = len(req.output_tokens)
        stop_ids = req.stop_token_ids or (
            [self.model_config.eos_token_id]
            if self.model_config.eos_token_id is not None
            else []
        )
        hit_stop = tok in stop_ids and n_out >= req.min_new_tokens
        total_len = self.lengths[s] + 1  # prompt + generated so far
        if hit_stop:
            self._free(s, "stop")
        elif n_out >= req.max_new_tokens or total_len + 1 >= self.max_seq_len:
            self._free(s, "length")

    def _free(self, s: int, reason: str) -> None:
        req = self.slot_req[s]
        with self._lock:
            self.slot_req[s] = None
            # retain the cache-backed prefix (positions < lengths) for
            # prefix-reuse admission; the pending last token's K/V was never
            # written, so it is excluded
            self.retained_len[s] = 0 if self._slot_vlm[s] else self.lengths[s]
            self.pool.note_free(
                s, self.seq_tokens[s], int(self.retained_len[s])
            )
            self._state_dirty = True
        if req is not None:
            req.finish(reason)

    def tier_occupancy(self) -> List[int]:
        """Active slots per length-cohort tier (metrics surface).  Called
        from the server's metrics thread while the worker mutates
        slot_req — snapshot under the lock."""
        with self._lock:
            return [
                sum(
                    self.slot_req[s] is not None
                    for s in range(
                        self.tier_start[t],
                        self.tier_start[t] + self.tier_size[t],
                    )
                )
                for t in range(self.n_tiers)
            ]

    def spec_acceptance_rates(self) -> List[float]:
        """Windowed per-tier draft acceptance rate steering the D ladder
        (metrics surface; 0.0 before any verify dispatch has reported)."""
        return [
            self._spec.acceptance_rate(t) or 0.0
            for t in range(self.n_tiers)
        ]

    def decode_attended_fraction(self) -> float:
        """Attended span / configured ceiling over all decode dispatches:
        1.0 means decode paid the full `max_seq_len` width (the pre-window
        behavior); the bucketed key-window drives this toward
        occupied/ceiling."""
        ceiling = self.stats["decode_ceiling_cols"]
        return (
            self.stats["decode_attended_cols"] / ceiling if ceiling else 1.0
        )

    def prefix_cache_hit_rate(self) -> float:
        """Fraction of admissions that reused resident K/V through the
        radix/paged pool (device hits + host swap-ins) over all
        admissions; the /metrics gauge mirrors this."""
        h = self.stats["prefix_cache_hits"]
        m = self.stats["prefix_cache_misses"]
        return h / (h + m) if (h + m) else 0.0

    def _plan_migrations(self, n: int) -> None:
        """Move slots about to outgrow their tier's ceiling into a roomier
        cohort.  Since decode reads the cache through the page table
        (ISSUE 16), a migration is a pure HOST-SIDE row remap — zero
        device copies, zero new programs: the request keeps its physical
        row under a new logical slot, and the destination's old retained
        prefix re-homes at the vacated slot (still radix-matchable, where
        the old copy path destroyed it).  When nothing roomier is free the
        slot simply stays — its own tier's K bucket grows to cover it
        (the top-tier fallback: ceilings are placement hints, never
        correctness)."""
        if self.n_tiers == 1:
            return
        now = time.monotonic()
        free_by_tier: List[List[int]] = [[] for _ in range(self.n_tiers)]
        for s in range(self.n_slots):
            if self.slot_req[s] is None and self._reserved_until[s] <= now:
                # prefer overwriting the least valuable retained cache
                free_by_tier[int(self.slot_tier[s])].append(s)
        for t in range(self.n_tiers):
            free_by_tier[t].sort(key=lambda s: int(self.retained_len[s]))
        moves: List[tuple] = []  # (src, dst)
        for s in range(self.n_slots):
            req = self.slot_req[s]
            t = int(self.slot_tier[s])
            if req is None or t == self.n_tiers - 1:
                continue
            if int(self.lengths[s]) + n < self.tier_bounds[t]:
                continue  # still inside its cohort for this whole chunk
            remaining = max(0, req.max_new_tokens - len(req.output_tokens))
            need = min(int(self.lengths[s]) + remaining + 1, self.max_seq_len)
            dst = None
            for u in range(t + 1, self.n_tiers):
                if self.tier_bounds[u] >= min(
                    need, int(self.lengths[s]) + n + 1
                ) and free_by_tier[u]:
                    # smallest adequate tier; the top tier always qualifies
                    if self.tier_bounds[u] >= need or u == self.n_tiers - 1:
                        dst = free_by_tier[u].pop(0)
                        break
            if dst is not None:
                moves.append((s, dst))
        if not moves:
            return
        with self._lock:
            for s, dst in moves:
                req = self.slot_req[s]
                if req is None:  # aborted since planning
                    continue
                dst_retained = int(self.retained_len[dst])
                dst_version = int(self.kv_version[dst])
                dst_vlm = bool(self._slot_vlm[dst])
                dst_tokens = self.seq_tokens[dst].copy()
                self.slot_req[dst] = req
                self.slot_req[s] = None
                for arr in (
                    self.lengths, self.rope_pos, self.last_tokens,
                    self.temperature, self.top_p, self.top_k,
                    self.kv_version, self.stream_ids, self._slot_vlm,
                ):
                    arr[dst] = arr[s]
                self.seq_tokens[dst] = self.seq_tokens[s]
                self.retained_len[dst] = 0
                self._reserved_until[dst] = 0.0
                # zero-copy remap: the request's KV follows it to `dst`
                # through the page table, and `dst`'s old retained prefix
                # (physical row + radix entry) re-homes at the vacated
                # logical slot — nothing is destroyed, nothing moves
                self.pool.swap(s, dst)
                self.seq_tokens[s] = dst_tokens
                self.retained_len[s] = (
                    0 if dst_vlm else dst_retained
                )
                self.kv_version[s] = dst_version
                self._slot_vlm[s] = dst_vlm
                self._reserved_until[s] = 0.0
                self.stats["tier_migrations"] += 1
            self._state_dirty = True

    def _sync_device_state(self) -> None:  # holds: _lock
        """(Re)build the device-resident decode state from the host
        bookkeeping mirrors.  Runs only when a host-side mutation
        (admission, free, migration, abort) dirtied the mirrors — the
        steady-state decode loop chains the previous chunk's outputs
        instead (C2 host-upload discipline: uploads live HERE, never per
        dispatch)."""
        active = np.asarray(
            [r is not None for r in self.slot_req], bool
        )
        # uploads are COMMITTED to the replicated sharding the chunk
        # programs emit: an uncommitted jnp.asarray here and a chained
        # chunk output there would otherwise each mint their own
        # executable per static signature (2x every decode/verify
        # program — pinned by the ragged soak's exact accounting)
        put = functools.partial(jax.device_put, device=self._rep_sharding)
        self._dev_state = {
            "tokens": put(self.last_tokens),
            "lengths": put(self.lengths),
            "rope_pos": put(self.rope_pos),
            "streams": put(self.stream_ids),
            "active": put(active),
            "temp": put(self.temperature),
            "top_p": put(self.top_p),
            "top_k": put(self.top_k),
            # page table: logical slot -> physical cache row (migration
            # remaps dirty the state, so this re-uploads exactly when it
            # changes and never per dispatch)
            "rows": put(self.pool.device_rows()),
        }
        self._state_dirty = False
        self.stats["state_syncs"] += 1

    def _dispatch_ragged(self, st, n, active, spec_plan) -> List[tuple]:
        """ISSUE 19: advance the WHOLE slot grid in one fused ragged
        dispatch.  The Pallas kernel gathers each slot's true page span
        through the page table, so the per-tier dispatch fan-out (one
        program per occupied length cohort) collapses into a single
        program per step; tiers remain as admission/migration placement
        policy but no longer cost a dispatch each.  When any tier drafted
        this step, every slot rides ONE grid-wide verify at the largest
        chosen D — draftless slots carry draft_lens=0 and emit exactly
        their plain-decode token (the counter-keyed sampler makes the
        stream partition-invariant, so collapsing dispatches cannot
        change it).  Returns dev_outs entries for step()'s delivery loop
        (tier label -1 = collapsed grid)."""
        M = self.max_seq_len
        page = self.prompt_bucket
        span = int(max(self.lengths[s] for s in active))
        lens = self.lengths[: self.n_slots].astype(np.int64)
        if spec_plan:
            d_grid = max(self._spec_tier_d[t] for t in spec_plan)
            self._spec_grid_d = d_grid
            drafts = np.zeros((self.n_slots, d_grid), np.int32)
            dlens = np.zeros(self.n_slots, np.int32)
            for t, (dr, dl) in spec_plan.items():
                lo = self.tier_start[t]
                hi = lo + self.tier_size[t]
                drafts[lo:hi, : dr.shape[1]] = dr
                dlens[lo:hi] = dl
            if self.decode_window:
                key_window = round_up_to_bucket(
                    span + d_grid + 1, page, M
                )
            else:
                key_window = M
            out_t, nem_t, self.cache, tok, ln, rp = self._verify_fn(
                self.params,
                self.cache,
                st["tokens"],
                st["lengths"],
                st["rope_pos"],
                st["streams"],
                st["active"],
                st["temp"],
                st["top_p"],
                st["top_k"],
                self._decode_key,
                st["rows"],
                jnp.asarray(drafts),
                jnp.asarray(dlens),
                0,
                self.n_slots,
                key_window,
                self._spec_grid_d,
                True,
            )
            st["tokens"], st["lengths"], st["rope_pos"] = tok, ln, rp
            rows = d_grid + 1
            self.stats["verify_calls"] += 1
            self.stats["spec_drafted"] += int(dlens.sum())
            attended = np.minimum(lens + rows, key_window)
            pages = int(((attended + page - 1) // page).sum())
            self.stats["ragged_dispatches"] += 1
            self.stats["ragged_attended_pages"] += pages
            # attended accounting is page-granular and PER SLOT — what
            # the kernel actually read, not tier_size x key_window
            self.stats["decode_attended_cols"] += pages * page
            self.stats["decode_ceiling_cols"] += M * self.n_slots * rows
            return [(-1, 0, self.n_slots, out_t, nem_t, rows, dlens)]
        if self.decode_window:
            key_window = round_up_to_bucket(span + n, page, M)
        else:
            key_window = M
        out_t, self.cache, tok, ln, rp = self._decode_fn(
            self.params,
            self.cache,
            st["tokens"],
            st["lengths"],
            st["rope_pos"],
            st["streams"],
            st["active"],
            st["temp"],
            st["top_p"],
            st["top_k"],
            self._decode_key,
            st["rows"],
            n,
            0,
            self.n_slots,
            key_window,
            True,
        )
        st["tokens"], st["lengths"], st["rope_pos"] = tok, ln, rp
        self.stats["decode_calls"] += 1
        steps = np.arange(1, n + 1, dtype=np.int64)[:, None]
        attended = np.minimum(lens[None, :] + steps, key_window)
        pages = int(((attended + page - 1) // page).sum())
        self.stats["ragged_dispatches"] += 1
        self.stats["ragged_attended_pages"] += pages
        self.stats["decode_attended_cols"] += pages * page
        self.stats["decode_ceiling_cols"] += M * self.n_slots * n
        return [(-1, 0, self.n_slots, out_t, None, n, None)]

    def step(self, chunk: Optional[int] = None) -> int:
        """Admit pending prompts, then advance every active slot by up to
        `chunk` tokens — ONE fused device program per non-empty
        length-cohort tier, each bounded to its own bucketed `key_window`
        (ISSUE 5: decode attention reads track the occupied span, not the
        `max_seq_len` ceiling).  Returns generated-token count actually
        delivered (overshoot past stop conditions excluded).

        A slot at its cache limit no longer clamps the whole grid's chunk
        (VERDICT r3 weak #3): the decode kernel clamps that slot's writes to
        its last cache position and the host frees it at the boundary, so
        every other slot keeps full-chunk round-trips.  Delivery is
        vectorised — stop/length scanning is numpy over [chunk, active]
        token matrices, not a Python token loop (slot grids of 64-256 would
        otherwise pay O(slots x chunk) interpreter overhead per step)."""
        self._admit()
        n = chunk or self.decode_chunk
        # a verify dispatch can advance a slot by up to D+1 tokens in one
        # step — migration planning must see the larger overshoot
        self._plan_migrations(
            max(n, self._spec_max_d + 1) if self.spec_decode else n
        )
        with self._lock:
            active = [s for s in range(self.n_slots) if self.slot_req[s] is not None]
            if not active:
                return 0
            # dirty-check + rebuild + snapshot are one atomic unit: an
            # abort/free landing between them would leave this chunk
            # decoding from stale device mirrors
            if self._dev_state is None or self._state_dirty:
                self._sync_device_state()
            st = self._dev_state
        S = self.n_slots + 1
        # per-tier dispatch: only tiers holding an active slot run; each
        # gets a key window bucketed from ITS occupants' spans
        tier_active = [[] for _ in range(self.n_tiers)]
        for s in active:
            tier_active[int(self.slot_tier[s])].append(s)
        M = self.max_seq_len
        # prompt-lookup drafting (ISSUE 12): host-side n-gram match over
        # each slot's accumulated tokens (seq_tokens holds the pending
        # last token at index lengths[s]); per-tier D comes off the static
        # ladder via the acceptance controller, or is pinned by
        # spec_draft_len.  Drafts are capped by cache room and remaining
        # token budget.  The chosen D parks in _spec_tier_d so the
        # dispatch's static arg is a self attr (C6 on-ladder lattice).
        spec_plan: Dict[int, tuple] = {}
        if self.spec_decode:
            self._spec_tier_d = {}
            for t in range(self.n_tiers):
                if not tier_active[t]:
                    continue
                d_t = (
                    self.spec_draft_len
                    if self.spec_draft_len is not None
                    else self._spec.draft_len(t)
                )
                if d_t <= 0:
                    continue
                lo = self.tier_start[t]
                drafts = np.zeros((self.tier_size[t], d_t), np.int32)
                dlens = np.zeros(self.tier_size[t], np.int32)
                for s in tier_active[t]:
                    req = self.slot_req[s]
                    if req is None:
                        continue
                    L = int(self.lengths[s])
                    cap = min(
                        d_t,
                        self.max_seq_len - 2 - L,
                        req.max_new_tokens - len(req.output_tokens) - 1,
                    )
                    if cap <= 0:
                        continue
                    d = propose_draft(
                        self.seq_tokens[s, : L + 1], cap,
                        self.spec_ngram_max, self.spec_ngram_min,
                    )
                    if d.size:
                        drafts[s - lo, : d.size] = d
                        dlens[s - lo] = d.size
                if dlens.any():
                    self._spec_tier_d[t] = d_t
                    spec_plan[t] = (drafts, dlens)
        # decode-chunk telemetry is the one per-dispatch cost, so the whole
        # block (clock reads, trace-id snapshot) is gated on the flag
        tele = telemetry.is_enabled()
        if tele:
            tier_trace = {
                t: [
                    (r.trace_id or r.rid)
                    for s in tier_active[t]
                    for r in (self.slot_req[s],)
                    if r is not None
                ]
                for t in range(self.n_tiers)
                if tier_active[t]
            }
            t_dispatch = time.perf_counter()
        # (tier label, block lo, block size, device out, device n_emit or
        # None, out rows, draft lens); label -1 = collapsed ragged grid
        dev_outs: List[tuple] = []
        try:
            if self._ragged_ok:
                # ISSUE 19: one grid-wide ragged dispatch replaces the
                # whole per-tier fan-out below
                dev_outs.extend(
                    self._dispatch_ragged(st, n, active, spec_plan)
                )
            for t in range(self.n_tiers):
                if self._ragged_ok or not tier_active[t]:
                    continue
                plan = spec_plan.get(t)
                if plan is not None:
                    # speculative step: pending token + D drafts verified
                    # in ONE dispatch; state advances by accepted count on
                    # device.  D=0 tiers fall through to the plain decode
                    # program below — no degenerate verify signature.
                    drafts, dlens = plan
                    if self.decode_window:
                        span = int(
                            max(self.lengths[s] for s in tier_active[t])
                        )
                        key_window = round_up_to_bucket(
                            span + self._spec_tier_d[t] + 1,
                            self.prompt_bucket, M,
                        )
                    else:
                        key_window = M
                    out_t, nem_t, self.cache, tok, ln, rp = self._verify_fn(
                        self.params,
                        self.cache,
                        st["tokens"],
                        st["lengths"],
                        st["rope_pos"],
                        st["streams"],
                        st["active"],
                        st["temp"],
                        st["top_p"],
                        st["top_k"],
                        self._decode_key,
                        st["rows"],
                        drafts,
                        dlens,
                        self.tier_start[t],
                        self.tier_size[t],
                        key_window,
                        self._spec_tier_d[t],
                        False,
                    )
                    st["tokens"], st["lengths"], st["rope_pos"] = tok, ln, rp
                    rows = self._spec_tier_d[t] + 1
                    self.stats["verify_calls"] += 1
                    self.stats["spec_drafted"] += int(dlens.sum())
                    self.stats["decode_attended_cols"] += (
                        key_window * self.tier_size[t] * rows
                    )
                    self.stats["decode_ceiling_cols"] += (
                        M * self.tier_size[t] * rows
                    )
                    dev_outs.append((
                        t, self.tier_start[t], self.tier_size[t],
                        out_t, nem_t, rows, dlens,
                    ))
                    continue
                if self.decode_window:
                    span = int(max(self.lengths[s] for s in tier_active[t]))
                    key_window = round_up_to_bucket(
                        span + n, self.prompt_bucket, M
                    )
                else:
                    key_window = M
                out_t, self.cache, tok, ln, rp = self._decode_fn(
                    self.params,
                    self.cache,
                    st["tokens"],
                    st["lengths"],
                    st["rope_pos"],
                    st["streams"],
                    st["active"],
                    st["temp"],
                    st["top_p"],
                    st["top_k"],
                    self._decode_key,
                    st["rows"],
                    n,
                    self.tier_start[t],
                    self.tier_size[t],
                    key_window,
                    False,
                )
                st["tokens"], st["lengths"], st["rope_pos"] = tok, ln, rp
                self.stats["decode_calls"] += 1
                self.stats["decode_attended_cols"] += (
                    key_window * self.tier_size[t] * n
                )
                self.stats["decode_ceiling_cols"] += (
                    M * self.tier_size[t] * n
                )
                dev_outs.append((
                    t, self.tier_start[t], self.tier_size[t],
                    out_t, None, n, None,
                ))
        except Exception:
            # a failed dispatch may have consumed (donated) device state
            with self._lock:
                self._dev_state = None
                self._state_dirty = True
            raise
        nm = max(rows for _, _, _, _, _, rows, _ in dev_outs)
        toks = np.zeros((nm, S), np.int32)
        logps = np.zeros((nm, S), np.float32)
        # per-slot usable token count: full chunk for decode tiers, the
        # accepted-run length (>= 1: the corrected token always emits) for
        # verify tiers — delivery masks everything beyond it
        avail = np.zeros(S, np.int64)
        for t, lo, sz, out_t, nem_t, rows, dlens in dev_outs:
            # areal-lint: disable=host-sync delivery point: ONE fused download per tier chunk is the designed host round-trip cadence
            arr = np.asarray(out_t)  # [2, rows, block size]
            hi = lo + sz
            toks[:rows, lo:hi] = arr[0].astype(np.int32)
            logps[:rows, lo:hi] = arr[1]
            if nem_t is None:
                avail[lo:hi] = rows
                drafted = accepted = 0
            else:
                # areal-lint: disable=host-sync delivery point: the accepted-count fetch rides the same per-tier delivery round-trip
                nem = np.asarray(nem_t).astype(np.int64)
                avail[lo:hi] = nem
                drafted = int(dlens.sum())
                accepted = int(np.maximum(nem - 1, 0).sum())
                self.stats["spec_accepted"] += accepted
                if t >= 0:
                    self._spec.record(t, drafted, accepted)
                else:
                    # collapsed grid-wide verify (ISSUE 19): feed each
                    # tier's acceptance controller its own slots' outcome
                    # so the per-tier D ladder keeps adapting
                    for tt in range(self.n_tiers):
                        l2 = self.tier_start[tt] - lo
                        h2 = l2 + self.tier_size[tt]
                        d_tt = int(dlens[l2:h2].sum())
                        if d_tt:
                            self._spec.record(
                                tt, d_tt,
                                int(np.maximum(nem[l2:h2] - 1, 0).sum()),
                            )
            if tele:
                lat = time.perf_counter() - t_dispatch
                telemetry.DECODE_CHUNK.observe(lat, tier=str(t))
                n_act = len(active) if t < 0 else len(tier_active[t])
                ids = (
                    [i for v in tier_trace.values() for i in v]
                    if t < 0
                    else tier_trace.get(t, [])
                )
                if nem_t is None:
                    telemetry.emit(
                        "decode_chunk",
                        tier=t,
                        chunk=n,
                        n_active=n_act,
                        latency_s=lat,
                        trace_ids=ids,
                    )
                else:
                    telemetry.emit(
                        "spec_verify",
                        tier=t,
                        draft_len=rows - 1,
                        drafted=drafted,
                        accepted=accepted,
                        n_active=n_act,
                        latency_s=lat,
                        trace_ids=ids,
                    )

        delivered = 0
        to_finish: List[tuple] = []
        version = self.version
        with self._lock:
            # re-snapshot under the lock: a concurrent abort_all (weight
            # update) may have freed slots while the chunk was on device
            pairs = [
                (s, self.slot_req[s])
                for s in active
                if self.slot_req[s] is not None
            ]
            if not pairs:
                return 0
            A = np.asarray([s for s, _ in pairs])
            reqs = [r for _, r in pairs]
            a = len(pairs)
            tk = toks[:, A]  # [nm, a]
            lp = logps[:, A]
            av = avail[A]  # per-slot usable rows (ragged under spec decode)
            c0 = np.fromiter((len(r.output_tokens) for r in reqs), np.int64, a)
            max_new = np.fromiter((r.max_new_tokens for r in reqs), np.int64, a)
            min_new = np.fromiter((r.min_new_tokens for r in reqs), np.int64, a)
            eos = self.model_config.eos_token_id
            stop = np.zeros((nm, a), bool)
            for j, r in enumerate(reqs):
                sids = r.stop_token_ids or ([eos] if eos is not None else [])
                if sids:
                    stop[:, j] = np.isin(tk[:, j], sids)
            steps = np.arange(1, nm + 1, dtype=np.int64)[:, None]  # [nm, 1]
            # rows past a slot's avail are rejected-draft / pad garbage:
            # they neither deliver nor trigger stop conditions
            valid = steps <= av[None, :]
            out_count = c0[None, :] + steps
            hit_stop = stop & (out_count >= min_new[None, :]) & valid
            # freeing at total_len + 1 >= max_seq_len keeps the NEXT decode
            # write in-bounds (same rule the token loop applied)
            total_len = self.lengths[A][None, :] + steps
            hit_len = ((out_count >= max_new[None, :]) | (
                total_len + 1 >= self.max_seq_len
            )) & valid
            done = hit_stop | hit_len
            any_done = done.any(axis=0)
            last = np.where(any_done, done.argmax(axis=0), av - 1)  # inclusive

            for j, (s, req) in enumerate(pairs):
                k = int(last[j]) + 1
                seq = tk[:k, j]
                if c0[j] == 0 and k > 0 and req.first_token_ts == 0.0:
                    req.first_token_ts = time.perf_counter()
                req.output_tokens.extend(seq.tolist())
                req.output_logprobs.extend(lp[:k, j].tolist())
                req.output_versions.extend([version] * k)
                L = int(self.lengths[s])
                # delivered tokens occupy cache positions L+1 .. L+k (the
                # pending last_token's K/V was written at L this chunk)
                self.seq_tokens[s, L + 1 : L + 1 + k] = seq
                self.lengths[s] = L + k
                self.rope_pos[s] += k
                self.last_tokens[s] = int(seq[-1])
                delivered += k
                if any_done[j]:
                    reason = "stop" if hit_stop[last[j], j] else "length"
                    self.slot_req[s] = None
                    self.retained_len[s] = (
                        0 if self._slot_vlm[s] else self.lengths[s]
                    )
                    self.pool.note_free(
                        s, self.seq_tokens[s], int(self.retained_len[s])
                    )
                    to_finish.append((req, reason))
            if to_finish:
                # host mirrors diverged from the device state (stop
                # trimming); resync before the next chunk
                self._state_dirty = True
        for req, reason in to_finish:
            req.finish(reason)
        return delivered

    def generate_blocking(self, reqs: List[GenRequest]) -> List[GenRequest]:
        """Synchronous helper (tests / offline eval): run until all done."""
        for r in reqs:
            self.submit(r)
        while any(not r.stop_reason for r in reqs):
            if self.step() == 0:
                # queued work may be parked behind an abort reservation
                # (holdback); only a genuinely idle engine is done
                if self.active_count() == 0:
                    break
                time.sleep(0.001)
            time.sleep(0)
        return reqs
