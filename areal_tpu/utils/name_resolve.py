"""Distributed key-value rendezvous ("name resolve").

Capability counterpart of the reference's `areal/utils/name_resolve.py` (1252
LoC: memory/NFS/etcd3/ray backends, watcher threads, delete_on_exit GC).  Two
backends here — in-process memory (tests, single-host) and NFS (a shared
filesystem is the natural multi-host rendezvous on TPU pods; every key is a
file).  The etcd3 client is not in this image, so the etcd backend is a stub
that raises with a clear message.
"""

import dataclasses
import os
import shutil
import tempfile
import threading
import time
import uuid
from abc import ABC, abstractmethod
from typing import Callable, Dict, List, Optional

from areal_tpu.api.config import NameResolveConfig
from areal_tpu.utils import logging

logger = logging.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


class NameRecordRepository(ABC):
    @abstractmethod
    def add(
        self,
        name: str,
        value: str,
        delete_on_exit: bool = True,
        keepalive_ttl: Optional[float] = None,
        replace: bool = False,
    ): ...

    @abstractmethod
    def get(self, name: str) -> str: ...

    @abstractmethod
    def get_subtree(self, name_root: str) -> List[str]: ...

    @abstractmethod
    def find_subtree(self, name_root: str) -> List[str]: ...

    @abstractmethod
    def delete(self, name: str): ...

    @abstractmethod
    def clear_subtree(self, name_root: str): ...

    @abstractmethod
    def reset(self): ...

    # --- shared conveniences ---
    def add_subentry(self, name: str, value: str, **kwargs) -> str:
        sub = f"{name}/{uuid.uuid4().hex[:8]}"
        self.add(sub, value, **kwargs)
        return sub

    def wait(
        self,
        name: str,
        timeout: Optional[float] = None,
        poll_frequency: float = 0.1,
    ) -> str:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"name_resolve.wait({name!r}) timed out")
                time.sleep(poll_frequency)

    def watch_names(
        self,
        names: List[str],
        call_back: Callable[[], None],
        poll_frequency: float = 2.0,
        wait_timeout: float = 300.0,
    ) -> threading.Thread:
        """Fire `call_back` once any watched name disappears (reference:
        name_resolve.py:141-181 — used for peer-death detection)."""

        def _watch():
            try:
                for n in names:
                    self.wait(n, timeout=wait_timeout, poll_frequency=poll_frequency)
            except TimeoutError:
                # a peer that never registered is as dead as one that vanished
                logger.warning(
                    f"watched names {names} did not appear within "
                    f"{wait_timeout}s; treating peer as dead"
                )
                call_back()
                return
            while True:
                try:
                    for n in names:
                        self.get(n)
                except NameEntryNotFoundError:
                    call_back()
                    return
                time.sleep(poll_frequency)

        t = threading.Thread(target=_watch, daemon=True)
        t.start()
        return t


class MemoryNameRecordRepository(NameRecordRepository):
    """Process-local dict; the default for unit tests and single-process runs."""

    def __init__(self):
        self._store: Dict[str, str] = {}
        self._lock = threading.Lock()

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        name = name.rstrip("/")
        with self._lock:
            if name in self._store and not replace:
                raise NameEntryExistsError(name)
            self._store[name] = str(value)

    def get(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            return self._store[name]

    def get_subtree(self, name_root):
        prefix = name_root.rstrip("/") + "/"
        with self._lock:
            return [
                v
                for k, v in sorted(self._store.items())
                if k.startswith(prefix) or k == name_root.rstrip("/")
            ]

    def find_subtree(self, name_root):
        prefix = name_root.rstrip("/") + "/"
        with self._lock:
            return sorted(
                k
                for k in self._store
                if k.startswith(prefix) or k == name_root.rstrip("/")
            )

    def delete(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            del self._store[name]

    def clear_subtree(self, name_root):
        prefix = name_root.rstrip("/") + "/"
        with self._lock:
            for k in [
                k
                for k in self._store
                if k.startswith(prefix) or k == name_root.rstrip("/")
            ]:
                del self._store[k]

    def reset(self):
        with self._lock:
            self._store.clear()


class NfsNameRecordRepository(NameRecordRepository):
    """Every key is a file under `record_root` on a shared filesystem.

    Works on any POSIX shared mount (NFS/GCSfuse/Lustre); atomicity via
    write-to-temp + rename (reference: name_resolve.py:282-410).
    """

    def __init__(self, record_root: str = "/tmp/areal_tpu/name_resolve"):
        self.record_root = record_root
        self._to_delete: List[str] = []
        os.makedirs(record_root, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.record_root, name.strip("/"), "ENTRY")

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        path = self._path(name)
        if os.path.exists(path) and not replace:
            raise NameEntryExistsError(name)
        # retry once: a concurrent delete() may prune our freshly-made dir
        for attempt in range(2):
            os.makedirs(os.path.dirname(path), exist_ok=True)
            try:
                fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
                break
            except FileNotFoundError:
                if attempt == 1:
                    raise
        with os.fdopen(fd, "w") as f:
            f.write(str(value))
        os.replace(tmp, path)
        if delete_on_exit:
            self._to_delete.append(name)

    def get(self, name):
        path = self._path(name)
        # Retry around NFS rename visibility races.
        for _ in range(2):
            try:
                with open(path) as f:
                    return f.read()
            except FileNotFoundError:
                time.sleep(0.005)
        raise NameEntryNotFoundError(name)

    def _walk(self, name_root):
        root = os.path.join(self.record_root, name_root.strip("/"))
        if not os.path.isdir(root):
            return []
        found = []
        for dirpath, _, filenames in os.walk(root):
            if "ENTRY" in filenames:
                rel = os.path.relpath(dirpath, self.record_root)
                found.append(rel.replace(os.sep, "/"))
        return sorted(found)

    def get_subtree(self, name_root):
        out = []
        for key in self._walk(name_root):
            try:
                out.append(self.get(key))
            except NameEntryNotFoundError:
                pass
        return out

    def find_subtree(self, name_root):
        return self._walk(name_root)

    def delete(self, name):
        path = self._path(name)
        if not os.path.exists(path):
            raise NameEntryNotFoundError(name)
        os.unlink(path)
        # best-effort prune of now-empty dirs; a concurrent add() may be
        # racing us between its makedirs and file write, so rmdir failures
        # (or listdir on a dir another process just removed) just stop the walk
        d = os.path.dirname(path)
        while d != self.record_root:
            try:
                if os.listdir(d):
                    break
                os.rmdir(d)
            except OSError:
                break
            d = os.path.dirname(d)

    def clear_subtree(self, name_root):
        root = os.path.join(self.record_root, name_root.strip("/"))
        if os.path.isdir(root):
            shutil.rmtree(root, ignore_errors=True)

    def reset(self):
        for name in list(self._to_delete):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self._to_delete.clear()


# --- module-level singleton, mirroring the reference's module API ---
DEFAULT_REPOSITORY: NameRecordRepository = MemoryNameRecordRepository()


def reconfigure(config: NameResolveConfig):
    global DEFAULT_REPOSITORY
    if config.type == "memory":
        DEFAULT_REPOSITORY = MemoryNameRecordRepository()
    elif config.type == "nfs":
        DEFAULT_REPOSITORY = NfsNameRecordRepository(config.nfs_record_root)
    elif config.type == "http":
        from areal_tpu.utils.kv_store import HttpNameRecordRepository

        DEFAULT_REPOSITORY = HttpNameRecordRepository(config.http_addr)
    elif config.type == "etcd3":
        raise NotImplementedError(
            "etcd3 client is not available in this environment; "
            "type='http' (areal_tpu.utils.kv_store — same TTL-lease "
            "semantics, first-party server) replaces it"
        )
    else:
        raise ValueError(f"unknown name_resolve backend {config.type!r}")


def reconfigure_from_env(fallback: "NameResolveConfig" = None):
    """Pick the backend from AREAL_NAME_RESOLVE ("memory" | "nfs:<root>"),
    falling back to the given config.  Launchers set the env var so every
    spawned process (gen servers, trainers on other hosts) rendezvouses in
    the same store."""
    spec = os.environ.get("AREAL_NAME_RESOLVE", "")
    if spec.startswith("nfs:"):
        reconfigure(NameResolveConfig(type="nfs", nfs_record_root=spec[4:]))
    elif spec.startswith("http:"):
        reconfigure(
            NameResolveConfig(type="http", http_addr=spec[len("http:"):])
        )
    elif spec == "memory":
        reconfigure(NameResolveConfig(type="memory"))
    elif fallback is not None and fallback.type != "memory":
        reconfigure(fallback)


def add(name, value, **kwargs):
    return DEFAULT_REPOSITORY.add(name, value, **kwargs)


def add_subentry(name, value, **kwargs):
    return DEFAULT_REPOSITORY.add_subentry(name, value, **kwargs)


def get(name):
    return DEFAULT_REPOSITORY.get(name)


def get_subtree(name_root):
    return DEFAULT_REPOSITORY.get_subtree(name_root)


def find_subtree(name_root):
    return DEFAULT_REPOSITORY.find_subtree(name_root)


def wait(name, **kwargs):
    return DEFAULT_REPOSITORY.wait(name, **kwargs)


def delete(name):
    return DEFAULT_REPOSITORY.delete(name)


def clear_subtree(name_root):
    return DEFAULT_REPOSITORY.clear_subtree(name_root)


def watch_names(names, call_back, **kwargs):
    if isinstance(names, str):
        names = [names]
    return DEFAULT_REPOSITORY.watch_names(names, call_back, **kwargs)


def reset():
    return DEFAULT_REPOSITORY.reset()
