"""Long-context discipline tests (VERDICT round-1 next-step #5).

- Train-side length bucketing must bound the number of compiled programs:
  arbitrary batch lengths land in power-of-two-of-quantum buckets, so a
  32k-max run compiles O(log) step programs, not one per length.
- The generation engine must serve a 32k-token cache at tiny hidden size
  (the capability the reference gets from SGLang's 32k serving; real-model
  32k throughput evidence lives in bench.py's ctx variant on hardware).
"""

import numpy as np

from areal_tpu.api.config import (
    MeshConfig,
    MicroBatchSpec,
    OptimizerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.jax_train import JaxTrainEngine
from areal_tpu.models.model_config import tiny_config
from areal_tpu.ops import sft_loss_fn
from areal_tpu.utils.datapack import round_up_to_bucket


def test_bucket_ladder_is_logarithmic():
    quantum, max_len = 512, 32768
    buckets = {round_up_to_bucket(n, quantum, max_len) for n in range(1, max_len + 1, 97)}
    assert buckets == {512, 1024, 2048, 4096, 8192, 16384, 32768}


def _batch(rng, n_seqs, max_len):
    lens = rng.integers(max_len // 4, max_len, n_seqs)
    L = int(lens.max())
    am = np.zeros((n_seqs, L), bool)
    for i, n in enumerate(lens):
        am[i, :n] = True
    ids = rng.integers(0, 128, (n_seqs, L)).astype(np.int32) * am
    return {
        "input_ids": ids,
        "attention_mask": am,
        "loss_mask": am.astype(np.float32),
    }


def test_no_recompilation_storm_across_batch_lengths():
    """Twelve batches of random lengths must reuse a handful of compiled
    step programs (cache keyed on bucketed row_len)."""
    eng = JaxTrainEngine(
        TrainEngineConfig(
            experiment_name="lc", trial_name="t", init_from_scratch=True,
            dtype="float32", param_dtype="float32",
            gradient_checkpointing=False, mesh=MeshConfig(),
            mb_spec=MicroBatchSpec(n_mbs=1),
            optimizer=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
            pack_length_quantum=64, max_pack_length=1024,
        ),
        model_config=tiny_config(vocab_size=128),
    )
    eng.initialize(ft_spec=FinetuneSpec(1, 64, 4))
    rng = np.random.default_rng(0)
    for _ in range(12):
        n_seqs = int(rng.integers(2, 6))
        eng.train_batch(
            _batch(rng, n_seqs, int(rng.integers(40, 900))),
            sft_loss_fn,
            lambda b: float(np.sum(b["loss_mask"])),
        )
    # buckets possible: 64,128,256,512,1024 (x row-count variations is
    # absorbed by rows_multiple padding) — well under one-per-batch
    assert len(eng._train_step_cache) <= 5, len(eng._train_step_cache)


def test_gen_engine_32k_cache():
    """A 32k-slot KV cache serves and respects the length stop at tiny
    hidden size; prompt buckets stay power-of-two."""
    import jax

    from areal_tpu.gen.engine import GenEngine, GenRequest
    from areal_tpu.models import init_params

    cfg = tiny_config(
        vocab_size=64, hidden_size=16, intermediate_size=32, num_layers=1,
        num_heads=2, num_kv_heads=1, max_position_embeddings=32768,
        eos_token_id=None,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = GenEngine(cfg, params=params, n_slots=2, max_seq_len=32768,
                       prompt_bucket=256, decode_chunk=8)
    rng = np.random.default_rng(0)
    # a ~31.5k prompt (the reference benchmark's generation regime is 31k
    # of 32k ctx) with a short completion budget
    long_prompt = rng.integers(0, 64, 31500).tolist()
    req = GenRequest(rid="long", input_ids=long_prompt, max_new_tokens=8,
                     temperature=0.0)
    engine.generate_blocking([req])
    assert len(req.output_tokens) == 8
    assert req.stop_reason == "length"
    # and a request that would overflow the cache is rejected up front
    too_long = GenRequest(rid="over", input_ids=rng.integers(0, 64, 32768).tolist(),
                          max_new_tokens=8)
    engine.submit(too_long)
    assert too_long.stop_reason == "length"
