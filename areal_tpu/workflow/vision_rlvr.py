"""Vision RLVR rollout workflow.

Behavioral counterpart of the reference's `VisionRLVRWorkflow`
(areal/workflow/vision_rlvr.py): episodes whose data carries `images` +
`messages`; an HF AutoProcessor turns (images, text) into input_ids with
image-placeholder tokens, the images travel to the inference server as
base64 in `ModelRequest.image_data`, and rewards are computed from the
decoded completion as in text RLVR (episode loop shared with RLVRWorkflow
via the request/reward hooks).

Serving note: the in-repo JAX generation engine is text-only today — this
workflow targets inference backends that accept image_data (the backend
protocol field is plumbed end-to-end); multimodal towers are the remaining
model-side work.
"""

import uuid
from typing import Any, Callable, Dict, Optional

from areal_tpu.api.config import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.utils.image import image2base64, load_images
from areal_tpu.workflow.rlvr import RLVRWorkflow


class VisionRLVRWorkflow(RLVRWorkflow):
    def __init__(
        self,
        reward_fn: Callable[..., float],
        gconfig: GenerationHyperparameters,
        tokenizer=None,
        processor=None,
        enable_thinking: bool = False,
        rollout_stat_scope: str = "rollout",
        dump_dir: Optional[str] = None,
    ):
        super().__init__(
            reward_fn,
            gconfig,
            tokenizer=tokenizer,
            enable_thinking=enable_thinking,
            rollout_stat_scope=rollout_stat_scope,
            dump_dir=dump_dir,
        )
        self.processor = processor

    def _build_request(self, data: Dict[str, Any]) -> ModelRequest:
        images = load_images(data["images"]) if "images" in data else None
        pixel_values = data.get("pixel_values")
        image_grid_thw = data.get("image_grid_thw")
        if "input_ids" in data:
            input_ids = list(data["input_ids"])
        else:
            if self.processor is None:
                raise ValueError(
                    "need an AutoProcessor or pre-tokenized input_ids"
                )
            processed = self.processor(
                images=images, text=data["messages"], padding=False
            )
            ids = processed["input_ids"]
            input_ids = list(ids[0] if hasattr(ids[0], "__len__") else ids)
            # the processor's patchified pixels feed the native VLM server
            # directly (gen/server.py pixel_values_b64 wire field)
            if pixel_values is None and "pixel_values" in processed:
                pixel_values = processed["pixel_values"]
                image_grid_thw = processed.get("image_grid_thw")
        return ModelRequest(
            rid=str(uuid.uuid4()),
            input_ids=input_ids,
            image_data=image2base64(images) if images is not None else None,
            pixel_values=pixel_values,
            image_grid_thw=image_grid_thw,
            gconfig=self.gconfig.new(n_samples=1),
            tokenizer=self.tokenizer,
            processor=self.processor,
        )

    def _reward_kwargs(self, data: Dict[str, Any]) -> Dict[str, Any]:
        return {k: v for k, v in data.items() if k != "images"}
