"""Chaos e2e (ISSUE 11 acceptance): a two-member rollout fleet behind the
router; one member is killed mid-run.  The run must complete with every
trajectory consumed or explicitly accounted lost, `resubmit` spans joining
the original trace_ids, the staleness ledger settled, and — after a
fixed-port restart — the rejoined backend force-reloaded to the fleet's
published weight version before taking placements again."""

import threading
import time

import pytest

from areal_tpu.api.config import GenerationHyperparameters, InferenceEngineConfig
from areal_tpu.engine.jax_remote import RemoteJaxEngine
from areal_tpu.gen.router import Router, RouterConfig
from areal_tpu.utils import telemetry
from areal_tpu.workflow.rlvr import RLVRWorkflow

from tests.fake_server import FakeGenServer
from tests.test_router import RouterHarness, _get, _post


@pytest.fixture()
def enabled_telemetry():
    was = telemetry.is_enabled()
    telemetry.set_enabled(True)
    telemetry.EVENTS.clear()
    yield
    telemetry.set_enabled(was)
    telemetry.EVENTS.clear()


def _reward(prompt, completion, prompt_ids, completion_ids, **kw):
    return float(len(completion_ids))


def test_kill_one_of_two_mid_run_completes_and_rejoins(enabled_telemetry):
    completion = list(range(100, 108))
    # shutdown_grace < delay_s: the kill ABORTS the chunk it catches in
    # flight instead of letting it finish, so the victim trajectory always
    # fails client-side (a graceful close would let the health checker
    # reroute every affinity before the client ever saw an error)
    servers = [FakeGenServer(completion=completion, chunk_size=2,
                             shutdown_grace=0.01)
               for _ in range(2)]
    for s in servers:
        s.delay_s = 0.05  # keep chunks in flight so the kill lands mid-run
    addrs = [s.start() for s in servers]
    router = Router(
        RouterConfig(
            schedule_policy="round_robin",
            health_check_interval=0.1,
            health_failure_threshold=1,
            health_probe_timeout=0.5,
        ),
        addresses=addrs,
    )
    h = RouterHarness(router)
    raddr = h.start()
    eng = RemoteJaxEngine(InferenceEngineConfig(
        experiment_name="chaos", trial_name="t", consumer_batch_size=8,
        max_concurrent_rollouts=8, request_timeout=10, request_retries=2,
        failover_retries=8,
    ))
    eng.initialize(addr=raddr)

    def _assassin():
        # wait for a CONTINUATION chunk (prompt grown past the 1-token
        # original): that trajectory has accumulated tokens client-side,
        # so aborting its in-flight chunk forces a resubmit that carries
        # them — the warm-start path under test — deterministically
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(
            len(r.get("input_ids", ())) > 1 for r in servers[0].requests
        ):
            time.sleep(0.005)
        servers[0].stop()

    killer = threading.Thread(target=_assassin)
    killer.start()
    restarted = None
    try:
        wf = RLVRWorkflow(
            reward_fn=_reward,
            gconfig=GenerationHyperparameters(max_new_tokens=16),
        )
        batch = eng.rollout_batch(
            [{"input_ids": [i]} for i in range(8)], workflow=wf
        )
        killer.join(timeout=10)

        # 1. every trajectory consumed or explicitly accounted lost
        n_out = batch["input_ids"].shape[0]
        assert n_out + eng.executor.lost_trajectories == 8
        assert eng.executor.lost_trajectories == 0, (
            "failover must save every trajectory while one replica survives"
        )

        # 2. resubmit spans join the ORIGINAL trace ids (one trajectory
        # surviving a server death, not N fresh submits)
        events = telemetry.EVENTS.snapshot()
        submits = {e["trace_id"] for e in events
                   if e["event"] == "rollout_submit"}
        resubmits = [e for e in events if e["event"] == "resubmit"]
        assert resubmits, "killing a loaded replica must trigger resubmits"
        assert all(e["trace_id"] in submits for e in resubmits)

        # 2b. resubmissions warm-start through the prefix cache (ISSUE 16):
        # the replacement server reports the accumulated tokens it served
        # from resident/derivable state as cache_hit_tokens, and the client
        # surfaces them as resubmit_cache_hit events + a counter — a
        # retried trajectory must not silently cold-prefill
        cache_hits = [e for e in events if e["event"] == "resubmit_cache_hit"]
        assert cache_hits, (
            "kill-one-of-two must report nonzero resubmit cache hits"
        )
        assert sum(e["hit_tokens"] for e in cache_hits) > 0
        assert all(e["trace_id"] in submits for e in cache_hits)
        counted = sum(
            v for _, _, v in telemetry.CLIENT_RESUBMIT_CACHE_HITS.samples()
        )
        assert counted >= len(cache_hits)

        # 3. staleness ledger settled: capacity returns to the churn invariant
        stats = eng.executor.staleness_manager.get_stats()
        assert stats.running == 0
        assert stats.submitted == stats.accepted + stats.rejected

        # 4. the router detected the death: breaker open, failovers counted
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            m = _get(raddr, "/metrics")
            if m["backend_states"].get(addrs[0], {}).get("state") == "open":
                break
            time.sleep(0.05)
        m = _get(raddr, "/metrics")
        assert m["backend_states"][addrs[0]]["state"] == "open"
        assert m["backend_states"][addrs[1]]["state"] == "closed"
        assert m["failovers"] >= 1

        # 5. degraded-mode publish: the survivor updates, the dead member is
        # skipped and counted — the publish must not wedge behind the corpse
        s, out = _post(raddr, "/update_weights",
                       {"path": "/tmp/chaos_ck/v3", "version": 3})
        assert s == 200 and out["version"] == 3
        assert servers[1].weight_updates[-1]["version"] == 3
        assert not servers[0].weight_updates
        m = _get(raddr, "/metrics")
        assert m["publish_partial_failures"] >= 1

        # 6. fixed-port restart: the rejoin path must force-reload the stale
        # member to the fleet version before re-admitting it to placement
        restarted = FakeGenServer(completion=completion, chunk_size=2,
                                  port=servers[0].port)
        restarted.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                if _get(raddr, "/health")["status"] == "ok":
                    break
            except Exception:  # 503 while still degraded
                pass
            time.sleep(0.05)
        health = _get(raddr, "/health")
        assert health["status"] == "ok"
        assert all(s["state"] == "closed" for s in health["servers"].values())
        # final weight version agrees across surviving + rejoined fleet
        assert restarted.version == 3
        assert restarted.weight_updates[-1] == {"path": "/tmp/chaos_ck/v3",
                                                "version": 3}
        assert servers[1].version == 3
    finally:
        eng.destroy()
        h.stop()
        servers[1].stop()
        if restarted is not None:
            restarted.stop()


def test_stale_rejoin_is_gated_until_reload_succeeds(enabled_telemetry):
    """A backend that answers probes but cannot be brought to the fleet
    version (its reload endpoint fails) must stay OUT of placement —
    half-open/open, never closed — so stale weights cannot leak into a
    batch."""
    from areal_tpu.utils.faults import Fault, FaultPlan

    healthy = FakeGenServer(completion=[100, 101])
    # the flaky member fails every /update_weights_from_disk call, so the
    # rejoin force-reload can never succeed
    plan = FaultPlan({("/update_weights_from_disk", i): Fault("http_500")
                      for i in range(64)})
    flaky = FakeGenServer(completion=[100, 101], fault_plan=plan)
    addrs = [healthy.start(), flaky.start()]
    router = Router(
        RouterConfig(
            schedule_policy="round_robin",
            health_check_interval=0.1,
            health_failure_threshold=1,
            health_probe_timeout=0.5,
        ),
        addresses=addrs,
    )
    h = RouterHarness(router)
    raddr = h.start()
    try:
        # publish v2: flaky's update endpoint 500s -> partial publish,
        # breaker trips it open
        s, out = _post(raddr, "/update_weights",
                       {"path": "/tmp/ck/v2", "version": 2})
        assert s == 200 and out["version"] == 2
        assert healthy.weight_updates[-1]["version"] == 2

        # probes keep answering (its /health is fine) so it cycles
        # open -> half_open -> rejoin reload fails -> open; it must never
        # reach closed, and placements must all land on the healthy member
        time.sleep(0.5)
        for i in range(4):
            s, out2 = _post(raddr, "/generate", {
                "rid": f"r{i}", "input_ids": [1],
                "sampling_params": {"max_new_tokens": 4},
            })
            assert s == 200 and out2["output_tokens"]
        assert not flaky.requests
        m = _get(raddr, "/metrics")
        assert m["backend_states"][addrs[1]]["state"] in ("open", "half_open")
    finally:
        h.stop()
        healthy.stop()
        flaky.stop()


@pytest.mark.parametrize("seed", [3, 11])
def test_chaos_span_completeness_property(enabled_telemetry, seed):
    """ISSUE 14 satellite: the trace analyzer must reconstruct every
    surviving trajectory from a chaos run's event log — resubmits joined
    to the ORIGINAL trace ids, no orphan spans, and the accounting
    identity intact — for any seeded fault sequence, not just the
    hand-picked kill scenario above."""
    from areal_tpu.obs.trace import analyze, check_accounting
    from areal_tpu.utils.faults import FaultPlan

    plan = FaultPlan.generate(seed, endpoints=("/generate",), n_calls=64,
                              rate=0.3, kinds=("http_500", "disconnect"))
    servers = [
        FakeGenServer(completion=list(range(100, 108)), chunk_size=2,
                      fault_plan=plan if i == 0 else None)
        for i in range(2)
    ]
    addrs = [s.start() for s in servers]
    router = Router(
        RouterConfig(
            schedule_policy="round_robin",
            health_check_interval=0.1,
            health_failure_threshold=2,
            health_probe_timeout=0.5,
        ),
        addresses=addrs,
    )
    h = RouterHarness(router)
    raddr = h.start()
    eng = RemoteJaxEngine(InferenceEngineConfig(
        experiment_name="chaos-prop", trial_name=f"s{seed}",
        consumer_batch_size=8, max_concurrent_rollouts=8,
        request_timeout=10, request_retries=3, failover_retries=8,
    ))
    eng.initialize(addr=raddr)
    try:
        wf = RLVRWorkflow(
            reward_fn=_reward,
            gconfig=GenerationHyperparameters(max_new_tokens=16),
        )
        batch = eng.rollout_batch(
            [{"input_ids": [i]} for i in range(8)], workflow=wf
        )
        n_out = batch["input_ids"].shape[0]
        lost = eng.executor.lost_trajectories
        assert plan.injected, "rate=0.3 over a chunked run must inject"

        rep = analyze(telemetry.EVENTS.snapshot(),
                      dropped_events=telemetry.EVENTS.dropped)
        comp = rep.completeness

        # every span in the log reconstructs: no orphans, resubmits all
        # joined to an earlier submit of the same trace, ring lossless
        assert comp.complete, comp
        assert comp.dropped_events == 0

        # surviving trajectories reconstruct as closed records with a
        # stage partition and a client e2e that satisfies the identity
        closed = rep.closed
        assert len(closed) == n_out
        assert len(closed) + lost == 8
        assert all(r.stages and r.span_s is not None for r in closed)
        acct = check_accounting(rep.records)
        assert acct.ok, acct
        # fakes emit no server-side spans: whole spans are opaque
        assert all("opaque" in r.stages for r in closed)

        # failovers that did happen joined the original trace ids (the
        # linter already proved it; cross-check against the raw events)
        events = telemetry.EVENTS.snapshot()
        submits = {e["trace_id"] for e in events
                   if e["event"] == "rollout_submit"}
        for e in events:
            if e["event"] == "resubmit":
                assert e["trace_id"] in submits
        by_rec = {r.trace_id: r for r in rep.records}
        for e in events:
            if e["event"] == "resubmit":
                assert by_rec[e["trace_id"]].resubmits >= 1
    finally:
        eng.destroy()
        h.stop()
        for s in servers:
            s.stop()
