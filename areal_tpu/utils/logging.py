"""Logging helpers (counterpart of areal/utils/logging.py in the reference).

Plain stdlib logging with an optional ANSI-colored formatter; no third-party
colorlog dependency.
"""

import logging
import os
import sys
import threading
from typing import Optional

_LOCK = threading.Lock()
_CONFIGURED = False

_LEVEL_COLORS = {
    logging.DEBUG: "\033[36m",  # cyan
    logging.INFO: "\033[32m",  # green
    logging.WARNING: "\033[33m",  # yellow
    logging.ERROR: "\033[31m",  # red
    logging.CRITICAL: "\033[41m",  # red background
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        color = _LEVEL_COLORS.get(record.levelno)
        if color and sys.stderr.isatty():
            return f"{color}{msg}{_RESET}"
        return msg


def _default_level() -> int:
    name = os.environ.get("AREAL_LOG_LEVEL", "INFO").upper()
    return getattr(logging, name, logging.INFO)


def getLogger(name: Optional[str] = None) -> logging.Logger:
    global _CONFIGURED
    with _LOCK:
        if not _CONFIGURED:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(
                _ColorFormatter(
                    fmt="%(asctime)s.%(msecs)03d %(name)s %(levelname)s: %(message)s",
                    datefmt="%Y%m%d-%H:%M:%S",
                )
            )
            root = logging.getLogger("areal_tpu")
            root.addHandler(handler)
            root.setLevel(_default_level())
            root.propagate = False
            _CONFIGURED = True
    full = f"areal_tpu.{name}" if name else "areal_tpu"
    return logging.getLogger(full)


getlogger = getLogger
