"""Colocated serving + training: one chip set, time-shared.

Runtime for `AllocationMode` expressions like `jax:d1t1|d1t1` (VERDICT r3
weak #4: the grammar parsed colocated allocations but nothing implemented
them).  The reference colocates by putting SGLang and the FSDP trainer on
the same GPUs and sleeping the server's allocator around train steps
(areal/api/alloc_mode.py colocated inference, vLLM sleep/wake); the
TPU-native shape is simpler and stronger:

- ONE process owns the chips.  A `GenEngine` serves rollouts between train
  steps on a background decode thread.
- `train_phase()` releases the engine's HBM — KV cache + bf16 serving
  weights (`GenEngine.release_memory`) — so the trainer's step fits.
- Weight publish is an IN-MEMORY handoff: the trainer's exported host tree
  goes straight into `GenEngine.restage` — no disk snapshot, no chunk
  streaming, no HTTP in the pause window at all.

Workflows run unmodified: `ColocatedEngine` implements the
agenerate/rollout_batch surface of the InferenceEngine API (api/engine.py)
with the same interruption-resume contract as the remote client
(accumulated tokens resubmitted on abort, core/remote.py:428-478
counterpart).
"""

import asyncio
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from areal_tpu.api.io_struct import ModelRequest, ModelResponse
from areal_tpu.gen.engine import GenEngine, GenRequest
from areal_tpu.utils import logging
from areal_tpu.utils.data import concat_padded_tensors

logger = logging.getLogger("colocated")


class ColocatedEngine:
    """Time-shared serving facade over an in-process GenEngine."""

    def __init__(self, model_config, params=None, model_path=None, **gen_kwargs):
        self.engine = GenEngine(
            model_config, params=params, model_path=model_path, **gen_kwargs
        )
        self._stop = threading.Event()
        self._stepper: Optional[threading.Thread] = None
        self._serving = False

    # ----------------------------- lifecycle ---------------------------

    def start_serving(self) -> None:
        if self._serving:
            return
        if self._stepper is not None and self._stepper.is_alive():
            # a previous stop_serving timed out and left its thread wedged
            # in step(); spawning a second stepper would race it on the
            # non-thread-safe engine
            raise RuntimeError(
                "previous serving stepper is still wedged in a decode "
                "step; cannot start a second one"
            )
        self._stop.clear()

        def _loop():
            while not self._stop.is_set():
                try:
                    if self.engine.active_count():
                        self.engine.step()
                    else:
                        time.sleep(0.001)
                except Exception:  # noqa: BLE001 — stepper must survive
                    logger.exception("decode step failed; stepper continues")
                    time.sleep(0.1)

        self._stepper = threading.Thread(target=_loop, daemon=True)
        self._stepper.start()
        self._serving = True

    def stop_serving(self) -> None:
        if not self._serving and not (
            self._stepper is not None and self._stepper.is_alive()
        ):
            # a wedged stepper left behind by a timed-out stop (below) must
            # still be waited out here, or callers proceed to mutate the
            # engine under the live thread the guard exists to prevent
            return
        self._stop.set()
        if self._stepper is not None:
            # the stepper MUST be parked before callers mutate engine state
            # (weight swap, HBM release): proceeding while a step() is
            # wedged — e.g. a first XLA compile of a new decode bucket —
            # would race the swap and let start_serving spawn a SECOND
            # thread into the non-thread-safe engine.  Wait as long as it
            # takes, loudly; only a dead-for-minutes step is fatal.
            deadline = time.monotonic() + 600
            while self._stepper.is_alive():
                self._stepper.join(timeout=30)
                if self._stepper.is_alive():
                    if time.monotonic() > deadline:
                        # truthful state for whoever catches this: we are
                        # not serving; _stepper stays set so start_serving
                        # refuses to spawn a second thread beside it
                        self._serving = False
                        raise RuntimeError(
                            "serving stepper failed to park within 600s; "
                            "refusing to mutate engine state under a live "
                            "decode thread"
                        )
                    logger.warning("waiting for in-flight decode step to "
                                   "finish before parking the stepper")
        self._stepper = None
        self._serving = False

    def train_phase(self, drop_params: bool = True):
        """Context manager bracketing a train step: serving paused and its
        HBM released on entry.  With the default `drop_params=True` the
        serving weights are freed too and re-arming REQUIRES
        `publish_weights(host_params, version)`; pass `drop_params=False`
        (cache-only release, the trainer's step must still fit) to allow a
        same-weights `resume_serving()` afterwards."""
        outer = self

        class _Phase:
            def __enter__(self):
                outer.stop_serving()
                outer.engine.release_memory(drop_params=drop_params)
                return outer

            def __exit__(self, *exc):
                return False

        return _Phase()

    def publish_weights(self, host_params, version: Optional[int] = None) -> None:
        """In-memory weight handoff (the colocated pause-window publish)."""
        self.engine.restage(params=host_params, version=version)
        self.start_serving()

    def update_weights_in_memory(self, host_params, version: int,
                                 interrupt: bool = False) -> float:
        """Publish WITHOUT releasing serving HBM (both sides resident —
        the async colocated regime): park the stepper between decode
        chunks, swap weights, restart.  Returns the achieved
        generation-idle window in seconds.

        Default is the LIVE swap (`GenEngine.swap_weights_live`): in-flight
        requests keep slots + KV and keep decoding under the new policy,
        per-token versions recording the transition — no abort, no
        re-prefill.  `interrupt=True` keeps the abort-and-resume
        choreography (the remote fleet's contract) for A/B measurement."""
        self.stop_serving()
        t0 = time.perf_counter()
        if interrupt:
            self.engine.load_weights(params=host_params, version=version)
        else:
            self.engine.swap_weights_live(host_params, version=version)
        pause = time.perf_counter() - t0
        self.start_serving()
        return pause

    def resume_serving(self) -> None:
        """Re-arm with the SAME weights (cache-only restage)."""
        self.engine.restage()
        self.start_serving()

    def destroy(self) -> None:
        self.stop_serving()
        self.engine.abort_all("abort")

    # ----------------------------- serving -----------------------------

    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        """Generate with the remote client's interruption contract: an
        abort (weight update / release) resubmits accumulated tokens."""
        if not self._serving:
            if self.engine.cache is not None:
                self.start_serving()
            else:
                # train phase in progress (engine released): wait for the
                # publish instead of stepping a cache-less engine
                while not self._serving:
                    await asyncio.sleep(0.01)
        g = req.gconfig
        accumulated: List[int] = []
        logprobs: List[float] = []
        versions: List[int] = []
        input_ids = list(req.input_ids)
        t0 = time.perf_counter()
        first_token_ts: Optional[float] = None
        while True:
            loop = asyncio.get_running_loop()
            fut: asyncio.Future = loop.create_future()

            def _done(gr: GenRequest, fut=fut, loop=loop):
                try:
                    loop.call_soon_threadsafe(
                        lambda: fut.done() or fut.set_result(gr)
                    )
                except RuntimeError:
                    # the caller's event loop is gone (teardown abort of a
                    # request whose client already left) — nothing to wake
                    pass

            budget = g.max_new_tokens - len(accumulated)
            gr = GenRequest(
                rid=req.rid,
                group_id=req.group_id,
                group_n=req.group_n,
                input_ids=input_ids + accumulated,
                max_new_tokens=budget,
                min_new_tokens=min(g.min_new_tokens, budget),
                temperature=0.0 if g.greedy else g.temperature,
                top_p=g.top_p,
                top_k=g.top_k,
                stop_token_ids=list(g.stop_token_ids),
                on_done=_done,
            )
            self.engine.submit(gr)
            gr = await fut
            if first_token_ts is None and gr.first_token_ts > 0.0:
                first_token_ts = gr.first_token_ts
            accumulated.extend(gr.output_tokens)
            logprobs.extend(gr.output_logprobs)
            versions.extend(gr.output_versions)
            if gr.stop_reason != "abort":
                break
            while not self._serving:  # train phase in progress
                await asyncio.sleep(0.01)
        return ModelResponse(
            input_tokens=list(req.input_ids),
            output_tokens=accumulated,
            output_logprobs=logprobs,
            output_versions=versions,
            stop_reason=gr.stop_reason,
            tokenizer=req.tokenizer,
            latency=time.perf_counter() - t0,
            ttft=(first_token_ts - t0 if first_token_ts is not None
                  else float("inf")),
        )

    def rollout_batch(
        self,
        data: List[Dict[str, Any]],
        workflow=None,
        workflow_builder: Optional[Callable] = None,
        should_accept: Optional[Callable] = None,
    ) -> Dict[str, Any]:
        """Run one episode per item concurrently against the in-process
        engine and concat the results (sync colocated loop: rollouts and
        train steps alternate, they never overlap)."""
        self.start_serving()

        async def _run():
            wfs = [
                workflow if workflow is not None else workflow_builder()
                for _ in data
            ]
            return await asyncio.gather(
                *[wf.arun_episode(self, item) for wf, item in zip(wfs, data)]
            )

        results = [r for r in asyncio.run(_run()) if r is not None]
        if should_accept is not None:
            results = [r for r in results if should_accept(r)]
        if not results:
            raise RuntimeError("colocated rollout produced no trajectories")
        return concat_padded_tensors(results)

    def get_version(self) -> int:
        return self.engine.version
