"""Agent abstraction + workflow adapter.

Capability counterpart of the reference's agent layer
(realhf/api/core/agent_api.py:15 `Agent.collect_trajectory` + registry;
driven by RolloutWorker, realhf/system/rollout_worker.py:204).  TPU-first
difference: instead of a dedicated worker process wired through ZMQ queues,
`AgentWorkflow` adapts any (agent, environment) pair to the asyncio
RolloutWorkflow surface, so agentic episodes run on the same
WorkflowExecutor/staleness machinery as plain RLVR rollouts.
"""

import abc
from typing import Any, Callable, Dict, List, Optional

from areal_tpu.api.env import Environment
from areal_tpu.api.workflow import RolloutWorkflow
from areal_tpu.utils.data import pad_sequences_to_tensors


class Agent(abc.ABC):
    """Collects one episode's trajectories against an environment."""

    @abc.abstractmethod
    async def collect_trajectory(
        self,
        engine,
        env: Optional[Environment],
        data: Dict[str, Any],
    ) -> List[Dict[str, Any]]:
        """Returns a list of trajectory dicts (input_ids/logprobs/loss_mask/
        versions arrays + scalar rewards), one per sample."""


_REGISTRY: Dict[str, type] = {}


def register_agent(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls

    return deco


def make_agent(name: str, **kwargs) -> Agent:
    if name not in _REGISTRY:
        raise ValueError(f"unknown agent {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


class AgentWorkflow(RolloutWorkflow):
    """(agent, env factory) -> RolloutWorkflow: each episode opens a fresh
    environment, lets the agent collect trajectories, and emits the padded
    batch the executor expects."""

    def __init__(self, agent: Agent, env_factory: Optional[Callable] = None):
        self.agent = agent
        self.env_factory = env_factory
        self._factory_takes_data: Optional[bool] = None

    def _make_env(self, data: Dict[str, Any]):
        """Factories may take the episode's data (per-episode ground truth,
        e.g. `lambda data: MathVerifyEnv(answer=data['answer'])`) or
        nothing.  Only REQUIRED positional parameters make a factory
        data-taking — `partial(Env, answer='7')` or `lambda seed=0: Env()`
        must keep their zero-arg call."""
        if self._factory_takes_data is None:
            import inspect

            try:
                sig = inspect.signature(self.env_factory)
                required = [
                    p
                    for p in sig.parameters.values()
                    if p.default is inspect.Parameter.empty
                    and p.kind
                    in (
                        inspect.Parameter.POSITIONAL_ONLY,
                        inspect.Parameter.POSITIONAL_OR_KEYWORD,
                    )
                ]
                self._factory_takes_data = len(required) >= 1
            except (TypeError, ValueError):
                self._factory_takes_data = False
        if self._factory_takes_data:
            return self.env_factory(data)
        return self.env_factory()

    async def arun_episode(self, engine, data: Dict[str, Any]):
        if self.env_factory is not None:
            async with self._make_env(data) as env:
                trajs = await self.agent.collect_trajectory(engine, env, data)
        else:
            trajs = await self.agent.collect_trajectory(engine, None, data)
        if not trajs:
            return None  # rejected episode (executor drops it)
        return pad_sequences_to_tensors(trajs)
