"""Fused LM-head cross-entropy parity (VERDICT r3 #2).

The fused vocab-chunked online-softmax head (ops/fused_xent.py) must match
the dense gather_logprobs_entropy numerics — values AND gradients — since
the GRPO/SFT losses train through it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.ops.fused_xent import _vocab_chunk, fused_logprobs_entropy
from areal_tpu.ops.functional import gather_logprobs_entropy, lm_logprobs_entropy


def _dense(h, w, labels, inv_t=1.0):
    logits = (h @ w).astype(jnp.float32) * inv_t
    logp, ent = gather_logprobs_entropy(logits, labels)
    corr = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    return logp, ent, corr


def _rand(n=48, d=16, v=96, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(size=(n, d)), dtype)
    w = jnp.asarray(rng.normal(0, 0.3, size=(d, v)), dtype)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)
    return h, w, labels


def test_vocab_chunk_mxu_aligned():
    # chunks are 128-multiples (MXU lane width); the padded tail is masked
    assert _vocab_chunk(151936, 8192) == 8192  # qwen2.5: 18 full + 1 partial
    assert _vocab_chunk(96, 32) == 128  # small vocabs pad up to one chunk
    assert _vocab_chunk(7, 100) == 128
    assert _vocab_chunk(151936, 8192) % 128 == 0


@pytest.mark.parametrize("v,chunk", [(96, 32), (96, 96), (90, 32), (7, 4)])
def test_forward_parity(v, chunk):
    h, w, labels = _rand(v=v)
    lp0, ent0, corr0 = _dense(h, w, labels)
    lp1, ent1, corr1 = fused_logprobs_entropy(h, w, labels, vocab_chunk=chunk)
    np.testing.assert_allclose(np.asarray(lp1), np.asarray(lp0), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ent1), np.asarray(ent0), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(corr1), np.asarray(corr0))


def test_forward_parity_temperature():
    h, w, labels = _rand(seed=1)
    lp0, ent0, _ = _dense(h, w, labels, inv_t=1.0 / 0.7)
    lp1, ent1, _ = fused_logprobs_entropy(
        h, w, labels, temperature=0.7, vocab_chunk=32
    )
    np.testing.assert_allclose(np.asarray(lp1), np.asarray(lp0), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ent1), np.asarray(ent0), rtol=1e-5, atol=1e-5)


def test_grad_parity_with_entropy():
    h, w, labels = _rand(seed=2)
    rng = np.random.default_rng(3)
    g1 = jnp.asarray(rng.normal(size=h.shape[0]), jnp.float32)
    g2 = jnp.asarray(rng.normal(size=h.shape[0]), jnp.float32)

    def loss_dense(h, w):
        lp, ent, _ = _dense(h, w, labels)
        return jnp.sum(g1 * lp) + jnp.sum(g2 * ent)

    def loss_fused(h, w):
        lp, ent, _ = fused_logprobs_entropy(
            h, w, labels, vocab_chunk=32, entropy_grad=True
        )
        return jnp.sum(g1 * lp) + jnp.sum(g2 * ent)

    dh0, dw0 = jax.grad(loss_dense, argnums=(0, 1))(h, w)
    dh1, dw1 = jax.grad(loss_fused, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(dh1), np.asarray(dh0), rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw1), np.asarray(dw0), rtol=2e-4, atol=1e-5)


def test_entropy_grad_off_is_stop_gradient():
    """entropy_grad=False: logp still trains, entropy behaves like
    stop_gradient(ent) — the GRPO stats-only case."""
    h, w, labels = _rand(seed=4)

    def loss_dense(h, w):
        lp, ent, _ = _dense(h, w, labels)
        return jnp.sum(lp) + jnp.sum(jax.lax.stop_gradient(ent))

    def loss_fused(h, w):
        lp, ent, _ = fused_logprobs_entropy(
            h, w, labels, vocab_chunk=32, entropy_grad=False
        )
        return jnp.sum(lp) + jnp.sum(ent)

    dh0, dw0 = jax.grad(loss_dense, argnums=(0, 1))(h, w)
    dh1, dw1 = jax.grad(loss_fused, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(dh1), np.asarray(dh0), rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw1), np.asarray(dw0), rtol=2e-4, atol=1e-5)


def test_bf16_inputs_close_to_fp32():
    h, w, labels = _rand(seed=5)
    lp0, ent0, _ = fused_logprobs_entropy(h, w, labels, vocab_chunk=32)
    lp1, ent1, _ = fused_logprobs_entropy(
        h.astype(jnp.bfloat16), w.astype(jnp.bfloat16), labels, vocab_chunk=32
    )
    np.testing.assert_allclose(np.asarray(lp1), np.asarray(lp0), rtol=0.05, atol=0.05)
    np.testing.assert_allclose(np.asarray(ent1), np.asarray(ent0), rtol=0.05, atol=0.05)


def test_lm_logprobs_entropy_fused_matches_chunked():
    """The LMOutput entry point: fused (default) and chunked (legacy) impls
    agree on values and gradients."""
    from areal_tpu.models.transformer import LMOutput

    h, w, labels = _rand(n=24, seed=6)
    labels2d = labels.reshape(2, 12)
    out = LMOutput(hidden=h.reshape(2, 12, -1), head=w, aux_loss=None)

    r_f = lm_logprobs_entropy(out, labels2d, impl="fused")
    r_c = lm_logprobs_entropy(out, labels2d, impl="chunked", chunk=8)
    for a, b in zip(r_f, r_c):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)

    def loss(hidden, head, impl):
        o = LMOutput(hidden=hidden, head=head, aux_loss=None)
        lp, ent, _ = lm_logprobs_entropy(o, labels2d, impl=impl, chunk=8)
        return jnp.sum(lp) + 0.3 * jnp.sum(ent)

    gf = jax.grad(loss, argnums=(0, 1))(out.hidden, w, "fused")
    gc = jax.grad(loss, argnums=(0, 1))(out.hidden, w, "chunked")
    for a, b in zip(gf, gc):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)


def test_grpo_loss_through_fused_head():
    """End to end: grpo_loss_fn over an LMOutput yields finite loss and
    gradients via the fused head."""
    from areal_tpu.models.transformer import LMOutput
    from areal_tpu.ops.functional import grpo_loss_fn

    h, w, labels = _rand(n=32, seed=7)
    rng = np.random.default_rng(8)
    T = 32
    batch = {
        "input_ids": jnp.asarray(rng.integers(0, 96, T), jnp.int32)[None],
        "loss_mask": jnp.ones((1, T), jnp.float32),
        "logprobs": jnp.asarray(rng.normal(-1, 0.1, T), jnp.float32)[None],
        "advantages": jnp.asarray(rng.normal(size=T), jnp.float32)[None],
        "prox_logp": jnp.asarray(rng.normal(-1, 0.1, T), jnp.float32)[None],
    }

    def loss(hidden, head):
        out = LMOutput(hidden=hidden, head=head, aux_loss=None)
        l, _ = grpo_loss_fn(out, batch, eps_clip=0.2)
        return l

    val, (dh, dw) = jax.value_and_grad(loss, argnums=(0, 1))(
        h.reshape(1, T, -1), w
    )
    assert np.isfinite(float(val))
    assert np.all(np.isfinite(np.asarray(dh)))
    assert np.all(np.isfinite(np.asarray(dw)))
    assert float(jnp.abs(dw).sum()) > 0


def test_vocab_chunk_knob_plumbs_through_lm_logprobs_entropy():
    """The plumbed `vocab_chunk` knob (TrainEngineConfig.lm_head_chunk ->
    loss partials -> here) must agree with the dense reference at widths
    that do NOT divide the vocab: the final chunk's padded tail is masked,
    never counted (ISSUE 20 satellite)."""
    from areal_tpu.models.transformer import LMOutput

    v = 300  # 3 chunks of 128 with a 84-wide padded tail
    h, w, labels = _rand(n=24, v=v, seed=9)
    labels2d = labels.reshape(2, 12)
    out = LMOutput(hidden=h.reshape(2, 12, -1), head=w, aux_loss=None)
    lp0, ent0, corr0 = _dense(h, w, labels)
    for chunk in (128, 256, 512):  # dividing and non-dividing widths
        lp1, ent1, corr1 = lm_logprobs_entropy(
            out, labels2d, impl="fused", vocab_chunk=chunk
        )
        np.testing.assert_allclose(
            np.asarray(lp1).ravel(), np.asarray(lp0), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(ent1).ravel(), np.asarray(ent0), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_array_equal(
            np.asarray(corr1).ravel(), np.asarray(corr0)
        )


def test_grpo_loss_fn_vocab_chunk_is_scheduling_only():
    """grpo_loss_fn(vocab_chunk=...) values/grads are chunk-width
    invariant — the bench ladder's sweep can't change the optimisation."""
    from areal_tpu.models.transformer import LMOutput
    from areal_tpu.ops.functional import grpo_loss_fn

    h, w, labels = _rand(n=32, v=300, seed=10)
    rng = np.random.default_rng(11)
    T = 32
    batch = {
        "input_ids": jnp.asarray(rng.integers(0, 300, T), jnp.int32)[None],
        "loss_mask": jnp.ones((1, T), jnp.float32),
        "logprobs": jnp.asarray(rng.normal(-1, 0.1, T), jnp.float32)[None],
        "advantages": jnp.asarray(rng.normal(size=T), jnp.float32)[None],
        "prox_logp": jnp.asarray(rng.normal(-1, 0.1, T), jnp.float32)[None],
    }

    def loss(hidden, head, chunk):
        out = LMOutput(hidden=hidden, head=head, aux_loss=None)
        l, _ = grpo_loss_fn(out, batch, eps_clip=0.2, vocab_chunk=chunk)
        return l

    vals, grads = [], []
    for chunk in (None, 128, 256):
        val, g = jax.value_and_grad(loss, argnums=(0, 1))(
            h.reshape(1, T, -1), w, chunk
        )
        vals.append(float(val))
        grads.append(g)
    np.testing.assert_allclose(vals[1:], vals[0], rtol=1e-6)
    for dh, dw in grads[1:]:
        np.testing.assert_allclose(np.asarray(dh), np.asarray(grads[0][0]),
                                   rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(grads[0][1]),
                                   rtol=2e-4, atol=1e-6)
