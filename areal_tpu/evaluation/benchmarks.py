"""Benchmark registry for the offline eval harness.

Counterpart of the reference's benchmark suite
(/root/reference/evaluation/: data/{aime24,aime25,amc23,math_500,
gpqa_diamond}/test.jsonl + per-model prompt templates in utils.py).  This
repo resolves benchmark data from a data root rather than vendoring the
problem sets (keep the repo code-only; `scripts/fetch_eval_data.py`
populates the root from public dataset hubs, or point AREAL_EVAL_DATA at
an existing checkout of the reference's `evaluation/data/`).

Prompting goes through the checkpoint's own chat template
(`tokenizer.apply_chat_template`) with the standard boxed-answer
instruction — the template-per-model tables the reference maintains
(utils.py PROMPT_TEMPLATES) exist because it renders raw strings per
architecture; rendering through the tokenizer makes one instruction work
for every model family this repo serves.
"""

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional

BOXED_INSTRUCTION = (
    "Please reason step by step, and put your final answer within \\boxed{}."
)
CHOICE_INSTRUCTION = (
    "Please reason step by step, and put the letter of your chosen option "
    "within \\boxed{} at the end."
)


@dataclass(frozen=True)
class BenchmarkSpec:
    name: str
    file: str  # relative to the data root
    question_field: str
    answer_field: str
    instruction: str = BOXED_INSTRUCTION
    # multiple-choice benchmarks render labeled options under the question
    options_field: Optional[str] = None
    # schema-level fallback for exports predating question_field (options
    # already embedded there); benchmarks without one keep a loud KeyError
    legacy_question_field: Optional[str] = None


BENCHMARKS: Dict[str, BenchmarkSpec] = {
    s.name: s
    for s in [
        BenchmarkSpec("aime24", "aime24/test.jsonl", "problem", "answer"),
        BenchmarkSpec("aime25", "aime25/test.jsonl", "problem", "answer"),
        BenchmarkSpec("amc23", "amc23/test.jsonl", "problem", "answer"),
        BenchmarkSpec("math_500", "math_500/test.jsonl", "problem", "answer"),
        BenchmarkSpec(
            "gpqa_diamond",
            "gpqa_diamond/test.jsonl",
            # the dataset's 'question' field already embeds the lettered
            # options; build from the raw question + labeled_options so the
            # options appear exactly once (every row carries both fields)
            "ori_question",
            "answer",
            instruction=CHOICE_INSTRUCTION,
            options_field="labeled_options",
            legacy_question_field="question",
        ),
    ]
}


def resolve_data_root(data_root: Optional[str] = None) -> str:
    """--data-root arg > AREAL_EVAL_DATA env > <repo>/evaluation/data."""
    if data_root:
        return data_root
    env = os.environ.get("AREAL_EVAL_DATA")
    if env:
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    return os.path.join(here, "evaluation", "data")


def load_benchmark(
    name: str, data_root: Optional[str] = None, limit: Optional[int] = None
) -> List[Dict]:
    """-> [{"messages": [...], "answer": str}, ...] ready for the engine."""
    spec = BENCHMARKS.get(name)
    if spec is None:
        raise KeyError(
            f"unknown benchmark {name!r}; known: {sorted(BENCHMARKS)}"
        )
    path = os.path.join(resolve_data_root(data_root), spec.file)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"benchmark data not found at {path}; run "
            f"scripts/fetch_eval_data.py or set AREAL_EVAL_DATA"
        )
    problems = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            legacy = (
                spec.legacy_question_field is not None
                and spec.question_field not in row
            )
            q = (
                row[spec.legacy_question_field]
                if legacy
                else row[spec.question_field]
            )
            if spec.options_field and spec.options_field in row:
                opts = row[spec.options_field]
                if isinstance(opts, str):
                    if opts.startswith("["):
                        # python-repr list (the reference's gpqa rows);
                        # literal_eval survives apostrophes inside options
                        import ast

                        opts = ast.literal_eval(opts)
                    else:
                        opts = [opts]
                # the embedded-already check applies ONLY to the legacy
                # shape, and skips appending only when EVERY option is
                # present verbatim: an ori_question that merely quotes one
                # option, or a legacy row with reformatted embeddings,
                # still gets the full canonical list appended
                if opts and (
                    not legacy or not all(str(o) in q for o in opts)
                ):
                    q = q + "\n" + "\n".join(str(o) for o in opts)
            problems.append(
                {
                    "messages": [
                        {"role": "user", "content": f"{q}\n{spec.instruction}"}
                    ],
                    "answer": str(row[spec.answer_field]),
                }
            )
            if limit and len(problems) >= limit:
                break
    if not problems:
        raise ValueError(f"no problems in {path}")
    return problems
