"""StalenessManager tests — parity with reference test_staleness_manager.py
(the capacity formula at staleness_manager.py:96 is the contract)."""

import threading

from areal_tpu.core.staleness import StalenessManager


def test_concurrency_cap():
    m = StalenessManager(max_concurrent_rollouts=4, consumer_batch_size=100,
                         max_staleness=100)
    assert m.get_capacity(0) == 4
    for _ in range(4):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    m.on_rollout_accepted()
    assert m.get_capacity(0) == 1


def test_staleness_limit_zero():
    # η=0: at version v, total samples allowed = (v+1)*B
    B = 4
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=B,
                         max_staleness=0)
    assert m.get_capacity(0) == B
    for _ in range(B):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    # accepting does not free budget at the same version
    for _ in range(B):
        m.on_rollout_accepted()
    assert m.get_capacity(0) == 0
    # version bump frees exactly one more batch
    assert m.get_capacity(1) == B


def test_staleness_limit_eta():
    B, eta = 2, 3
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=B,
                         max_staleness=eta)
    assert m.get_capacity(0) == (eta + 1) * B
    for _ in range((eta + 1) * B):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    assert m.get_capacity(2) == 2 * B


def test_rejected_rollouts_free_capacity():
    m = StalenessManager(max_concurrent_rollouts=10, consumer_batch_size=2,
                         max_staleness=0)
    m.on_rollout_submitted()
    m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    m.on_rollout_rejected()
    # rejected sample no longer counts against staleness budget
    assert m.get_capacity(0) == 1


def test_negative_capacity():
    m = StalenessManager(max_concurrent_rollouts=2, consumer_batch_size=1,
                         max_staleness=0)
    for _ in range(2):
        m.on_rollout_submitted()
    # staleness budget of 1 sample, 2 running -> negative
    assert m.get_capacity(0) < 0


def test_min_clamps():
    m = StalenessManager(max_concurrent_rollouts=0, consumer_batch_size=0,
                         max_staleness=0)
    # clamped to 1 concurrent & batch size 1
    assert m.get_capacity(0) == 1


def test_thread_safety():
    m = StalenessManager(max_concurrent_rollouts=10**6,
                         consumer_batch_size=10**6, max_staleness=10)
    n, iters = 8, 500

    def work():
        for _ in range(iters):
            m.on_rollout_submitted()
            m.on_rollout_accepted()

    threads = [threading.Thread(target=work) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = m.get_stats()
    assert s.submitted == n * iters
    assert s.accepted == n * iters
    assert s.running == 0


def test_ledger_invariant_catches_unbalanced_settle():
    """ISSUE 11 satellite: every transition self-checks
    submitted == accepted + rejected + running.  A double-settle (the
    capacity-leak bug class that death paths can introduce) must fail
    loudly AT the broken transition, not wedge admission much later."""
    import pytest

    m = StalenessManager(max_concurrent_rollouts=4, consumer_batch_size=2,
                         max_staleness=0)
    m.on_rollout_submitted()
    m.on_rollout_accepted()
    with pytest.raises(RuntimeError, match="staleness ledger violated"):
        m.on_rollout_accepted()  # settling the same rollout twice


def test_mid_flight_kill_settles_capacity():
    """Regression (ISSUE 11): a backend killed mid-trajectory with the
    failover budget exhausted must settle the staleness ledger through
    the reject path — running returns to 0, the loss is counted, and
    admission capacity fully recovers (no leaked slot)."""
    import threading
    import time as _time

    import pytest

    from areal_tpu.api.config import (
        GenerationHyperparameters,
        InferenceEngineConfig,
    )
    from areal_tpu.engine.jax_remote import RemoteJaxEngine
    from areal_tpu.workflow.rlvr import RLVRWorkflow
    from tests.fake_server import FakeGenServer

    server = FakeGenServer(completion=list(range(100, 106)), chunk_size=2)
    server.delay_s = 0.05
    addr = server.start()
    cfg = InferenceEngineConfig(
        experiment_name="e", trial_name="t", consumer_batch_size=2,
        max_concurrent_rollouts=4, max_head_offpolicyness=0,
        request_timeout=5, request_retries=1, failover_retries=1,
    )
    eng = RemoteJaxEngine(cfg)
    eng.initialize(addr=addr)

    def _assassin():
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline and not server.requests:
            _time.sleep(0.005)
        server.stop()

    killer = threading.Thread(target=_assassin)
    killer.start()
    try:
        wf = RLVRWorkflow(
            reward_fn=lambda *a, **k: 0.0,
            gconfig=GenerationHyperparameters(max_new_tokens=16),
        )
        mgr = eng.executor.staleness_manager
        cap0 = mgr.get_capacity(0)
        eng.submit({"input_ids": [1, 2]}, workflow=wf)
        with pytest.raises(TimeoutError):
            eng.wait(1, timeout=5)  # the lone trajectory is lost, not batched
        killer.join(timeout=10)
        assert eng.executor.lost_trajectories == 1
        stats = mgr.get_stats()
        assert stats.submitted == 1
        assert stats.rejected == 1
        assert stats.running == 0
        assert mgr.get_capacity(0) == cap0  # no leaked admission slot
    finally:
        eng.destroy()
