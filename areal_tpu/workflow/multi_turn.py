"""Multi-turn retry-until-correct workflow.

Behavioral counterpart of the reference's `MultiTurnWorkflow`
(areal/workflow/multi_turn.py:22): keep asking the model to try again with an
amended feedback prompt until the reward function accepts or the turn budget
is exhausted; earlier turns' rewards are discounted.
"""

import asyncio
import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api.config import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.api.reward import AsyncRewardWrapper
from areal_tpu.api.workflow import RolloutWorkflow
from areal_tpu.utils.data import pad_sequences_to_tensors

DEFAULT_FEEDBACK = (
    "\nYour answer is either wrong or not parsable. "
    "Please try to answer it again."
)


class MultiTurnWorkflow(RolloutWorkflow):
    def __init__(
        self,
        reward_fn: Callable[..., float],
        gconfig: GenerationHyperparameters,
        tokenizer,
        max_turns: int = 3,
        turn_discount: float = 0.9,
        feedback_text: str = DEFAULT_FEEDBACK,
    ):
        self.reward_fn = AsyncRewardWrapper(reward_fn)
        self.gconfig = gconfig.new(n_samples=1)
        self.tokenizer = tokenizer
        self.max_turns = max_turns
        self.turn_discount = turn_discount
        self.feedback_text = feedback_text

    async def arun_episode(self, engine, data: Dict[str, Any]):
        if "messages" in data:
            input_ids = self.tokenizer.apply_chat_template(
                data["messages"], add_generation_prompt=True, tokenize=True
            )
        else:
            input_ids = list(data["input_ids"])
        seq: List[int] = list(input_ids)
        logprobs: List[float] = [0.0] * len(input_ids)
        loss_mask: List[int] = [0] * len(input_ids)
        versions: List[int] = [-1] * len(input_ids)
        reward, discount = 0.0, 1.0
        for turn in range(self.max_turns):
            req = ModelRequest(
                rid=str(uuid.uuid4()),
                input_ids=seq,
                gconfig=self.gconfig,
                tokenizer=self.tokenizer,
            )
            resp = await engine.agenerate(req)
            seq = seq + resp.output_tokens
            logprobs += resp.output_logprobs
            loss_mask += [1] * resp.output_len
            versions += resp.output_versions
            completion_str = self.tokenizer.decode(resp.output_tokens)
            prompt_str = self.tokenizer.decode(input_ids)
            reward = await self.reward_fn(
                prompt_str, completion_str, resp.input_tokens, resp.output_tokens,
                **data,
            )
            if reward > 0 or turn == self.max_turns - 1:
                break
            # wrong answer: append feedback (not trained on) and retry
            feedback_ids = self.tokenizer.encode(
                self.feedback_text, add_special_tokens=False
            )
            seq += feedback_ids
            logprobs += [0.0] * len(feedback_ids)
            loss_mask += [0] * len(feedback_ids)
            versions += [-1] * len(feedback_ids)
            discount *= self.turn_discount
        return pad_sequences_to_tensors(
            [
                dict(
                    input_ids=np.array(seq, dtype=np.int32),
                    logprobs=np.array(logprobs, dtype=np.float32),
                    loss_mask=np.array(loss_mask, dtype=np.int32),
                    versions=np.array(versions, dtype=np.int32),
                    rewards=np.float32(reward * discount),
                )
            ]
        )
