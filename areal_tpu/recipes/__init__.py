from areal_tpu.recipes.aent import AEntConfig, AEntPPOActorConfig, JaxAEntPPOActor

__all__ = ["AEntConfig", "AEntPPOActorConfig", "JaxAEntPPOActor"]
