"""Generic JSONL prompt dataset: each line {prompt|messages, answer?, ...}."""

import json
from typing import Optional

from areal_tpu.dataset import register_dataset


@register_dataset("jsonl")
def load_jsonl(
    path: str,
    split: str = "train",
    tokenizer=None,
    max_length: Optional[int] = None,
    **kwargs,
):
    rows = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            x = json.loads(line)
            x.setdefault("query_id", str(i))
            rows.append(x)
    if max_length is not None and tokenizer is not None:
        rows = [
            x
            for x in rows
            if len(
                tokenizer.apply_chat_template(
                    x["messages"], add_generation_prompt=True, tokenize=True
                )
                if "messages" in x
                else tokenizer.encode(x["prompt"])
            )
            <= max_length
        ]
    return rows
