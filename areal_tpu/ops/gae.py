"""Generalised Advantage Estimation over padded and packed sequences.

TPU-native counterpart of the reference's CUDA `cugae` kernel
(csrc/cugae/gae.cu:10-60 `gae_1d_nolp_misalign`) and lite's python GAE loop
(areal/engine/ppo/actor.py:136-151).  Instead of a hand-written backward CUDA
kernel, a single reverse `jax.lax.scan` runs the recurrence

    adv[t] = delta[t] + gamma * lam * (not boundary[t]) * adv[t+1]
    delta[t] = r[t] + gamma * V[t+1] * (not boundary[t]) - V[t]

across the whole (packed) buffer at once; sequence boundaries reset the
carry, which is exactly the cu_seqlens-misalignment handling of the CUDA
kernel, but shape-static and fusable by XLA.
"""
# areal-lint: hot-path

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def gae_padded(
    rewards: jax.Array,  # [B, L]
    values: jax.Array,  # [B, L]
    mask: jax.Array,  # [B, L] loss mask; holes allowed (multi-turn)
    gamma: float,
    lam: float,
) -> Tuple[jax.Array, jax.Array]:
    """GAE over [B, L] batches; bootstrap value after the last masked token
    is 0 (terminal).  Returns (advantages, returns) masked to 0 off-mask.

    Positions with mask 0 — trailing padding *and* interior holes such as
    multi-turn user tokens — are skipped exactly as the reference does
    (areal/engine/ppo/actor.py:146-151): the accumulated lastgaelam and the
    bootstrap value are frozen across them, so the recurrence connects each
    loss token directly to the next loss token with a single gamma*lam step.
    """
    mask = mask.astype(jnp.float32)
    rewards = rewards.astype(jnp.float32) * mask
    values = values.astype(jnp.float32) * mask
    B = rewards.shape[0]

    def step(carry, xs):
        lastgaelam, nextvalues = carry
        r, v, m = xs
        delta = r + gamma * nextvalues - v
        newgaelam = delta + gamma * lam * lastgaelam
        lastgaelam = m * newgaelam + (1.0 - m) * lastgaelam
        nextvalues = m * v + (1.0 - m) * nextvalues
        return (lastgaelam, nextvalues), lastgaelam

    # reverse scan over time, batched over B via transpose
    init = (jnp.zeros(B, jnp.float32), jnp.zeros(B, jnp.float32))
    _, adv_rev = jax.lax.scan(
        step, init, (rewards.T[::-1], values.T[::-1], mask.T[::-1])
    )
    adv = adv_rev[::-1].T * mask
    returns = adv + values
    return adv, returns * mask


def gae_segments(
    rewards: jax.Array,  # [T] packed
    values: jax.Array,  # [T]
    segment_ids: jax.Array,  # [T], -1 on filler
    gamma: float,
    lam: float,
    loss_mask: Optional[jax.Array] = None,  # [T]; holes allowed
) -> Tuple[jax.Array, jax.Array]:
    """GAE over a packed flat buffer; boundaries where segment id changes.

    Equivalent to cugae's `gae_1d_nolp_misalign` with per-sequence terminal
    bootstrap 0 (RLVR episodes end at the final token).  `loss_mask` holes
    inside a segment freeze the carry exactly as in `gae_padded`.
    """
    valid = segment_ids >= 0
    m = valid.astype(jnp.float32)
    if loss_mask is not None:
        m = m * loss_mask.astype(jnp.float32)
    rewards = rewards.astype(jnp.float32) * m
    values = values.astype(jnp.float32) * m
    # carry resets (to 0) at segment boundaries, scanning in reverse:
    # position t is a boundary start if segment_ids[t] != segment_ids[t+1]
    nxt_same = jnp.concatenate(
        [(segment_ids[1:] == segment_ids[:-1]) & valid[1:], jnp.zeros((1,), bool)]
    ).astype(jnp.float32)

    def step(carry, xs):
        lastgaelam, nextvalues = carry
        r, v, mm, same = xs
        lastgaelam = lastgaelam * same
        nextvalues = nextvalues * same
        delta = r + gamma * nextvalues - v
        newgaelam = delta + gamma * lam * lastgaelam
        lastgaelam = mm * newgaelam + (1.0 - mm) * lastgaelam
        nextvalues = mm * v + (1.0 - mm) * nextvalues
        return (lastgaelam, nextvalues), lastgaelam

    init = (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
    _, adv_rev = jax.lax.scan(
        step, init, (rewards[::-1], values[::-1], m[::-1], nxt_same[::-1])
    )
    adv = adv_rev[::-1] * m
    returns = adv + values
    return adv, returns * m


# ---------------------------------------------------------------------------
# Host-side numpy reference (used by tests and by host-side advantage calc)
# ---------------------------------------------------------------------------


def gae_numpy(
    rewards: np.ndarray, values: np.ndarray, lens: np.ndarray, gamma: float, lam: float
):
    """Straightforward per-sequence loop over a padded [B, L] batch."""
    B, L = rewards.shape
    adv = np.zeros_like(rewards, dtype=np.float64)
    for b in range(B):
        n = int(lens[b])
        carry = 0.0
        for t in reversed(range(n)):
            nxt = values[b, t + 1] if t + 1 < n else 0.0
            delta = rewards[b, t] + gamma * nxt - values[b, t]
            carry = delta + gamma * lam * carry
            adv[b, t] = carry
    ret = adv + np.where(
        np.arange(L)[None, :] < lens[:, None], values.astype(np.float64), 0.0
    )
    return adv, ret
