import threading
import time

import pytest

from areal_tpu.utils import name_resolve, names
from areal_tpu.utils.name_resolve import (
    MemoryNameRecordRepository,
    NameEntryExistsError,
    NameEntryNotFoundError,
    NfsNameRecordRepository,
)


def _start_kv_server():
    import asyncio

    from aiohttp import web

    from areal_tpu.utils.kv_store import KVServer

    server = KVServer(sweep_interval=0.1)
    holder, started = {}, threading.Event()

    def _run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def go():
            runner = web.AppRunner(server.app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            holder["addr"] = f"127.0.0.1:{runner.addresses[0][1]}"
            started.set()

        loop.run_until_complete(go())
        loop.run_forever()

    threading.Thread(target=_run, daemon=True).start()
    assert started.wait(10)
    return holder["addr"]


@pytest.fixture(params=["memory", "nfs", "http"])
def repo(request, tmp_path):
    if request.param == "memory":
        return MemoryNameRecordRepository()
    if request.param == "http":
        from areal_tpu.utils.kv_store import HttpNameRecordRepository

        return HttpNameRecordRepository(_start_kv_server(), ttl=2.0)
    return NfsNameRecordRepository(str(tmp_path / "nr"))


def test_add_get_delete(repo):
    repo.add("a/b/c", "v1")
    assert repo.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        repo.add("a/b/c", "v2")
    repo.add("a/b/c", "v2", replace=True)
    assert repo.get("a/b/c") == "v2"
    repo.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/b/c")


def test_subtree(repo):
    repo.add("root/x/1", "a")
    repo.add("root/x/2", "b")
    repo.add("root/y", "c")
    assert sorted(repo.get_subtree("root/x")) == ["a", "b"]
    assert repo.find_subtree("root/x") == ["root/x/1", "root/x/2"]
    repo.clear_subtree("root")
    assert repo.get_subtree("root") == []


def test_add_subentry(repo):
    k1 = repo.add_subentry("servers", "addr1")
    k2 = repo.add_subentry("servers", "addr2")
    assert k1 != k2
    assert sorted(repo.get_subtree("servers")) == ["addr1", "addr2"]


def test_wait_blocks_until_added(repo):
    def adder():
        time.sleep(0.2)
        repo.add("late/key", "done")

    t = threading.Thread(target=adder)
    t.start()
    assert repo.wait("late/key", timeout=5, poll_frequency=0.02) == "done"
    t.join()
    with pytest.raises(TimeoutError):
        repo.wait("never", timeout=0.2, poll_frequency=0.05)


def test_watch_names_fires_on_delete(repo):
    repo.add("watched/a", "1")
    fired = threading.Event()
    repo.watch_names(["watched/a"], fired.set, poll_frequency=0.02, wait_timeout=1)
    time.sleep(0.1)
    assert not fired.is_set()
    repo.delete("watched/a")
    assert fired.wait(timeout=2)


def test_module_level_api():
    name_resolve.add(names.gen_server("e", "t", "0"), "addr:1234")
    assert name_resolve.get_subtree(names.gen_servers("e", "t")) == ["addr:1234"]


def test_nfs_reset_removes_own_entries(tmp_path):
    repo = NfsNameRecordRepository(str(tmp_path / "nr"))
    repo.add("a/1", "x", delete_on_exit=True)
    repo.add("a/2", "y", delete_on_exit=False)
    repo.reset()
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/1")
    assert repo.get("a/2") == "y"


def test_watch_names_fires_when_peer_never_appears():
    repo = MemoryNameRecordRepository()
    fired = threading.Event()
    repo.watch_names(["never/appears"], fired.set, poll_frequency=0.02, wait_timeout=0.1)
    assert fired.wait(timeout=2)


def test_http_ttl_lease_expires_without_keepalive():
    """kv_store: a TTL'd key whose owner stops refreshing disappears — the
    etcd3-lease liveness signal (reference name_resolve.py:411)."""
    from areal_tpu.utils.kv_store import HttpNameRecordRepository

    addr = _start_kv_server()
    # generous ttl: the keepalive thread refreshes at ttl/3, and a loaded
    # CI runner must not be able to miss a whole window
    owner = HttpNameRecordRepository(addr, ttl=3.0)
    reader = HttpNameRecordRepository(addr, ttl=3.0)
    owner.add("fleet/worker/0", "alive", keepalive_ttl=3.0)
    assert reader.get("fleet/worker/0") == "alive"
    time.sleep(4.0)  # > ttl: only the keepalive can have kept it alive
    assert reader.get("fleet/worker/0") == "alive"
    owner._stop.set()  # owner "crashes": no more refreshes
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            reader.get("fleet/worker/0")
            time.sleep(0.1)
        except NameEntryNotFoundError:
            break
    else:
        raise AssertionError("leased key never expired")


def test_http_backend_via_env(monkeypatch):
    addr = _start_kv_server()
    monkeypatch.setenv("AREAL_NAME_RESOLVE", f"http:{addr}")
    name_resolve.reconfigure_from_env()
    try:
        name_resolve.add("env/test/x", "42", delete_on_exit=False)
        assert name_resolve.get("env/test/x") == "42"
        assert name_resolve.find_subtree("env/test") == ["env/test/x"]
    finally:
        name_resolve.DEFAULT_REPOSITORY = MemoryNameRecordRepository()
