"""Frame codec shared by the RPC server (aiohttp) and the stdlib-only
client: [8-byte LE kwargs length][kwargs JSON][DistributedBatch npz?]."""

import json
import struct


def encode_frame(kwargs: dict, batch_blob: bytes = b"") -> bytes:
    kw = json.dumps(kwargs).encode()
    return struct.pack("<Q", len(kw)) + kw + batch_blob


def decode_frame(body: bytes):
    (n,) = struct.unpack("<Q", body[:8])
    kwargs = json.loads(body[8 : 8 + n].decode())
    return kwargs, body[8 + n :]
