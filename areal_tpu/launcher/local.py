"""Local launcher: one host, generation servers + trainer as subprocesses.

Behavioral counterpart of the reference's `LocalLauncher`
(areal/launcher/local.py:81): parse the allocation expression, start the
LLM servers (here `areal_tpu.gen.server`), register/discover addresses via
name_resolve env plumbing, start the trainer entrypoint, babysit everything,
and relaunch the whole run on failure (auto-recover loop,
RECOVER_TIME_INTERVAL) up to `recover.retries` times with AREAL_RUN_ID
incremented so `check_if_recover` (utils/recover.py) resumes from the dump.

Two relaunch classes (ISSUE 15):
- crash (any unexpected rc): consumes one of `recover.retries`, waits out
  RECOVER_TIME_INTERVAL — the dump on disk is whatever the dying process
  last committed;
- preemption (rc == RESUME_EXIT_CODE, utils/shutdown.py): the trainer
  announced an orderly retreat with a known-good force-dump, so the
  relaunch is immediate and does NOT burn a crash retry.

Either way AREAL_RUN_ID increments per launch, so run artifacts
(events_run{N}.jsonl, logs) never collide and `check_if_recover`'s
``fault`` mode sees a relaunch.

Usage:
    python -m areal_tpu.launcher.local entry.py --config cfg.yaml [k=v ...]
"""

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from areal_tpu.api.alloc import AllocationMode
from areal_tpu.api.config import GRPOConfig, load_expr_config
from areal_tpu.utils import logging, name_resolve, names, network
from areal_tpu.utils.shutdown import RESUME_EXIT_CODE

logger = logging.getLogger("launcher.local")

RECOVER_TIME_INTERVAL = 10.0
# brief pause before a preemption relaunch: lets sockets/ports settle
# without hot-spinning if the entry exits with the resume code instantly
RESUME_RELAUNCH_DELAY = 1.0


class LocalLauncher:
    def __init__(self, entry: str, config_args: List[str]):
        self.entry = entry
        self.config_args = config_args
        self.config, _ = load_expr_config(config_args, GRPOConfig, ignore_unknown_top=True)
        self.procs: List[subprocess.Popen] = []
        self.server_addrs: List[str] = []

    # ------------------------------------------------------------------

    def _spawn(self, cmd: List[str], env: Optional[Dict[str, str]] = None,
               tag: str = "") -> subprocess.Popen:
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        log_dir = os.path.join(
            self.config.cluster.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "logs",
        )
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"{tag}.log")
        log_f = open(log_path, "a")
        logger.info(f"spawn [{tag}]: {' '.join(cmd)} (log: {log_path})")
        p = subprocess.Popen(
            cmd, env=full_env, stdout=log_f, stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        self.procs.append(p)
        return p

    def start_gen_servers(self, n_servers: int) -> List[str]:
        addrs = []
        for idx in range(n_servers):
            port = network.find_free_port()
            cmd = [
                sys.executable, "-m", "areal_tpu.gen.server",
                "--model-path", self.config.gen_server.model_path,
                "--port", str(port),
                "--n-slots", str(self.config.gen_server.max_seqs),
                "--max-seq-len", str(self.config.gen_server.max_context_len),
                "--experiment-name", self.config.experiment_name,
                "--trial-name", self.config.trial_name,
                "--server-idx", str(idx),
            ]
            self._spawn(cmd, tag=f"gen_server_{idx}")
            addrs.append(f"127.0.0.1:{port}")
        return addrs

    def start_trainer(self, server_addrs: List[str], run_id: int) -> subprocess.Popen:
        env = {
            "AREAL_LLM_SERVER_ADDRS": ",".join(server_addrs),
            "AREAL_RUN_ID": str(run_id),
        }
        cmd = [sys.executable, self.entry, *self.config_args]
        return self._spawn(cmd, env=env, tag=f"trainer_run{run_id}")

    def stop_all(self):
        for p in self.procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
        self.procs.clear()

    # ------------------------------------------------------------------

    def run(self) -> int:
        alloc = None
        if self.config.allocation_mode:
            alloc = AllocationMode.from_str(self.config.allocation_mode)
        n_servers = 1
        if alloc is not None and alloc.gen is not None:
            n_servers = max(1, alloc.gen.dp_size)

        retries = max(1, self.config.recover.retries)
        run_id = int(os.environ.get("AREAL_RUN_ID", 0))
        failures = 0  # crash relaunches consumed; preemptions don't count
        rc = 1
        try:
            while True:
                self.server_addrs = self.start_gen_servers(n_servers)
                trainer = self.start_trainer(self.server_addrs, run_id)
                rc = self._babysit(trainer)
                self.stop_all()
                if rc == 0:
                    logger.info("trainer finished successfully")
                    return 0
                if self.config.recover.mode == "disabled":
                    return rc
                run_id += 1
                if rc == RESUME_EXIT_CODE:
                    # orderly preemption retreat (utils/shutdown.py): the
                    # dump is known-good — relaunch now, keep the retry
                    # budget for real crashes
                    logger.warning(
                        f"trainer preempted (rc={rc}); relaunching "
                        f"immediately (run {run_id})"
                    )
                    time.sleep(RESUME_RELAUNCH_DELAY)
                    continue
                failures += 1
                if failures < retries and self.config.recover.mode in (
                        "auto", "fault"):
                    logger.warning(
                        f"trainer exited rc={rc}; relaunching (run {run_id}) "
                        f"in {RECOVER_TIME_INTERVAL}s "
                        f"[crash {failures}/{retries}]"
                    )
                    time.sleep(RECOVER_TIME_INTERVAL)
                else:
                    break
            return rc
        finally:
            self.stop_all()

    def _babysit(self, trainer: subprocess.Popen) -> int:
        """Wait for the trainer; if any gen server dies first, fail the run."""
        while True:
            rc = trainer.poll()
            if rc is not None:
                return rc
            for p in self.procs:
                if p is not trainer and p.poll() is not None:
                    logger.error("a generation server died; restarting run")
                    return 1
            time.sleep(1.0)


def main():
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(2)
    entry, args = sys.argv[1], sys.argv[2:]
    launcher = LocalLauncher(entry, args)
    sys.exit(launcher.run())


if __name__ == "__main__":
    main()
