"""Generation engine: KV-cache decode parity, continuous batching, sampling,
interruption.  (Reference analog: realhf/tests cpu inference tests plus the
fake-server tests — here the real engine runs on CPU.)"""

import os
import time

import numpy as np
import pytest

from areal_tpu.gen.engine import GenEngine, GenRequest
from areal_tpu.models import forward, init_params
from areal_tpu.models.model_config import tiny_config


@pytest.fixture(scope="module", autouse=True)
def _debug_locks():
    """Run every engine in this module with the runtime lock assertions
    armed (areal-lint C1 acceptance): if the static annotation set ever
    drifts from actual lock usage, these concurrency tests raise
    LockDisciplineError instead of racing silently."""
    old = os.environ.get("AREAL_DEBUG_LOCKS")
    os.environ["AREAL_DEBUG_LOCKS"] = "1"
    yield
    if old is None:
        os.environ.pop("AREAL_DEBUG_LOCKS", None)
    else:
        os.environ["AREAL_DEBUG_LOCKS"] = old


@pytest.fixture(scope="module")
def setup(_debug_locks):
    import jax

    cfg = tiny_config(vocab_size=97, qkv_bias=True, hf_architecture="Qwen2ForCausalLM",
                      eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = GenEngine(cfg, params=params, n_slots=4, max_seq_len=128,
                       prompt_bucket=16)
    return cfg, params, engine


def _greedy_reference(cfg, params, prompt, n_new):
    """Step-by-step argmax using the full (cache-free) forward."""
    seq = list(prompt)
    out = []
    for _ in range(n_new):
        L = len(seq)
        ids = np.asarray(seq, np.int32)[None]
        pos = np.arange(L, dtype=np.int32)[None]
        seg = np.zeros((1, L), np.int32)
        logits = np.asarray(forward(params, cfg, ids, pos, seg))[0, -1]
        tok = int(np.argmax(logits))
        out.append(tok)
        seq.append(tok)
    return out


def test_greedy_matches_full_forward(setup):
    cfg, params, engine = setup
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 97, 7).tolist()
    ref = _greedy_reference(cfg, params, prompt, 12)
    req = GenRequest(rid="a", input_ids=prompt, max_new_tokens=12, temperature=0.0)
    engine.generate_blocking([req])
    assert req.output_tokens == ref
    assert req.stop_reason == "length"
    # logprobs are the true logprobs of the emitted tokens
    assert all(lp <= 0 for lp in req.output_logprobs)
    assert len(req.output_versions) == 12


def test_gemma2_greedy_matches_full_forward():
    """The serving paths (bucketed prefill + fused decode) agree with the
    cache-free forward for the gemma2 structure: sandwich norms, alternating
    sliding/full layers, logit softcaps, scaled embeddings."""
    import jax

    cfg = tiny_config(
        vocab_size=97,
        num_layers=2,
        eos_token_id=None,
        hf_architecture="Gemma2ForCausalLM",
        hidden_act="gelu_pytorch_tanh",
        scale_embeddings=True,
        norm_unit_offset=True,
        sandwich_norms=True,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        query_pre_attn_scalar=8.0,
        sliding_window=8,
        layer_is_sliding=(True, False),
    )
    params = init_params(cfg, jax.random.PRNGKey(2))
    engine = GenEngine(cfg, params=params, n_slots=2, max_seq_len=64,
                       prompt_bucket=16)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 97, 11).tolist()
    ref = _greedy_reference(cfg, params, prompt, 10)
    req = GenRequest(rid="g", input_ids=prompt, max_new_tokens=10,
                     temperature=0.0)
    engine.generate_blocking([req])
    assert req.output_tokens == ref


def test_gpt2_greedy_matches_full_forward():
    """Serving paths agree with the cache-free forward for the gpt2
    structure: LayerNorm+bias, learned positions (no rope), fused-qkv
    checkpoints load into split leaves, non-gated gelu MLP, biases."""
    import jax

    cfg = tiny_config(
        vocab_size=97,
        num_layers=2,
        eos_token_id=None,
        hf_architecture="GPT2LMHeadModel",
        hidden_act="gelu_pytorch_tanh",
        norm_type="layernorm",
        pos_emb="learned",
        mlp_gated=False,
        qkv_bias=True,
        attn_output_bias=True,
        mlp_bias=True,
        num_kv_heads=4,
        max_position_embeddings=64,
        tie_word_embeddings=True,
    )
    params = init_params(cfg, jax.random.PRNGKey(5))
    engine = GenEngine(cfg, params=params, n_slots=2, max_seq_len=64,
                       prompt_bucket=16)
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, 97, 9).tolist()
    ref = _greedy_reference(cfg, params, prompt, 10)
    req = GenRequest(rid="p", input_ids=prompt, max_new_tokens=10,
                     temperature=0.0)
    engine.generate_blocking([req])
    assert req.output_tokens == ref


def test_concurrent_slots_independent(setup):
    """Interleaved decoding must equal solo decoding for each request."""
    cfg, params, engine = setup
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 97, n).tolist() for n in (3, 9, 5)]
    solo = [_greedy_reference(cfg, params, p, 8) for p in prompts]
    reqs = [
        GenRequest(rid=str(i), input_ids=p, max_new_tokens=8, temperature=0.0)
        for i, p in enumerate(prompts)
    ]
    engine.generate_blocking(reqs)
    for r, ref in zip(reqs, solo):
        assert r.output_tokens == ref, r.rid


def test_more_requests_than_slots(setup):
    cfg, params, engine = setup
    rng = np.random.default_rng(2)
    reqs = [
        GenRequest(rid=str(i), input_ids=rng.integers(0, 97, 4).tolist(),
                   max_new_tokens=5, temperature=0.0)
        for i in range(11)  # > n_slots=4
    ]
    engine.generate_blocking(reqs)
    assert all(len(r.output_tokens) == 5 for r in reqs)
    assert all(r.stop_reason == "length" for r in reqs)


def test_stop_tokens_and_min_new_tokens(setup):
    cfg, params, engine = setup
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 97, 6).tolist()
    ref = _greedy_reference(cfg, params, prompt, 16)
    stop_tok = ref[4]
    first_hit = ref.index(stop_tok)  # the engine stops at the FIRST occurrence
    req = GenRequest(rid="s", input_ids=prompt, max_new_tokens=16,
                     temperature=0.0, stop_token_ids=[stop_tok])
    engine.generate_blocking([req])
    assert req.stop_reason == "stop"
    assert req.output_tokens == ref[: first_hit + 1]
    # min_new_tokens suppresses that stop
    req2 = GenRequest(rid="s2", input_ids=prompt, max_new_tokens=16,
                      temperature=0.0, stop_token_ids=[stop_tok],
                      min_new_tokens=16)
    engine.generate_blocking([req2])
    assert len(req2.output_tokens) == 16


def test_sampling_modes(setup):
    cfg, params, engine = setup
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, 97, 5).tolist()
    reqs = [
        GenRequest(rid=f"t{i}", input_ids=prompt, max_new_tokens=10,
                   temperature=1.0, top_p=0.9, top_k=20)
        for i in range(4)
    ]
    engine.generate_blocking(reqs)
    outs = {tuple(r.output_tokens) for r in reqs}
    assert len(outs) > 1  # stochastic sampling diversifies
    assert all(np.isfinite(r.output_logprobs).all() for r in reqs)


def test_weight_update_aborts_and_bumps_version(setup):
    cfg, params, engine = setup
    import jax

    rng = np.random.default_rng(5)
    prompt = rng.integers(0, 97, 4).tolist()
    req = GenRequest(rid="w", input_ids=prompt, max_new_tokens=50, temperature=0.0)
    engine.submit(req)
    for _ in range(6):
        engine.step()
    assert not req.stop_reason
    v0 = engine.version
    new_params = init_params(cfg, jax.random.PRNGKey(99))
    engine.load_weights(params=new_params)
    assert req.stop_reason == "abort"
    assert engine.version == v0 + 1
    assert 0 < len(req.output_tokens) < 50
    # new weights generate under the new version, tagged per token
    req2 = GenRequest(rid="w2", input_ids=prompt, max_new_tokens=4, temperature=0.0)
    engine.generate_blocking([req2])
    assert set(req2.output_versions) == {engine.version}
    ref_new = _greedy_reference(cfg, new_params, prompt, 4)
    assert req2.output_tokens == ref_new
    # restore original weights for other tests (module-scoped engine)
    engine.load_weights(params=params)


def test_live_swap_keeps_requests_decoding(setup):
    """swap_weights_live mid-generation: no abort, no re-prefill — the
    in-flight request keeps decoding under the NEW policy and its per-token
    versions record the transition (the colocated publish path)."""
    cfg, params, _ = setup
    import jax

    rng = np.random.default_rng(17)
    prompt = rng.integers(0, 97, 6).tolist()
    eng = _fresh_engine(cfg, params)
    req = GenRequest(rid="lv", input_ids=prompt, max_new_tokens=12,
                     temperature=0.0)
    eng.submit(req)
    while len(req.output_tokens) < 4:
        eng.step(chunk=2)
    pre_swap = len(req.output_tokens)
    prefills_before = eng.stats["prefill_calls"] + eng.stats["suffix_calls"]
    new_params = init_params(cfg, jax.random.PRNGKey(123))
    v = eng.swap_weights_live(new_params)
    assert v == 1 and eng.last_pause_s >= 0
    assert not req.stop_reason  # still in flight — nothing aborted
    while not req.stop_reason:
        eng.step(chunk=2)
    assert req.stop_reason == "length"
    assert len(req.output_tokens) == 12
    # both policies contributed tokens, recorded per token
    assert set(req.output_versions) == {0, 1}
    assert req.output_versions[:pre_swap] == [0] * pre_swap
    assert req.output_versions[-1] == 1
    # no re-prefill happened: decoding continued on the same slot/KV
    assert eng.stats["prefill_calls"] + eng.stats["suffix_calls"] \
        == prefills_before
    # a fresh request (distinct prompt — no retained-prefix match, which
    # would deliberately reuse old-policy KV) is pure new-policy
    p2 = rng.integers(0, 97, 6).tolist()
    r2 = GenRequest(rid="lv2", input_ids=p2, max_new_tokens=4,
                    temperature=0.0)
    eng.generate_blocking([r2])
    assert r2.output_tokens == _greedy_reference(cfg, new_params, p2, 4)


def test_live_swap_honors_strict_reload_and_drops_stale_standby(setup):
    """swap_weights_live must (a) clear retained prefixes under
    retain_kv_on_reload=False — strict mode promises resumes recompute
    under the new policy — and (b) invalidate a pre-staged standby tree,
    or a later commit_staged would silently roll the version BACK."""
    cfg, params, _ = setup
    import jax

    rng = np.random.default_rng(21)
    prompt = rng.integers(0, 97, 8).tolist()
    eng = _fresh_engine(cfg, params, retain_kv_on_reload=False)
    r1 = GenRequest(rid="s", input_ids=prompt, max_new_tokens=4,
                    temperature=0.0)
    eng.generate_blocking([r1])
    assert any(eng.retained_len)  # finished slot retains its prefix...
    p1 = init_params(cfg, jax.random.PRNGKey(7))
    assert eng.stage_params(p1, version=1) and eng.has_standby
    p2 = init_params(cfg, jax.random.PRNGKey(8))
    eng.swap_weights_live(p2, version=2)
    # ...until a strict-mode swap wipes it
    assert not any(eng.retained_len)
    # and the older staged tree cannot be committed over the newer publish
    assert not eng.has_standby
    assert eng.version == 2
    with pytest.raises(RuntimeError):
        eng.commit_staged()

    # a STRICTLY NEWER standby survives an older publish: its pending
    # commit must not be lost (staged v6 vs disk publish v5 race)
    p3 = init_params(cfg, jax.random.PRNGKey(9))
    assert eng.stage_params(p3, version=6)
    eng.load_weights(params=p2, version=5)
    assert eng.has_standby and eng.staged_version == 6
    assert eng.commit_staged() == 6


def test_prompt_too_long_rejected(setup):
    cfg, params, engine = setup
    req = GenRequest(rid="x", input_ids=list(range(90)) + list(range(40)),
                     max_new_tokens=4)
    engine.submit(req)
    assert req.stop_reason == "length"
    assert req.output_tokens == []


def test_decode_chunk_parity(setup):
    """chunk>1 (multi-token device scan) must produce identical greedy
    output to chunk=1, including stop trimming."""
    cfg, params, _ = setup
    rng = np.random.default_rng(6)
    prompt = rng.integers(0, 97, 6).tolist()
    outs = []
    for chunk in (1, 4, 7):
        eng = GenEngine(cfg, params=params, n_slots=2, max_seq_len=64,
                        prompt_bucket=16, decode_chunk=chunk)
        req = GenRequest(rid="c", input_ids=prompt, max_new_tokens=13,
                         temperature=0.0)
        eng.generate_blocking([req])
        outs.append((tuple(req.output_tokens), req.stop_reason))
    assert outs[0] == outs[1] == outs[2]


def test_batched_admission_single_prefill(setup):
    """A burst of prompts sharing a bucket is admitted in ONE prefill call."""
    import jax

    cfg, params, _ = setup
    engine = GenEngine(cfg, params=params, n_slots=4, max_seq_len=128,
                       prompt_bucket=16)
    calls = {"n": 0}
    orig = engine._prefill_fn

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    engine._prefill_fn = counting
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 97, n).tolist() for n in (5, 9, 12, 7)]
    solo = [_greedy_reference(cfg, params, p, 6) for p in prompts]
    reqs = [
        GenRequest(rid=f"b{i}", input_ids=p, max_new_tokens=6, temperature=0.0)
        for i, p in enumerate(prompts)
    ]
    engine.generate_blocking(reqs)
    assert calls["n"] == 1, f"expected 1 batched prefill, got {calls['n']}"
    for req, ref in zip(reqs, solo):
        assert req.output_tokens == ref


def test_tp_sharded_serving_parity(setup):
    """tp=2 mesh serving: same tokens and logprobs as the tp=1 engine
    (VERDICT round-1 missing #2: model-parallel generation)."""
    cfg, params, _ = setup
    e1 = GenEngine(cfg, params=params, n_slots=2, max_seq_len=128,
                   prompt_bucket=16, tp=1)
    e2 = GenEngine(cfg, params=params, n_slots=2, max_seq_len=128,
                   prompt_bucket=16, tp=2)
    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, 97, n).tolist() for n in (6, 11)]
    for engine in (e1, e2):
        reqs = [
            GenRequest(rid=f"t{i}", input_ids=p, max_new_tokens=8, temperature=0.0)
            for i, p in enumerate(prompts)
        ]
        engine.generate_blocking(reqs)
        if engine is e1:
            ref = [(r.output_tokens, r.output_logprobs) for r in reqs]
        else:
            for r, (toks, logps) in zip(reqs, ref):
                assert r.output_tokens == toks
                np.testing.assert_allclose(r.output_logprobs, logps,
                                           rtol=1e-4, atol=1e-4)


def test_7b_shape_tp_serving_compiles():
    """qwen2.5-7B shapes lower over a tp=4 mesh (serving a model too big for
    one chip).  Tiny depth/vocab keep it fast; the sharding-relevant dims
    (heads, kv heads, head_dim) are the real 7B values."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.models.model_config import qwen25_7b

    cfg = qwen25_7b().replace(num_layers=2, vocab_size=1024, remat=False,
                              dtype="float32", param_dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = GenEngine(cfg, params=params, n_slots=2, max_seq_len=64,
                       prompt_bucket=16, tp=4)
    req = GenRequest(rid="7b", input_ids=[1, 2, 3], max_new_tokens=4,
                     temperature=0.0)
    engine.generate_blocking([req])
    assert len(req.output_tokens) == 4


# ---------------------------------------------------------------------------
# KV prefix reuse (VERDICT r3 #3) + near-cache-end decoupling (weak #3)
# ---------------------------------------------------------------------------


def _fresh_engine(cfg, params, **kw):
    from areal_tpu.gen.engine import GenEngine

    base = dict(n_slots=4, max_seq_len=128, prompt_bucket=16,
                kv_dtype="float32", reuse_min_tokens=4)
    base.update(kw)
    return GenEngine(cfg, params=params, **base)


def test_multi_turn_suffix_prefill_matches_fresh(setup):
    """Turn 2 extends turn 1's transcript: the engine must reuse the
    retained cache (suffix-only prefill) and emit EXACTLY the tokens a
    fresh engine produces."""
    cfg, params, _ = setup
    rng = np.random.default_rng(7)
    turn1 = rng.integers(0, 97, 24).tolist()

    eng = _fresh_engine(cfg, params)
    r1 = GenRequest(rid="t", input_ids=turn1, max_new_tokens=6, temperature=0.0)
    eng.generate_blocking([r1])
    transcript = turn1 + r1.output_tokens + rng.integers(0, 97, 5).tolist()

    # same turn-2 prompt on a reuse engine and on a cold engine
    r2 = GenRequest(rid="t", input_ids=transcript, max_new_tokens=6,
                    temperature=0.0)
    eng.generate_blocking([r2])
    cold = _fresh_engine(cfg, params, kv_reuse=False)
    r2c = GenRequest(rid="t", input_ids=list(transcript), max_new_tokens=6,
                     temperature=0.0)
    cold.generate_blocking([r2c])
    assert r2.output_tokens == r2c.output_tokens
    assert eng.stats["suffix_calls"] == 1
    assert eng.stats["reused_tokens"] >= 24  # the shared prefix was NOT recomputed
    # turn-2 prefill cost is proportional to the NEW tokens, not the context
    assert eng.stats["suffix_tokens"] <= len(transcript) - eng.stats["reused_tokens"] + 1


def test_interruption_resume_reuses_prefix(setup):
    """abort (weight update) -> client resubmits prompt + accumulated tokens:
    the resume must be a suffix prefill over the retained cache."""
    cfg, params, _ = setup
    rng = np.random.default_rng(8)
    prompt = rng.integers(0, 97, 16).tolist()
    eng = _fresh_engine(cfg, params)
    r1 = GenRequest(rid="i", input_ids=prompt, max_new_tokens=8, temperature=0.0)
    eng.submit(r1)
    while len(r1.output_tokens) < 3:  # partial decode, then interrupt
        eng.step(chunk=2)
    eng.abort_all("abort")
    got = len(r1.output_tokens)
    assert got > 0 and r1.stop_reason == "abort"

    resumed = GenRequest(rid="i", input_ids=prompt + r1.output_tokens,
                         max_new_tokens=8 - got, temperature=0.0)
    eng.generate_blocking([resumed])
    assert eng.stats["suffix_calls"] >= 1
    assert eng.stats["reused_tokens"] >= len(prompt) - 1
    # the resumed continuation equals the uninterrupted greedy rollout
    ref = _greedy_reference(cfg, params, prompt, 8)
    assert r1.output_tokens + resumed.output_tokens == ref


def test_abort_callbacks_run_outside_engine_lock(setup):
    """Regression (ISSUE 9 / C5 blocking-under-lock): abort_all fires
    terminal callbacks AFTER releasing _lock.  A callback that re-enters
    the engine's public API (active_count / tier_occupancy both take
    _lock, a non-reentrant threading.Lock) used to self-deadlock."""
    import threading

    cfg, params, _ = setup
    eng = _fresh_engine(cfg, params)
    rng = np.random.default_rng(40)
    req = GenRequest(rid="cb", input_ids=rng.integers(0, 97, 8).tolist(),
                     max_new_tokens=16, temperature=0.0)
    seen = {}

    def on_done(r):
        seen["active"] = eng.active_count()
        seen["tiers"] = eng.tier_occupancy()

    req.on_done = on_done
    eng.submit(req)
    while not req.output_tokens:
        eng.step(chunk=2)
    t = threading.Thread(target=eng.abort_all, args=("abort",), daemon=True)
    t.start()
    t.join(timeout=20.0)
    assert not t.is_alive(), "abort_all deadlocked inside a terminal callback"
    assert req.stop_reason == "abort"
    # slot state had already settled when the callback observed it
    assert seen["active"] == 0 and sum(seen["tiers"]) == 0


def test_near_cache_end_slot_does_not_clamp_grid(setup):
    """One slot close to max_seq_len must not force the whole grid into
    1-token decode round-trips (VERDICT r3 weak #3)."""
    cfg, params, _ = setup
    eng = _fresh_engine(cfg, params, max_seq_len=64, kv_reuse=False)
    rng = np.random.default_rng(9)
    near = GenRequest(rid="near", input_ids=rng.integers(0, 97, 58).tolist(),
                      max_new_tokens=32, temperature=0.0)
    far = GenRequest(rid="far", input_ids=rng.integers(0, 97, 4).tolist(),
                     max_new_tokens=32, temperature=0.0)
    solo_far = _greedy_reference(cfg, params, far.input_ids, 32)
    eng.generate_blocking([near, far])
    # near hits the cache wall quickly...
    assert near.stop_reason == "length" and len(near.output_tokens) <= 6
    # ...while far still decodes its full budget CORRECTLY
    assert far.output_tokens == solo_far
    # and the grid kept full-chunk steps: 32 tokens / chunk 8 => ~4-6 calls,
    # not ~32 one-token calls
    assert eng.stats["decode_calls"] <= 8, eng.stats


def test_reuse_disabled_under_flag(setup):
    cfg, params, _ = setup
    rng = np.random.default_rng(10)
    prompt = rng.integers(0, 97, 20).tolist()
    eng = _fresh_engine(cfg, params, kv_reuse=False)
    r1 = GenRequest(rid="x", input_ids=prompt, max_new_tokens=4, temperature=0.0)
    eng.generate_blocking([r1])
    r2 = GenRequest(rid="x", input_ids=prompt + r1.output_tokens,
                    max_new_tokens=4, temperature=0.0)
    eng.generate_blocking([r2])
    assert eng.stats["suffix_calls"] == 0


def test_reload_flush_policy(setup):
    """retain_kv_on_reload=False drops retained prefixes at load_weights."""
    cfg, params, _ = setup
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, 97, 20).tolist()
    eng = _fresh_engine(cfg, params, retain_kv_on_reload=False)
    r1 = GenRequest(rid="f", input_ids=prompt, max_new_tokens=4, temperature=0.0)
    eng.generate_blocking([r1])
    assert eng.retained_len.max() > 0
    eng.load_weights(params=params, version=1)
    assert eng.retained_len.max() == 0


def test_abort_storm_resubmissions_keep_their_prefixes(setup):
    """VERDICT r4 #3: N in-flight requests aborted by a publish race back
    over few slots in ADVERSARIAL order, interleaved with fresh prompts.
    Queue-wide prefix matching + abort reservations must hand each retained
    prefix to the request that can reuse it — no resubmission may pay a
    full re-prefill."""
    cfg, params, _ = setup
    rng = np.random.default_rng(13)
    eng = _fresh_engine(cfg, params, n_slots=4, max_seq_len=128)
    inflight = [
        GenRequest(rid=f"s{i}", input_ids=rng.integers(0, 97, 24).tolist(),
                   max_new_tokens=32, temperature=0.0)
        for i in range(4)
    ]
    for r in inflight:
        eng.submit(r)
    while any(len(r.output_tokens) < 4 for r in inflight):
        eng.step(chunk=2)
    eng.abort_all("abort")
    assert all(r.stop_reason == "abort" for r in inflight)

    # resubmissions arrive LAST, behind a burst of fresh prompts — the
    # exact arrival order that used to evict every retained prefix
    fresh = [
        GenRequest(rid=f"f{i}", input_ids=rng.integers(0, 97, 24).tolist(),
                   max_new_tokens=4, temperature=0.0)
        for i in range(4)
    ]
    resumed = [
        GenRequest(rid=r.rid, input_ids=r.input_ids + r.output_tokens,
                   max_new_tokens=32 - len(r.output_tokens), temperature=0.0)
        for r in inflight
    ]
    for r in fresh + resumed:
        eng.submit(r)
    before_prefill = eng.stats["prefill_tokens"]
    while any(not r.stop_reason for r in fresh + resumed):
        eng.step()
    # every resumed request found its retained prefix: reused tokens cover
    # all four prompts' cached spans and no resumed prompt re-prefilled
    assert eng.stats["reused_tokens"] >= sum(
        len(r.input_ids) + 3 for r in inflight
    )
    # fresh prompts were NOT starved — they completed too, through full
    # prefill once the reservations were either honored or expired
    assert eng.stats["prefill_tokens"] - before_prefill >= 4 * 24
    # every reservation was HONORED (the resubmissions arrived within the
    # TTL), so none lapsed — the counter that makes abort_reserve_s
    # observable (VERDICT r6 #10) must stay at zero here
    assert eng.stats["reservations_lapsed"] == 0
    # and the resumed continuations are exact (greedy): reuse is lossless —
    # a cold engine run of the same prompts must emit identical tokens
    cold = _fresh_engine(cfg, params, n_slots=4, max_seq_len=128,
                         kv_reuse=False)
    refs = [
        GenRequest(rid=f"c{i}", input_ids=list(r.input_ids),
                   max_new_tokens=32, temperature=0.0)
        for i, r in enumerate(inflight)
    ]
    cold.generate_blocking(refs)
    for orig, res, ref in zip(inflight, resumed, refs):
        assert orig.output_tokens + res.output_tokens == ref.output_tokens


def test_fresh_prompts_wait_out_reservation_then_proceed(setup):
    """A reservation must park fresh prompts only briefly: when the aborted
    owner never resubmits, the TTL lapses and fresh prompts take the slot."""
    cfg, params, _ = setup
    eng = _fresh_engine(cfg, params, n_slots=1, max_seq_len=128,
                        abort_reserve_s=0.2)
    rng = np.random.default_rng(14)
    r1 = GenRequest(rid="gone", input_ids=rng.integers(0, 97, 24).tolist(),
                    max_new_tokens=16, temperature=0.0)
    eng.submit(r1)
    while len(r1.output_tokens) < 2:
        eng.step(chunk=2)
    eng.abort_all("abort")

    f = GenRequest(rid="fresh", input_ids=rng.integers(0, 97, 8).tolist(),
                   max_new_tokens=4, temperature=0.0)
    eng.submit(f)
    eng.step()
    # still parked: the only slot is reserved for the aborted owner
    assert not f.stop_reason and eng.slot_req[0] is None
    t0 = time.monotonic()
    while not f.stop_reason and time.monotonic() - t0 < 10:
        eng.step()
    assert f.stop_reason  # admitted after the TTL lapsed
    assert eng.stats["prefill_tokens"] >= len(f.input_ids)
    # the owner never resubmitted: exactly this slot's reservation lapsed,
    # and the counter records it (VERDICT r6 #10 observability)
    assert eng.stats["reservations_lapsed"] == 1


def test_slot_grid_scales_to_64(setup):
    """VERDICT r3 weak #5: slot counts representative of real serving
    (n_slots >> 8).  64 concurrent sequences decode correctly — each
    request's output equals its solo greedy rollout — and the vectorised
    delivery keeps host work per step bounded (decode_calls stays at the
    chunked schedule, not per-token)."""
    cfg, params, _ = setup
    eng = _fresh_engine(cfg, params, n_slots=64, max_seq_len=64,
                        kv_reuse=False)
    rng = np.random.default_rng(12)
    prompts = [rng.integers(0, 97, 4 + (i % 5)).tolist() for i in range(64)]
    reqs = [
        GenRequest(rid=str(i), input_ids=p, max_new_tokens=16,
                   temperature=0.0)
        for i, p in enumerate(prompts)
    ]
    eng.generate_blocking(reqs)
    assert all(len(r.output_tokens) == 16 for r in reqs)
    # spot-check correctness against the cache-free forward on 4 requests
    for i in (0, 17, 40, 63):
        ref = _greedy_reference(cfg, params, prompts[i], 16)
        assert reqs[i].output_tokens == ref, i
    # 16 tokens / chunk 8 => 2 decode rounds (+1 slack for admission timing)
    assert eng.stats["decode_calls"] <= 4, eng.stats
