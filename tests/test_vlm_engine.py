"""VLM train-engine tests: padded-row preparation, vision-key sharding, and
the GRPO update end-to-end on a tiny vision-language model (reference VLM
train path: base_hf_engine.py VLM branch + vision_rlvr workflow)."""

import numpy as np
import pytest

from areal_tpu.api.config import (
    MeshConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.vlm_engine import JaxVLMEngine, JaxVLMPPOActor
from areal_tpu.models.model_config import VisionConfig, tiny_config
from areal_tpu.models.vision import mrope_position_ids

IMG_TOK = 60

VCFG = VisionConfig(
    patch_size=2,
    temporal_patch_size=1,
    in_channels=3,
    hidden_size=16,
    intermediate_size=32,
    num_layers=1,
    num_heads=2,
    spatial_merge_size=2,
    out_hidden_size=48,
)


def _model_cfg():
    return tiny_config(
        vocab_size=64,
        hidden_size=48,
        num_heads=4,
        num_kv_heads=2,
        qkv_bias=True,
        dtype="float32",
        param_dtype="float32",
        hf_architecture="Qwen2VLForConditionalGeneration",
    ).replace(vision=VCFG, image_token_id=IMG_TOK, mrope_section=(2, 3, 3))


def _cfg(mesh=None, group_size=2):
    return PPOActorConfig(
        experiment_name="vlm",
        trial_name="t",
        init_from_scratch=True,
        dtype="float32",
        gradient_checkpointing=False,
        mesh=mesh or MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=1),
        optimizer=OptimizerConfig(
            lr=5e-3, warmup_steps_proportion=0.0, weight_decay=0.0
        ),
        pack_length_quantum=16,
        group_size=group_size,
        ppo_n_minibatches=1,
        adv_norm=NormConfig(
            mean_level="group", std_level="group", group_size=group_size
        ),
    )


def _vlm_batch(rng, B=4, L=16):
    """Every sequence: 2 text tokens, a 4x4-patch image (4 placeholders),
    then text; one image per sequence, in order."""
    ids = rng.integers(0, 40, (B, L)).astype(np.int32)
    ids[:, 2:6] = IMG_TOK
    mask = np.ones((B, L), bool)
    loss_mask = np.zeros((B, L), np.float32)
    loss_mask[:, 6:] = 1.0
    patches = rng.normal(size=(B * 16, VCFG.patch_dim)).astype(np.float32)
    patch_img_ids = np.repeat(np.arange(B), 16).astype(np.int32)
    grid = np.array([[1, 4, 4]])
    mrope = np.stack(
        [mrope_position_ids(ids[b], grid, IMG_TOK).T for b in range(B)]
    ).astype(np.int32)  # [B, L, 3]
    return {
        "input_ids": ids,
        "attention_mask": mask,
        "loss_mask": loss_mask,
        "logprobs": rng.normal(-1.0, 0.1, (B, L)).astype(np.float32) * loss_mask,
        "rewards": (ids[:, 6] % 2 == 0).astype(np.float32),
        "versions": np.zeros((B, L), np.int32),
        "pixel_values": patches,
        "patch_img_ids": patch_img_ids,
        "mrope_positions": mrope,
    }


def test_vlm_engine_requires_vision_config():
    with pytest.raises(ValueError, match="vision"):
        JaxVLMEngine(_cfg(), model_config=tiny_config(vocab_size=64))


def test_vlm_grpo_update_single_device():
    actor = JaxVLMPPOActor(_cfg(), model_config=_model_cfg())
    actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    try:
        assert "vision" in actor.params  # scratch tower materialised
        rng = np.random.default_rng(0)
        batch = _vlm_batch(rng)
        logp = actor.compute_logp(batch)
        assert logp.shape == batch["input_ids"].shape
        assert np.isfinite(logp[batch["attention_mask"]]).all()

        batch["prox_logp"] = logp
        actor.compute_advantages(batch)
        stats = actor.ppo_update(batch)
        assert stats and np.isfinite(stats[-1]["loss"])
        assert stats[-1]["n_tokens"] > 0
    finally:
        actor.destroy()


def test_vlm_grpo_update_microbatched():
    """n_mbs=2 grad accumulation: patch arrays carve along row groups via
    patches_per_row and the scan sees uniform per-mb shapes."""
    cfg = _cfg()
    cfg.mb_spec = MicroBatchSpec(n_mbs=2)
    actor = JaxVLMPPOActor(cfg, model_config=_model_cfg())
    actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    try:
        rng = np.random.default_rng(7)
        batch = _vlm_batch(rng, B=4)
        batch["patches_per_row"] = np.full(4, 16, np.int64)
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)

        # REAL carve coverage: an identically-initialised n_mbs=1 actor's
        # update must agree on loss and grad norm — a span off-by-one that
        # pairs rows with wrong images would change both
        actor1 = JaxVLMPPOActor(_cfg(), model_config=_model_cfg())
        actor1.initialize(ft_spec=FinetuneSpec(1, 64, 8))
        try:
            stats1 = actor1.ppo_update(dict(batch))
            stats2 = actor.ppo_update(dict(batch))
            np.testing.assert_allclose(
                stats1[-1]["loss"], stats2[-1]["loss"], rtol=1e-4, atol=1e-6
            )
            np.testing.assert_allclose(
                stats1[-1]["grad_norm"], stats2[-1]["grad_norm"],
                rtol=1e-4, atol=1e-6,
            )
        finally:
            actor1.destroy()
        stats = stats2
        assert np.isfinite(stats[-1]["loss"])

        # micro-batching without spans is refused loudly
        batch2 = _vlm_batch(rng, B=4)
        batch2["prox_logp"] = batch2["logprobs"].copy()
        actor.compute_advantages(batch2)
        with pytest.raises(ValueError, match="patches_per_row"):
            actor.ppo_update(batch2)
    finally:
        actor.destroy()


def test_vlm_grpo_update_sharded_mesh():
    """dp2 x tp2 on the virtual CPU mesh: filler rows/patches pad shapes to
    shard divisibility and the update still runs."""
    mesh = MeshConfig(
        data_parallel_size=2,
        fsdp_parallel_size=1,
        sequence_parallel_size=1,
        tensor_parallel_size=2,
    )
    actor = JaxVLMPPOActor(_cfg(mesh=mesh), model_config=_model_cfg())
    actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    try:
        rng = np.random.default_rng(1)
        # B=6 not divisible by dp=2*... -> exercises row padding
        batch = _vlm_batch(rng, B=6)
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)
        stats = actor.ppo_update(batch)
        assert np.isfinite(stats[-1]["loss"])
    finally:
        actor.destroy()


def test_vlm_logp_parity_with_plain_model_when_no_image_contribution():
    """With loss over text positions far from images and identical weights,
    the VLM forward must agree with itself across runs (determinism) and
    produce different logps when pixels change (vision actually wired)."""
    actor = JaxVLMPPOActor(_cfg(), model_config=_model_cfg())
    actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    try:
        rng = np.random.default_rng(2)
        batch = _vlm_batch(rng)
        l1 = actor.compute_logp(batch)
        l2 = actor.compute_logp(batch)
        np.testing.assert_allclose(l1, l2, rtol=1e-6)

        batch2 = dict(batch)
        batch2["pixel_values"] = batch["pixel_values"] + 1.0
        l3 = actor.compute_logp(batch2)
        # positions after the image must see different context
        assert not np.allclose(l1[:, 6:], l3[:, 6:])
    finally:
        actor.destroy()


def test_vlm_ppo_minibatches_span_aware():
    """VERDICT r2 #3: ppo_n_minibatches>1 on vision batches — contiguous row
    groups carve patch arrays by span; summed minibatch losses must equal an
    n=1 run's loss (same loss normalisation, disjoint row coverage)."""
    cfg2 = _cfg()
    cfg2.ppo_n_minibatches = 2
    actor2 = JaxVLMPPOActor(cfg2, model_config=_model_cfg())
    actor2.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    actor1 = JaxVLMPPOActor(_cfg(), model_config=_model_cfg())
    actor1.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    try:
        rng = np.random.default_rng(11)
        batch = _vlm_batch(rng, B=4)
        batch["patches_per_row"] = np.full(4, 16, np.int64)
        batch["prox_logp"] = actor1.compute_logp(batch)
        actor1.compute_advantages(batch)

        stats2 = actor2.ppo_update(dict(batch))
        assert len(stats2) == 2
        assert all(np.isfinite(s["loss"]) for s in stats2)
        stats1 = actor1.ppo_update(dict(batch))
        # each minibatch normalises by its own token count; the token-
        # weighted mean of the two minibatch losses equals the full loss
        n = np.array([s["n_tokens"] for s in stats2])
        mb_mean = float(np.sum([s["loss"] * s["n_tokens"] for s in stats2]) / n.sum())
        np.testing.assert_allclose(mb_mean, stats1[-1]["loss"], rtol=1e-4, atol=1e-6)

        # without spans, a multi-minibatch update is refused loudly
        bad = {k: v for k, v in batch.items() if k != "patches_per_row"}
        with pytest.raises(ValueError, match="patches_per_row"):
            actor2.ppo_update(bad)
    finally:
        actor1.destroy()
        actor2.destroy()


def test_vlm_dynamic_sampling_filters_constant_groups():
    """Dynamic sampling on vision batches: groups with identical rewards are
    dropped, their pixels dropped with them, image ids renumbered."""
    cfg = _cfg()
    cfg.dynamic_sampling = True
    actor = JaxVLMPPOActor(cfg, model_config=_model_cfg())
    actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    try:
        rng = np.random.default_rng(13)
        batch = _vlm_batch(rng, B=4)  # group_size=2 -> groups (0,1), (2,3)
        batch["patches_per_row"] = np.full(4, 16, np.int64)
        batch["rewards"] = np.array([1.0, 1.0, 1.0, 0.0], np.float32)
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)
        stats = actor.ppo_update(batch)
        # group (0,1) has constant reward -> dropped; 2 sequences remain
        assert np.isfinite(stats[-1]["loss"])
        assert stats[-1]["n_tokens"] == float(batch["loss_mask"][2:].sum())
    finally:
        actor.destroy()


def test_vlm_select_rows_vision_renumbers_images():
    from areal_tpu.utils.data import select_rows_vision

    batch = {
        "input_ids": np.arange(12, dtype=np.int32).reshape(4, 3),
        "pixel_values": np.arange(8, dtype=np.float32).reshape(8, 1),
        # rows 0..3 own images 0,1,2,3 with 2 patches each
        "patch_img_ids": np.repeat(np.arange(4), 2).astype(np.int32),
        "patches_per_row": np.full(4, 2, np.int64),
    }
    out = select_rows_vision(batch, [1, 3])
    np.testing.assert_array_equal(out["input_ids"], [[3, 4, 5], [9, 10, 11]])
    np.testing.assert_array_equal(
        out["pixel_values"][:, 0], [2.0, 3.0, 6.0, 7.0]
    )
    # image ids renumbered by first appearance: 1 -> 0, 3 -> 1
    np.testing.assert_array_equal(out["patch_img_ids"], [0, 0, 1, 1])
    np.testing.assert_array_equal(out["patches_per_row"], [2, 2])


def test_vlm_grpo_update_sp_mesh():
    """VERDICT r2 #3: sp>1 VLM training — the padded rows shard along the
    sequence axis; loss/grad must match the single-device run."""
    rng = np.random.default_rng(0)
    batch = _vlm_batch(rng)
    batch["patches_per_row"] = np.full(4, 16, np.int64)
    results = {}
    for name, mesh in [
        ("single", MeshConfig()),
        ("sp2", MeshConfig(sequence_parallel_size=2, tensor_parallel_size=2)),
    ]:
        actor = JaxVLMPPOActor(_cfg(mesh), model_config=_model_cfg())
        actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
        try:
            b = dict(batch)
            b["prox_logp"] = actor.compute_logp(b)
            actor.compute_advantages(b)
            stats = actor.ppo_update(b)
            results[name] = (stats[-1]["loss"], stats[-1]["grad_norm"])
        finally:
            actor.destroy()
    np.testing.assert_allclose(
        results["single"], results["sp2"], rtol=1e-5, atol=1e-7
    )
