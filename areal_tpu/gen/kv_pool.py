"""Unified radix/paged KV pool with a host-DRAM overflow tier (ISSUE 16).

The engine grew five independent prefix mechanisms on top of interruptible
generation — retained multi-turn reuse, GRPO group fan-out, tiered-decode
migration copies, spec-decode draft headroom, and failover resubmits — each
with its own slot bookkeeping.  This module collapses their *lookup and
placement* state into one object:

- ``page_table``: the logical-slot -> physical-cache-row indirection (the
  block table).  Decode/verify dispatches read the cache *through* it
  (models/transformer.py ``rows=``), so a tier migration is an O(1) host-side
  row remap instead of a device-side cache copy; the displaced retained
  prefix keeps its physical row and simply re-homes at the vacated logical
  slot.  Pages are cache rows in this revision — the indirection layer and
  its typestate are what the finer block granularity will ride on.
- ``RadixIndex``: a compressed radix tree over the token transcripts of every
  resident KV prefix (device-retained and host-spilled alike).  One
  ``match()`` walk replaces the per-mechanism linear lcp scans: system
  prompts, GRPO siblings, multi-turn history, and failover resubmits all
  become hits through the same structure.  Matching is exact: for every
  entry the walk returns ``lcp(entry.tokens, ids)`` — byte-for-byte the
  number the old vectorised ``seq_tokens`` scan produced — so the engine's
  greedy global assignment (and therefore its admission composition, and
  therefore its counter-keyed token streams) is unchanged bit for bit.
- ``HostOverflowTier``: an LRU byte-capped store of spilled KV prefixes in
  host DRAM.  A retained prefix about to be overwritten by admission is
  gathered to host (ops/kv_copy.py ``gather_kv_prefix``); a later radix hit
  scatters it back into a free row (``scatter_kv_prefix``) and the request
  suffix-prefills exactly as a device-retained hit would.  Transfers round-
  trip the raw cache dtype (no conversion), so a swapped-in prefix is
  bit-identical to the one that was evicted.

All lookups are host-side Python/numpy over tens of entries; nothing here
touches jax, so the admission planner stays free of device syncs and the
static-shape discipline of the compiled programs is untouched.
"""

import base64
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


def lcp_ids(a, b) -> int:
    """Longest common prefix of two token sequences (vectorised)."""
    m = min(len(a), len(b))
    if m == 0:
        return 0
    neq = np.asarray(a[:m], np.int64) != np.asarray(b[:m], np.int64)
    return int(neq.argmax()) if neq.any() else m


# ------------------------ handoff wire format --------------------------
# Cross-server KV page streaming (ISSUE 17): an exported prefix travels
# as JSON — token ids, the host-tier metadata, and each KV array as raw
# bytes base64'd with dtype+shape.  No float conversion anywhere, so an
# export -> wire -> import -> swap-in chain lands byte-for-byte the same
# cache content a local spill/swap-in round trip would (the exactness
# argument for disaggregated handoff rests on this plus the counter-keyed
# sampler streams).


def _wire_array(a: np.ndarray) -> Dict:
    a = np.ascontiguousarray(a)
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _unwire_array(doc: Dict) -> np.ndarray:
    flat = np.frombuffer(
        base64.b64decode(doc["b64"]), dtype=np.dtype(doc["dtype"])
    )
    return flat.reshape(doc["shape"])


def wire_encode_entry(entry: Dict) -> Dict:
    """JSON-safe wire form of an exported KV entry (the /kv_export
    response body / /kv_import request body)."""
    kv = {k: np.asarray(v) for k, v in entry["kv"].items()}
    return {
        "tokens": [int(t) for t in entry["tokens"]],
        "valid_len": int(entry["valid_len"]),
        "version": int(entry["version"]),
        "block": int(entry["block"]),
        # payload size before base64 inflation — the router's transfer
        # ledger and the handoff telemetry read this
        "nbytes": int(sum(a.nbytes for a in kv.values())),
        "kv": {k: _wire_array(a) for k, a in kv.items()},
    }


def wire_decode_entry(doc: Dict) -> Dict:
    """Inverse of wire_encode_entry; KV arrays come back bit-identical
    (read-only views over the decoded buffer — the import path never
    mutates them)."""
    return {
        "tokens": np.asarray(doc["tokens"], np.int64),
        "valid_len": int(doc["valid_len"]),
        "version": int(doc["version"]),
        "block": int(doc["block"]),
        "kv": {k: _unwire_array(v) for k, v in doc["kv"].items()},
    }


# --------------------------- radix index -------------------------------


class _Node:
    __slots__ = ("children", "entries", "parent")

    def __init__(self, parent: Optional["_Node"] = None):
        # first-token -> (edge tokens np.int64 [e], child node)
        self.children: Dict[int, Tuple[np.ndarray, "_Node"]] = {}
        self.entries: set = set()
        self.parent = parent


@dataclass
class _Entry:
    tokens: np.ndarray  # np.int64 [n] — the full resident transcript prefix
    node: _Node


class RadixIndex:
    """Compressed radix tree over token prefixes.

    Entries are attached at the node whose root path spells their exact
    token sequence; edges compress runs with no branch point.  ``match``
    walks the query once and reports, for EVERY entry, the exact longest
    common prefix with the query — entries hanging off the matched path get
    their divergence depth (including a partial match into the diverging
    edge), entries on the path get their own full length.
    """

    def __init__(self):
        self.root = _Node()
        self._entries: Dict[object, _Entry] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    def tokens(self, key) -> np.ndarray:
        return self._entries[key].tokens

    def insert(self, key, tokens) -> None:
        """(Re)attach `key` at the node spelling `tokens`, splitting a
        compressed edge at the divergence point when needed."""
        if key in self._entries:
            self.remove(key)
        toks = np.asarray(tokens, np.int64)
        node, d = self.root, 0
        while d < len(toks):
            t0 = int(toks[d])
            hop = node.children.get(t0)
            if hop is None:
                child = _Node(parent=node)
                node.children[t0] = (toks[d:], child)
                node, d = child, len(toks)
                continue
            edge, child = hop
            m = lcp_ids(edge, toks[d:])
            if m == len(edge):
                node, d = child, d + m
                continue
            # split the edge at the divergence point
            mid = _Node(parent=node)
            node.children[t0] = (edge[:m], mid)
            mid.children[int(edge[m])] = (edge[m:], child)
            child.parent = mid
            if d + m == len(toks):
                node, d = mid, len(toks)
            else:
                leaf = _Node(parent=mid)
                mid.children[int(toks[d + m])] = (toks[d + m:], leaf)
                node, d = leaf, len(toks)
        node.entries.add(key)
        self._entries[key] = _Entry(tokens=toks, node=node)

    def remove(self, key) -> Optional[np.ndarray]:
        """Detach `key`; prunes now-empty leaf nodes.  Returns the entry's
        tokens, or None when the key was absent."""
        ent = self._entries.pop(key, None)
        if ent is None:
            return None
        node = ent.node
        node.entries.discard(key)
        # prune empty leaves upward (edges re-merge lazily on insert)
        while (
            node.parent is not None
            and not node.entries
            and not node.children
        ):
            parent = node.parent
            for t0, (edge, child) in list(parent.children.items()):
                if child is node:
                    del parent.children[t0]
                    break
            node = parent
        return ent.tokens

    def clear(self) -> None:
        self.root = _Node()
        self._entries = {}

    def match(self, ids) -> Dict[object, int]:
        """Exact lcp against EVERY entry: {key: lcp(entry.tokens, ids)}."""
        out: Dict[object, int] = {}
        if not self._entries:
            return out
        ids = np.asarray(ids, np.int64)
        node, d = self.root, 0
        while node is not None:
            for key in node.entries:
                out[key] = d  # entry == ids[:d] exactly
            nxt = None
            tok = int(ids[d]) if d < len(ids) else None
            for t0, (edge, child) in node.children.items():
                if tok is not None and t0 == tok:
                    m = lcp_ids(edge, ids[d:])
                    if m == len(edge):
                        nxt = (child, d + m)
                    else:
                        self._collect(child, d + m, out)
                else:
                    self._collect(child, d, out)
            node, d = nxt if nxt is not None else (None, d)
        return out

    def _collect(self, node: _Node, lcp: int, out: Dict[object, int]):
        stack = [node]
        while stack:
            n = stack.pop()
            for key in n.entries:
                out[key] = lcp
            for _, child in n.children.values():
                stack.append(child)


# ------------------------ host overflow tier ---------------------------


@dataclass
class HostEntry:
    tokens: np.ndarray  # np.int64 [vlen]
    valid_len: int
    version: int
    block: int  # bucketed positions held by the kv arrays
    kv: Dict[str, np.ndarray]  # {"k": [L, block, Hkv, hd], "v": ...}
    nbytes: int = field(init=False)

    def __post_init__(self):
        self.nbytes = sum(int(a.nbytes) for a in self.kv.values())


class HostOverflowTier:
    """LRU byte-capped host-DRAM store of spilled KV prefixes.

    Insert evicts least-recently-used entries until the new one fits; a
    take (swap-in) removes the entry — the prefix becomes device-resident
    again and re-enters the radix as a device entry.  Arrays keep the raw
    cache dtype, so a spill/swap-in round trip is bit-identical.
    """

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = int(capacity_bytes)
        self.used_bytes = 0
        self._store: "OrderedDict[int, HostEntry]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._store)

    def __contains__(self, hid: int) -> bool:
        return hid in self._store

    def put(self, hid: int, entry: HostEntry) -> List[int]:
        """Insert; returns the hids LRU-evicted to make room.  An entry
        larger than the whole capacity is refused (returned as its own
        eviction) rather than flushing the tier for nothing."""
        if entry.nbytes > self.capacity_bytes:
            return [hid]
        evicted: List[int] = []
        while (
            self.used_bytes + entry.nbytes > self.capacity_bytes
            and self._store
        ):
            old_hid, old = self._store.popitem(last=False)
            self.used_bytes -= old.nbytes
            evicted.append(old_hid)
        self._store[hid] = entry
        self.used_bytes += entry.nbytes
        return evicted

    def take(self, hid: int) -> Optional[HostEntry]:
        ent = self._store.pop(hid, None)
        if ent is not None:
            self.used_bytes -= ent.nbytes
        return ent

    def touch(self, hid: int) -> None:
        if hid in self._store:
            self._store.move_to_end(hid)

    def clear(self) -> int:
        n = len(self._store)
        self._store.clear()
        self.used_bytes = 0
        return n


# ------------------------------ the pool -------------------------------


class KVPool:
    """Radix-fronted paged KV pool for one engine's slot grid.

    Owns the page table (logical slot -> physical cache row), the radix
    index over every resident prefix (device slots and host spills in ONE
    tree), and the optional host overflow tier.  The engine remains the
    owner of the per-slot numpy mirrors (``retained_len``/``seq_tokens``/
    ``kv_version`` — the C7 typestate arrays); this object is the lookup
    structure kept in lockstep with them at every acquire/release site.

    Consistency contract: a device entry exists only for a FREE slot and
    mirrors ``seq_tokens[s][:retained_len[s]]`` at insert time; matches are
    additionally validated against the engine's live ``retained_len``
    before use, so a missed bookkeeping call can cost a hit but can never
    fabricate one.
    """

    def __init__(self, n_slots: int, host_bytes: int = 0):
        self.n_slots = n_slots
        self.page_table = np.arange(n_slots + 1, dtype=np.int32)
        self.radix = RadixIndex()
        self.host: Optional[HostOverflowTier] = (
            HostOverflowTier(host_bytes) if host_bytes > 0 else None
        )
        self._next_host_id = 0

    # --- page table -----------------------------------------------------

    def row(self, slot: int) -> int:
        """Physical cache row backing a logical slot."""
        return int(self.page_table[slot])

    def rows_of(self, slots) -> np.ndarray:
        return self.page_table[np.asarray(slots, np.int64)]

    def device_rows(self) -> np.ndarray:
        """Kernel-consumable snapshot of the page table: a contiguous
        int32 copy (the ragged kernel scalar-prefetches it, and the
        decode dispatches upload it as traced data).  A COPY, not a
        view — the live table mutates under migration/free while an
        uploaded snapshot must stay frozen until the next state sync."""
        return np.ascontiguousarray(self.page_table, dtype=np.int32)

    def swap(self, a: int, b: int) -> None:
        """Remap two logical slots' physical rows (tier migration): the
        moving request's KV follows it with zero copies and the displaced
        retained prefix re-homes at the vacated slot.  Radix entries swap
        with their physical rows."""
        pt = self.page_table
        ra, rb = int(pt[a]), int(pt[b])
        pt[a], pt[b] = rb, ra
        ta = self.radix.remove(("dev", a))
        tb = self.radix.remove(("dev", b))
        if ta is not None:
            self.radix.insert(("dev", b), ta)
        if tb is not None:
            self.radix.insert(("dev", a), tb)

    # --- device entries -------------------------------------------------

    def note_free(self, slot: int, seq_row: np.ndarray, valid_len: int):
        """A slot released with `valid_len` retained tokens: (re)index its
        transcript prefix for radix matching."""
        if valid_len > 0:
            self.radix.insert(("dev", slot), seq_row[:valid_len].copy())
        else:
            self.radix.remove(("dev", slot))

    def drop_device(self, slot: int) -> int:
        """A slot's retained prefix is being overwritten (acquire).
        Returns the dropped entry's length (0 when none was indexed)."""
        toks = self.radix.remove(("dev", slot))
        return 0 if toks is None else len(toks)

    def device_tokens(self, slot: int) -> Optional[np.ndarray]:
        key = ("dev", slot)
        return self.radix.tokens(key) if key in self.radix else None

    def match_device(self, ids) -> Dict[int, int]:
        """{slot: exact lcp} over device-resident retained prefixes."""
        return {
            key[1]: l
            for key, l in self.radix.match(ids).items()
            if key[0] == "dev"
        }

    def clear_device(self) -> int:
        """Drop every device entry (strict weight swap / cache release)."""
        dropped = 0
        for key in [k for k in self.radix._entries if k[0] == "dev"]:
            self.radix.remove(key)
            dropped += 1
        return dropped

    # --- host overflow tier ---------------------------------------------

    def host_put(
        self,
        tokens: np.ndarray,
        valid_len: int,
        version: int,
        block: int,
        kv: Dict[str, np.ndarray],
    ) -> int:
        """Spill an evicted prefix to host DRAM; returns how many OLDER
        host entries the LRU evicted to make room (0 when it fit)."""
        assert self.host is not None, "host tier disabled"
        hid = self._next_host_id
        self._next_host_id += 1
        ent = HostEntry(
            tokens=np.asarray(tokens[:valid_len], np.int64).copy(),
            valid_len=valid_len, version=version, block=block, kv=kv,
        )
        evicted = self.host.put(hid, ent)
        if hid not in evicted:
            self.radix.insert(("host", hid), ent.tokens)
        n_evicted = 0
        for old in evicted:
            if old != hid:
                self.radix.remove(("host", old))
            n_evicted += 1
        return n_evicted

    def host_take(self, hid: int) -> Optional[HostEntry]:
        """Remove a host entry for swap-in (it becomes device-resident)."""
        self.radix.remove(("host", hid))
        return self.host.take(hid) if self.host is not None else None

    def host_entry(self, hid: int) -> Optional[HostEntry]:
        return self.host._store.get(hid) if self.host is not None else None

    def match_host(self, ids) -> Dict[int, int]:
        """{hid: exact lcp} over host-spilled prefixes."""
        return {
            key[1]: l
            for key, l in self.radix.match(ids).items()
            if key[0] == "host"
        }

    # --- lifecycle -------------------------------------------------------

    def clear(self) -> None:
        """Strict reset of every resident prefix, device AND host (strict
        weight swap: no old-policy KV may seed new decoding anywhere)."""
        self.radix.clear()
        if self.host is not None:
            self.host.clear()

    def reset(self) -> None:
        """Full reset including the page table (cache released/reallocated:
        physical rows are fresh, identity mapping is correct again)."""
        self.clear()
        self.page_table = np.arange(self.n_slots + 1, dtype=np.int32)

    def check_page_table(self) -> None:
        """The page table must stay a permutation with the scratch row
        pinned — the paged analogue of the C7 slot typestate (a duplicate
        row would alias two slots' KV; a lost row leaks cache)."""
        pt = np.sort(self.page_table)
        if not np.array_equal(pt, np.arange(self.n_slots + 1)):
            raise AssertionError(
                f"page_table is not a permutation: {self.page_table}"
            )
