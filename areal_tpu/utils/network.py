"""Host/network helpers (reference: areal/utils/network.py)."""

import socket
from contextlib import closing
from typing import List


def gethostname() -> str:
    return socket.gethostname()


def gethostip() -> str:
    try:
        # A UDP "connection" never sends packets; it just selects the outbound
        # interface so we learn our routable address.
        with closing(socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())


def find_free_ports(count: int, low: int = 1024, high: int = 65535) -> List[int]:
    """Reserve `count` distinct currently-free TCP ports."""
    ports: List[int] = []
    socks = []
    try:
        while len(ports) < count:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port = s.getsockname()[1]
            if low <= port <= high and port not in ports:
                ports.append(port)
                socks.append(s)
            else:
                s.close()
    finally:
        for s in socks:
            s.close()
    return ports


def find_free_port(**kw) -> int:
    return find_free_ports(1, **kw)[0]
