"""SLO report generation from lifecycle trace analytics.

Turns one analyzed event log (:func:`areal_tpu.obs.trace.analyze`) into
the repo's canonical SLO artifact — ``SLO_REPORT_*.json`` plus a
human-readable markdown twin:

- p50/p90/p99 per lifecycle stage (admission wait, prefill, decode,
  interrupt windows, delivery tail), TTFT, inter-token latency, and
  client-measured end-to-end;
- goodput (delivered trajectories/s and output tokens/s over the log's
  wall span);
- staleness-at-consumption and pause-window distributions (the paper's
  bounded-asynchrony evidence);
- the completeness verdict and the accounting-identity check, so a
  report built from a lossy or self-inconsistent log says so up front.

`scripts/check_slo.py` diffs these reports against a checked-in
baseline with per-metric tolerance bands; CI's `slo-smoke` job builds
one from a short replay run every push.

CLI::

    python -m areal_tpu.obs.slo events.jsonl --out SLO_REPORT_r01.json \
        --md SLO_REPORT_r01.md --run-id r01 [--require-complete] \
        [--require-identity] [--strict-open]
"""

import argparse
import json
import time
from typing import Any, Dict, List, Optional

from areal_tpu.obs import trace as trace_mod
from areal_tpu.obs.trace import (AccountingCheck, TraceReport,
                                 check_accounting, dist_summary)

SCHEMA = "areal-slo-report/v1"


def build_report(source: trace_mod.EventSource, *, run_id: str = "",
                 source_name: str = "", tolerance: float = 0.05,
                 abs_floor_s: float = 0.025, strict_open: bool = False,
                 dropped_events: Optional[int] = None) -> Dict[str, Any]:
    """Analyze ``source`` and assemble the SLO report dict."""
    rep: TraceReport = trace_mod.analyze(
        source, strict_open=strict_open, dropped_events=dropped_events)
    closed = rep.closed
    acct: AccountingCheck = check_accounting(
        rep.records, tolerance=tolerance, abs_floor_s=abs_floor_s)

    stage_samples: Dict[str, List[float]] = {}
    for r in closed:
        for k, v in r.stages.items():
            stage_samples.setdefault(k, []).append(v)

    out_tokens = sum(r.output_len or 0 for r in closed)
    span = rep.wall_span_s
    goodput = {
        "wall_span_s": span,
        "trajectories": len(closed),
        "output_tokens": out_tokens,
        "trajectories_per_s": (len(closed) / span) if span > 0 else None,
        "output_tokens_per_s": (out_tokens / span) if span > 0 else None,
    }

    pause_by_kind: Dict[str, int] = {}
    for p in rep.pauses:
        pause_by_kind[str(p.get("kind", ""))] = (
            pause_by_kind.get(str(p.get("kind", "")), 0) + 1)

    comp = rep.completeness
    report: Dict[str, Any] = {
        "schema": SCHEMA,
        "run_id": run_id,
        "source": source_name or (source if isinstance(source, str) else ""),
        "generated_unix": time.time(),
        "complete": comp.complete and acct.ok,
        "completeness": {
            "complete": comp.complete,
            "dropped_events": comp.dropped_events,
            "n_events": comp.n_events,
            "n_traces": comp.n_traces,
            "open_traces": comp.open_traces,
            "orphan_traces": comp.orphan_traces,
            "unjoined_resubmits": comp.unjoined_resubmits,
            "incomplete_interrupts": comp.incomplete_interrupts,
            "unmatched_consumes": comp.unmatched_consumes,
            "strict_open": comp.strict_open,
            "errors": comp.errors,
        },
        "accounting": {
            "ok": acct.ok,
            "tolerance": acct.tolerance,
            "abs_floor_s": acct.abs_floor_s,
            "checked": acct.checked,
            "violations": acct.violations,
            "max_rel_err": acct.max_rel_err,
            "mean_rel_err": acct.mean_rel_err,
        },
        "trajectories": {
            "n": len(rep.records),
            "closed": len(closed),
            "lost": sum(1 for r in rep.records if r.lost),
            "open": comp.open_traces,
            "resubmits": sum(r.resubmits for r in rep.records),
            "interrupts": sum(r.interrupts for r in rep.records),
        },
        "e2e_s": dist_summary(r.e2e_s for r in closed
                              if r.e2e_s is not None),
        "ttft_s": dist_summary(r.ttft_s for r in closed
                               if r.ttft_s is not None),
        "inter_token_s": dist_summary(r.inter_token_s for r in closed
                                      if r.inter_token_s is not None),
        "stages": {k: dist_summary(v)
                   for k, v in sorted(stage_samples.items())},
        "goodput": goodput,
        "staleness": dist_summary(r.staleness for r in rep.records
                                  if r.staleness is not None),
        "consume_latency_s": dist_summary(
            r.consume_latency_s for r in rep.records
            if r.consume_latency_s is not None),
        "reward": dist_summary(r.reward for r in rep.records
                               if r.reward is not None),
        "pause": {
            "n": len(rep.pauses),
            "by_kind": pause_by_kind,
            "dur_s": dist_summary(float(p.get("dur_s", 0.0) or 0.0)
                                  for p in rep.pauses),
        },
        "decode_chunks": {
            "per_tier": {
                str(tier): {"n": len(lats), "latency_s": dist_summary(lats)}
                for tier, lats in sorted(rep.chunk_latency_by_tier.items())
            },
        },
        "prefill": _prefill_summary(rep),
        "handoff": _handoff_summary(rep, closed),
    }
    return report


def _prefill_summary(rep: TraceReport) -> Dict[str, Any]:
    kinds: Dict[str, int] = {}
    cold = inherited = 0
    for r in rep.records:
        for k in r.prefill_kinds:
            kinds[k] = kinds.get(k, 0) + 1
        cold += r.cold_tokens
        inherited += r.inherited_tokens
    total = cold + inherited
    return {
        "kinds": kinds,
        "cold_tokens": cold,
        "inherited_tokens": inherited,
        "shared_fraction": (inherited / total) if total else None,
    }


def _handoff_summary(rep: TraceReport, closed) -> Dict[str, Any]:
    """Disaggregated prefill->decode handoff ledger (ISSUE 17): transfer
    counts/bytes from the `handoff` events plus the per-trajectory
    handoff-stage latency (the same samples the `stages.handoff` band in
    check_slo gates on)."""
    n = sum(r.handoffs for r in rep.records)
    return {
        "n": n,
        "trajectories": sum(1 for r in rep.records if r.handoffs),
        "bytes": sum(r.handoff_bytes for r in rep.records),
        "latency_s": dist_summary(
            r.stages["handoff"] for r in closed if "handoff" in r.stages),
    }


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v >= 1.0:
        return f"{v:.2f}s"
    return f"{v * 1e3:.1f}ms"


def _dist_row(name: str, d: Optional[Dict[str, float]]) -> str:
    if not d:
        return f"| {name} | - | - | - | - | - |"
    return (f"| {name} | {d['count']} | {_fmt_s(d['p50'])} "
            f"| {_fmt_s(d['p90'])} | {_fmt_s(d['p99'])} "
            f"| {_fmt_s(d['max'])} |")


def render_markdown(report: Dict[str, Any]) -> str:
    """Human twin of the JSON report: headline verdicts + stage table."""
    comp = report["completeness"]
    acct = report["accounting"]
    traj = report["trajectories"]
    good = report["goodput"]
    lines = [
        f"# SLO report {report.get('run_id') or ''}".rstrip(),
        "",
        f"- source: `{report.get('source', '')}`",
        f"- complete: **{report['complete']}** "
        f"(dropped_events={comp['dropped_events']}, "
        f"orphans={len(comp['orphan_traces'])}, "
        f"unjoined_resubmits={comp['unjoined_resubmits']}, "
        f"open={comp['open_traces']})",
        f"- accounting identity: **{'ok' if acct['ok'] else 'VIOLATED'}** "
        f"({acct['checked']} trajectories checked, "
        f"max_rel_err={acct['max_rel_err'] if acct['max_rel_err'] is None else round(acct['max_rel_err'], 4)}, "
        f"tol={acct['tolerance']})",
        f"- trajectories: {traj['closed']} closed / {traj['open']} open / "
        f"{traj['lost']} lost ({traj['resubmits']} resubmits, "
        f"{traj['interrupts']} interrupts)",
        f"- goodput: {_rate(good['trajectories_per_s'])} traj/s, "
        f"{_rate(good['output_tokens_per_s'])} output tok/s "
        f"over {good['wall_span_s']:.1f}s",
        "",
        "| stage | n | p50 | p90 | p99 | max |",
        "|---|---|---|---|---|---|",
        _dist_row("end-to-end", report["e2e_s"]),
        _dist_row("ttft", report["ttft_s"]),
        _dist_row("inter-token", report["inter_token_s"]),
    ]
    for name, d in (report.get("stages") or {}).items():
        lines.append(_dist_row(f"stage:{name}", d))
    for tier, td in (report["decode_chunks"]["per_tier"] or {}).items():
        lines.append(_dist_row(f"decode-chunk tier={tier}", td["latency_s"]))
    st = report.get("staleness")
    if st:
        st_line = ("- staleness at consumption: "
                   f"p50={st['p50']:.1f} p99={st['p99']:.1f} "
                   f"max={st['max']:.0f}")
    else:
        st_line = "- staleness at consumption: n/a"
    pa = report.get("pause", {})
    pause_line = f"- pause windows: n={pa.get('n', 0)}"
    if pa.get("dur_s"):
        pause_line += f" p99={_fmt_s(pa['dur_s']['p99'])}"
    ho = report.get("handoff") or {}
    if ho.get("n"):
        ho_line = (f"- kv handoffs: {ho['n']} over "
                   f"{ho.get('trajectories', 0)} trajectories, "
                   f"{ho.get('bytes', 0)} bytes")
        if ho.get("latency_s"):
            ho_line += f", p99={_fmt_s(ho['latency_s']['p99'])}"
    else:
        ho_line = "- kv handoffs: none"
    lines += ["", st_line, pause_line, ho_line, ""]
    return "\n".join(lines)


def _rate(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.2f}"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Build an SLO report from a lifecycle events JSONL")
    ap.add_argument("events", help="events.jsonl from EventLog.dump_jsonl")
    ap.add_argument("--out", default="", help="report JSON path")
    ap.add_argument("--md", default="", help="markdown twin path")
    ap.add_argument("--run-id", default="")
    ap.add_argument("--tolerance", type=float, default=0.05)
    ap.add_argument("--abs-floor-s", type=float, default=0.025)
    ap.add_argument("--strict-open", action="store_true")
    ap.add_argument("--require-complete", action="store_true",
                    help="exit 1 unless completeness passes")
    ap.add_argument("--require-identity", action="store_true",
                    help="exit 1 unless the accounting identity holds")
    args = ap.parse_args(argv)

    report = build_report(
        args.events, run_id=args.run_id, tolerance=args.tolerance,
        abs_floor_s=args.abs_floor_s, strict_open=args.strict_open)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=False)
            f.write("\n")
    if args.md:
        with open(args.md, "w") as f:
            f.write(render_markdown(report))
    print(render_markdown(report))

    rc = 0
    if args.require_complete and not report["completeness"]["complete"]:
        print("FAIL: trace completeness violated")
        rc = 1
    if args.require_identity and not report["accounting"]["ok"]:
        print("FAIL: accounting identity violated")
        rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
