"""CLEVR-count SFT — supervised vision-language finetuning.

Behavioral counterpart of the reference's
`examples/vlm/clevr_count_70k_sft.py`: (image, question, count) triples
train the LM loss on the answer span, with pixels flowing through the
vision tower exactly as in RL training (engine/vlm_engine.py).

Dataset rows come from the clevr loader (areal_tpu/dataset/clevr.py):
either an AutoProcessor patchifies images at collate time, or rows are
pre-patchified (offline manifests with inline pixel_values +
image_grid_thw).

Launch:  python examples/vlm/clevr_sft.py --config examples/vlm/clevr_sft.yaml
"""

import sys

import numpy as np

from areal_tpu.api.config import SFTConfig, load_expr_config
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta, StepInfo
from areal_tpu.dataset import get_custom_dataset
from areal_tpu.engine.vlm_engine import JaxVLMLMEngine
from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.models.vision import mrope_position_ids
from areal_tpu.utils import logging, seeding, stats
from areal_tpu.utils.data import pad_sequences_to_tensors
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.stats_logger import StatsLogger

logger = logging.getLogger("clevr_sft")


def tokenize_sample(sample, tokenizer, processor, model_cfg, max_length):
    """-> token row (input_ids, loss_mask, mrope_positions) + patch arrays."""
    if "input_ids" in sample:
        prompt_ids = list(sample["input_ids"])
        pv = np.asarray(sample["pixel_values"], np.float32)
        grid = np.asarray(sample["image_grid_thw"], np.int64).reshape(-1, 3)
    else:
        if processor is None:
            raise ValueError("need an AutoProcessor or pre-tokenized rows")
        from areal_tpu.utils.image import load_images

        processed = processor(
            images=load_images(sample["images"]),
            text=sample["messages"],
            padding=False,
        )
        ids = processed["input_ids"]
        prompt_ids = list(ids[0] if hasattr(ids[0], "__len__") else ids)
        pv = np.asarray(processed["pixel_values"], np.float32)
        grid = np.asarray(processed["image_grid_thw"], np.int64).reshape(-1, 3)
    answer_ids = tokenizer.encode(
        str(sample["answer"]), add_special_tokens=False
    )
    if tokenizer.eos_token_id is not None:
        answer_ids = answer_ids + [tokenizer.eos_token_id]
    if len(prompt_ids) >= max_length:
        # NEVER truncate into the prompt: cutting an image-placeholder run
        # desyncs patches from tokens (mrope would reject the row anyway)
        return None
    ids = (prompt_ids + answer_ids)[:max_length]
    n_prompt = len(prompt_ids)
    loss_mask = [0.0] * n_prompt + [1.0] * (len(ids) - n_prompt)
    merge = model_cfg.vision.spatial_merge_size
    mrope = mrope_position_ids(
        np.asarray(ids, np.int64), grid, model_cfg.image_token_id,
        spatial_merge_size=merge,
    ).T  # [T, 3]
    return (
        {
            "input_ids": np.asarray(ids, np.int32),
            "loss_mask": np.asarray(loss_mask, np.float32),
            "mrope_positions": mrope.astype(np.int32),
        },
        pv,
        grid,
    )


def collate(samples, tokenizer, processor, model_cfg, max_length):
    from areal_tpu.models.vision import patch_arrays_for_rows

    rows, pv_parts, grids = [], [], []
    for s in samples:
        tokenized = tokenize_sample(
            s, tokenizer, processor, model_cfg, max_length
        )
        if tokenized is None:
            logger.warning("dropping over-length sample %s",
                           s.get("query_id", "?"))
            continue
        row, pv, grid = tokenized
        rows.append(row)
        pv_parts.append(pv)
        grids.append(grid)
    if not rows:
        raise ValueError(
            "every sample in the batch exceeded max_length; raise "
            "train_dataset.max_length"
        )
    batch = pad_sequences_to_tensors(rows)
    ids, pos_hw, spans = patch_arrays_for_rows(
        grids, model_cfg.vision.spatial_merge_size
    )
    batch["pixel_values"] = np.concatenate(pv_parts)
    batch["patch_img_ids"] = ids
    batch["patch_pos_hw"] = pos_hw
    batch["patches_per_row"] = spans
    return batch


def main(argv):
    config, _ = load_expr_config(argv, SFTConfig)
    seeding.set_random_seed(config.seed, "sft")

    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(
        config.tokenizer_path or config.model.path
    )
    processor = None
    try:
        from transformers import AutoProcessor

        processor = AutoProcessor.from_pretrained(
            config.tokenizer_path or config.model.path
        )
    except Exception:  # noqa: BLE001 — pre-tokenized manifests need none
        logger.warning("no AutoProcessor; expecting pre-tokenized rows")

    model_cfg = TransformerConfig.from_hf(config.model.path)
    if model_cfg.vision is None:
        raise ValueError(f"{config.model.path} has no vision_config")

    train_dataset = get_custom_dataset(
        path=config.train_dataset.path,
        type=config.train_dataset.type or "clevr",
        split="train",
        tokenizer=tokenizer,
        processor=processor,
        max_length=config.train_dataset.max_length,
    )
    dataloader = StatefulDataLoader(
        train_dataset,
        batch_size=config.train_dataset.batch_size,
        shuffle=config.train_dataset.shuffle,
        drop_last=config.train_dataset.drop_last,
        seed=config.seed,
    )
    steps_per_epoch = len(dataloader)
    total_steps = config.total_train_steps or (
        config.total_train_epochs * steps_per_epoch
    )
    ft_spec = FinetuneSpec(
        total_train_epochs=config.total_train_epochs,
        dataset_size=len(train_dataset),
        train_batch_size=config.train_dataset.batch_size,
    )

    engine = JaxVLMLMEngine(config.model, model_config=model_cfg)
    engine.initialize(ft_spec=ft_spec)
    saver = Saver(config.saver, ft_spec)
    stats_logger = StatsLogger(config.stats_logger)
    max_len = config.train_dataset.max_length or 1024

    global_step = 0
    step_info = StepInfo(
        global_step=0, epoch=0, epoch_step=0, steps_per_epoch=steps_per_epoch
    )
    for epoch in range(config.total_train_epochs):
        for epoch_step, samples in enumerate(dataloader):
            if global_step >= total_steps:
                break
            batch = collate(samples, tokenizer, processor, model_cfg, max_len)
            with stats.DEFAULT_TRACKER.scope("sft"):
                st = engine.train_lm(batch)
                stats.DEFAULT_TRACKER.scalar(
                    **{k: v for k, v in st.items() if np.isscalar(v)}
                )
            engine.step_lr_scheduler()
            step_info = StepInfo(
                global_step=global_step,
                epoch=epoch,
                epoch_step=epoch_step,
                steps_per_epoch=steps_per_epoch,
            )
            saver.save(engine, epoch, epoch_step, global_step, tokenizer=tokenizer)
            stats_logger.commit(
                epoch, epoch_step, global_step,
                [stats.DEFAULT_TRACKER.export()],
            )
            logger.info(
                f"Epoch {epoch + 1}/{config.total_train_epochs} "
                f"Step {epoch_step + 1}/{steps_per_epoch} done. "
                f"loss={st['loss']:.4f} ppl={st['ppl']:.2f}"
            )
            global_step += 1

    engine.save(
        SaveLoadMeta(path=saver.save_path(step_info, "final"), tokenizer=tokenizer)
    )
    stats_logger.close()
    engine.destroy()


if __name__ == "__main__":
    main(sys.argv[1:])
