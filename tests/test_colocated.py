"""Colocated (time-shared) allocation runtime (VERDICT r3 weak #4).

The `a|b` allocation now has a real implementation: serving and training
alternate on the same devices, the engine's HBM is released around train
steps, and weights hand over in memory.
"""

import asyncio

import jax
import numpy as np
import pytest

from areal_tpu.api.config import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.api.workflow import RolloutWorkflow
from areal_tpu.engine.colocated import ColocatedEngine
from areal_tpu.models import init_params
from areal_tpu.models.model_config import tiny_config


@pytest.fixture(scope="module")
def cfg_params():
    cfg = tiny_config(vocab_size=97, eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


class _EchoWorkflow(RolloutWorkflow):
    async def arun_episode(self, engine, data):
        resp = await engine.agenerate(ModelRequest(
            rid=str(data["query_id"]),
            input_ids=list(data["ids"]),
            gconfig=GenerationHyperparameters(max_new_tokens=6, greedy=True),
        ))
        ids = list(data["ids"]) + resp.output_tokens
        return {
            "input_ids": np.array([ids], np.int32),
            "attention_mask": np.ones((1, len(ids)), bool),
            "versions": np.array([resp.output_versions[-1:] * len(ids)],
                                 np.int32),
        }


def test_colocated_rollout_train_alternation(cfg_params):
    cfg, params = cfg_params
    eng = ColocatedEngine(cfg, params=params, n_slots=4, max_seq_len=64,
                          prompt_bucket=16)
    rng = np.random.default_rng(0)
    data = [{"query_id": i, "ids": rng.integers(0, 97, 5).tolist()}
            for i in range(6)]
    batch = eng.rollout_batch(data, workflow=_EchoWorkflow())
    assert batch["input_ids"].shape[0] == 6

    # train phase: serving HBM released, then in-memory weight handoff
    with eng.train_phase():
        assert eng.engine.cache is None
        assert eng.engine.params is None  # text model: everything dropped
        new_params = init_params(cfg, jax.random.PRNGKey(1))  # "train step"
    eng.publish_weights(new_params, version=1)
    assert eng.get_version() == 1
    assert eng.engine.cache is not None

    # serving works again under the new weights
    batch2 = eng.rollout_batch(data, workflow=_EchoWorkflow())
    assert batch2["input_ids"].shape[0] == 6
    assert int(batch2["versions"].max()) == 1
    eng.destroy()


def test_colocated_abort_resume_contract(cfg_params):
    """A request in flight when the train phase begins is aborted and then
    transparently resumed (accumulated tokens resubmitted) after publish."""
    cfg, params = cfg_params
    eng = ColocatedEngine(cfg, params=params, n_slots=2, max_seq_len=64,
                          prompt_bucket=16)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 97, 5).tolist()

    async def _run():
        task = asyncio.create_task(eng.agenerate(ModelRequest(
            rid="r", input_ids=ids,
            gconfig=GenerationHyperparameters(max_new_tokens=24, greedy=True),
        )))
        # let some tokens land, then interrupt with a weight update
        await asyncio.sleep(0.3)
        with eng.train_phase():
            pass
        eng.publish_weights(init_params(cfg, jax.random.PRNGKey(2)), version=5)
        return await task

    resp = asyncio.run(_run())
    assert len(resp.output_tokens) == 24
    assert resp.stop_reason in ("stop", "length")
    # if the abort landed mid-generation, version spans prove the resume
    assert set(resp.output_versions) <= {0, 5}


def test_resume_serving_same_weights(cfg_params):
    cfg, params = cfg_params
    eng = ColocatedEngine(cfg, params=params, n_slots=2, max_seq_len=64,
                          prompt_bucket=16)
    with eng.train_phase():
        pass
    with pytest.raises(RuntimeError, match="restage"):
        eng.resume_serving()  # params were dropped; same-weight resume needs them
    eng.destroy()

    # with drop_params=False the cache-only cycle works
    eng2 = ColocatedEngine(cfg, params=params, n_slots=2, max_seq_len=64,
                           prompt_bucket=16)
    eng2.stop_serving()
    eng2.engine.release_memory(drop_params=False)
    eng2.resume_serving()
    rng = np.random.default_rng(2)
    data = [{"query_id": 0, "ids": rng.integers(0, 97, 5).tolist()}]
    batch = eng2.rollout_batch(data, workflow=_EchoWorkflow())
    assert batch["input_ids"].shape[0] == 1
    eng2.destroy()
