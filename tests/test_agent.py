"""Agent layer tests: math env, single-step and multi-turn agents, the
AgentWorkflow adapter (reference analog: realhf/impl/agent math agents +
rollout-worker driving; here the asyncio workflow surface drives them)."""

import asyncio

import numpy as np

from areal_tpu.agent import AgentWorkflow, MathMultiTurnAgent, MathSingleStepAgent, make_agent
from areal_tpu.agent.math_env import MathVerifyEnv
from areal_tpu.api.config import GenerationHyperparameters


class _Tok:
    def encode(self, text, add_special_tokens=False):
        return [ord(c) % 256 for c in text]

    def decode(self, tokens):
        return "".join(chr(t) for t in tokens)

    def apply_chat_template(self, messages, **kw):
        return self.encode("".join(m["content"] for m in messages))


class _ScriptedEngine:
    """Replies from a script, one entry per agenerate call."""

    def __init__(self, replies):
        self.replies = list(replies)
        self.calls = 0

    async def agenerate(self, req):
        text = self.replies[min(self.calls, len(self.replies) - 1)]
        self.calls += 1
        out = [ord(c) % 256 for c in text]

        class R:
            input_tokens = list(req.input_ids)
            output_tokens = out
            output_logprobs = [-0.2] * len(out)
            output_versions = [1] * len(out)
            input_len = len(req.input_ids)
            output_len = len(out)
            stop_reason = "stop"

        return R()


def test_math_env_verifies():
    async def run():
        async with MathVerifyEnv("42") as env:
            assert env.list_tools()[0]["name"] == "verify_answer"
            _, r_good, done = await env.aexecute_tool(
                "verify_answer", {"completion": "the answer is 42"}
            )
            _, r_bad, _ = await env.aexecute_tool(
                "verify_answer", {"completion": "the answer is 41"}
            )
            return r_good, done, r_bad

    r_good, done, r_bad = asyncio.run(run())
    assert r_good == 1.0 and done
    assert r_bad == 0.0


def test_single_step_agent_workflow():
    agent = MathSingleStepAgent(
        GenerationHyperparameters(n_samples=2, max_new_tokens=8), tokenizer=_Tok()
    )
    wf = AgentWorkflow(agent, env_factory=lambda: MathVerifyEnv("7"))
    engine = _ScriptedEngine(["the answer is 7"])
    batch = asyncio.run(wf.arun_episode(engine, {"prompt": "what is 3+4?"}))
    assert batch["input_ids"].shape[0] == 2
    np.testing.assert_array_equal(batch["rewards"], [1.0, 1.0])


def test_multi_turn_agent_retries_with_discount():
    agent = MathMultiTurnAgent(
        GenerationHyperparameters(max_new_tokens=8),
        tokenizer=_Tok(),
        max_turns=3,
        turn_discount=0.5,
    )
    wf = AgentWorkflow(agent, env_factory=lambda: MathVerifyEnv("9"))
    engine = _ScriptedEngine(["the answer is 3", "the answer is 9"])
    batch = asyncio.run(wf.arun_episode(engine, {"prompt": "what is 4+5?"}))
    assert engine.calls == 2  # wrong once, then correct
    np.testing.assert_allclose(batch["rewards"], [0.5])  # one retry discount
    # feedback tokens are present but not trained on
    assert batch["loss_mask"].sum() < (batch["input_ids"] != 0).sum()


def test_agent_registry():
    agent = make_agent(
        "math-multi-turn",
        gconfig=GenerationHyperparameters(),
        tokenizer=_Tok(),
    )
    assert isinstance(agent, MathMultiTurnAgent)
