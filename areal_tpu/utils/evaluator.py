"""Frequency-controlled evaluation callback
(reference: areal/utils/evaluator.py `Evaluator`)."""

from typing import Callable, Optional

from areal_tpu.api.config import EvaluatorConfig
from areal_tpu.utils import logging
from areal_tpu.utils.timer import FrequencyControl

logger = logging.getLogger("evaluator")


class Evaluator:
    def __init__(self, config: EvaluatorConfig, ft_spec=None):
        self.config = config
        self.ft_spec = ft_spec
        self.freq = FrequencyControl(config)

    def evaluate(
        self,
        evaluate_fn: Callable[[], Optional[dict]],
        epoch: int,
        epoch_step: int,
        global_step: int,
        force: bool = False,
    ) -> Optional[dict]:
        if not self.freq.check(epoch, global_step, force=force):
            return None
        result = evaluate_fn()
        logger.info(f"eval @ step {global_step}: {result}")
        return result

    def state_dict(self):
        return {"freq": self.freq.state_dict()}

    def load_state_dict(self, state):
        self.freq.load_state_dict(state["freq"])
