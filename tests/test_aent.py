"""AEnt recipe: clamped entropy + adaptive entropy-coefficient GRPO
(reference: recipe/AEnt)."""

import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.config import (
    MeshConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.models.model_config import tiny_config
from areal_tpu.ops.functional import _clamped_entropy
from areal_tpu.recipes import AEntConfig, AEntPPOActorConfig, JaxAEntPPOActor

MODEL_CFG = tiny_config(vocab_size=64, qkv_bias=True, hf_architecture="Qwen2ForCausalLM")


def test_clamped_entropy_math():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(5, 32)).astype(np.float32))
    full = _clamped_entropy(logits, 0.0)
    p = np.exp(np.asarray(logits)) / np.exp(np.asarray(logits)).sum(-1, keepdims=True)
    expect = -(p * np.log(p)).sum(-1)
    np.testing.assert_allclose(np.asarray(full), expect, rtol=1e-5)

    # clamping reduces entropy (mass renormalised over fewer tokens)
    clamped = _clamped_entropy(logits, 0.5)
    assert np.all(np.asarray(clamped) <= np.asarray(full) + 1e-6)

    # extreme clamp -> near-deterministic over the single kept token
    extreme = _clamped_entropy(logits, 1.0 - 1.0 / 32)
    assert np.all(np.asarray(extreme) < 0.7)


def _actor(aent: AEntConfig, group_size=4):
    cfg = AEntPPOActorConfig(
        experiment_name="aent",
        trial_name="t",
        init_from_scratch=True,
        dtype="float32",
        gradient_checkpointing=False,
        mesh=MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=1),
        optimizer=OptimizerConfig(
            lr=5e-3, warmup_steps_proportion=0.0, weight_decay=0.0
        ),
        pack_length_quantum=16,
        group_size=group_size,
        ppo_n_minibatches=1,
        adv_norm=NormConfig(
            mean_level="group", std_level="group", group_size=group_size
        ),
        aent=aent,
    )
    actor = JaxAEntPPOActor(cfg, model_config=MODEL_CFG)
    actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    return actor


def _batch(rng, B=8, L=16, prompt_len=4):
    ids = rng.integers(0, MODEL_CFG.vocab_size, (B, L)).astype(np.int32)
    loss_mask = np.zeros((B, L), np.float32)
    loss_mask[:, prompt_len:] = 1.0
    return {
        "input_ids": ids,
        "attention_mask": np.ones((B, L), bool),
        "loss_mask": loss_mask,
        "logprobs": rng.normal(-1.0, 0.1, (B, L)).astype(np.float32) * loss_mask,
        "rewards": (ids[:, prompt_len] % 2 == 0).astype(np.float32),
        "versions": np.zeros((B, L), np.int32),
    }


def test_aent_update_and_adaptive_coeff():
    aent = AEntConfig(
        entropy_coeff=5e-3,
        entropy_clamp=0.25,
        adaptive=True,
        entropy_low=100.0,  # force H < low -> coeff must INCREASE
        entropy_high=200.0,
        coeff_lr=1e-3,
        coeff_box_high=1.0,
        warmup_steps=0,
    )
    actor = _actor(aent)
    try:
        rng = np.random.default_rng(1)
        batch = _batch(rng)
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)

        c0 = actor.actor.entropy_coeff
        stats = actor.ppo_update(batch)
        assert np.isfinite(stats[-1]["loss"])
        assert stats[-1]["entropy"] > 0
        # entropy (a few nats) << entropy_low=100 -> coeff rises
        assert actor.actor.entropy_coeff > c0
        assert stats[-1]["entropy_coeff"] == actor.actor.entropy_coeff

        # coefficient stays inside the box under repeated updates
        for _ in range(2):
            actor.compute_advantages(batch)
            actor.ppo_update(batch)
        assert aent.coeff_box_low <= actor.actor.entropy_coeff <= aent.coeff_box_high
    finally:
        actor.destroy()


def test_aent_coeff_decreases_above_band():
    aent = AEntConfig(
        entropy_coeff=5e-3,
        adaptive=True,
        entropy_low=0.0,
        entropy_high=1e-6,  # force H > high -> coeff must DECREASE
        coeff_lr=1e-4,
        warmup_steps=0,
    )
    actor = _actor(aent)
    try:
        rng = np.random.default_rng(2)
        batch = _batch(rng)
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)
        c0 = actor.actor.entropy_coeff
        actor.ppo_update(batch)
        assert actor.actor.entropy_coeff < c0
    finally:
        actor.destroy()
