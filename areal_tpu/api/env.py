"""Agent environment interface (reference: areal/api/env_api.py:5)."""

import abc
from typing import Any, Dict, List, Tuple


class Environment(abc.ABC):
    """Tool-providing environment for agentic rollouts."""

    async def ainitialize(self) -> None: ...

    async def aclose(self) -> None: ...

    @abc.abstractmethod
    def list_tools(self) -> List[Dict[str, Any]]:
        """JSON-schema tool descriptions exposed to the policy."""

    @abc.abstractmethod
    async def aexecute_tool(
        self, tool_name: str, arguments: Dict[str, Any]
    ) -> Tuple[Any, float, bool]:
        """Execute a tool; returns (observation, reward, done)."""

    async def __aenter__(self):
        await self.ainitialize()
        return self

    async def __aexit__(self, *exc):
        await self.aclose()
