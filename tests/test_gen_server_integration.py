"""Full async-RL slice against the REAL generation server: aiohttp server on
the real GenEngine (tiny model, CPU) driven by RemoteJaxEngine +
RLVRWorkflow + WorkflowExecutor, including a disk weight update mid-stream.

This is the integration pattern of the reference's test_sglang_engine.py
(spin up a real tiny server) rather than the fake-server unit tests."""

import asyncio
import threading
import time

import numpy as np
import pytest
from aiohttp import web

from areal_tpu.api.config import GenerationHyperparameters, InferenceEngineConfig
from areal_tpu.api.io_struct import ModelRequest, WeightUpdateMeta
from areal_tpu.engine.jax_remote import RemoteJaxEngine
from areal_tpu.gen.engine import GenEngine
from areal_tpu.gen.server import GenServer
from areal_tpu.models import init_params
from areal_tpu.models.hf import save_hf_checkpoint
from areal_tpu.models.model_config import tiny_config
from areal_tpu.utils import network
from areal_tpu.workflow.rlvr import RLVRWorkflow

CFG = tiny_config(vocab_size=89, qkv_bias=True, hf_architecture="Qwen2ForCausalLM",
                  eos_token_id=None)


def _boot_server(engine: GenEngine):
    """Start a GenServer + aiohttp loop thread around `engine`; returns
    (server, addr, stop) where stop() tears the loop down."""
    server = GenServer(engine)
    server.start()
    port = network.find_free_port()
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(server.app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.time() + 10
    import urllib.request

    while time.time() < deadline:
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=1)
            break
        except Exception:
            time.sleep(0.1)
    else:
        raise RuntimeError("server did not come up")

    def stop():
        server.shutdown.set()
        loop.call_soon_threadsafe(loop.stop)

    return server, f"127.0.0.1:{port}", stop


@pytest.fixture(scope="module")
def live_server():
    import jax

    params = init_params(CFG, jax.random.PRNGKey(0))
    engine = GenEngine(CFG, params=params, n_slots=4, max_seq_len=96,
                       prompt_bucket=16)
    _, addr, stop = _boot_server(engine)
    yield engine, addr
    stop()


def _client(addr, **kw) -> RemoteJaxEngine:
    cfg = InferenceEngineConfig(
        experiment_name="e", trial_name="t", consumer_batch_size=2,
        max_concurrent_rollouts=8, request_timeout=30,
        max_head_offpolicyness=100, **kw,
    )
    eng = RemoteJaxEngine(cfg)
    eng.initialize(addr=addr)
    return eng


def test_agenerate_against_real_server(live_server):
    engine, addr = live_server
    client = _client(addr)
    try:
        resp = asyncio.run(client.agenerate(ModelRequest(
            input_ids=[5, 6, 7],
            gconfig=GenerationHyperparameters(max_new_tokens=8, greedy=True),
        )))
        assert len(resp.output_tokens) == 8
        assert resp.stop_reason == "length"
        assert len(resp.output_logprobs) == 8
        assert all(v == engine.version for v in resp.output_versions)
    finally:
        client.destroy()


def test_rollout_batch_with_rlvr_workflow(live_server):
    engine, addr = live_server
    client = _client(addr)
    try:
        wf = RLVRWorkflow(
            reward_fn=lambda prompt, comp, ptoks, ctoks, **kw: float(len(ctoks) % 2),
            gconfig=GenerationHyperparameters(n_samples=2, max_new_tokens=6),
        )
        data = [{"input_ids": [3, 4, 5]}, {"input_ids": [9, 8, 7, 6]}]
        batch = client.rollout_batch(data, workflow=wf)
        assert batch["input_ids"].shape[0] == 4  # 2 prompts x 2 samples
        assert "logprobs" in batch and "rewards" in batch and "versions" in batch
        assert batch["attention_mask"].any(axis=1).all()
    finally:
        client.destroy()


def test_disk_weight_update_changes_outputs(live_server, tmp_path):
    import jax

    engine, addr = live_server
    client = _client(addr)
    try:
        req = ModelRequest(
            input_ids=[11, 12, 13],
            gconfig=GenerationHyperparameters(max_new_tokens=6, greedy=True),
        )
        before = asyncio.run(client.agenerate(req))
        v0 = engine.version

        new_params = init_params(CFG, jax.random.PRNGKey(123))
        ckpt = tmp_path / "w"
        save_hf_checkpoint(new_params, CFG, str(ckpt), save_dtype="float32")
        client.pause()
        client.update_weights(WeightUpdateMeta(type="disk", path=str(ckpt)))
        client.resume()
        assert engine.version == v0 + 1

        after = asyncio.run(client.agenerate(req.copy()))
        assert set(after.output_versions) == {v0 + 1}
        assert after.output_tokens != before.output_tokens
    finally:
        client.destroy()


def test_staged_transfer_commit_is_pointer_swap(live_server):
    """VERDICT r3 weak #2: after the trainer streams chunks and POSTs
    `prepare`, the weights sit pre-placed on device; `commit` is an
    O(abort) pointer swap, NOT a host->device placement inside the pause.
    Exercises the raw wire protocol end to end."""
    import base64
    import json
    import urllib.request

    import jax
    import ml_dtypes

    from areal_tpu.models.hf import params_to_hf_state

    engine, addr = live_server

    def post(ep, payload=None, data=None, headers=None, expect=200):
        if data is not None:
            req = urllib.request.Request(
                f"http://{addr}{ep}", data=data,
                headers={"Content-Type": "application/octet-stream",
                         **(headers or {})},
            )
        else:
            req = urllib.request.Request(
                f"http://{addr}{ep}", data=json.dumps(payload or {}).encode(),
                headers={"Content-Type": "application/json"},
            )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                assert resp.status == expect
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            assert e.code == expect, (e.code, e.read()[:300])
            return json.loads(e.read() or b"{}")

    # stream a fresh param set as binary chunks
    new_params = init_params(CFG, jax.random.PRNGKey(123))
    bf16 = np.dtype(ml_dtypes.bfloat16)
    for name, arr in params_to_hf_state(
        jax.tree_util.tree_map(np.asarray, new_params), CFG
    ):
        raw = np.ascontiguousarray(arr.astype(bf16)).tobytes()
        post(
            "/update_weights_chunk", data=raw,
            headers={
                "X-Weight-Name": name,
                "X-Weight-Dtype": "bfloat16",
                "X-Weight-Shape": json.dumps(list(arr.shape)),
                "X-Weight-Nbytes": str(len(raw)),
                "X-Weight-Offset": "0",
            },
        )

    v_target = engine.version + 7
    out = post("/update_weights_chunk", {"prepare": True, "version": v_target})
    assert out["staged"] is True
    # generation still runs between prepare and commit, with OLD weights
    assert engine.has_standby and engine.staged_version == v_target
    r = post("/generate", {"rid": "mid", "input_ids": [3, 4, 5],
                           "sampling_params": {"max_new_tokens": 4,
                                               "temperature": 0.0}})
    assert r["version"] == v_target - 7  # still the old version

    out = post("/update_weights_chunk", {"commit": True, "version": v_target})
    assert out["version"] == v_target
    assert engine.version == v_target
    assert not engine.has_standby
    # the achieved pause window was recorded and is tiny (pointer swap,
    # not a model-sized placement — generous bound for CI jitter)
    m = json.loads(urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=10).read())
    assert m["last_pause_s"] < 1.0
    # serving continues under the new weights
    r = post("/generate", {"rid": "post", "input_ids": [3, 4, 5],
                           "sampling_params": {"max_new_tokens": 4,
                                               "temperature": 0.0}})
    assert r["version"] == v_target

    # prepare without chunks is a clean 409
    post("/update_weights_chunk", {"prepare": True}, expect=409)


@pytest.fixture(scope="module")
def race_server():
    """Separate server with enough sequence headroom that long-budget
    requests are still decoding while a whole weight publish streams in —
    the truly-concurrent regime (no pause_generation anywhere)."""
    import jax

    params = init_params(CFG, jax.random.PRNGKey(5))
    engine = GenEngine(CFG, params=params, n_slots=4, max_seq_len=1024,
                       prompt_bucket=16)
    _, addr, stop = _boot_server(engine)
    yield engine, addr
    stop()


def test_live_commit_races_concurrent_generation(race_server):
    """VERDICT r4 weak #6: drive concurrent generation + live commit +
    per-token version stamping through the HTTP stack with NO pause — the
    decode loop races the chunk stream, the device-stage and the live
    commit, and every in-flight request must survive with its per-token
    versions recording the policy transition."""
    import json
    import urllib.request

    import jax
    import ml_dtypes

    from areal_tpu.models.hf import params_to_hf_state

    engine, addr = race_server
    v0 = engine.version

    def post(ep, payload=None, data=None, headers=None):
        if data is not None:
            req = urllib.request.Request(
                f"http://{addr}{ep}", data=data,
                headers={"Content-Type": "application/octet-stream",
                         **(headers or {})},
            )
        else:
            req = urllib.request.Request(
                f"http://{addr}{ep}", data=json.dumps(payload or {}).encode(),
                headers={"Content-Type": "application/json"},
            )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read())

    # pre-encode every chunk BEFORE generation starts so the racing window
    # is pure wire traffic, not numpy conversion time
    new_params = init_params(CFG, jax.random.PRNGKey(77))
    bf16 = np.dtype(ml_dtypes.bfloat16)
    chunks = []
    for name, arr in params_to_hf_state(
        jax.tree_util.tree_map(np.asarray, new_params), CFG
    ):
        raw = np.ascontiguousarray(arr.astype(bf16)).tobytes()
        chunks.append((raw, {
            "X-Weight-Name": name,
            "X-Weight-Dtype": "bfloat16",
            "X-Weight-Shape": json.dumps(list(arr.shape)),
            "X-Weight-Nbytes": str(len(raw)),
            "X-Weight-Offset": "0",
        }))

    boxes = [{} for _ in range(3)]

    def _gen(i):
        boxes[i]["resp"] = post("/generate", {
            "rid": f"race-{i}", "input_ids": [7 + i, 8, 9],
            "sampling_params": {"max_new_tokens": 700, "temperature": 1.0},
        })

    threads = [threading.Thread(target=_gen, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        reqs = [r for r in engine.slot_req if r is not None]
        if len(reqs) == 3 and all(len(r.output_tokens) >= 3 for r in reqs):
            break
        time.sleep(0.005)
    else:
        pytest.fail("requests never started decoding")

    # stream + stage + commit while decoding continues (NO pause)
    for raw, hdrs in chunks:
        post("/update_weights_chunk", data=raw, headers=hdrs)
    v1 = v0 + 1
    out = post("/update_weights_chunk", {"prepare": True, "version": v1})
    assert out["staged"] is True
    out = post("/update_weights_chunk",
               {"commit": True, "version": v1, "live": True})
    assert out["version"] == v1
    # the commit landed mid-flight: nobody was aborted and at least one
    # request is still decoding under the new weights
    still_running = [i for i, b in enumerate(boxes) if "resp" not in b]
    assert still_running, (
        "all requests finished before the live commit landed — the race "
        "window closed; raise max_new_tokens"
    )

    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads)
    straddled = 0
    for b in boxes:
        resp = b["resp"]
        assert resp["stop_reason"] == "length"
        assert len(resp["output_tokens"]) == 700
        vs = resp["output_versions"]
        assert len(vs) == 700
        # versions never go backwards and only {v0, v1} appear
        assert all(a <= b2 for a, b2 in zip(vs, vs[1:]))
        assert set(vs) <= {v0, v1}
        if set(vs) == {v0, v1}:
            straddled += 1
    assert straddled >= 1, "no request recorded the policy transition"


def test_live_commit_keeps_inflight_request_decoding(live_server):
    """`commit` with `live: true` swaps staged weights WITHOUT aborting:
    an in-flight request survives the publish and its per-token versions
    record the policy transition (the wire-level counterpart of
    GenEngine.swap_weights_live; WeightUpdateMeta.live_commit sends this)."""
    import json
    import threading as _threading
    import urllib.request

    import jax
    import ml_dtypes

    from areal_tpu.models.hf import params_to_hf_state

    engine, addr = live_server
    v0 = engine.version

    def post(ep, payload=None, data=None, headers=None):
        if data is not None:
            req = urllib.request.Request(
                f"http://{addr}{ep}", data=data,
                headers={"Content-Type": "application/octet-stream",
                         **(headers or {})},
            )
        else:
            req = urllib.request.Request(
                f"http://{addr}{ep}", data=json.dumps(payload or {}).encode(),
                headers={"Content-Type": "application/json"},
            )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read())

    # long-budget request in flight on a background thread
    box = {}

    def _gen():
        box["resp"] = post("/generate", {
            "rid": "live", "input_ids": [11, 12, 13],
            "sampling_params": {"max_new_tokens": 60, "temperature": 0.0},
        })

    t = _threading.Thread(target=_gen)
    t.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        reqs = [r for r in engine.slot_req if r is not None]
        if reqs and len(reqs[0].output_tokens) >= 3:
            break
        time.sleep(0.005)
    else:
        pytest.fail("request never started decoding")
    # park decoding deterministically while we stage + commit
    post("/pause_generation")

    new_params = init_params(CFG, jax.random.PRNGKey(321))
    bf16 = np.dtype(ml_dtypes.bfloat16)
    for name, arr in params_to_hf_state(
        jax.tree_util.tree_map(np.asarray, new_params), CFG
    ):
        raw = np.ascontiguousarray(arr.astype(bf16)).tobytes()
        post("/update_weights_chunk", data=raw, headers={
            "X-Weight-Name": name,
            "X-Weight-Dtype": "bfloat16",
            "X-Weight-Shape": json.dumps(list(arr.shape)),
            "X-Weight-Nbytes": str(len(raw)),
            "X-Weight-Offset": "0",
        })
    v1 = v0 + 3
    out = post("/update_weights_chunk", {"prepare": True, "version": v1})
    assert out["staged"] is True
    out = post("/update_weights_chunk",
               {"commit": True, "version": v1, "live": True})
    assert out["version"] == v1
    # the in-flight request was NOT aborted by the live commit
    assert "resp" not in box or box["resp"]["stop_reason"] != "abort"
    post("/continue_generation")

    t.join(timeout=60)
    assert not t.is_alive()
    resp = box["resp"]
    assert resp["stop_reason"] == "length"
    assert len(resp["output_tokens"]) == 60
    # tokens straddle the publish: old version before, new after
    assert resp["output_versions"][0] == v0
    assert resp["output_versions"][-1] == v1
    assert set(resp["output_versions"]) == {v0, v1}


def test_generate_batch_groups_share_prefix(live_server):
    """POST /generate_batch submits a whole GRPO group in one request: the
    engine admits it as one prefix-sharing cluster (one representative
    prefill + device-side KV fan-out), every member gets a full result,
    and /metrics surfaces the shared-token accounting for the fleet."""
    import json
    import urllib.request

    engine, addr = live_server
    shared_before = engine.stats["shared_tokens"]
    prompt = list(range(5, 25))  # > reuse_min_tokens so the cluster forms
    body = {
        "requests": [
            {"rid": f"gb-{i}", "group_id": "gb", "group_n": 3,
             "input_ids": prompt,
             "sampling_params": {"max_new_tokens": 4, "temperature": 1.0}}
            for i in range(3)
        ]
    }
    req = urllib.request.Request(
        f"http://{addr}/generate_batch",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    out = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert len(out["results"]) == 3
    for r in out["results"]:
        assert len(r["output_tokens"]) == 4
        assert r["stop_reason"] == "length"
    # the two siblings rode the representative's prefix KV
    assert (engine.stats["shared_tokens"] - shared_before
            >= 2 * (len(prompt) - 1))
    m = json.loads(urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=5
    ).read())
    assert m["shared_tokens"] >= 2 * (len(prompt) - 1)
    assert m["copy_calls"] >= 1
    # the abort-reservation TTL counter is exported (VERDICT r6 #10) and
    # stays zero on this storm-free path
    assert m["reservations_lapsed"] == 0
    # tiered decode observability (ISSUE 5): attended-span fraction,
    # per-cohort occupancy/layout, and the migration counter all ride
    # /metrics so the fleet can see what decode actually pays
    assert 0.0 < m["decode_attended_fraction"] <= 1.0
    assert isinstance(m["tier_occupancy"], list)
    assert m["tier_slots"] and sum(m["tier_slots"]) == engine.n_slots
    assert m["tier_lens"][-1] == engine.max_seq_len
    # spec-decode accounting (ISSUE 12) is always exported; this engine
    # runs with spec decode off so every field sits at zero
    assert m["spec_drafted"] == 0
    assert m["spec_accepted"] == 0
    assert m["spec_acceptance_rate"] == 0.0
    assert m["verify_calls"] == 0
    assert m["tier_migrations"] >= 0
    # unified prefix cache (ISSUE 16): the two siblings are hits through
    # the radix pool, so the global hit-rate reflects them; each result
    # reports its warm-started prompt span on the wire
    assert m["prefix_cache_hits"] >= 2
    assert m["prefix_cache_misses"] >= 1
    assert 0.0 < m["prefix_cache_hit_rate"] <= 1.0
    assert m["prefix_cache_evictions"] >= 0
    assert m["prefix_cache_host_swaps"] == 0  # host tier off by default
    hits = sorted(r["cache_hit_tokens"] for r in out["results"])
    assert hits[0] == 0  # the representative cold-prefilled
    assert hits[-1] >= len(prompt) - 1  # siblings rode its prefix K/V


def test_stats_key_miss_is_counted_not_silent(live_server):
    """ISSUE 18 satellite: an absent/renamed engine.stats key must not
    silently degrade to 0 in the legacy /metrics JSON — every tolerant
    fallback lookup increments areal_gen_stats_key_misses_total so the
    drift is visible on the Prometheus surface."""
    import json
    import urllib.request

    engine, addr = live_server
    removed = engine.stats.pop("copy_calls", None)
    try:
        legacy = json.loads(urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=10).read())
        # the scrape still serves (tolerant fallback) ...
        assert legacy["copy_calls"] == 0
        prom = urllib.request.urlopen(
            f"http://{addr}/metrics?format=prometheus", timeout=10
        ).read().decode()
        lines = [
            ln for ln in prom.splitlines()
            if ln.startswith("areal_gen_stats_key_misses_total")
        ]
        # ... but the degradation is counted, not silent
        assert lines, "stats-miss counter missing from the scrape surface"
        assert float(lines[0].split()[-1]) >= 1.0
    finally:
        if removed is not None:
            engine.stats["copy_calls"] = removed


def test_ragged_telemetry_on_scrape_surface():
    """ISSUE 19 satellite: a ragged-enabled server exposes the kernel's
    dispatch counter and the attended-pages gauge on BOTH scrape surfaces
    (legacy JSON and Prometheus) after real decode traffic, with names
    pinned in tests/data/metrics_schema.json."""
    import json
    import urllib.request

    import jax

    params = init_params(CFG, jax.random.PRNGKey(0))
    engine = GenEngine(CFG, params=params, n_slots=4, max_seq_len=96,
                       prompt_bucket=16, ragged_attn=True)
    assert engine._ragged_ok
    _, addr, stop = _boot_server(engine)
    try:
        client = _client(addr)
        try:
            resp = asyncio.run(client.agenerate(ModelRequest(
                input_ids=[5, 6, 7],
                gconfig=GenerationHyperparameters(max_new_tokens=8,
                                                  greedy=True),
            )))
            assert len(resp.output_tokens) == 8
        finally:
            client.destroy()

        legacy = json.loads(urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=10).read())
        assert legacy["ragged_dispatches"] > 0
        assert legacy["ragged_attended_pages"] > 0
        prom = urllib.request.urlopen(
            f"http://{addr}/metrics?format=prometheus", timeout=10
        ).read().decode()
        scraped = {
            ln.split()[0]: float(ln.split()[-1])
            for ln in prom.splitlines()
            if ln and not ln.startswith("#")
        }
        assert scraped.get("areal_gen_ragged_dispatches_total", 0) > 0
        assert scraped.get("areal_gen_ragged_attended_pages_total", 0) > 0
        # mean pages gathered per dispatch — the kernel's work metric
        assert "areal_gen_ragged_attended_pages" in scraped
        assert scraped["areal_gen_ragged_attended_pages"] > 0
    finally:
        stop()
