"""Mixture-of-Experts feed-forward block, GShard/Switch style.

Capability counterpart of the reference's MoE stack
(realhf/impl/model/modules/moe/{experts,router,grouped GEMM} and the
Megatron EP path, areal/engine/megatron_engine.py:451-535;
alloc grammar e/etp dims, areal/api/alloc_mode.py:80-117).  TPU-first
design:

- **Two dispatch implementations** behind one `moe_ffn` entry point:
  "capacity" uses dense dispatch/combine tensors ([tokens, E, C] one-hot)
  so routing becomes three einsums that XLA tiles straight onto the MXU —
  replacing the reference's grouped-GEMM CUDA kernels and permutation
  indices, with capacity C bounding each expert's work; "dropless" sorts
  assignments by expert and runs `lax.ragged_dot` grouped GEMMs (the
  MegaBlocks shape), reproducing HF Mixtral/Qwen3-MoE exactly — loaded
  checkpoints default to it (model_config.from_hf_dict).
- Expert weights live as [E, D, F] leaves sharded over the mesh's `ep`
  axis (partition specs in transformer.param_partition_specs); the
  dispatch einsum's contraction over tokens is what GSPMD turns into the
  all-to-all the reference drives through NCCL EP groups.
- Top-k routing with renormalised gates (mixtral convention), plus the
  Switch-style load-balancing auxiliary loss E * sum(f_i * P_i), threaded
  functionally through the layer scan (no global state).
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.model_config import TransformerConfig

Params = Dict[str, jax.Array]


def expert_capacity(
    n_tokens: int, num_experts: int, top_k: int, capacity_factor: float = 1.25
) -> int:
    """Static per-expert token budget; multiples of 8 for TPU tiling."""
    c = int(n_tokens * top_k / num_experts * capacity_factor) + 1
    return max(8, (c + 7) // 8 * 8)


def _route(lp: Params, x: jax.Array, k: int):
    """Shared top-k router: -> (probs [N, E] fp32, gate_vals [N, k]
    renormalised, gate_idx [N, k])."""
    router_logits = jnp.einsum(
        "nd,de->ne", x.astype(jnp.float32), lp["router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [N, E] fp32
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [N, k]
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)
    return probs, gate_vals, gate_idx


def _aux_loss(probs: jax.Array, gate_idx: jax.Array, E: int) -> jax.Array:
    """Switch load-balancing loss: E * sum_i f_i * P_i where f_i is the
    fraction of tokens whose FIRST choice is expert i and P_i the mean
    router probability for i."""
    first = jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32)
    f = jnp.mean(first, axis=0)
    p = jnp.mean(probs, axis=0)
    return jnp.asarray(E, jnp.float32) * jnp.sum(f * p)


def moe_ffn(
    cfg: TransformerConfig,
    lp: Params,  # router [D, E], w_gate/w_up [E, D, Fm], w_down [E, Fm, D]
    h: jax.Array,  # [B, T, D]
    dtype,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (output [B, T, D], load-balance aux loss scalar fp32).

    cfg.moe_impl picks the dispatch: "capacity" (GShard dense dispatch,
    tokens past the per-expert budget dropped) or "dropless" (exact HF
    Mixtral/Qwen3-MoE semantics via sort + grouped GEMM)."""
    if cfg.moe_impl == "dropless":
        return _moe_ffn_dropless(cfg, lp, h, dtype)
    return _moe_ffn_capacity(cfg, lp, h, dtype)


def _moe_ffn_dropless(
    cfg: TransformerConfig, lp: Params, h: jax.Array, dtype
) -> Tuple[jax.Array, jax.Array]:
    """Dropless token routing — the semantics real HF MoE checkpoints were
    trained with (HF MixtralSparseMoeBlock / Qwen3MoeSparseMoeBlock apply
    every top-k assignment with no capacity bound), so loaded checkpoints
    produce batch-size-independent logits (ADVICE r3).

    TPU shape: sort the N*k (token, expert) assignments by expert id, run
    one grouped GEMM per projection with `lax.ragged_dot` (MegaBlocks-style
    — the expert boundary is a group-sizes vector, shapes stay static), and
    scatter-add weighted outputs back.  FLOPs equal capacity-mode at factor
    1.0 with zero drops; no [N, E, C] dispatch tensors are materialised."""
    B, T, D = h.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    N = B * T
    x = h.reshape(N, D)
    probs, gate_vals, gate_idx = _route(lp, x, k)

    e_flat = gate_idx.reshape(-1)  # [N*k] expert id per assignment
    order = jnp.argsort(e_flat)  # stable: preserves token order per expert
    tok = order // k  # source token per sorted assignment
    xs = jnp.take(x, tok, axis=0)  # [N*k, D]
    group_sizes = jnp.bincount(e_flat, length=E).astype(jnp.int32)

    gate = jax.lax.ragged_dot(xs, lp["w_gate"].astype(dtype), group_sizes)
    up = jax.lax.ragged_dot(xs, lp["w_up"].astype(dtype), group_sizes)
    ys = jax.lax.ragged_dot(
        jax.nn.silu(gate) * up, lp["w_down"].astype(dtype), group_sizes
    )  # [N*k, D]

    w_sorted = jnp.take(gate_vals.reshape(-1), order).astype(dtype)
    out = jnp.zeros((N, D), dtype).at[tok].add(ys * w_sorted[:, None])
    return out.reshape(B, T, D), _aux_loss(probs, gate_idx, E)


def _moe_ffn_capacity(
    cfg: TransformerConfig, lp: Params, h: jax.Array, dtype
) -> Tuple[jax.Array, jax.Array]:
    B, T, D = h.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    N = B * T
    C = expert_capacity(N, E, k, cfg.moe_capacity_factor)
    x = h.reshape(N, D)
    probs, gate_vals, gate_idx = _route(lp, x, k)

    # position-in-expert assignment, choice-major priority (first choices
    # beat second choices for capacity, standard GShard ordering)
    dispatch = jnp.zeros((N, E, C), jnp.float32)
    combine = jnp.zeros((N, E, C), jnp.float32)
    fill = jnp.zeros((E,), jnp.float32)
    for j in range(k):  # k is tiny and static
        oh = jax.nn.one_hot(gate_idx[:, j], E, dtype=jnp.float32)  # [N, E]
        pos = jnp.cumsum(oh, axis=0) - 1 + fill[None, :]  # [N, E]
        keep = oh * (pos < C)
        slot = jax.nn.one_hot(
            jnp.sum(pos * oh, axis=-1).astype(jnp.int32), C, dtype=jnp.float32
        )  # [N, C]
        d_j = keep[:, :, None] * slot[:, None, :]  # [N, E, C]
        dispatch = dispatch + d_j
        combine = combine + d_j * gate_vals[:, j, None, None]
        fill = fill + jnp.sum(oh, axis=0)

    xe = jnp.einsum("nec,nd->ecd", dispatch.astype(dtype), x)  # [E, C, D]
    gate = jnp.einsum("ecd,edf->ecf", xe, lp["w_gate"].astype(dtype))
    up = jnp.einsum("ecd,edf->ecf", xe, lp["w_up"].astype(dtype))
    ye = jnp.einsum(
        "ecf,efd->ecd", jax.nn.silu(gate) * up, lp["w_down"].astype(dtype)
    )  # [E, C, D]
    out = jnp.einsum("nec,ecd->nd", combine.astype(dtype), ye)
    return out.reshape(B, T, D), _aux_loss(probs, gate_idx, E)
