"""Device-mesh construction and sharding helpers.

TPU-native counterpart of the reference's process-group plumbing: what FSDP2
DeviceMesh setup (areal/utils/fsdp/parallel.py:87), Megatron 5-D initialization
(areal/engine/megatron_engine.py:176-237) and the legacy ParallelGrid
(realhf/base/topology.py:369) achieve with explicit NCCL groups is here a
single `jax.sharding.Mesh` over axes (dp, fsdp, sp, tp); GSPMD derives every
collective from PartitionSpecs, so there is no group bookkeeping to port.

Axis semantics:
- dp: pure data parallel (replicated params, sharded batch rows)
- fsdp: ZeRO-style — params/optimizer sharded here AND batch rows sharded
  (the reference's dp axis under FSDP2 plays both roles too)
- sp: sequence dimension of activations (Ulysses/CP-equivalent; GSPMD
  inserts the head/seq all-to-alls the reference hand-writes in
  areal/utils/ulysses.py)
- tp: tensor parallel (megatron column/row split via the model's specs)
- ep: expert parallel (MoE expert dim; the reference's
  expert_parallel_size, alloc_mode.py:80-117 / megatron EP groups) — the
  ep axis also carries batch rows when dense layers run, so ep chips are
  never idle outside MoE blocks
"""

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from areal_tpu.api.alloc import ParallelStrategy

MeshAxes = ("dp", "fsdp", "ep", "sp", "tp")


def build_mesh(
    dp: int = 1,
    fsdp: int = 1,
    sp: int = 1,
    tp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build the 5-axis mesh. Axis order puts tp innermost so tensor-parallel
    collectives ride the fastest ICI links."""
    if devices is None:
        devices = jax.devices()
    need = dp * fsdp * sp * tp * ep
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    dev = np.asarray(devices[:need]).reshape(dp, fsdp, ep, sp, tp)
    return Mesh(dev, MeshAxes)


def mesh_from_alloc(
    strategy: ParallelStrategy, devices: Optional[Sequence[Any]] = None
) -> Mesh:
    """Map an allocation-DSL ParallelStrategy onto mesh axes.

    The DSL's context/sequence parallel sizes both land on the `sp` axis
    (they are the same axis on TPU: shard the sequence dim, let GSPMD insert
    gathers); pipeline parallel is intentionally not an axis — GSPMD+ICI
    covers TPU slices without PP (SURVEY.md §7).
    """
    if strategy.pipeline_parallel_size > 1:
        raise NotImplementedError(
            "pipeline parallelism is not a TPU mesh axis; use fsdp/tp/sp"
        )
    sp = strategy.sequence_parallel_size * strategy.context_parallel_size
    return build_mesh(
        dp=strategy.data_parallel_size,
        fsdp=strategy.fsdp_parallel_size,
        sp=sp,
        tp=strategy.tensor_parallel_size,
        ep=strategy.expert_parallel_size,
        devices=devices,
    )


def batch_spec(per_token: bool = True) -> P:
    """PartitionSpec for [R, L(, ...)] batch arrays: rows over
    (dp, fsdp, ep) — ep chips carry rows through the dense layers and
    exchange tokens for expert compute inside the MoE block — sequence
    over sp."""
    if per_token:
        return P(("dp", "fsdp", "ep"), "sp")
    return P(("dp", "fsdp", "ep"))


def named_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def shard_pytree(mesh: Mesh, tree: Any, specs: Any) -> Any:
    """Host pytree -> sharded device pytree (specs mirrors tree).

    Single-process: plain device_put.  Multi-process: every process holds
    the identical host values and contributes its local shards via
    make_array_from_callback (device_put cannot target non-addressable
    devices)."""
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
        )

    def _make(x, s):
        sharding = NamedSharding(mesh, s)
        if isinstance(x, jax.Array):
            # already a device array (e.g. the trainer's params in a
            # colocated publish): reshard device-to-device — np.asarray
            # would gather through the host (and raise outright on
            # non-addressable shards)
            return jax.device_put(x, sharding)
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx, x=x: x[idx]
        )

    return jax.tree_util.tree_map(_make, tree, specs)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
