"""Real-TPU probe: 1.5B GRPO train-step throughput vs batch size / ctx.

Finds the HBM-filling workload for bench.py and prints tokens/sec + step
time + achieved TFLOP/s per configuration.
"""

import sys
import time

import jax
import numpy as np

sys.path.insert(0, "/root/repo")

from areal_tpu.api.config import (
    MeshConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.ppo import JaxPPOActor
from areal_tpu.models.model_config import qwen25_1p5b


def make_batch(rng, n_rows, row_len, vocab, seqs_per_row=2):
    seq_len = row_len // seqs_per_row
    B = n_rows * seqs_per_row
    ids = rng.integers(0, vocab, (B, seq_len)).astype(np.int32)
    mask = np.ones((B, seq_len), bool)
    prompt = seq_len // 4
    loss_mask = np.zeros((B, seq_len), np.float32)
    loss_mask[:, prompt:] = 1.0
    return {
        "input_ids": ids,
        "attention_mask": mask,
        "loss_mask": loss_mask,
        "logprobs": rng.normal(-1.0, 0.1, (B, seq_len)).astype(np.float32),
        "rewards": rng.integers(0, 2, B).astype(np.float32),
        "versions": np.zeros((B, seq_len), np.int32),
    }


def run(n_rows, row_len, n_mbs, attn_impl="auto", scan_unroll=4,
        remat_policy="full", split_transpose=False):
    # scan_unroll/remat_policy live on the TRAIN config (the engine
    # overrides model_config with them, jax_train.py:156-161);
    # split_transpose only exists on the model config
    model_cfg = qwen25_1p5b().replace(
        attn_impl=attn_impl, scan_split_transpose=split_transpose
    )
    cfg = PPOActorConfig(
        experiment_name="bench",
        trial_name="bench",
        init_from_scratch=True,
        dtype="bfloat16",
        param_dtype="bfloat16",
        gradient_checkpointing=True,
        remat_policy=remat_policy,
        scan_unroll=scan_unroll,
        async_stats=True,
        mesh=MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=n_mbs),
        optimizer=OptimizerConfig(lr=1e-5, warmup_steps_proportion=0.0),
        pack_length_quantum=row_len,
        max_pack_length=row_len,
        group_size=2,
        ppo_n_minibatches=1,
        use_decoupled_loss=True,
        adv_norm=NormConfig(mean_level="group", std_level="group", group_size=2),
    )
    actor = JaxPPOActor(cfg, model_config=model_cfg)
    actor.initialize(ft_spec=FinetuneSpec(1, 1024, 8))
    rng = np.random.default_rng(0)
    batch = make_batch(rng, n_rows, row_len, model_cfg.vocab_size)
    batch["prox_logp"] = batch["logprobs"].copy()
    actor.compute_advantages(batch)
    tokens = int(batch["attention_mask"].sum())
    for _ in range(4):
        actor.ppo_update(batch)
    jax.block_until_ready(actor.params)
    t0 = time.perf_counter()
    N = 3
    for _ in range(N):
        actor.ppo_update(batch)
    jax.block_until_ready(actor.params)
    dt = (time.perf_counter() - t0) / N
    tps = tokens / dt
    # 6*P FLOPs/token (fwd+bwd) + remat refwd (2*P) + attention
    P = 1.54e9
    flops = tokens * 6 * P
    print(
        f"rows={n_rows} len={row_len} mbs={n_mbs} impl={attn_impl} "
        f"unroll={scan_unroll} remat={remat_policy} split={split_transpose}: "
        f"{tps:,.0f} tok/s  step={dt * 1e3:.0f} ms  "
        f"model-flops {flops / dt / 1e12:.1f} TF/s",
        flush=True,
    )
    actor.destroy()
    return tps


if __name__ == "__main__":
    # (n_rows, row_len, n_mbs) + knob overrides; run as
    #   python scripts/tpu_train_probe.py [sweep]
    sweep = sys.argv[1:] == ["sweep"]
    combos = (
        [  # unroll ladder x remat policy with the fused LM head resident
            dict(scan_unroll=4, remat_policy="full"),
            dict(scan_unroll=7, remat_policy="full"),
            dict(scan_unroll=14, remat_policy="full"),
            dict(scan_unroll=2, remat_policy="full"),
            dict(scan_unroll=4, remat_policy="full", split_transpose=True),
            dict(scan_unroll=4, remat_policy="save_attn"),
            dict(scan_unroll=7, remat_policy="save_attn"),
        ]
        if sweep
        else [dict()]
    )
    for kw in combos:
        for args in [(8, 2048, 1)] if sweep else [(12, 2048, 1), (16, 2048, 1)]:
            try:
                run(*args, **kw)
            except Exception as e:
                msg = str(e)
                print(
                    f"{args} {kw}: FAIL "
                    f"{'OOM' if 'RESOURCE_EXHAUSTED' in msg else msg[:200]}",
                    flush=True,
                )
