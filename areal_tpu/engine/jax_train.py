"""JaxTrainEngine — the SPMD training backend.

Capability counterpart of BOTH reference train engines: FSDPEngine
(areal/engine/fsdp_engine.py:64) and MegatronEngine
(areal/engine/megatron_engine.py:67).  One engine suffices on TPU because a
single GSPMD mesh (dp, fsdp, sp, tp) subsumes FSDP2 sharding, megatron TP/SP
and Ulysses:

- "create_process_group" = build the Mesh (no NCCL group zoo).
- "parallelize_model" = device_put params with PartitionSpecs from
  `areal_tpu.models.param_partition_specs`; XLA inserts all collectives.
- train_batch = ONE jit per (loss_fn, shape-signature): micro-batch gradient
  accumulation is a `lax.scan` over a stacked [n_mb, rows, row_len] batch —
  the whole optimizer step (fwd, bwd, accumulate, clip, adamw, lr schedule)
  is a single XLA program with donated state (the reference needs a python
  loop over micro-batches + DTensor full_tensor gathers).
- Batches use the row-packed layout (utils/data.py `pack_into_rows`):
  packed like the reference's flat layout (base_hf_engine.py:257
  prepare_mb_list) yet shardable over (dp, fsdp) with static shapes.

Loss functions follow the reference's protocol (engine_api.py train_batch):
`loss_fn(logits, mb) -> (sum_loss, stats_sums)`, `loss_weight_fn(batch) ->
float`; gradients are globally normalised by the summed weight across all
micro-batches (fsdp_engine.py:499-606's global loss-weight normalisation).
loss_fn must be a *stable* callable — the compiled step is cached per
(id(loss_fn), shapes).
"""
# areal-lint: hot-path

import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from areal_tpu.api.config import TrainEngineConfig
from areal_tpu.api.engine import TrainEngine
from areal_tpu.api.io_struct import (
    FinetuneSpec,
    SaveLoadMeta,
    WeightUpdateMeta,
)
from areal_tpu.models import (
    TransformerConfig,
    forward_lm as model_forward_lm,
    init_params,
    param_partition_specs,
)
from areal_tpu.models.hf import load_hf_params, save_hf_checkpoint
from areal_tpu.parallel import (
    batch_spec,
    build_mesh,
    distributed,
    mesh_from_alloc,
    shard_pytree,
)
from areal_tpu.utils import logging, name_resolve, names, telemetry
from areal_tpu.utils import stats as tracker
from areal_tpu.utils.data import (
    RowPackedBatch,
    pack_into_rows,
    unpack_rows,
)
from areal_tpu.utils.datapack import round_up_to_bucket
from areal_tpu.ops.functional import lm_logprobs_entropy

logger = logging.getLogger("jax_train")


def _logp_hook(model_out, mb):
    """Default forward hook: next-token logprobs at predictor positions
    (the reference's compute_logp convention, ppo/actor.py:52)."""
    labels = jnp.roll(mb["input_ids"], -1, axis=-1)
    logp, _, _ = lm_logprobs_entropy(model_out, labels, with_entropy=False)
    return logp


class JaxTrainEngine(TrainEngine):
    def __init__(
        self,
        config: TrainEngineConfig,
        model_config: Optional[TransformerConfig] = None,
    ):
        self.config = config
        self.model_config = model_config
        self.mesh = None
        self.params = None
        self.opt_state = None
        self.step_count = 0
        self._version = 0
        self._optimizer = None
        self._schedule = None
        self._train_step_cache: Dict[Tuple, Callable] = {}
        self._forward_cache: Dict[Tuple, Callable] = {}
        self._ft_spec: Optional[FinetuneSpec] = None
        self._transfer_executor = None  # lazy: weight-transfer push thread
        self._staged = None  # (meta.type, version) staged by stage_weights
        self.last_weight_update_seconds: Optional[float] = None
        self.initialized = False
        # the jitted step functions call self._model_fn(params, cfg, ids,
        # positions, segment_ids, mesh=mesh); the default returns a deferred
        # LMOutput (chunked-head memory discipline); value/reward engines
        # override it to return per-token values instead
        self._model_fn = model_forward_lm

    # ------------------------------------------------------------------
    # setup
    # ------------------------------------------------------------------

    def create_process_group(self, alloc_mode=None) -> None:
        if self.mesh is not None:
            return
        # multi-host: join the global JAX runtime first (env-gated no-op in
        # the single-process dev path) — the TPU equivalent of
        # init_process_group (reference: fsdp_engine.py:112)
        distributed.init_distributed()
        if alloc_mode is not None and getattr(alloc_mode, "train", None):
            self.mesh = mesh_from_alloc(alloc_mode.train)
        else:
            m = self.config.mesh
            self.mesh = build_mesh(
                dp=m.data_parallel_size,
                fsdp=m.fsdp_parallel_size,
                sp=m.sequence_parallel_size,
                tp=m.tensor_parallel_size,
                ep=getattr(m, "expert_parallel_size", 1),
            )
        logger.info(f"mesh: {dict(zip(self.mesh.axis_names, self.mesh.devices.shape))}")

    def initialize(
        self,
        addr: Optional[str] = None,
        ft_spec: Optional[FinetuneSpec] = None,
    ) -> None:
        self.create_process_group()
        self._ft_spec = ft_spec
        cfg = self.config
        if getattr(cfg, "attn_impl", "auto") not in (
            "auto", "splash", "naive", "ring",
        ):
            # forwarded verbatim into the model config: an unknown value
            # (typo, or this field's pre-wiring legacy spellings) would
            # silently select the splash/auto ladder
            raise ValueError(
                f"unknown attn_impl {cfg.attn_impl!r}: use auto, splash, "
                "naive, or ring"
            )
        if cfg.path and not cfg.init_from_scratch:
            host_params, mc = load_hf_params(
                cfg.path, self.model_config, dtype=cfg.param_dtype
            )
            self.model_config = mc
        else:
            if self.model_config is None:
                raise ValueError("init_from_scratch requires model_config")
            host_params = init_params(
                self.model_config.replace(param_dtype=cfg.param_dtype),
                jax.random.PRNGKey(0),
            )
        # this clamp must run AFTER the checkpoint resolves model_config:
        # the common route (gpt2 checkpoint via cfg.path, model_config=None)
        # only learns pos_emb=='learned' from the loaded config, and the
        # packer's row shapes are compiled from max_pack_length below
        if (
            self.model_config.pos_emb == "learned"
            and cfg.max_pack_length > self.model_config.max_position_embeddings
        ):
            # jnp.take clamps, so rows packed past the table would silently
            # train every overflow position on the last embedding.
            # max_pack_length is a cap (row lengths bucket up to it), so
            # clamping keeps short batches working; a single sequence longer
            # than the table still fails loudly in the packer.
            logger.warning(
                "clamping max_pack_length %d to the learned position table "
                "(%d): gpt2-family models cannot extrapolate positions",
                cfg.max_pack_length,
                self.model_config.max_position_embeddings,
            )
            cfg.max_pack_length = self.model_config.max_position_embeddings
        self.model_config = self.model_config.replace(
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            remat=cfg.gradient_checkpointing,
            remat_policy=getattr(cfg, "remat_policy", "full"),
            scan_unroll=getattr(cfg, "scan_unroll", 1),
            layer_group_size=getattr(cfg, "layer_group_size", 1),
            # an explicitly-set model config wins; the engine config is the
            # yaml-reachable path for checkpoints (from_hf leaves "auto")
            attn_impl=(
                self.model_config.attn_impl
                if self.model_config.attn_impl != "auto"
                else getattr(cfg, "attn_impl", "auto")
            ),
        )
        # fail the two-level scan contracts HERE, before any tracing: a
        # non-divisor group size inside jit surfaces as a trace error deep
        # in the first train step otherwise.  effective_scan_unroll warns
        # loudly on a non-divisor unroll and falls back to 1; the value it
        # settles on rides every train-stats dict so a silently forfeited
        # unroll is visible in logged artifacts, not just stderr.
        mc_ = self.model_config
        if mc_.num_layers % max(1, mc_.layer_group_size):
            raise ValueError(
                f"layer_group_size={mc_.layer_group_size} must divide "
                f"num_layers={mc_.num_layers}"
            )
        from areal_tpu.models.transformer import effective_scan_unroll

        self._effective_scan_unroll = effective_scan_unroll(mc_)
        if getattr(cfg, "lora", None) is not None and cfg.lora.enabled:
            from areal_tpu.models.lora import add_lora_params

            self.model_config = self.model_config.replace(
                lora_rank=cfg.lora.rank,
                lora_alpha=cfg.lora.alpha,
                lora_targets=tuple(cfg.lora.target_modules),
            )
            host_params = add_lora_params(
                host_params, self.model_config, jax.random.PRNGKey(1)
            )
        specs = param_partition_specs(
            self.model_config, tp=self.mesh.shape["tp"]
        )
        # subtrees the text-model spec doesn't know (e.g. the vision tower
        # loaded from a VLM checkpoint) are small: replicate them
        for key in host_params:
            if key not in specs:
                specs[key] = jax.tree_util.tree_map(
                    lambda _: P(), host_params[key]
                )
        self.params = shard_pytree(self.mesh, host_params, specs)

        if cfg.optimizer is not None:
            self._build_optimizer(ft_spec)
        self.initialized = True
        n = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params))
        logger.info(f"initialized {n / 1e6:.1f}M params on mesh {self.mesh.shape}")

    def _build_optimizer(self, ft_spec: Optional[FinetuneSpec]) -> None:
        oc = self.config.optimizer
        total_steps = ft_spec.total_train_steps if ft_spec is not None else 1_000_000
        # the schedule is indexed per optimizer update, and PPO-style engines
        # make ppo_n_minibatches updates per dataset iteration
        total_steps *= max(1, getattr(self.config, "ppo_n_minibatches", 1))
        warmup = int(oc.warmup_steps_proportion * total_steps)
        peak, floor = oc.lr, oc.lr * oc.min_lr_ratio
        if oc.lr_scheduler_type == "cosine":
            decay = optax.cosine_decay_schedule(
                peak, max(1, total_steps - warmup), alpha=oc.min_lr_ratio
            )
        elif oc.lr_scheduler_type == "linear":
            decay = optax.linear_schedule(peak, floor, max(1, total_steps - warmup))
        else:
            decay = optax.constant_schedule(peak)
        if warmup > 0:
            self._schedule = optax.join_schedules(
                [optax.linear_schedule(0.0, peak, warmup), decay], [warmup]
            )
        else:
            self._schedule = decay
        wd_mask = jax.tree_util.tree_map(lambda p: p.ndim >= 2, self.params)
        self._optimizer = optax.chain(
            optax.clip_by_global_norm(oc.gradient_clipping),
            optax.adamw(
                learning_rate=self._schedule,
                b1=oc.beta1,
                b2=oc.beta2,
                eps=oc.eps,
                weight_decay=oc.weight_decay,
                mask=wd_mask,
            ),
        )
        if self.model_config.lora_rank:
            # adapters only: optax.masked keeps moment state solely for the
            # adapter leaves — the memory point of LoRA (the base weights
            # are already stop_gradient-frozen in the forward)
            from areal_tpu.models.lora import trainable_mask

            self._optimizer = optax.masked(
                self._optimizer, trainable_mask(self.params)
            )
        # Eager init: zeros_like inherits each param's NamedSharding for
        # mu/nu; scalar counters are explicitly replicated over the mesh so
        # the compiled step sees one consistent device set (and so an orbax
        # restore — which commits whatever it loads — matches too).
        with self.mesh:
            self.opt_state = self._optimizer.init(self.params)
        self.opt_state = self._replicate_scalars(self.opt_state)

    def _replicate_scalars(self, tree):
        rep = NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, rep)
            if isinstance(x, jax.Array) and x.ndim == 0
            else x,
            tree,
        )

    def destroy(self) -> None:
        self.params = None
        self.opt_state = None
        self._train_step_cache.clear()
        self._forward_cache.clear()
        if self._transfer_executor is not None:
            self._transfer_executor.shutdown(wait=False)
            self._transfer_executor = None
        self.initialized = False

    # ------------------------------------------------------------------
    # data-parallel topology (single-controller: one process owns the mesh)
    # ------------------------------------------------------------------

    @property
    def data_parallel_rank(self) -> int:
        return jax.process_index()

    @property
    def data_parallel_world_size(self) -> int:
        return jax.process_count()

    def is_data_parallel_head(self) -> bool:
        return jax.process_index() == 0

    def current_data_parallel_head(self) -> int:
        return 0

    # ------------------------------------------------------------------
    # batch preparation
    # ------------------------------------------------------------------

    def _row_len(self, batch: Dict[str, np.ndarray]) -> int:
        lens = batch["attention_mask"].astype(np.int64).sum(-1)
        longest = int(lens.max()) if lens.size else 1
        return round_up_to_bucket(
            longest, self.config.pack_length_quantum, self.config.max_pack_length
        )

    def _prepare_rows(
        self, batch: Dict[str, np.ndarray], n_mbs: int
    ) -> Tuple[RowPackedBatch, Dict[str, np.ndarray], int]:
        """Row-pack a padded batch; rows divisible by n_mbs * dp * fsdp."""
        row_len = self._row_len(batch)
        dp = (self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
              * self.mesh.shape.get("ep", 1))
        rp = pack_into_rows(
            batch, row_len, rows_multiple=n_mbs * dp, rows_bucket_pow2=True
        )
        data = dict(rp.data)
        data["input_ids"] = data["input_ids"].astype(np.int32)
        # filler rows/tokens must never contribute to the loss
        if "loss_mask" in data:
            data["loss_mask"] = data["loss_mask"] * (data["segment_ids"] >= 0)
        return rp, data, row_len

    def _stack_mbs(self, data: Dict[str, np.ndarray], n_mbs: int) -> Dict[str, np.ndarray]:
        """[R, L] -> [n_mbs, R/n_mbs, L]; rows were FFD-balanced so token
        counts are roughly even across micro-batches."""
        out = {}
        for k, v in data.items():
            R = v.shape[0]
            out[k] = v.reshape(n_mbs, R // n_mbs, *v.shape[1:])
        return out

    def _device_batch(self, data: Dict[str, np.ndarray], stacked: bool):
        """Shard host arrays: rows over (dp, fsdp), sequence over sp.

        Multi-process: the batch must be identical on every process (the
        dist-rollout coordinator broadcasts it); each process contributes
        its local shards."""
        spec = batch_spec()
        if stacked:
            spec = P(None, *spec)
        if jax.process_count() > 1:
            return distributed.make_global_batch(
                self.mesh, {k: spec for k in data}, data
            )
        sharding = NamedSharding(self.mesh, spec)
        return {k: jax.device_put(v, sharding) for k, v in data.items()}

    # ------------------------------------------------------------------
    # train / eval / forward
    # ------------------------------------------------------------------

    def _call_model(self, params, batch):
        """Model forward over one (micro-)batch dict.  The single seam the
        jitted step/eval/forward programs call; modality subclasses (VLM)
        override it to consume extra batch keys (pixels, mrope)."""
        return self._model_fn(
            params,
            self.model_config,
            batch["input_ids"],
            batch["positions"],
            batch["segment_ids"],
            mesh=self.mesh,
        )

    def _build_train_step(self, loss_fn: Callable):
        optimizer = self._optimizer
        schedule = self._schedule
        call_model = self._call_model

        def train_step(params, opt_state, batch, total_weight, step_idx):
            def mb_loss(p, mb):
                logits = call_model(p, mb)
                loss, stats = loss_fn(logits, mb)
                return loss / total_weight, stats

            grad_fn = jax.value_and_grad(mb_loss, has_aux=True)
            if batch["input_ids"].shape[0] == 1:
                # single micro-batch: no accumulator buffer (one full
                # gradient tree of HBM saved — the margin that decides the
                # largest fitting batch on a 16G chip)
                (loss, stats), grads = grad_fn(
                    params, jax.tree_util.tree_map(lambda v: v[0], batch)
                )
            else:
                # accumulate at master-weight precision: fp32 masters get
                # fp32 accumulation (reference behavior); bf16-master
                # (memory-tight) runs avoid doubling gradient HBM
                zero_grads = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, p.dtype), params
                )

                def scan_body(carry, mb):
                    grads_acc, loss_acc = carry
                    (loss, stats), grads = grad_fn(params, mb)
                    grads_acc = jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(a.dtype), grads_acc, grads
                    )
                    return (grads_acc, loss_acc + loss), stats

                (grads, loss), stats = jax.lax.scan(
                    scan_body, (zero_grads, jnp.zeros((), jnp.float32)), batch
                )
                stats = jax.tree_util.tree_map(
                    lambda s: jnp.sum(s, axis=0), stats
                )
            grad_norm = optax.global_norm(grads)
            updates, new_opt_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            stats = dict(stats)
            stats["grad_norm"] = grad_norm
            stats["loss"] = loss
            # lr is evaluated inside the jitted step: an eager schedule call
            # per step costs several device round-trips (painful on tunneled
            # TPU runtimes where each eager dispatch is a network hop)
            stats["lr"] = schedule(step_idx)
            return new_params, new_opt_state, stats

        # pin state outputs to the CURRENT shardings: without this, GSPMD
        # is free to re-layout the updated params/opt-state however it
        # likes — on a real mesh that silently abandons the intended
        # fsdp/tp distribution after step 1, and every downstream program
        # consuming params (forward, export, serving publish) retraces
        # against the drifted shardings (one surprise compile each)
        def shard_of(x):
            return getattr(x, "sharding", None)

        out_shardings = (
            jax.tree_util.tree_map(shard_of, self.params),
            jax.tree_util.tree_map(shard_of, self.opt_state),
            None,  # stats: let XLA choose (replicated scalars)
        )
        return jax.jit(
            train_step, donate_argnums=(0, 1), out_shardings=out_shardings
        )

    # keys the jitted forward program may read (the _call_model seam plus
    # the in-tree post-hooks).  forward() filters the packed batch to these
    # so EXTRA rollout keys (rewards, versions, loss_mask, ...) and their
    # pipeline-dependent dtypes can never change the jit cache signature —
    # workflows adding fields must not trigger surprise in-loop recompiles,
    # and warm_shapes' synthetic batches compile the very program the real
    # call requests.  Subclasses with richer model seams extend (VLM adds
    # pixels/mrope); custom post_hooks reading other per-token keys must
    # extend it too.
    FORWARD_KEYS = ("input_ids", "positions", "segment_ids")

    def _forward_batch_view(self, data: Dict[str, np.ndarray]):
        return {k: data[k] for k in self.FORWARD_KEYS if k in data}

    def _forward_fn_for(self, post_hook, row_len: int, n_rows: int):
        """Resolve (building + caching if needed) the jitted forward for a
        (hook, shape) signature; returns the cache key."""
        if post_hook is None:
            post_hook = _logp_hook
        key = ("fwd", post_hook, row_len, n_rows)
        if key not in self._forward_cache:
            call_model = self._call_model

            def fwd_step(params, batch):
                logits = call_model(params, batch)
                return post_hook(logits, batch)

            # multi-process: output rows are sharded across hosts — jit
            # replicates them so every process can read the full array
            out_shardings = (
                NamedSharding(self.mesh, P())
                if jax.process_count() > 1
                else None
            )
            self._forward_cache[key] = jax.jit(
                fwd_step, out_shardings=out_shardings
            )
        return key

    def precompile_forward(
        self,
        input_: Dict[str, np.ndarray],
        post_hook: Optional[Callable] = None,
    ) -> None:
        """AOT-compile the no-grad forward for this batch's shape signature
        (see precompile_train_batch)."""
        assert self.initialized
        rp, data, row_len = self._prepare_rows(input_, 1)
        dev_batch = self._device_batch(self._forward_batch_view(data),
                                       stacked=False)
        key = self._forward_fn_for(post_hook, row_len,
                                   data["input_ids"].shape[0])
        with self.mesh:
            self._forward_cache[key].lower(self.params, dev_batch).compile()

    def precompile_train_batch(
        self, input_: Dict[str, np.ndarray], loss_fn: Callable
    ) -> None:
        """Compile the train-step program for this batch's shape signature
        WITHOUT executing it.  AOT `jit.lower(...).compile()` populates the
        same executable cache the real call uses (measured: the next real
        call is a cache hit), and — unlike executing a warm step — donates
        nothing and mutates nothing.  PPOActor.warm_shapes drives this so
        varying rollout lengths never compile inside the training loop."""
        assert self.initialized and self._optimizer is not None
        n_mbs = max(1, self.config.mb_spec.n_mbs)
        rp, data, row_len = self._prepare_rows(input_, n_mbs)
        stacked = self._stack_mbs(data, n_mbs)
        dev_batch = self._device_batch(stacked, stacked=True)
        key = (loss_fn, n_mbs, row_len, stacked["input_ids"].shape[1])
        if key not in self._train_step_cache:
            self._train_step_cache[key] = self._build_train_step(loss_fn)
        with self.mesh:
            self._train_step_cache[key].lower(
                self.params,
                self.opt_state,
                dev_batch,
                jnp.float32(1.0),
                jnp.int32(self.step_count),
            ).compile()

    def _consume_telemetry(
        self, input_: Dict[str, np.ndarray]
    ) -> Dict[str, np.ndarray]:
        """Strip telemetry-only keys and record consumption evidence.

        `trace_keys` must never reach _prepare_rows: train_batch devices
        the WHOLE prepared batch (there is no FORWARD_KEYS filter on this
        path), so an extra key would mint a new XLA signature per run
        mode.  Staleness-at-consumption = trainer's current version minus
        each row's max behavior version (per-token `versions`, -1 =
        prompt) — the paper's bounded-staleness evidence, observed here
        at the exact consumption point."""
        keys = input_.get("trace_keys")
        if keys is not None:
            input_ = {k: v for k, v in input_.items() if k != "trace_keys"}
        if not telemetry.is_enabled():
            return input_
        versions = np.asarray(input_.get("versions", ()))
        if versions.ndim != 2:
            return input_
        behavior = np.where(versions >= 0, versions, -1).max(axis=-1)
        tks = None if keys is None else np.asarray(keys).reshape(-1).tolist()
        consumed = self._version
        for i, bv in enumerate(behavior.tolist()):
            if bv < 0:
                continue
            staleness = max(0, consumed - int(bv))
            telemetry.STALENESS_AT_CONSUMPTION.observe(staleness)
            telemetry.emit(
                "train_consume",
                trace_key=(tks[i] if tks is not None and i < len(tks) else None),
                behavior_version=int(bv),
                consumed_version=consumed,
                staleness=staleness,
            )
        return input_

    def _scan_stats(self) -> Dict[str, float]:
        """Layer-scan configuration evidence for every stats dict: the
        group size actually compiled and the unroll the scan actually used
        (a non-divisor scan_unroll falls back to 1 with a warning — this
        keeps the fallback visible in logged artifacts too)."""
        return {
            "layer_group_size": float(
                max(1, self.model_config.layer_group_size)
            ),
            "effective_scan_unroll": float(
                getattr(self, "_effective_scan_unroll", 1)
            ),
        }

    def train_batch(
        self,
        input_: Dict[str, np.ndarray],
        loss_fn: Callable,
        loss_weight_fn: Callable,
    ) -> Dict[str, float]:
        assert self.initialized and self._optimizer is not None
        input_ = self._consume_telemetry(input_)
        n_mbs = max(1, self.config.mb_spec.n_mbs)
        rp, data, row_len = self._prepare_rows(input_, n_mbs)
        total_weight = float(loss_weight_fn(data))
        if total_weight <= 0:
            raise ValueError("loss_weight_fn returned non-positive total weight")
        stacked = self._stack_mbs(data, n_mbs)
        dev_batch = self._device_batch(stacked, stacked=True)

        # the callable itself is part of the key: the strong reference keeps
        # it alive, so CPython cannot reuse its address for a different fn
        key = (loss_fn, n_mbs, row_len, stacked["input_ids"].shape[1])
        if key not in self._train_step_cache:
            self._train_step_cache[key] = self._build_train_step(loss_fn)
        step_fn = self._train_step_cache[key]

        t0 = time.perf_counter()
        with self.mesh:
            self.params, self.opt_state, stats = step_fn(
                self.params,
                self.opt_state,
                dev_batch,
                jnp.float32(total_weight),
                # optax evaluates the schedule at the pre-increment count
                jnp.int32(self.step_count),
            )
        self.step_count += 1
        if self.config.async_stats:
            # deferred fetch: the caller reads stats later (one batched
            # transfer), so the NEXT step can be dispatched while this one
            # still runs — per-step step_time/tflops/mfu are omitted because
            # there is no sync point to measure them against
            pending = tracker.PendingTrainStats(
                stats,
                lambda tree: {
                    k: float(v)
                    for k, v in distributed.fetch_replicated(tree).items()
                },
            )
            def _finish(st: Dict[str, float]) -> Dict[str, float]:
                st = {**st, "total_loss_weight": total_weight}
                st.update(self._scan_stats())
                if telemetry.is_enabled():
                    telemetry.publish_train_stats(st)
                return st

            return pending.then(_finish)
        # ONE host transfer for every stat; per-scalar float() would pay a
        # device round-trip each.  Stats are replicated reductions, so each
        # process reads its own full replica.
        stats = {
            k: float(v) for k, v in distributed.fetch_replicated(stats).items()
        }
        stats["total_loss_weight"] = total_weight
        stats.update(self._scan_stats())
        stats["step_time"] = time.perf_counter() - t0
        # per-chip MFU from the analytic flops model (the role of the
        # reference's flops_counter + kineto categorisation, monitor.py:404)
        from areal_tpu.utils.profiling import mfu, train_flops_per_token

        seg = data["segment_ids"]
        tokens = int((seg >= 0).sum())
        # attention flops scale with SEGMENT length, not packed row length —
        # rows packed with several short sequences attend within segments
        n_segs = int(np.sum(np.where(seg.max(axis=-1) >= 0, seg.max(axis=-1) + 1, 0)))
        mean_seg = max(1, tokens // max(1, n_segs))
        n_chips = self.mesh.devices.size
        tps = tokens / max(stats["step_time"], 1e-9)
        stats["tflops_per_chip"] = (
            tps * train_flops_per_token(self.model_config, mean_seg)
            / 1e12 / n_chips
        )
        m = mfu(tps, self.model_config, mean_seg, n_chips=n_chips)
        if m is not None:
            stats["mfu"] = m
        if telemetry.is_enabled():
            telemetry.publish_train_stats(stats)
        return stats

    def eval_batch(
        self,
        input_: Dict[str, np.ndarray],
        loss_fn: Callable,
        loss_weight_fn: Callable,
    ) -> Dict[str, float]:
        assert self.initialized
        # honor mb_spec: eval must not materialise logits for rows the train
        # path would split across micro-batches
        n_mbs = max(1, self.config.mb_spec.n_mbs)
        rp, data, row_len = self._prepare_rows(input_, n_mbs)
        total_weight = float(loss_weight_fn(data))
        stacked = self._stack_mbs(data, n_mbs)
        dev_batch = self._device_batch(stacked, stacked=True)

        key = ("eval", loss_fn, n_mbs, row_len, stacked["input_ids"].shape[1])
        if key not in self._forward_cache:

            call_model = self._call_model

            def eval_step(params, batch):
                def mb_loss(carry, mb):
                    logits = call_model(params, mb)
                    loss, stats = loss_fn(logits, mb)
                    return carry + loss, stats

                loss, stats = jax.lax.scan(mb_loss, jnp.zeros(()), batch)
                return loss, jax.tree_util.tree_map(
                    lambda s: jnp.sum(s, axis=0), stats
                )

            self._forward_cache[key] = jax.jit(eval_step)
        with self.mesh:
            loss, stats = self._forward_cache[key](self.params, dev_batch)
        loss, stats = distributed.fetch_replicated((loss, stats))
        out = {k: float(v) for k, v in stats.items()}
        out["loss"] = float(loss) / max(total_weight, 1e-8)
        return out

    def forward(
        self,
        input_: Dict[str, np.ndarray],
        output_key: str = "logprobs",
        post_hook: Optional[Callable] = None,
        aggregate_fn: Callable = None,
    ) -> np.ndarray:
        """No-grad forward; returns a padded [B, L] array aligned with the
        input batch (default: next-token logprobs at predictor positions,
        the reference's compute_logp convention)."""
        assert self.initialized
        if output_key != "logprobs":
            raise NotImplementedError(
                "forward() returns per-token arrays directly; output_key "
                "selection does not apply to this engine"
            )
        if aggregate_fn is not None:
            raise NotImplementedError(
                "forward() runs one fused program — there are no per-microbatch "
                "outputs to aggregate; post-process the returned array instead"
            )
        rp, data, row_len = self._prepare_rows(input_, 1)
        dev_batch = self._device_batch(self._forward_batch_view(data),
                                       stacked=False)
        key = self._forward_fn_for(post_hook, row_len,
                                   data["input_ids"].shape[0])
        with self.mesh:
            out = self._forward_cache[key](self.params, dev_batch)
            if jax.process_count() > 1:
                # out_shardings replicated it; read the local full replica
                out = distributed.fetch_replicated(out)
            rows_out = np.asarray(out)
        B, L = input_["attention_mask"].shape
        return unpack_rows(rp, rows_out, B, L)

    # ------------------------------------------------------------------
    # weights
    # ------------------------------------------------------------------

    def _host_params(self):
        if jax.process_count() == 1:
            return jax.tree_util.tree_map(np.asarray, self.params)
        # multi-process: shards live on other hosts; replicate leaf-by-leaf
        # through jit (bounded extra memory: one leaf) and read the local
        # replica — the role of DTensor.full_tensor() in the reference's
        # save path (fsdp_engine.py:228-254)
        rep = NamedSharding(self.mesh, P())
        gather = jax.jit(lambda x: x, out_shardings=rep)
        return jax.tree_util.tree_map(
            lambda x: np.asarray(gather(x).addressable_data(0)), self.params
        )

    def _export_params(self):
        """Host params in served form: LoRA adapters folded into the base
        (reference pushes merged weights, fsdp_engine.py:270)."""
        from areal_tpu.models.lora import merge_lora

        return merge_lora(self._host_params(), self.model_config)

    def export_device_params(self):
        """Serving-ready bf16 params WITHOUT leaving the device — the
        colocated publish path (engine/colocated.py): trainer and serving
        engine share the chips, so the disk/host round trip of the other
        publish modes is pure waste there.  Leaves are COPIES (jnp.array
        copy=True), so the trainer's next donated update cannot invalidate
        the serving engine's buffers.  LoRA folds on the host path only —
        adapters make this fall back to _export_params."""
        if self.model_config.lora_rank > 0:
            return self._export_params()
        # keep the configured param_dtype: an fp32 smoke config must stay
        # fp32 or the serving engine retraces mid-measurement
        target = jnp.dtype(self.model_config.param_dtype)
        return jax.tree_util.tree_map(
            lambda x: jnp.array(x, target, copy=True)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            self.params,
        )

    def update_weights(self, meta: WeightUpdateMeta) -> None:
        """Publish fresh weights to inference servers.

        - "disk" (reference: fsdp_engine.py:403-425): write an HF snapshot
          under `meta.path/v{version}` — staged in a temp dir and renamed,
          so a client that misses a pause can never read a half-written
          checkpoint (round-1 weak #8) — and publish a version timestamp in
          name_resolve.  Servers resolve the newest `v*` dir.
        - "transfer" (reference NCCL path: fsdp_engine.py:298-401): stream
          host-gathered bf16 arrays chunk-wise over HTTP straight into each
          server (`/update_weights_chunk`), then commit.  No shared
          filesystem in the loop.
        """
        try:
            if meta.type == "disk":
                if self._staged != ("disk", self._version):
                    self._write_disk_snapshot(meta)
                if distributed.is_head():
                    name_resolve.add(
                        names.update_weights_from_disk(
                            meta.experiment_name, meta.trial_name, self._version
                        ),
                        str(time.time_ns()),
                        replace=True,
                    )
            elif meta.type == "transfer":
                self._update_weights_transfer(meta)
            else:
                raise NotImplementedError(f"weight update type {meta.type!r}")
        finally:
            # ALWAYS consume the staged marker: a failed commit (e.g. a
            # server restarted and lost its staged chunks -> 409) must make
            # the retry re-push rather than skip to another doomed commit
            self._staged = None

    def stage_weights(self, meta: WeightUpdateMeta) -> None:
        """Run the EXPENSIVE half of a weight publish while generation is
        still running, so only the cheap commit sits inside the pause
        window: disk = export + snapshot write (publication of the
        name_resolve version key waits for update_weights); transfer =
        export + chunk streaming into the servers' staging buffers (the
        swap waits for the commit).  Call with the same version that
        update_weights will publish."""
        if meta.type == "disk":
            self._write_disk_snapshot(meta)
        elif meta.type == "transfer":
            self._push_transfer_chunks(meta)
        else:
            raise NotImplementedError(f"weight update type {meta.type!r}")
        self._staged = (meta.type, self._version)

    def _write_disk_snapshot(self, meta: WeightUpdateMeta) -> None:
        final = os.path.join(meta.path, f"v{self._version}")
        tmp = os.path.join(meta.path, f".tmp-v{self._version}-{os.getpid()}")
        if distributed.is_head():
            host = self._export_params()
            save_hf_checkpoint(
                host,
                self.model_config,
                tmp,
                save_dtype="bfloat16",
                tokenizer_src=self.config.path or None,
            )
            if os.path.isdir(final):  # re-publish of the same version
                import shutil

                shutil.rmtree(final)
            os.rename(tmp, final)
            self._prune_weight_dirs(meta.path, keep=2)
        else:
            self._host_params()  # participate in the replication collectives

    @staticmethod
    def _prune_weight_dirs(root: str, keep: int) -> None:
        import re
        import shutil

        vs = sorted(
            (int(m.group(1)), d)
            for d in os.listdir(root)
            if (m := re.fullmatch(r"v(\d+)", d)) and os.path.isdir(os.path.join(root, d))
        )
        for _, d in vs[:-keep]:
            shutil.rmtree(os.path.join(root, d), ignore_errors=True)

    def _server_addrs(self, meta: WeightUpdateMeta, timeout: float = 30.0) -> list:
        """Same discovery chain as the rollout client
        (core/remote.py:_discover_servers), with a registration-race poll."""
        env = os.environ.get("AREAL_LLM_SERVER_ADDRS")
        if env:
            return env.split(",")
        key = names.gen_servers(meta.experiment_name, meta.trial_name)
        deadline = time.monotonic() + timeout
        while True:
            found = name_resolve.get_subtree(key)
            if found:
                return sorted(found)
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    "no generation servers registered for weight transfer"
                )
            time.sleep(0.5)

    def _update_weights_transfer(self, meta: WeightUpdateMeta) -> None:
        """Chunk-streamed push + commit (reference NCCL-broadcast intent,
        fsdp_engine.py:298-401, over HTTP/DCN).  With a prior
        `stage_weights` call the chunks already sit in the servers'
        staging buffers and only the commit (weight swap) runs here.  The
        measured wall time lands in `self.last_weight_update_seconds`."""
        t0 = time.perf_counter()
        if self._staged != ("transfer", self._version):
            self._push_transfer_chunks(meta)
        self._commit_transfer(meta)
        self._notify_router(meta)
        self.last_weight_update_seconds = time.perf_counter() - t0

    def _ensure_transfer_executor(self):
        if self._transfer_executor is None:
            import concurrent.futures

            self._transfer_executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="weight-transfer"
            )
        return self._transfer_executor

    def _run_on_transfer_thread(self, coro) -> None:
        """Run an asyncio coroutine on the dedicated transfer thread (the
        caller thread may own its own event loop) and block on it —
        weight publication is a synchronous control-plane action."""
        import asyncio

        self._ensure_transfer_executor().submit(asyncio.run, coro).result()

    def _push_transfer_chunks(self, meta: WeightUpdateMeta) -> None:
        """Stream every HF-named array, sliced into <= chunk_mb pieces, as
        raw `application/octet-stream` bodies (name/dtype/shape/offset in
        X-Weight-* headers — no base64 inflation or per-chunk json parse)
        into every server's staging buffer (gen/server.py assembles by
        (name, offset)).  Does NOT swap weights — safe while the servers
        are still generating."""
        import asyncio
        import json as _json

        import ml_dtypes

        from areal_tpu.models.hf import params_to_hf_state
        from areal_tpu.utils.http import apost_bytes_with_retry

        host = self._export_params()
        if not distributed.is_head():
            return
        addrs = self._server_addrs(meta)
        bf16 = np.dtype(ml_dtypes.bfloat16)
        chunk_bytes = max(1, meta.chunk_mb) << 20
        # bf16 raw bytes are built while the host tree is alive (fp32
        # masters: transient ~3x model bytes), then the host tree is
        # dropped so only ~1x bf16 remains for the push
        state = [
            (name, np.ascontiguousarray(arr.astype(bf16)).tobytes(), list(arr.shape))
            for name, arr in params_to_hf_state(host, self.model_config)
        ]
        del host

        version = self._version

        async def push(addr: str):
            import aiohttp

            from areal_tpu.utils.http import (
                arequest_with_retry,
                get_default_connector,
            )

            async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=600.0, sock_connect=30.0),
                connector=get_default_connector(),
            ) as session:
                for name, raw, shape in state:
                    meta_hdrs = {
                        "X-Weight-Name": name,
                        "X-Weight-Dtype": "bfloat16",
                        "X-Weight-Shape": _json.dumps(shape),
                        "X-Weight-Nbytes": str(len(raw)),
                    }
                    for off in range(0, len(raw) or 1, chunk_bytes):
                        await apost_bytes_with_retry(
                            addr=addr,
                            endpoint="/update_weights_chunk",
                            data=raw[off : off + chunk_bytes],
                            headers={**meta_hdrs, "X-Weight-Offset": str(off)},
                            timeout=300.0,
                            session=session,
                        )
                # device-stage the assembled tree while generation keeps
                # running: the later commit becomes an O(abort) pointer
                # swap (best-effort — a server without standby HBM falls
                # back to commit-time placement)
                await arequest_with_retry(
                    addr=addr,
                    endpoint="/update_weights_chunk",
                    payload={"prepare": True, "version": version},
                    method="POST",
                    timeout=600.0,
                )

        async def run():
            await asyncio.gather(*[push(a) for a in addrs])

        self._run_on_transfer_thread(run())

    def _commit_transfer(self, meta: WeightUpdateMeta) -> None:
        """Swap the staged weights in on every server."""
        import asyncio

        from areal_tpu.utils.http import arequest_with_retry

        if not distributed.is_head():
            return
        addrs = self._server_addrs(meta)
        version = self._version

        async def run():
            await asyncio.gather(*[
                arequest_with_retry(
                    addr=a,
                    endpoint="/update_weights_chunk",
                    payload={"commit": True, "version": version,
                             "live": meta.live_commit},
                    method="POST",
                    timeout=600.0,
                )
                for a in addrs
            ])

        self._run_on_transfer_thread(run())

    def _notify_router(self, meta: WeightUpdateMeta) -> None:
        """Transfer publishes leave no disk checkpoint for a router's
        watcher to see, so its fleet staleness gate needs the version pushed
        explicitly (ADVICE r3: the gate's budget otherwise never grows and
        admission wedges at 409).  Best-effort: the router also polls the
        backends' served version as a safety net."""
        if not distributed.is_head():
            return
        try:
            addr = name_resolve.get(
                names.gen_router(meta.experiment_name, meta.trial_name)
            )
        except Exception:  # noqa: BLE001 — no router in this deployment
            return
        version = self._version

        def _post():
            try:
                import requests

                requests.post(
                    f"http://{addr}/set_version",
                    json={"version": version},
                    timeout=5,
                )
            except Exception as e:  # noqa: BLE001 — poller covers the miss
                logger.warning(
                    f"router /set_version failed (poll covers it): {e}"
                )

        # fire-and-forget on the transfer thread: a stale router address
        # must not stall the publish path on a connect timeout
        self._ensure_transfer_executor().submit(_post)

    def save(self, meta: SaveLoadMeta) -> None:
        """Model weights as an HF safetensors dir (interop with inference
        servers and transformers); optimizer state via orbax/tensorstore —
        sharded (each process writes only the shards it owns), structure-
        checked on restore, and not tied to optax's leaf ordering the way
        the old positional npz dump was (round-1 weak #5).

        With LoRA: exports (with_optim=False) fold the adapters into the
        base weights for downstream consumers; recover checkpoints
        (with_optim=True) keep the base UNMERGED and persist the adapters
        alongside the optimizer state so load() round-trips exactly."""
        from areal_tpu.models.lora import split_lora

        lora_on = self.model_config.lora_rank > 0
        if meta.with_optim:
            host, host_adapters = (
                split_lora(self._host_params()) if lora_on
                else (self._host_params(), None)
            )
        else:
            host, host_adapters = self._export_params(), None
        save_hf_checkpoint(
            host,
            self.model_config,
            meta.path,
            save_dtype="bfloat16" if not meta.with_optim else "float32",
            tokenizer_src=self.config.path or None,
        )
        if meta.with_optim and self.opt_state is not None:
            import orbax.checkpoint as ocp

            state = {
                "opt_state": self.opt_state,
                "step": jnp.asarray(self.step_count, jnp.int32),
            }
            if host_adapters is not None:
                state["lora"] = host_adapters
            ckptr = ocp.StandardCheckpointer()
            with self.mesh:
                ckptr.save(
                    os.path.abspath(os.path.join(meta.path, "optimizer_state")),
                    state,
                    force=True,
                )
                ckptr.wait_until_finished()
            ckptr.close()

    def load(self, meta: SaveLoadMeta) -> None:
        host_params, mc = load_hf_params(
            meta.path, self.model_config, dtype=self.config.param_dtype
        )
        lora_on = (
            self.model_config is not None and self.model_config.lora_rank > 0
        )
        self.model_config = mc.replace(
            dtype=self.config.dtype,
            param_dtype=self.config.param_dtype,
            remat=self.config.gradient_checkpointing,
            remat_policy=getattr(self.config, "remat_policy", "full"),
            scan_unroll=getattr(self.config, "scan_unroll", 1),
            layer_group_size=getattr(self.config, "layer_group_size", 1),
            lora_rank=self.model_config.lora_rank if lora_on else 0,
            lora_alpha=self.model_config.lora_alpha,
            lora_targets=self.model_config.lora_targets if lora_on else (),
        )
        # the checkpoint may carry a different depth: re-apply the
        # grouped-scan contracts against the loaded num_layers
        if self.model_config.num_layers % max(
            1, self.model_config.layer_group_size
        ):
            raise ValueError(
                f"layer_group_size={self.model_config.layer_group_size} "
                f"must divide the loaded checkpoint's "
                f"num_layers={self.model_config.num_layers}"
            )
        from areal_tpu.models.transformer import effective_scan_unroll

        self._effective_scan_unroll = effective_scan_unroll(self.model_config)
        if lora_on:
            from areal_tpu.models.lora import add_lora_params

            host_params = add_lora_params(
                host_params, self.model_config, jax.random.PRNGKey(1)
            )
        self.params = shard_pytree(
            self.mesh,
            host_params,
            param_partition_specs(self.model_config, tp=self.mesh.shape["tp"]),
        )
        opt_path = os.path.abspath(os.path.join(meta.path, "optimizer_state"))
        if meta.with_optim and os.path.isdir(opt_path):
            import orbax.checkpoint as ocp

            from areal_tpu.models.lora import split_lora

            template = {
                "opt_state": self.opt_state,
                "step": jnp.asarray(self.step_count, jnp.int32),
            }
            if lora_on:
                # sharded live adapters as the template: orbax restores
                # each process's shards in place (np.asarray would crash on
                # multi-host global arrays)
                template["lora"] = split_lora(self.params)[1]
            ckptr = ocp.StandardCheckpointer()
            with self.mesh:
                # the live opt_state is the template: orbax restores each
                # leaf with the matching sharding and validates structure
                restored = ckptr.restore(opt_path, template)
            ckptr.close()
            self.opt_state = self._replicate_scalars(restored["opt_state"])
            self.step_count = int(restored["step"])
            if lora_on:
                layers = dict(self.params["layers"])
                for key, arr in restored["lora"].items():
                    sub_name, leaf = key.split(".", 1)
                    sub = dict(layers[sub_name])
                    sub[leaf] = jax.device_put(
                        arr, self.params["layers"][sub_name][leaf].sharding
                    )
                    layers[sub_name] = sub
                self.params = {**self.params, "layers": layers}

    def step_lr_scheduler(self) -> None:
        # the schedule is step-indexed inside the jitted update; nothing to do
        pass

    def set_version(self, version: int) -> None:
        self._version = version

    def get_version(self) -> int:
        return self._version
