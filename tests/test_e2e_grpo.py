"""End-to-end GRPO slice: real generation server + RemoteJaxEngine client +
async prepare_batch + PPO actor + DISK weight sync, for multiple steps on a
tiny model (the reference's test_examples.py smoke, without subprocesses).

Also validates the example config parses into GRPOConfig."""

import asyncio
import os
import threading
import time

import numpy as np
import pytest
from aiohttp import web

from areal_tpu.api.config import (
    GRPOConfig,
    GenerationHyperparameters,
    InferenceEngineConfig,
    MeshConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
    load_expr_config,
)
from areal_tpu.api.io_struct import FinetuneSpec, WeightUpdateMeta
from areal_tpu.engine.jax_remote import RemoteJaxEngine
from areal_tpu.engine.ppo import JaxPPOActor
from areal_tpu.gen.engine import GenEngine
from areal_tpu.gen.server import GenServer
from areal_tpu.models import init_params
from areal_tpu.models.hf import save_hf_checkpoint
from areal_tpu.models.model_config import tiny_config
from areal_tpu.utils import network
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.workflow.rlvr import RLVRWorkflow

CFG = tiny_config(vocab_size=89, qkv_bias=True, hf_architecture="Qwen2ForCausalLM",
                  eos_token_id=None)


def _token7_reward(prompt, completion, prompt_ids, completion_ids, **kw):
    """Module-level: reward fns run in a process pool and must pickle."""
    return float(7 in completion_ids)


def test_example_config_parses():
    cfg, _ = load_expr_config(
        ["--config", "examples/math/gsm8k_grpo.yaml", "actor.optimizer.lr=2e-6"],
        GRPOConfig,
    )
    assert cfg.actor.optimizer.lr == 2e-6
    assert cfg.gconfig.n_samples == 4
    assert cfg.actor.experiment_name == cfg.experiment_name  # propagated


def test_grpo_end_to_end_with_disk_weight_sync(tmp_path):
    import jax

    # initial checkpoint on disk; BOTH sides load it
    ckpt0 = tmp_path / "init"
    params = init_params(CFG, jax.random.PRNGKey(0))
    save_hf_checkpoint(params, CFG, str(ckpt0), save_dtype="float32")

    engine = GenEngine(CFG.replace(dtype="float32"), model_path=str(ckpt0),
                       n_slots=4, max_seq_len=96, prompt_bucket=16,
                       decode_chunk=4)
    server = GenServer(engine)
    server.start()
    port = network.find_free_port()
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(server.app())
        loop.run_until_complete(runner.setup())
        loop.run_until_complete(web.TCPSite(runner, "127.0.0.1", port).start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    import urllib.request

    for _ in range(100):
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=1)
            break
        except Exception:
            time.sleep(0.1)

    rollout = RemoteJaxEngine(InferenceEngineConfig(
        experiment_name="e2e", trial_name="t", consumer_batch_size=4,
        max_concurrent_rollouts=8, request_timeout=60,
        max_head_offpolicyness=2,
    ))
    rollout.initialize(addr=f"127.0.0.1:{port}")

    actor = JaxPPOActor(
        PPOActorConfig(
            experiment_name="e2e", trial_name="t", path=str(ckpt0),
            dtype="float32", gradient_checkpointing=False,
            mesh=MeshConfig(), mb_spec=MicroBatchSpec(n_mbs=1),
            optimizer=OptimizerConfig(lr=5e-3, warmup_steps_proportion=0.0),
            pack_length_quantum=32, max_pack_length=96,
            group_size=2, ppo_n_minibatches=1,
            use_decoupled_loss=True, recompute_logprob=True,
            adv_norm=NormConfig(mean_level="group", std_level="group", group_size=2),
        ),
    )
    actor.initialize(ft_spec=FinetuneSpec(1, 16, 4))

    from areal_tpu.api.reward import prewarm_reward_pool

    prewarm_reward_pool()
    # reward: 1 if completion contains token 7
    wf = RLVRWorkflow(
        reward_fn=_token7_reward,
        gconfig=GenerationHyperparameters(n_samples=2, max_new_tokens=8),
    )
    rng = np.random.default_rng(0)
    dataset = [{"input_ids": rng.integers(0, 89, 5).tolist(),
                "query_id": str(i)} for i in range(16)]
    dataloader = StatefulDataLoader(dataset, batch_size=4, seed=0)
    weight_dir = tmp_path / "updates"

    try:
        for step in range(3):
            batch = rollout.prepare_batch(dataloader, workflow=wf)
            assert batch["input_ids"].shape[0] >= 4
            assert "rewards" in batch and "versions" in batch

            batch["prox_logp"] = actor.compute_logp(batch)
            actor.compute_advantages(batch)
            stats = actor.ppo_update(batch)
            assert np.isfinite(stats[-1]["loss"])

            # disk weight sync: trainer dumps, server reloads, versions bump
            meta = WeightUpdateMeta(
                type="disk", path=str(weight_dir),
                experiment_name="e2e", trial_name="t",
            )
            rollout.pause()
            actor.set_version(step + 1)
            actor.update_weights(meta)
            rollout.update_weights(meta)
            rollout.set_version(step + 1)
            rollout.resume()
            assert engine.version >= 1
        # staleness accounting let 3 consumer batches through
        assert rollout.get_version() == 3
    finally:
        rollout.destroy()
        server.shutdown.set()
        loop.call_soon_threadsafe(loop.stop)


def test_grpo_transfer_weight_sync(tmp_path):
    """Transfer (non-disk) weight sync: trainer streams bf16 chunks over
    /update_weights_chunk and commits (VERDICT round-1 next-step #4).
    Reports both paths' update latency."""
    import jax

    from areal_tpu.utils import name_resolve, names

    ckpt0 = tmp_path / "init"
    params = init_params(CFG, jax.random.PRNGKey(0))
    save_hf_checkpoint(params, CFG, str(ckpt0), save_dtype="float32")

    engine = GenEngine(CFG.replace(dtype="float32"), model_path=str(ckpt0),
                       n_slots=4, max_seq_len=96, prompt_bucket=16,
                       decode_chunk=4)
    server = GenServer(engine)
    server.start()
    port = network.find_free_port()
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(server.app())
        loop.run_until_complete(runner.setup())
        loop.run_until_complete(web.TCPSite(runner, "127.0.0.1", port).start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    import urllib.request

    for _ in range(100):
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=1)
            break
        except Exception:
            time.sleep(0.1)

    # register for trainer-side discovery (the launcher's job in real runs)
    name_resolve.add(
        names.gen_server("e2e-tr", "t", "0"), f"127.0.0.1:{port}", replace=True
    )

    actor = JaxPPOActor(
        PPOActorConfig(
            experiment_name="e2e-tr", trial_name="t", path=str(ckpt0),
            dtype="float32", gradient_checkpointing=False,
            mesh=MeshConfig(), mb_spec=MicroBatchSpec(n_mbs=1),
            optimizer=OptimizerConfig(lr=5e-3, warmup_steps_proportion=0.0),
            pack_length_quantum=32, max_pack_length=96,
            group_size=2, ppo_n_minibatches=1,
        ),
    )
    actor.initialize(ft_spec=FinetuneSpec(1, 16, 4))

    try:
        # --- transfer path: chunk small enough to force multi-part arrays
        meta_t = WeightUpdateMeta.from_transfer("e2e-tr", "t", chunk_mb=1,
                                        live_commit=False)
        actor.set_version(1)
        t0 = time.perf_counter()
        actor.update_weights(meta_t)
        dt_transfer = time.perf_counter() - t0
        assert engine.version == 1

        # server now runs the trainer's weights: greedy outputs must match a
        # local engine fed the same params (round-trip integrity)
        local = GenEngine(CFG.replace(dtype="float32"),
                          params=actor._host_params(), n_slots=1,
                          max_seq_len=96, prompt_bucket=16)
        from areal_tpu.gen.engine import GenRequest

        prompt = [3, 1, 4, 1, 5]
        r_local = GenRequest(rid="l", input_ids=list(prompt),
                             max_new_tokens=6, temperature=0.0)
        local.generate_blocking([r_local])
        import json
        import urllib.request as rq

        req = rq.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({
                "rid": "r", "input_ids": prompt,
                "sampling_params": {"max_new_tokens": 6, "temperature": 0.0},
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        remote = json.loads(rq.urlopen(req, timeout=60).read())
        assert remote["output_tokens"] == r_local.output_tokens

        # --- disk path for latency comparison (versioned atomic dirs)
        weight_dir = tmp_path / "updates"
        weight_dir.mkdir()
        meta_d = WeightUpdateMeta(type="disk", path=str(weight_dir),
                                  experiment_name="e2e-tr", trial_name="t")
        actor.set_version(2)
        t0 = time.perf_counter()
        actor.update_weights(meta_d)
        dt_disk_write = time.perf_counter() - t0
        assert (weight_dir / "v2").is_dir()
        v = engine.load_weights(path=str(weight_dir), version=2)
        assert v == 2
        print(f"update latency: transfer={dt_transfer*1e3:.0f}ms "
              f"disk_write={dt_disk_write*1e3:.0f}ms")
    finally:
        server.shutdown.set()
        loop.call_soon_threadsafe(loop.stop)


def test_staged_weight_sync_splits_push_from_commit(tmp_path):
    """stage_weights streams chunks while the server is un-paused and does
    NOT swap weights; the later update_weights commit is the only part
    that needs the pause window (docs/perf.md round-4 lever, now wired)."""
    import urllib.request

    import jax

    from areal_tpu.utils import name_resolve, names

    ckpt0 = tmp_path / "init"
    params = init_params(CFG, jax.random.PRNGKey(0))
    save_hf_checkpoint(params, CFG, str(ckpt0), save_dtype="float32")
    engine = GenEngine(CFG.replace(dtype="float32"), model_path=str(ckpt0),
                       n_slots=4, max_seq_len=96, prompt_bucket=16)
    server = GenServer(engine)
    server.start()
    port = network.find_free_port()
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(server.app())
        loop.run_until_complete(runner.setup())
        loop.run_until_complete(web.TCPSite(runner, "127.0.0.1", port).start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    for _ in range(100):
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=1)
            break
        except Exception:
            time.sleep(0.1)
    name_resolve.add(
        names.gen_server("e2e-st", "t", "0"), f"127.0.0.1:{port}", replace=True
    )
    actor = JaxPPOActor(
        PPOActorConfig(
            experiment_name="e2e-st", trial_name="t", path=str(ckpt0),
            dtype="float32", gradient_checkpointing=False,
            mesh=MeshConfig(), mb_spec=MicroBatchSpec(n_mbs=1),
            optimizer=OptimizerConfig(lr=5e-3, warmup_steps_proportion=0.0),
            pack_length_quantum=32, max_pack_length=96,
            group_size=2, ppo_n_minibatches=1,
        ),
    )
    actor.initialize(ft_spec=FinetuneSpec(1, 16, 4))
    try:
        meta = WeightUpdateMeta.from_transfer("e2e-st", "t", chunk_mb=1,
                                      live_commit=False)
        actor.set_version(1)
        actor.stage_weights(meta)
        # staged but NOT swapped: server still serves version 0 un-paused.
        # Staging now goes all the way to DEVICE (the standby tree), so the
        # later commit is a pointer swap — the chunk buffer is already
        # drained by the `prepare` message.
        assert engine.version == 0
        assert engine.has_standby and engine.staged_version == 1
        assert not server._chunk_buf
        assert not server.paused.is_set()
        t0 = time.perf_counter()
        actor.update_weights(meta)  # commit only
        commit_s = time.perf_counter() - t0
        assert engine.version == 1
        assert not engine.has_standby  # consumed by the commit
        assert engine.last_pause_s <= commit_s
        # staged state is single-use: a second update re-pushes
        actor.set_version(2)
        actor.update_weights(meta)
        assert engine.version == 2
        print(f"staged commit: {commit_s*1e3:.0f}ms")

        # disk path staging: snapshot written before publish
        weight_dir = tmp_path / "updates"
        weight_dir.mkdir()
        meta_d = WeightUpdateMeta(type="disk", path=str(weight_dir),
                                  experiment_name="e2e-st", trial_name="t")
        actor.set_version(3)
        actor.stage_weights(meta_d)
        assert (weight_dir / "v3").is_dir()
        key = names.update_weights_from_disk("e2e-st", "t", 3)
        try:
            name_resolve.get(key)
            raise AssertionError("version published before update_weights")
        except name_resolve.NameEntryNotFoundError:
            pass
        actor.update_weights(meta_d)
        assert name_resolve.get(key)
    finally:
        server.shutdown.set()
        loop.call_soon_threadsafe(loop.stop)
