"""DEAD despite the internal cycle: cycle_a <-> cycle_b import each
other but nothing outside the pair reaches them."""

import myproj.cycle_b  # noqa: F401
