"""AEnt: adaptive entropy-regularized GRPO.

Behavioral counterpart of the reference's `recipe/AEnt` (actor.py,
functional.py, aent_args.py): GRPO with a clamped-entropy bonus whose
coefficient is adapted online to keep policy entropy inside a target band —

    after each update:
        coeff -= coeff_lr * (min(0, H - H_low) + max(0, H - H_high))
        coeff  clamped to [box_low, box_high]        (actor.py:154-159)

The entropy itself is *token-space clamped*: the bottom `entropy_clamp`
fraction of the vocabulary is masked before the entropy is computed
(functional.py clamped_softmax_entropy), so the bonus cannot be farmed by
spreading mass over junk tokens.

TPU-first detail: the live coefficient enters the jitted loss through the
batch (a per-row array) instead of a Python closure — rebuilding the
closure each step would recompile the fused train step on every
coefficient change.
"""
# areal-lint: disable=dead-module AEnt recipe consumed by user training scripts via areal_tpu.recipes (reference parity: AReaL recipe/AEnt); covered by tests/test_aent.py

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from areal_tpu.api.config import PPOActorConfig
from areal_tpu.engine.jax_train import JaxTrainEngine
from areal_tpu.engine.ppo.actor import PPOActor
from areal_tpu.ops.functional import grpo_loss_fn, lm_logprobs_entropy, ppo_actor_loss_fn


@dataclass
class AEntConfig:
    """reference: recipe/AEnt/aent_args.py"""

    entropy_coeff: float = 1e-3
    entropy_clamp: float = 0.0  # fraction of vocab masked from the entropy
    adaptive: bool = True
    entropy_low: float = 0.2
    entropy_high: float = 0.4
    coeff_lr: float = 1e-3
    coeff_box_low: float = 0.0
    coeff_box_high: float = 1e-2
    warmup_steps: int = 0


@dataclass
class AEntPPOActorConfig(PPOActorConfig):
    aent: AEntConfig = field(default_factory=AEntConfig)


def aent_grpo_loss_fn(
    model_out,
    batch: Dict[str, jnp.ndarray],
    eps_clip: float,
    c_clip: Optional[float] = None,
    behav_imp_weight_cap: Optional[float] = None,
    temperature: float = 1.0,
    use_decoupled_loss: bool = True,
    eps_clip_higher: Optional[float] = None,
    entropy_clamp: float = 0.0,
):
    """grpo_loss_fn with a clamped-entropy bonus scaled by the per-batch
    `entropy_coeff` array (reference: recipe/AEnt/actor.py aent_grpo_loss_fn)."""
    labels = jnp.roll(batch["input_ids"], -1, axis=-1)
    loss_mask = batch["loss_mask"].astype(jnp.float32)
    logprobs, entropy, _ = lm_logprobs_entropy(
        model_out, labels, temperature=temperature, entropy_clamp=entropy_clamp
    )
    prox = batch.get("prox_logp") if use_decoupled_loss else None
    loss, stats = ppo_actor_loss_fn(
        logprobs=logprobs,
        old_logprobs=batch["logprobs"],
        advantages=batch["advantages"],
        eps_clip=eps_clip,
        loss_mask=loss_mask,
        c_clip=c_clip,
        proximal_logprobs=prox,
        behav_imp_weight_cap=behav_imp_weight_cap,
        eps_clip_higher=eps_clip_higher,
    )
    # live coefficient rides in the batch: max over loss tokens of a
    # constant-filled array recovers the scalar without a fixed position
    coeff = jnp.max(batch["entropy_coeff"] * loss_mask)
    loss = loss - coeff * jnp.sum(entropy * loss_mask)
    aux = getattr(model_out, "aux_loss", None)
    if aux is not None:
        # MoE load-balance penalty, same fold-in as grpo_loss_fn
        loss = loss + aux * jnp.sum(loss_mask)
        stats["moe_aux_loss"] = aux * jnp.sum(loss_mask)
    stats["entropy"] = jnp.sum(entropy * loss_mask)
    stats["new_logp"] = jnp.sum(logprobs * loss_mask)
    stats["old_logp"] = jnp.sum(batch["logprobs"] * loss_mask)
    return loss, stats


class AEntPPOActor(PPOActor):
    LOSS_KEYS = PPOActor.LOSS_KEYS + ("entropy_coeff",)

    def __init__(self, config: AEntPPOActorConfig, engine):
        super().__init__(config, engine)
        self.aent = config.aent
        self.entropy_coeff = float(self.aent.entropy_coeff)
        self._updates_done = 0
        # override the parent's cached loss fn with the AEnt variant; the
        # partial is built ONCE so the engine's train-step cache hits
        self._loss_fn = functools.partial(
            aent_grpo_loss_fn,
            eps_clip=config.eps_clip,
            c_clip=config.c_clip,
            behav_imp_weight_cap=config.behav_imp_weight_cap,
            temperature=config.temperature,
            use_decoupled_loss=config.use_decoupled_loss,
            eps_clip_higher=config.eps_clip_higher,
            entropy_clamp=self.aent.entropy_clamp,
        )

    def ppo_update(self, batch: Dict[str, np.ndarray]) -> List[Dict[str, float]]:
        shape = batch["input_ids"].shape
        batch = dict(batch)
        batch["entropy_coeff"] = np.full(shape, self.entropy_coeff, np.float32)
        all_stats = super().ppo_update(batch)
        if self.aent.adaptive:
            self._updates_done += 1
            if self._updates_done > self.aent.warmup_steps:
                ent = float(np.mean([s["entropy"] for s in all_stats]))
                self.entropy_coeff -= self.aent.coeff_lr * (
                    min(0.0, ent - self.aent.entropy_low)
                    + max(0.0, ent - self.aent.entropy_high)
                )
                self.entropy_coeff = float(
                    np.clip(
                        self.entropy_coeff,
                        self.aent.coeff_box_low,
                        self.aent.coeff_box_high,
                    )
                )
        for s in all_stats:
            s["entropy_coeff"] = self.entropy_coeff
        return all_stats


class JaxAEntPPOActor(JaxTrainEngine):
    """JaxTrainEngine + AEnt actor (mirrors JaxPPOActor's wiring)."""

    def __init__(self, config: AEntPPOActorConfig, model_config=None):
        super().__init__(config, model_config)
        self.actor = AEntPPOActor(config, self)

    def compute_logp(self, batch):
        return self.actor.compute_logp(batch)

    def compute_advantages(self, batch):
        self.actor.compute_advantages(batch)

    def ppo_update(self, batch):
        return self.actor.ppo_update(batch)
