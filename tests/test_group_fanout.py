"""Group fan-out prefill: cross-slot KV prefix sharing for GRPO groups
(ISSUE 2).  A group of `group_size` requests over one prompt must pay ONE
prefill of the shared prefix — the representative's — with the siblings
receiving it via a device-side cache copy and suffix-prefilling only their
remainder.  Covers greedy parity, sampling independence, the token
accounting identity (shared + suffix + cold + reused == total), abort-storm
x live-publish composition, the no-regression guarantee vs unclustered
admission, steady-state compile-signature stability, and the r5 advice
fixes (reservation off-by-one, holdback abort safety, match-window cap)."""

import os
import time

import numpy as np
import pytest

from areal_tpu.gen.engine import GenEngine, GenRequest
from areal_tpu.models import forward, init_params
from areal_tpu.models.model_config import tiny_config


@pytest.fixture(scope="module", autouse=True)
def _debug_locks():
    """Abort-storm x live-publish composition runs with the runtime lock
    assertions armed (areal-lint C1 acceptance): annotation drift raises
    LockDisciplineError instead of racing silently."""
    old = os.environ.get("AREAL_DEBUG_LOCKS")
    os.environ["AREAL_DEBUG_LOCKS"] = "1"
    yield
    if old is None:
        os.environ.pop("AREAL_DEBUG_LOCKS", None)
    else:
        os.environ["AREAL_DEBUG_LOCKS"] = old


@pytest.fixture(scope="module")
def setup(_debug_locks):
    import jax

    cfg = tiny_config(vocab_size=97, qkv_bias=True,
                      hf_architecture="Qwen2ForCausalLM", eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **kw):
    base = dict(n_slots=8, max_seq_len=128, prompt_bucket=16,
                kv_dtype="float32", reuse_min_tokens=4)
    base.update(kw)
    return GenEngine(cfg, params=params, **base)


def _greedy_reference(cfg, params, prompt, n_new):
    seq = list(prompt)
    out = []
    for _ in range(n_new):
        L = len(seq)
        ids = np.asarray(seq, np.int32)[None]
        pos = np.arange(L, dtype=np.int32)[None]
        seg = np.zeros((1, L), np.int32)
        logits = np.asarray(forward(params, cfg, ids, pos, seg))[0, -1]
        tok = int(np.argmax(logits))
        out.append(tok)
        seq.append(tok)
    return out


def _group(prompt, n, gid, max_new=6, temperature=0.0, counts=None):
    reqs = []
    for i in range(n):
        r = GenRequest(rid=f"{gid}-{i}", input_ids=list(prompt),
                       max_new_tokens=max_new, temperature=temperature,
                       group_id=gid, group_n=n)
        if counts is not None:
            counts[r.rid] = 0
            r.on_done = lambda rr: counts.__setitem__(
                rr.rid, counts[rr.rid] + 1
            )
        reqs.append(r)
    return reqs


def _acct_total(eng):
    st = eng.stats
    return (st["prefill_tokens"] + st["suffix_tokens"]
            + st["reused_tokens"] + st["shared_tokens"])


def test_group_fanout_greedy_matches_solo(setup):
    """Every sibling of a greedy GRPO group emits exactly the solo greedy
    rollout, while only the representative prefills the shared prefix."""
    cfg, params = setup
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 97, 24).tolist()
    ref = _greedy_reference(cfg, params, prompt, 6)
    eng = _engine(cfg, params)
    reqs = _group(prompt, 4, "G")
    eng.generate_blocking(reqs)
    for r in reqs:
        assert r.output_tokens == ref, r.rid
    # one fresh prefill (the representative), one fan-out copy, and the
    # 3 siblings rode the shared prefix: len-1 tokens each never recomputed
    assert eng.stats["prefill_calls"] == 1
    assert eng.stats["prefill_tokens"] == len(prompt)
    assert eng.stats["copy_calls"] == 1
    assert eng.stats["shared_tokens"] == 3 * (len(prompt) - 1)
    assert _acct_total(eng) == 4 * len(prompt)


def test_group_fanout_sampling_stays_independent(setup):
    """Siblings share prefix K/V, not randomness: a stochastic group must
    still diversify (per-row categorical draws in the suffix batch)."""
    cfg, params = setup
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 97, 20).tolist()
    eng = _engine(cfg, params)
    reqs = _group(prompt, 6, "S", max_new=10, temperature=1.0)
    eng.generate_blocking(reqs)
    outs = {tuple(r.output_tokens) for r in reqs}
    assert len(outs) > 1
    assert all(np.isfinite(r.output_logprobs).all() for r in reqs)
    assert eng.stats["shared_tokens"] == 5 * (len(prompt) - 1)


def test_shared_accounting_identity_mixed_workload(setup):
    """The fast tier-1 accounting invariant: over a mixed workload (GRPO
    group + multi-turn retained reuse + distinct cold prompts), every
    admitted prompt token is counted exactly once as cold (prefill),
    suffix, retained-reused, or shared."""
    cfg, params = setup
    rng = np.random.default_rng(2)
    eng = _engine(cfg, params)
    admitted_tokens = 0

    # 1) a GRPO group
    p1 = rng.integers(0, 97, 20).tolist()
    g = _group(p1, 4, "A", max_new=4)
    eng.generate_blocking(g)
    admitted_tokens += 4 * len(p1)
    # 2) a multi-turn extension of one transcript (retained reuse)
    turn2 = p1 + g[0].output_tokens + rng.integers(0, 97, 5).tolist()
    r2 = GenRequest(rid="t2", input_ids=turn2, max_new_tokens=4,
                    temperature=0.0)
    eng.generate_blocking([r2])
    admitted_tokens += len(turn2)
    assert eng.stats["reused_tokens"] > 0  # the retained path engaged
    # 3) distinct cold prompts
    cold = [GenRequest(rid=f"c{i}",
                       input_ids=rng.integers(0, 97, 12).tolist(),
                       max_new_tokens=3, temperature=0.0) for i in range(3)]
    eng.generate_blocking(cold)
    admitted_tokens += 3 * 12
    assert _acct_total(eng) == admitted_tokens, eng.stats


def test_clustered_admission_admits_no_fewer_than_unclustered(setup):
    """Regression guard: clustering changes HOW prompts prefill, never
    whether they admit.  The same burst over share and no-share engines
    must admit the same number of requests on the first pass and complete
    identically under greedy decoding."""
    cfg, params = setup
    rng = np.random.default_rng(3)
    p_a = rng.integers(0, 97, 18).tolist()
    p_b = rng.integers(0, 97, 14).tolist()
    singles = [rng.integers(0, 97, 10).tolist() for _ in range(2)]

    def burst():
        reqs = _group(p_a, 3, "A", max_new=4) + _group(p_b, 3, "B", max_new=4)
        reqs += [GenRequest(rid=f"s{i}", input_ids=list(p),
                            max_new_tokens=4, temperature=0.0)
                 for i, p in enumerate(singles)]
        return reqs

    admitted = {}
    outputs = {}
    for share in (True, False):
        eng = _engine(cfg, params, share_prefix=share)
        reqs = burst()
        for r in reqs:
            eng.submit(r)
        # the group hold may park a pass; give it the TTL then count
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            eng.step(chunk=1)
            if sum(r is not None for r in eng.slot_req) == len(reqs):
                break
        admitted[share] = sum(r is not None for r in eng.slot_req)
        eng.generate_blocking(reqs)  # drain
        outputs[share] = [tuple(r.output_tokens) for r in reqs]
    assert admitted[True] >= admitted[False]
    assert outputs[True] == outputs[False]


def test_group_fanout_under_abort_storm_and_live_publish(setup):
    """The composition case the tentpole must survive: a group decodes,
    a LIVE weight publish lands mid-flight (no abort — versions transition
    per token), then an abort storm hits and every sibling resubmits with
    accumulated tokens.  Siblings keep their own retained prefixes, no
    request sees a second terminal callback, and per-token output_versions
    stay monotonic."""
    import jax

    cfg, params = setup
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, 97, 24).tolist()
    eng = _engine(cfg, params, n_slots=4)
    counts: dict = {}
    reqs = _group(prompt, 4, "W", max_new=24, counts=counts)
    for r in reqs:
        eng.submit(r)
    while any(len(r.output_tokens) < 4 for r in reqs):
        eng.step(chunk=2)
    # live publish: nobody dies, decoding continues under the new policy
    new_params = init_params(cfg, jax.random.PRNGKey(42))
    eng.swap_weights_live(new_params, version=1)
    assert all(not r.stop_reason for r in reqs)
    while any(len(r.output_tokens) < 8 for r in reqs):
        eng.step(chunk=2)
    # abort storm
    eng.abort_all("abort")
    assert all(r.stop_reason == "abort" for r in reqs)
    assert all(counts[r.rid] == 1 for r in reqs)
    reused_before = eng.stats["reused_tokens"]
    resubs = []
    for r in reqs:
        rr = GenRequest(rid=r.rid, input_ids=r.input_ids + r.output_tokens,
                        max_new_tokens=24 - len(r.output_tokens),
                        temperature=0.0, group_id="W", group_n=4)
        counts[("re", rr.rid)] = 0
        rr.on_done = lambda x, k=("re", rr.rid): counts.__setitem__(
            k, counts[k] + 1
        )
        resubs.append(rr)
    eng.submit_batch(resubs)
    eng.generate_blocking(resubs)
    # every sibling found ITS retained prefix (prompt + its own tokens) —
    # the storm never collapsed the group onto one reserved slot
    assert eng.stats["reused_tokens"] - reused_before >= sum(
        len(r.input_ids) for r in reqs
    )
    # exactly one terminal callback per request object
    assert all(counts[r.rid] == 1 for r in reqs)
    assert all(counts[("re", rr.rid)] == 1 for rr in resubs)
    # versions never decrease along any trajectory
    for r, rr in zip(reqs, resubs):
        versions = r.output_versions + rr.output_versions
        assert all(a <= b for a, b in zip(versions, versions[1:])), versions
        assert versions[0] == 0 and versions[-1] == 1


def test_no_new_compile_signatures_in_steady_state(setup):
    """Acceptance: shared-prefix admission must not mint XLA programs
    mid-loop.  After a warmup over the bucket ladder, further mixed-length
    group workloads add ZERO entries to the prefill / suffix-prefill jit
    caches (the fan-out copy is fused into the suffix program with
    bucketed copy lengths, so it shares the same cache)."""
    cfg, params = setup
    rng = np.random.default_rng(5)
    eng = _engine(cfg, params, n_slots=8, max_seq_len=256)

    def run_groups(lens, sizes):
        for n, g in zip(lens, sizes):
            prompt = rng.integers(0, 97, n).tolist()
            reqs = _group(prompt, g, f"g{n}-{g}", max_new=2)
            eng.generate_blocking(reqs)

    # warmup: hit every (rows, prompt-bucket, copy-block, key-window)
    # signature the steady state will use — the ladder is log-bounded, so
    # covering it is a handful of groups (33 sits just past the 32 bucket
    # boundary: copy-block 32 but key-window 64)
    run_groups([25, 20, 60, 17, 44, 33], [5, 3, 2, 5, 3, 5])
    sizes = {
        "prefill": eng._prefill_fn._cache_size(),
        "suffix": eng._suffix_prefill_fn._cache_size(),
    }
    # steady state: different lengths and group sizes, same bucket ladder
    run_groups([33, 25, 60, 17, 44], [5, 3, 2, 5, 3])
    run_groups([19, 47, 30], [4, 2, 5])
    assert eng._prefill_fn._cache_size() == sizes["prefill"]
    assert eng._suffix_prefill_fn._cache_size() == sizes["suffix"]

    # ISSUE 9: cross-check against the checked-in C6 signature budget —
    # the static ladder proof and this runtime soak must agree
    # (regenerate with `python scripts/lint.py --write-budget`).
    import json

    from areal_tpu.analysis.jit_signatures import BUDGET_PATH

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, BUDGET_PATH)) as f:
        ref = json.load(f)["reference_configs"]["group_fanout_soak"]
    assert ref["config"] == {"n_slots": 8, "max_seq_len": 256,
                             "prompt_bucket": 16, "decode_tiers": 1}
    assert eng._prefill_fn._cache_size() <= ref["budgets"]["prefill"]
    assert (eng._suffix_prefill_fn._cache_size()
            <= ref["budgets"]["suffix_prefill"])


def test_abort_reservation_strictly_greater_threshold(setup):
    """ADVICE r5: a slot whose retained_len == reuse_min_tokens must NOT be
    reserved by abort_all (its owner's resubmission could never be the only
    claimant for the full TTL); strictly longer prefixes still reserve."""
    cfg, params = setup
    rng = np.random.default_rng(6)
    eng = _engine(cfg, params, n_slots=2, reuse_min_tokens=8,
                  abort_reserve_s=30.0)
    # slot at exactly the threshold: prompt 7 + 1 generated = lengths 8
    r1 = GenRequest(rid="eq", input_ids=rng.integers(0, 97, 7).tolist(),
                    max_new_tokens=8, temperature=0.0)
    eng.submit(r1)
    while len(r1.output_tokens) < 1:
        eng.step(chunk=1)
    s_eq = next(s for s in range(2) if eng.slot_req[s] is r1)
    assert int(eng.lengths[s_eq]) == 8
    eng.abort_all("abort")
    assert eng._reserved_until[s_eq] == 0.0  # NOT reserved at equality
    # strictly above the threshold: reserved
    r2 = GenRequest(rid="gt", input_ids=rng.integers(0, 97, 16).tolist(),
                    max_new_tokens=8, temperature=0.0)
    eng.submit(r2)
    while len(r2.output_tokens) < 2:
        eng.step(chunk=1)
    s_gt = next(s for s in range(2) if eng.slot_req[s] is r2)
    eng.abort_all("abort")
    assert eng._reserved_until[s_gt] > time.monotonic()


def test_abort_during_admit_pass_never_resurrects_holdback(setup):
    """ADVICE r5: an abort_all landing mid-_admit must not let the pass
    write drained-but-unadmitted requests back into _holdback behind their
    terminal callback — the abort generation counter finishes them with
    'abort' instead, exactly once."""
    cfg, params = setup
    rng = np.random.default_rng(7)
    eng = _engine(cfg, params, n_slots=2)
    counts: dict = {}
    reqs = []
    for i in range(6):  # > n_slots so some must be held back
        r = GenRequest(rid=f"h{i}",
                       input_ids=rng.integers(0, 97, 10).tolist(),
                       max_new_tokens=4, temperature=0.0)
        counts[r.rid] = 0
        r.on_done = lambda rr: counts.__setitem__(rr.rid, counts[rr.rid] + 1)
        reqs.append(r)
        eng.submit(r)
    orig = eng._plan_clusters

    def aborting_plan(entries, matched):
        # fire the abort in the window between the intake swap and the
        # holdback write-back — the race the generation counter closes
        eng.abort_all("abort")
        return orig(entries, matched)

    eng._plan_clusters = aborting_plan
    eng.step()
    eng._plan_clusters = orig
    # nothing lingers in holdback unfinished, and nobody ever gets a
    # second terminal callback (guarded field: read under the lock, which
    # the armed AREAL_DEBUG_LOCKS assertions enforce even for tests)
    with eng._lock:
        assert not eng._holdback
    for r in reqs:
        assert counts[r.rid] <= 1, r.rid
        if r.stop_reason == "abort":
            assert counts[r.rid] == 1
    # the engine still serves cleanly afterwards
    fresh = GenRequest(rid="after", input_ids=rng.integers(0, 97, 8).tolist(),
                       max_new_tokens=3, temperature=0.0)
    eng.generate_blocking([fresh])
    assert fresh.stop_reason == "length"


def test_group_hold_admits_partial_group_after_ttl(setup):
    """A declared group missing members is parked only for group_hold_s;
    the partial group then admits (a finished sibling never resubmits)."""
    cfg, params = setup
    rng = np.random.default_rng(8)
    eng = _engine(cfg, params, group_hold_s=0.15)
    prompt = rng.integers(0, 97, 16).tolist()
    partial = _group(prompt, 4, "P", max_new=3)[:2]  # 2 of a declared 4
    for r in partial:
        eng.submit(r)
    eng.step()
    assert all(r is None for r in eng.slot_req[: eng.n_slots])  # held
    deadline = time.monotonic() + 10
    while any(not r.stop_reason for r in partial):
        eng.step()
        assert time.monotonic() < deadline
    # the two that did arrive still clustered with each other
    assert eng.stats["shared_tokens"] == len(prompt) - 1


def test_strict_reload_zeroes_shared_prefixes_like_retained(setup):
    """retain_kv_on_reload=False: after a live publish, neither retained
    nor fan-out-shared prefixes may seed reuse, and kv_version reflects
    that no pre-swap KV survives."""
    import jax

    cfg, params = setup
    rng = np.random.default_rng(9)
    eng = _engine(cfg, params, retain_kv_on_reload=False)
    prompt = rng.integers(0, 97, 20).tolist()
    reqs = _group(prompt, 4, "Z", max_new=3)
    eng.generate_blocking(reqs)
    assert eng.stats["shared_tokens"] > 0
    assert eng.retained_len.max() > 0
    eng.swap_weights_live(init_params(cfg, jax.random.PRNGKey(11)), version=1)
    assert eng.retained_len.max() == 0
    assert (eng.kv_version == 1).all()
    # an identical prompt now pays a fresh representative prefill (no
    # suffix against pre-swap KV) — only in-group sharing, under the new
    # policy, remains
    suffix_before = eng.stats["reused_tokens"]
    reqs2 = _group(prompt, 2, "Z2", max_new=3)
    eng.generate_blocking(reqs2)
    assert eng.stats["reused_tokens"] == suffix_before
    assert (eng.kv_version == 1).all()


def test_match_window_caps_lcp_scan(setup):
    """The global lcp scan is bounded by match_window, not the (larger)
    drain window — requests beyond the cap still admit, just without the
    retained-prefix match."""
    cfg, params = setup
    rng = np.random.default_rng(10)
    eng = _engine(cfg, params, n_slots=4, match_window=2,
                  admission_window=16)
    # seed a retained prefix
    p = rng.integers(0, 97, 16).tolist()
    r0 = GenRequest(rid="seed", input_ids=p, max_new_tokens=2,
                    temperature=0.0)
    eng.generate_blocking([r0])
    assert eng.retained_len.max() > 0
    # a burst where the retained-matching candidate sits BEYOND the cap
    others = [GenRequest(rid=f"o{i}",
                         input_ids=rng.integers(0, 97, 8).tolist(),
                         max_new_tokens=2, temperature=0.0)
              for i in range(2)]
    resume = GenRequest(rid="seed", input_ids=p + r0.output_tokens,
                        max_new_tokens=2, temperature=0.0)
    for r in others + [resume]:
        eng.submit(r)
    eng.generate_blocking(others + [resume])
    # all complete regardless of whether the match was scanned
    assert all(r.stop_reason for r in others + [resume])
