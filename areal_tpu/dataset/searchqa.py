"""Search-QA dataset: questions + answers over a retrieval corpus.

Capability counterpart of the reference's search-agent example data
(examples/search-agent/local_1.5b_example.yaml — QA pairs graded after
retrieval).  Rows feed `AgentWorkflow` + `SearchQAAgent` +
`LocalSearchEnv` via the `workflow=search` entry-point branch.

Manifest layout (jsonl): {"question": ..., "answer": ...,
"corpus"?: [...passages...]} — per-row corpora override the shared
corpus file (`corpus.jsonl`/`corpus.txt` next to the manifest, one
passage per line).
"""

import json
import os
from typing import Optional

from areal_tpu.dataset import register_dataset

PROMPT = (
    "Answer the question below. You can search a reference corpus by "
    "writing <search>your query</search>; results appear inside "
    "<information> tags. When you know the answer, give it inside "
    "\\boxed{{}}.\n\nQuestion: {question}"
)


def _load_corpus(base: str):
    for name in ("corpus.jsonl", "corpus.txt"):
        p = os.path.join(base, name)
        if os.path.exists(p):
            with open(p) as f:
                lines = [ln.strip() for ln in f if ln.strip()]
            if name.endswith(".jsonl"):
                return [
                    json.loads(ln).get("text", ln) if ln.startswith("{") else ln
                    for ln in lines
                ]
            return lines
    return []


@register_dataset("searchqa")
def get_searchqa_dataset(
    path: str,
    split: str = "train",
    tokenizer=None,
    max_length: Optional[int] = None,
    **kwargs,
):
    from areal_tpu.agent.search_env import SearchIndex

    manifest = path
    if os.path.isdir(path):
        manifest = os.path.join(path, f"{split}.jsonl")
    base = os.path.dirname(os.path.abspath(manifest))
    shared_corpus = _load_corpus(base)
    # one BM25 index for the shared corpus: rows reference it via
    # "_search_index" so envs never rebuild tf/df tables per episode
    shared_index = SearchIndex(shared_corpus) if shared_corpus else None
    samples = []
    with open(manifest) as f:
        for i, line in enumerate(f):
            if not line.strip():
                continue
            row = json.loads(line)
            prompt = PROMPT.format(question=row["question"])
            sample = {
                "messages": [{"role": "user", "content": prompt}],
                "answer": str(row["answer"]),
                "corpus": row.get("corpus", shared_corpus),
                "query_id": str(row.get("query_id", i)),
            }
            if "corpus" not in row and shared_index is not None:
                sample["_search_index"] = shared_index
            if "input_ids" in row:
                sample["input_ids"] = row["input_ids"]
            elif tokenizer is not None and not hasattr(
                tokenizer, "apply_chat_template"
            ):
                sample["input_ids"] = tokenizer.encode(prompt)
            if (
                max_length
                and "input_ids" in sample
                and len(sample["input_ids"]) > max_length
            ):
                continue
            samples.append(sample)
    return samples
