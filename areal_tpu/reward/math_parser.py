"""Math answer extraction and verification.

Behavioral counterpart of the reference's rule-based math verifier
(areal/reward/math_parser.py, 867 LoC with vendored latex2sympy;
realhf/impl/model/interface/math_rw_interface.py): extract the model's final
answer (\\boxed{...}, "the answer is", or trailing expression), normalise
latex/number formatting, and compare against ground truth — string match,
then numeric, then sympy symbolic equivalence.

Runs inside the reward process pool (api/reward.py), so sympy hangs are
bounded by the pool timeout rather than an in-process alarm.
"""

import re
from typing import Optional

# --------------------------------------------------------------------------
# extraction
# --------------------------------------------------------------------------


def _find_boxed(text: str) -> Optional[str]:
    """Last \\boxed{...} / \\fbox{...} content, brace-balanced."""
    idx = max(text.rfind("\\boxed"), text.rfind("\\fbox"))
    if idx < 0:
        return None
    brace = text.find("{", idx)
    if brace < 0:
        # \boxed 42 form
        m = re.match(r"\\boxed\s+(\S+)", text[idx:])
        return m.group(1) if m else None
    depth = 0
    for i in range(brace, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[brace + 1 : i]
    return None


_ANSWER_PATTERNS = [
    r"(?:final answer|the answer)\s*(?:is|:)?\s*([^\n\.]+)",
    r"####\s*([^\n]+)",
]


def extract_answer(text: str) -> Optional[str]:
    boxed = _find_boxed(text)
    if boxed is not None:
        return boxed.strip()
    low = text.lower()
    for pat in _ANSWER_PATTERNS:
        matches = list(re.finditer(pat, low))
        if matches:
            m = matches[-1]
            return text[m.start(1) : m.end(1)].strip()
    # fall back to the last number in the text
    nums = re.findall(r"-?\d[\d,]*(?:\.\d+)?", text)
    return nums[-1] if nums else None


# --------------------------------------------------------------------------
# normalisation & comparison
# --------------------------------------------------------------------------

_LATEX_SUBS = [
    (r"\\left|\\right", ""),
    (r"\\!|\\,|\\;|\\:|~", ""),
    (r"\\text\{([^{}]*)\}", r"\1"),
    (r"\\mathrm\{([^{}]*)\}", r"\1"),
    (r"\\mbox\{([^{}]*)\}", r"\1"),
    (r"\\\$|\$", ""),
    (r"\\%|%", ""),
    (r"\\dfrac", r"\\frac"),
    (r"\\tfrac", r"\\frac"),
    (r"\\cdot", "*"),
    (r"\\times", "*"),
    (r"\\div", "/"),
    (r"\\pi", "pi"),
    (r"\\infty", "oo"),
    (r"\\circ", ""),
    (r"\\degree", ""),
    (r"\s+", ""),
]


def normalize_answer(ans: str) -> str:
    s = ans.strip()
    for pat, rep in _LATEX_SUBS:
        s = re.sub(pat, rep, s)
    # \frac{a}{b} -> (a)/(b)
    while True:
        m = re.search(r"\\frac\{([^{}]*)\}\{([^{}]*)\}", s)
        if not m:
            break
        s = s[: m.start()] + f"(({m.group(1)})/({m.group(2)}))" + s[m.end() :]
    s = re.sub(r"\\sqrt\{([^{}]*)\}", r"sqrt(\1)", s)
    s = re.sub(r"\\sqrt(\w)", r"sqrt(\1)", s)
    s = s.replace("^", "**").replace("{", "(").replace("}", ")")
    s = s.replace(",", "")  # thousands separators
    s = s.rstrip(".")
    # drop a single unbalanced paren at either end; never touch balanced ones
    if s.count("(") > s.count(")"):
        if s.endswith("("):
            s = s[:-1]
        elif s.startswith("("):
            s = s[1:]
    elif s.count(")") > s.count("("):
        if s.startswith(")"):
            s = s[1:]
        elif s.endswith(")"):
            s = s[:-1]
    return s.lower()


def _to_number(s: str) -> Optional[float]:
    try:
        return float(s)
    except (ValueError, TypeError):
        pass
    m = re.fullmatch(r"\(*\(?(-?[\d\.]+)\)?/\(?(-?[\d\.]+)\)?\)*", s)
    if m:
        try:
            return float(m.group(1)) / float(m.group(2))
        except (ValueError, ZeroDivisionError):
            return None
    return None


def math_equal(pred: str, target: str, rel_tol: float = 1e-4) -> bool:
    if pred is None or target is None:
        return False
    p, t = normalize_answer(str(pred)), normalize_answer(str(target))
    if p == t:
        return True
    pn, tn = _to_number(p), _to_number(t)
    if pn is not None and tn is not None:
        return abs(pn - tn) <= rel_tol * max(1.0, abs(tn))
    if (pn is None) != (tn is None):
        # one side numeric, other symbolic: let sympy decide
        pass
    try:
        import sympy
        from sympy.parsing.sympy_parser import parse_expr

        diff = sympy.simplify(parse_expr(p) - parse_expr(t))
        return diff == 0
    except Exception:  # noqa: BLE001 — unparseable => not equal
        return False


# --------------------------------------------------------------------------
# reward functions (signature: prompt, completion, prompt_ids, completion_ids,
# **data -> float; reference: areal/reward usage in workflows)
# --------------------------------------------------------------------------


def gsm8k_reward_fn(prompt, completions, prompt_ids, completion_ids, answer, **kw):
    pred = extract_answer(completions)
    return float(pred is not None and math_equal(pred, answer))


def math_verify_reward(prompt, completions, prompt_ids, completion_ids, solution=None,
                       answer=None, **kw):
    target = answer if answer is not None else extract_answer(solution or "")
    pred = extract_answer(completions)
    return float(pred is not None and target is not None and math_equal(pred, target))
