"""C6 negative fixture: every static argument provably on the ladder."""
# areal-lint: hot-path (C6 fixture: jitted callables live here)

import jax

from areal_tpu.utils.datapack import round_up_to_bucket


def _decode(params, tokens, n, key_window):
    return tokens


def _gae(arrs, out_len, gamma):
    return arrs


_gae_fn = jax.jit(_gae, static_argnums=(2,))


def run_gae(arrs, cfg):
    # config attribute chains are engine-lifetime constants
    return _gae_fn(arrs, 0, cfg.gamma)


class Engine:
    def __init__(self):
        self.max_seq_len = 256
        self.bucket = 16
        self.tier_bounds = [64, 256]
        self._decode_fn = jax.jit(_decode, static_argnums=(3,))

    def bucketed(self, tokens, span):
        kw = round_up_to_bucket(span + 1, self.bucket, self.max_seq_len)
        return self._decode_fn(None, tokens, 4, kw)

    def config_window(self, tokens):
        return self._decode_fn(None, tokens, 4, self.max_seq_len)

    def tiered(self, tokens, t, full):
        kw = (
            self.max_seq_len
            if full
            else min(self.tier_bounds[t], self.max_seq_len)
        )
        return self._decode_fn(None, tokens, 4, kw)

    def windowed(self, tokens, key_window=0):
        # parameter: the resolved caller passes nothing; the default (a
        # sentinel 0) applies
        return self._decode_fn(None, tokens, 4, key_window)

    def outer(self, tokens):
        return self.windowed(tokens)
