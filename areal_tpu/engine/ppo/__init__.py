from areal_tpu.engine.ppo.actor import JaxPPOActor, PPOActor
from areal_tpu.engine.ppo.critic import JaxPPOCritic

__all__ = ["PPOActor", "JaxPPOActor", "JaxPPOCritic"]
