"""JaxTrainEngine on an 8-virtual-device CPU mesh.

Ports the reference's engine test strategy (areal/tests/test_train_engine.py,
test_fsdp_engine_nccl.py, torchrun/run_fsdp_ulysses_forward.py): training
reduces the loss, forward logprobs match an unsharded reference, and results
are invariant to the mesh layout (dp/fsdp/tp/sp splits)."""

import functools

import numpy as np
import pytest

from areal_tpu.api.config import (
    MeshConfig,
    MicroBatchSpec,
    OptimizerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta
from areal_tpu.engine.jax_train import JaxTrainEngine
from areal_tpu.models.model_config import tiny_config
from areal_tpu.ops import sft_loss_fn
from areal_tpu.utils.data import pack_into_rows, unpack_rows


MODEL_CFG = tiny_config(vocab_size=128, qkv_bias=True, hf_architecture="Qwen2ForCausalLM")


def _engine(mesh: MeshConfig, n_mbs: int = 1, lr: float = 1e-2) -> JaxTrainEngine:
    cfg = TrainEngineConfig(
        experiment_name="t",
        trial_name="t",
        init_from_scratch=True,
        dtype="float32",
        gradient_checkpointing=False,
        mesh=mesh,
        mb_spec=MicroBatchSpec(n_mbs=n_mbs),
        optimizer=OptimizerConfig(lr=lr, warmup_steps_proportion=0.0, weight_decay=0.0),
        pack_length_quantum=16,
    )
    eng = JaxTrainEngine(cfg, model_config=MODEL_CFG)
    eng.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    return eng


def _batch(rng, B=8, L=12):
    lens = rng.integers(4, L + 1, B)
    mask = np.arange(L)[None, :] < lens[:, None]
    ids = rng.integers(0, MODEL_CFG.vocab_size, (B, L)) * mask
    loss_mask = mask.copy()
    # exclude each sequence's last valid token (no next-token target)
    loss_mask[np.arange(B), lens - 1] = False
    return {
        "input_ids": ids.astype(np.int32),
        "attention_mask": mask,
        "loss_mask": loss_mask.astype(np.float32),
    }


def _weight(batch):
    return float(np.sum(batch["loss_mask"]))


def test_row_packing_roundtrip():
    rng = np.random.default_rng(0)
    b = _batch(rng)
    rp = pack_into_rows(b, row_len=16, rows_multiple=4)
    assert rp.data["input_ids"].shape[0] % 4 == 0
    # every sequence's tokens appear exactly once
    out = unpack_rows(rp, rp.data["input_ids"], 8, 12)
    np.testing.assert_array_equal(out * b["attention_mask"], b["input_ids"])


def test_train_loss_decreases():
    rng = np.random.default_rng(1)
    eng = _engine(MeshConfig(data_parallel_size=2, fsdp_parallel_size=2,
                             tensor_parallel_size=2))
    batch = _batch(rng)
    losses = []
    for _ in range(8):
        stats = eng.train_batch(batch, sft_loss_fn, _weight)
        losses.append(stats["loss"])
    assert losses[-1] < losses[0] * 0.7, losses
    assert stats["grad_norm"] > 0
    assert stats["lr"] > 0


def test_train_loss_decreases_gpt2_and_gemma2():
    """The trainer's grad path covers the non-llama structures: gpt2
    (LayerNorm biases, learned positions, non-gated MLP) and gemma2
    (sandwich norms, softcaps -> chunked head fallback) on a sharded mesh."""
    for kw in (
        dict(hf_architecture="GPT2LMHeadModel", norm_type="layernorm",
             pos_emb="learned", mlp_gated=False, qkv_bias=True,
             attn_output_bias=True, mlp_bias=True, num_kv_heads=4,
             hidden_act="gelu_pytorch_tanh", tie_word_embeddings=True),
        dict(hf_architecture="Gemma2ForCausalLM", sandwich_norms=True,
             norm_unit_offset=True, scale_embeddings=True,
             hidden_act="gelu_pytorch_tanh", attn_logit_softcap=50.0,
             final_logit_softcap=30.0, sliding_window=8,
             layer_is_sliding=(True, False), tie_word_embeddings=True),
    ):
        mc = tiny_config(vocab_size=128, **kw)
        cfg = TrainEngineConfig(
            experiment_name="t", trial_name="t", init_from_scratch=True,
            dtype="float32", gradient_checkpointing=False,
            mesh=MeshConfig(data_parallel_size=2, fsdp_parallel_size=2,
                            tensor_parallel_size=2),
            mb_spec=MicroBatchSpec(n_mbs=1),
            optimizer=OptimizerConfig(lr=1e-2, warmup_steps_proportion=0.0,
                                      weight_decay=0.0),
            pack_length_quantum=16,
        )
        eng = JaxTrainEngine(cfg, model_config=mc)
        eng.initialize(ft_spec=FinetuneSpec(1, 64, 8))
        rng = np.random.default_rng(2)
        batch = _batch(rng)
        losses = [
            eng.train_batch(batch, sft_loss_fn, _weight)["loss"]
            for _ in range(8)
        ]
        assert losses[-1] < losses[0] * 0.7, (kw["hf_architecture"], losses)
        eng.destroy()


def test_train_step_ring_attention_matches_naive():
    """attn_impl=ring (K/V sequence-sharded, rotating blocks) reproduces the
    naive-attention loss through the full train step on a dp2 x sp2 x tp2
    mesh — context parallelism as a drop-in numerics-preserving switch."""
    losses = {}
    for impl in ("naive", "ring"):
        mc = tiny_config(vocab_size=128, qkv_bias=True,
                         hf_architecture="Qwen2ForCausalLM", attn_impl=impl)
        cfg = TrainEngineConfig(
            experiment_name="t", trial_name="t", init_from_scratch=True,
            dtype="float32", gradient_checkpointing=True,
            mesh=MeshConfig(data_parallel_size=2, sequence_parallel_size=2,
                            tensor_parallel_size=2),
            mb_spec=MicroBatchSpec(n_mbs=1),
            optimizer=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0,
                                      weight_decay=0.0),
            pack_length_quantum=16,
        )
        eng = JaxTrainEngine(cfg, model_config=mc)
        eng.initialize(ft_spec=FinetuneSpec(1, 64, 8))
        rng = np.random.default_rng(3)
        batch = _batch(rng)
        losses[impl] = [
            eng.train_batch(batch, sft_loss_fn, _weight)["loss"]
            for _ in range(2)
        ]
        eng.destroy()
    np.testing.assert_allclose(losses["ring"], losses["naive"], rtol=2e-4)


def test_forward_matches_unsharded():
    rng = np.random.default_rng(2)
    batch = _batch(rng)
    ref_eng = _engine(MeshConfig())
    ref = ref_eng.forward(batch)
    for mesh in (
        MeshConfig(data_parallel_size=2, fsdp_parallel_size=2, tensor_parallel_size=2),
        MeshConfig(fsdp_parallel_size=2, sequence_parallel_size=2,
                   tensor_parallel_size=2),
        MeshConfig(data_parallel_size=8),
    ):
        eng = _engine(mesh)
        got = eng.forward(batch)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_train_invariant_to_microbatching():
    """Global loss-weight normalisation: the update must not depend on the
    micro-batch split (reference invariant of fsdp_engine.py:499-606)."""
    rng = np.random.default_rng(3)
    batch = _batch(rng)
    stats1 = _engine(MeshConfig(), n_mbs=1).train_batch(batch, sft_loss_fn, _weight)
    stats4 = _engine(MeshConfig(), n_mbs=4).train_batch(batch, sft_loss_fn, _weight)
    np.testing.assert_allclose(stats1["loss"], stats4["loss"], rtol=1e-4)
    np.testing.assert_allclose(stats1["grad_norm"], stats4["grad_norm"], rtol=1e-3)


def test_train_invariant_to_mesh():
    rng = np.random.default_rng(4)
    batch = _batch(rng)

    def run(mesh):
        eng = _engine(mesh)
        for _ in range(3):
            stats = eng.train_batch(batch, sft_loss_fn, _weight)
        return stats, eng.forward(batch)

    stats_ref, logp_ref = run(MeshConfig())
    stats_dist, logp_dist = run(
        MeshConfig(data_parallel_size=2, fsdp_parallel_size=2, tensor_parallel_size=2)
    )
    np.testing.assert_allclose(stats_dist["loss"], stats_ref["loss"], rtol=1e-3)
    np.testing.assert_allclose(logp_dist, logp_ref, rtol=2e-3, atol=2e-3)


def test_eval_batch_and_version():
    rng = np.random.default_rng(5)
    eng = _engine(MeshConfig())
    batch = _batch(rng)
    out = eng.eval_batch(batch, sft_loss_fn, _weight)
    assert out["loss"] > 0
    eng.set_version(3)
    assert eng.get_version() == 3


def test_save_load_roundtrip(tmp_path):
    rng = np.random.default_rng(6)
    eng = _engine(MeshConfig(fsdp_parallel_size=2))
    batch = _batch(rng)
    eng.train_batch(batch, sft_loss_fn, _weight)
    logp_before = eng.forward(batch)
    eng.save(SaveLoadMeta(path=str(tmp_path / "ck"), with_optim=True))

    eng2 = _engine(MeshConfig(fsdp_parallel_size=2))
    eng2.load(SaveLoadMeta(path=str(tmp_path / "ck"), with_optim=True))
    logp_after = eng2.forward(batch)
    np.testing.assert_allclose(logp_after, logp_before, rtol=1e-4, atol=1e-4)
    assert eng2.step_count == eng.step_count
    # loaded engine keeps training identically to the original
    s1 = eng.train_batch(batch, sft_loss_fn, _weight)
    s2 = eng2.train_batch(batch, sft_loss_fn, _weight)
    np.testing.assert_allclose(s1["loss"], s2["loss"], rtol=1e-4)


def test_async_stats_pipeline_matches_sync():
    """async_stats defers the fetch; numbers must equal the sync path and
    the tracker commit must happen at materialisation, not dispatch."""
    from areal_tpu.utils import stats as stats_mod

    rng = np.random.default_rng(7)
    batch = _batch(rng)
    mesh = MeshConfig(data_parallel_size=2, fsdp_parallel_size=2,
                      tensor_parallel_size=2)

    sync_eng = _engine(mesh)
    async_eng = _engine(mesh)
    async_eng.config.async_stats = True

    sync_losses, pendings = [], []
    for _ in range(4):
        sync_losses.append(sync_eng.train_batch(batch, sft_loss_fn, _weight)["loss"])
        pendings.append(async_eng.train_batch(batch, sft_loss_fn, _weight))
    for p in pendings:
        assert isinstance(p, stats_mod.PendingTrainStats)
        assert p._result is None  # not yet materialised
    async_losses = [p["loss"] for p in pendings]  # read -> materialise
    np.testing.assert_allclose(async_losses, sync_losses, rtol=1e-5)
    # async mode omits per-step wall-clock-derived keys (no sync point)
    assert "step_time" not in pendings[0].materialize()
    assert pendings[0]["total_loss_weight"] == _weight(batch)
    # finalizers registered via .then run once, at materialisation
    seen = []
    p = async_eng.train_batch(batch, sft_loss_fn, _weight)
    p.then(lambda st: (seen.append(True), st)[1])
    assert not seen
    _ = p["loss"]
    assert seen == [True]


def test_learned_pos_clamp_applies_on_checkpoint_route(tmp_path):
    """The common route — gpt2 checkpoint given via cfg.path with
    model_config=None — only learns pos_emb=='learned' from the loaded
    config, so the max_pack_length clamp must run after the checkpoint
    resolves (r4 advisor: the guard previously ran before load_hf_params
    and silently skipped, training overflow positions on the last wpe row)."""
    import jax

    from areal_tpu.models import init_params
    from areal_tpu.models.hf import save_hf_checkpoint

    mc = tiny_config(
        vocab_size=128, hf_architecture="GPT2LMHeadModel",
        norm_type="layernorm", pos_emb="learned", mlp_gated=False,
        qkv_bias=True, attn_output_bias=True, mlp_bias=True, num_kv_heads=4,
        hidden_act="gelu_pytorch_tanh", tie_word_embeddings=True,
        max_position_embeddings=32,
    )
    ckpt = tmp_path / "gpt2"
    save_hf_checkpoint(init_params(mc, jax.random.PRNGKey(0)), mc, str(ckpt),
                       save_dtype="float32")
    cfg = TrainEngineConfig(
        experiment_name="t", trial_name="t", path=str(ckpt),
        dtype="float32", gradient_checkpointing=False,
        mesh=MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=1),
        optimizer=OptimizerConfig(lr=1e-2, warmup_steps_proportion=0.0,
                                  weight_decay=0.0),
        pack_length_quantum=16, max_pack_length=4096,
    )
    eng = JaxTrainEngine(cfg, model_config=None)
    eng.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    assert eng.config.max_pack_length == 32
    eng.destroy()
