"""Native (C++) data-plane kernels with compile-on-first-use loading.

The shared library is built from csrc/dataplane.cpp with g++ on first
import and cached under AREAL_NATIVE_CACHE (default: alongside the source,
keyed by a source hash, so editing the .cpp rebuilds).  Every binding has a
pure-Python fallback — `available()` reports which path is active, and the
parity tests assert both agree (tests/test_native.py).
"""

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

from areal_tpu.utils import logging

logger = logging.getLogger("native")

_SRC = os.path.join(os.path.dirname(__file__), "csrc", "dataplane.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_dir() -> str:
    d = os.environ.get("AREAL_NATIVE_CACHE") or os.path.join(
        os.path.dirname(__file__), "_build"
    )
    os.makedirs(d, exist_ok=True)
    return d


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            with open(_SRC, "rb") as f:
                tag = hashlib.sha256(f.read()).hexdigest()[:16]
            so = os.path.join(_build_dir(), f"dataplane-{tag}.so")
            if not os.path.exists(so):
                tmp = f"{so}.tmp.{os.getpid()}"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     _SRC, "-o", tmp],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, so)  # atomic vs concurrent builders
            lib = ctypes.CDLL(so)
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
            lib.ffd_assign.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i32p]
            lib.ffd_assign.restype = ctypes.c_int64
            lib.lpt_assign.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i32p]
            lib.lpt_assign.restype = None
            _LIB = lib
            logger.info(f"native dataplane loaded ({so})")
        except Exception as e:  # noqa: BLE001 — fall back to Python
            logger.warning(f"native dataplane unavailable ({e}); using Python")
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


def ffd_assign(sizes: Sequence[int], capacity: int) -> Optional[np.ndarray]:
    """bin_of[i] for first-fit-decreasing packing, or None when the native
    library is unavailable (callers fall back to the Python path)."""
    lib = _load()
    if lib is None:
        return None
    s = np.ascontiguousarray(sizes, dtype=np.int64)
    out = np.empty(len(s), dtype=np.int32)
    lib.ffd_assign(s, len(s), int(capacity), out)
    return out


def lpt_assign(sizes: Sequence[int], k: int) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    s = np.ascontiguousarray(sizes, dtype=np.int64)
    out = np.empty(len(s), dtype=np.int32)
    lib.lpt_assign(s, len(s), int(k), out)
    return out
