"""Populate evaluation/data/ with the public math benchmark sets.

The offline eval harness (areal_tpu/evaluation/run_eval.py --benchmark)
reads `<data-root>/<bench>/test.jsonl` rows shaped like the reference's
evaluation/data/ files ({"problem": ..., "answer": ...}).  This script
builds that layout from the public HF dataset hub (needs egress; in
air-gapped environments point AREAL_EVAL_DATA at an existing checkout of
the reference's evaluation/data/ instead).

    python scripts/fetch_eval_data.py [--root evaluation/data] \
        [--benchmarks aime24,aime25,amc23,math_500]
"""

import argparse
import json
import os

# benchmark -> (hub dataset id, split, question key, answer key)
SOURCES = {
    "aime24": ("HuggingFaceH4/aime_2024", "train", "problem", "answer"),
    "aime25": ("math-ai/aime25", "test", "problem", "answer"),
    "amc23": ("math-ai/amc23", "test", "question", "answer"),
    "math_500": ("HuggingFaceH4/MATH-500", "test", "problem", "answer"),
}


def fetch(root: str, benchmarks):
    from datasets import load_dataset  # requires egress

    for name in benchmarks:
        if name == "gpqa_diamond":
            print(
                "gpqa_diamond: the GPQA dataset is gated (Idavidrein/gpqa "
                "license click-through) and cannot be fetched here; accept "
                "the license on the HF hub and export rows as "
                f"{os.path.join(root, 'gpqa_diamond', 'test.jsonl')} with "
                "fields ori_question (options NOT embedded) / "
                "labeled_options / answer — a plain 'question' field also "
                "works, options are appended only when missing — or point "
                "AREAL_EVAL_DATA at an existing benchmark-data checkout."
            )
            continue
        if name not in SOURCES:
            print(f"skipping unknown benchmark {name!r}")
            continue
        repo, split, qk, ak = SOURCES[name]
        print(f"fetching {name} from {repo}:{split} ...")
        ds = load_dataset(repo, split=split)
        out_dir = os.path.join(root, name)
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, "test.jsonl")
        with open(path, "w") as f:
            for i, row in enumerate(ds):
                f.write(json.dumps({
                    "id": i,
                    "problem": row[qk],
                    "answer": str(row[ak]),
                }) + "\n")
        print(f"  wrote {len(ds)} problems to {path}")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    default_root = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "evaluation", "data",
    )
    p.add_argument("--root", default=default_root)
    p.add_argument("--benchmarks", default=",".join(SOURCES))
    args = p.parse_args()
    fetch(args.root, [b.strip() for b in args.benchmarks.split(",")])


if __name__ == "__main__":
    main()
