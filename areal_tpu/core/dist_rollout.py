"""DP-head rollout coordination for multi-process training.

Behavioral counterpart of the reference's `DistRolloutCoordinator`
(areal/core/dist_rollout.py:93): in a multi-process run only the data-parallel
head talks to the inference servers (one client, one staleness gate — N
clients would each admit max_concurrent_rollouts and overshoot the global
staleness budget); every other process receives the assembled batch and
contributes its shard of the global device batch.

TPU-first differences from the reference:
- The reference redistributes *slices* to each dp rank over NCCL
  (dist_rollout.py:99-146 FFD split + broadcast to the tp/sp subgroup).
  Here the whole host batch is broadcast (parallel/distributed.py
  broadcast_pytree, two device collectives) and sharding happens when the
  engine builds the global jax.Array — GSPMD owns placement, so host-side
  slicing plans are unnecessary; each process materialises only the shards
  it owns.
- No process groups to pick: the broadcast rides the same global runtime
  the train step uses.
"""
# areal-lint: disable=dead-module multi-process subsystem consumed by the tests/mp worker harness and user multi-process train scripts; no in-tree daemon imports it yet (multi-host workstream)

from typing import Any, Callable, Dict, List, Optional

from areal_tpu.parallel import distributed
from areal_tpu.utils import logging

logger = logging.getLogger("dist_rollout")


class DistRolloutCoordinator:
    """Wraps an InferenceEngine-like rollout client so that only the head
    process drives it; results are broadcast to every process."""

    def __init__(self, rollout_engine):
        self.rollout = rollout_engine

    @property
    def is_head(self) -> bool:
        return distributed.is_head()

    def rollout_batch(
        self,
        data: List[Dict[str, Any]],
        workflow=None,
        workflow_builder: Optional[Callable] = None,
        should_accept: Optional[Callable] = None,
    ) -> Dict[str, Any]:
        batch = None
        if self.is_head:
            batch = self.rollout.rollout_batch(
                data,
                workflow=workflow,
                workflow_builder=workflow_builder,
                should_accept=should_accept,
            )
        return distributed.broadcast_pytree(batch)

    def prepare_batch(
        self,
        dataloader,
        workflow=None,
        workflow_builder: Optional[Callable] = None,
        should_accept: Optional[Callable] = None,
    ) -> Dict[str, Any]:
        batch = None
        if self.is_head:
            batch = self.rollout.prepare_batch(
                dataloader,
                workflow=workflow,
                workflow_builder=workflow_builder,
                should_accept=should_accept,
            )
        return distributed.broadcast_pytree(batch)

    def pause(self):
        if self.is_head:
            self.rollout.pause()

    def resume(self):
        if self.is_head:
            self.rollout.resume()
