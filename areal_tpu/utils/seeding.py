"""Deterministic seeding across python/numpy/jax (reference: areal/utils/seeding.py).

JAX is functional — the important artifact is the root `jax.random.key` derived
here; stateful numpy/python seeding only covers host-side shuffling code.
"""

import hashlib
import random
from typing import Optional

import numpy as np

_BASE_SEED: Optional[int] = None
_EXPR_NAME = ""
_TRIAL_NAME = ""


def _fold(seed: int, *keys: str) -> int:
    h = hashlib.blake2b(digest_size=8)
    h.update(str(seed).encode())
    for k in keys:
        h.update(b"\x00" + k.encode())
    return int.from_bytes(h.digest(), "little") % (2**31 - 1)


def set_random_seed(base_seed: int, key: str = "") -> int:
    """Seed python & numpy with a value derived from (base_seed, key).

    Different `key`s (e.g. worker identities) get decorrelated streams from the
    same base seed, mirroring the reference's per-worker seeding.
    """
    global _BASE_SEED
    _BASE_SEED = base_seed
    seed = _fold(base_seed, key)
    random.seed(seed)
    np.random.seed(seed % (2**32 - 1))
    return seed


def get_seed() -> int:
    if _BASE_SEED is None:
        raise RuntimeError("set_random_seed() has not been called")
    return _BASE_SEED


def jax_root_key(key: str = ""):
    """Root jax PRNG key for a named stream, derived from the base seed."""
    import jax

    return jax.random.key(_fold(get_seed(), "jax", key))
