"""CLEVR-count GRPO — vision-language RL on the VLM engine.

Behavioral counterpart of the reference's
`examples/vlm/clevr_count_70k_grpo.py`: a Qwen2-VL-class model learns to
count objects in CLEVR scenes with a binary counting reward.  Same loop
shape as examples/math/gsm8k_grpo.py, with the VLM swaps:

- dataset type "clevr" (areal_tpu/dataset/clevr.py) — jsonl manifest with
  image paths + counting questions (offline-friendly);
- VisionRLVRWorkflow: AutoProcessor patchifies images, pixels ride to the
  native VLM server (gen/server.py pixel wire fields) and back into the
  train batch with mrope positions;
- JaxVLMPPOActor: vision tower + mrope decoder training, patch-span-aware
  minibatching and dynamic sampling (engine/vlm_engine.py).

Launch:  python examples/vlm/clevr_grpo.py --config examples/vlm/clevr_grpo.yaml
(or via the launcher, which also starts a generation server:
 python -m areal_tpu.launcher.local examples/vlm/clevr_grpo.py --config ...)
"""

import copy
import os
import sys

import numpy as np

from areal_tpu.api.config import GRPOConfig, load_expr_config
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo, WeightUpdateMeta
from areal_tpu.dataset import get_custom_dataset
from areal_tpu.dataset.clevr import clevr_count_reward
from areal_tpu.engine.jax_remote import RemoteJaxEngine
from areal_tpu.engine.vlm_engine import JaxVLMPPOActor
from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.utils import logging, seeding, stats
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.utils.evaluator import Evaluator
from areal_tpu.utils.recover import RecoverHandler, check_if_recover
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.stats_logger import StatsLogger
from areal_tpu.workflow.vision_rlvr import VisionRLVRWorkflow

logger = logging.getLogger("clevr_grpo")


def main(argv):
    config, _ = load_expr_config(argv, GRPOConfig)
    seeding.set_random_seed(config.seed, "trainer")

    tokenizer = processor = None
    if config.tokenizer_path:
        from transformers import AutoProcessor, AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(config.tokenizer_path)
        try:
            processor = AutoProcessor.from_pretrained(config.tokenizer_path)
        except Exception:  # noqa: BLE001 — pre-tokenized manifests need none
            logger.warning("no AutoProcessor at %s; expecting pre-tokenized "
                           "manifest rows", config.tokenizer_path)

    train_dataset = get_custom_dataset(
        path=config.train_dataset.path,
        type=config.train_dataset.type or "clevr",
        split="train",
        tokenizer=tokenizer,
        processor=processor,
        max_length=config.train_dataset.max_length,
    )
    dataloader = StatefulDataLoader(
        train_dataset,
        batch_size=config.train_dataset.batch_size,
        shuffle=config.train_dataset.shuffle,
        drop_last=config.train_dataset.drop_last,
        seed=config.seed,
    )
    ft_spec = FinetuneSpec(
        total_train_epochs=config.total_train_epochs,
        dataset_size=len(train_dataset),
        train_batch_size=config.train_dataset.batch_size,
    )

    rollout = RemoteJaxEngine(config.rollout)
    rollout.initialize(train_data_parallel_size=1)
    eval_rollout = RemoteJaxEngine(copy.deepcopy(config.rollout))
    eval_rollout.config.max_head_offpolicyness = int(1e12)
    eval_rollout.initialize(train_data_parallel_size=1)

    valid_dataset = get_custom_dataset(
        path=config.valid_dataset.path,
        type=config.valid_dataset.type or "clevr",
        split="test",
        tokenizer=tokenizer,
        processor=processor,
        max_length=config.valid_dataset.max_length,
    ) if config.valid_dataset is not None else None

    # the VLM actor needs the full (text + vision) model config up front
    model_config = TransformerConfig.from_hf(config.actor.path)
    if model_config.vision is None:
        raise ValueError(
            f"{config.actor.path} has no vision_config — clevr_grpo needs a "
            "Qwen2-VL-class checkpoint"
        )
    actor = JaxVLMPPOActor(config.actor, model_config=model_config)
    actor.create_process_group()
    actor.initialize(ft_spec=ft_spec)
    if config.warm_pack_shapes:
        # fail at startup rather than silently skipping the documented warm
        actor.warm_shapes([tuple(s) for s in config.warm_pack_shapes])

    if config.weight_update_mode == "transfer":
        weight_meta = WeightUpdateMeta.from_transfer(
            config.experiment_name, config.trial_name,
            live_commit=config.weight_update_live_commit,
        )
    else:
        weight_meta = WeightUpdateMeta.from_disk(
            config.experiment_name, config.trial_name, config.cluster.fileroot
        )

    from areal_tpu.api.reward import prewarm_reward_pool

    prewarm_reward_pool()
    spatial_merge = (
        model_config.vision.spatial_merge_size if model_config.vision else 2
    )
    workflow = VisionRLVRWorkflow(
        reward_fn=clevr_count_reward,
        gconfig=config.gconfig,
        tokenizer=tokenizer,
        processor=processor,
        image_token_id=model_config.image_token_id,
        spatial_merge_size=spatial_merge,
        dump_dir=os.path.join(
            StatsLogger.get_log_path(config.stats_logger), "generated"
        ),
    )
    eval_workflow = VisionRLVRWorkflow(
        reward_fn=clevr_count_reward,
        gconfig=config.gconfig.new(n_samples=1, temperature=0.0),
        tokenizer=tokenizer,
        processor=processor,
        image_token_id=model_config.image_token_id,
        spatial_merge_size=spatial_merge,
        rollout_stat_scope="eval-rollout",
        dump_dir=os.path.join(
            StatsLogger.get_log_path(config.stats_logger), "generated-eval"
        ),
    )

    saver = Saver(config.saver, ft_spec)
    checkpointer = Saver(config.checkpointer, ft_spec, for_recover=True)
    evaluator = Evaluator(config.evaluator, ft_spec)
    stats_logger = StatsLogger(config.stats_logger)
    recover = RecoverHandler(config.recover, ft_spec)

    start_step = 0
    if check_if_recover(config.recover, run_id=int(os.environ.get("AREAL_RUN_ID", 0))):
        info = recover.load(
            actor,
            saver=saver,
            evaluator=evaluator,
            stats_logger=stats_logger,
            dataloader=dataloader,
            inference_engine=rollout,
            weight_update_meta=weight_meta,
        )
        if info is not None:
            start_step = info.recover_start.global_step

    total_steps = config.total_train_steps or ft_spec.total_train_steps
    steps_per_epoch = ft_spec.steps_per_epoch

    def iter_or_cycle(dl):
        while True:
            yield from dl

    for global_step in range(start_step, total_steps):
        epoch = global_step // steps_per_epoch
        epoch_step = global_step % steps_per_epoch
        step_info = StepInfo(
            epoch=epoch, epoch_step=epoch_step, global_step=global_step,
            steps_per_epoch=steps_per_epoch,
        )

        with stats.record_timing("rollout"):
            if config.async_training:
                batch = rollout.prepare_batch(dataloader, workflow=workflow)
            else:
                batch = rollout.rollout_batch(
                    next(iter_or_cycle(dataloader)), workflow=workflow
                )

        if config.actor.recompute_logprob:
            with stats.record_timing("recompute_logp"):
                batch["prox_logp"] = actor.compute_logp(batch)

        with stats.record_timing("compute_advantages"):
            actor.compute_advantages(batch)

        with stats.record_timing("ppo_update"):
            train_stats = actor.ppo_update(batch)
            actor.step_lr_scheduler()

        # the expensive half (snapshot write / chunk streaming) runs while
        # generation continues; only the swap needs the pause — timed
        # separately so the pause-window cost stays visible in the stats
        with stats.record_timing("stage_weights"):
            actor.set_version(global_step + 1)
            actor.stage_weights(weight_meta)
        with stats.record_timing("update_weights"):
            # a live transfer commit swaps without aborting — the server
            # keeps decoding through the publish, so the client pipeline
            # need not pause; only the abort choreography drains in-flight
            live = (weight_meta.type == "transfer"
                    and weight_meta.live_commit)
            if not live:
                rollout.pause()
            actor.update_weights(weight_meta)
            rollout.update_weights(weight_meta)
            rollout.set_version(global_step + 1)
            eval_rollout.set_version(global_step + 1)
            if not live:
                rollout.resume()

        with stats.record_timing("save_eval"):
            saver.save(actor, epoch, epoch_step, global_step, tokenizer=tokenizer)
            if checkpointer.freq.check(epoch, global_step):
                recover.dump(
                    actor, step_info, saver=saver, evaluator=evaluator,
                    stats_logger=stats_logger, dataloader=dataloader,
                    tokenizer=tokenizer,
                )

        with stats.record_timing("eval"):
            def evaluate_fn():
                if valid_dataset is None:
                    return None
                eval_batch = eval_rollout.rollout_batch(
                    list(valid_dataset), workflow=eval_workflow
                )
                rew = np.asarray(eval_batch["rewards"], np.float32)
                result = {"eval_reward_mean": float(rew.mean()),
                          "eval_n": int(rew.size)}
                stats.scalar(**result)
                return result

            evaluator.evaluate(evaluate_fn, epoch, epoch_step, global_step)

        actor.flush_stats()
        reward_mean = float(np.mean(batch["rewards"])) if "rewards" in batch else 0.0
        stats.scalar(reward=reward_mean, n_seqs=len(batch.get("rewards", [])))
        stats_logger.commit(
            epoch, epoch_step, global_step,
            [stats.export()] + train_stats,
        )
        logger.info(
            f"Epoch {epoch + 1}/{config.total_train_epochs} "
            f"Step {epoch_step + 1}/{steps_per_epoch} "
            f"(global {global_step + 1}/{total_steps}) done."
        )

    stats_logger.close()
    eval_rollout.destroy()
    rollout.destroy()
    actor.destroy()


if __name__ == "__main__":
    main(sys.argv[1:])
