"""DAPO / Dr.GRPO / LitePPO recipe entry points (reference:
examples/experimental/{dapo,dr.grpo,lite_ppo}/gsm8k_*.py).

The variants are pure configuration over the shared GRPO loop, so the
proof obligations are: each shipped yaml parses into the schema with the
recipe's knobs intact, each knob actually changes the math where the
recipe says it should, and the entry point runs the real loop end-to-end.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from areal_tpu.api.config import GRPOConfig, NormConfig, load_expr_config
from tests.fixtures import make_gsm8k_jsonl, make_tiny_ckpt
from tests.test_algo_engines import _actor, _rollout_batch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VARIANTS = {
    "dapo": "examples/experimental/dapo/gsm8k_dapo",
    "dr_grpo": "examples/experimental/dr_grpo/gsm8k_drgrpo",
    "lite_ppo": "examples/experimental/lite_ppo/gsm8k_liteppo",
}


def _load(variant):
    cfg, _ = load_expr_config(
        ["--config", os.path.join(REPO, VARIANTS[variant] + ".yaml")],
        GRPOConfig,
    )
    return cfg


def test_dapo_yaml_carries_the_recipe():
    cfg = _load("dapo")
    a = cfg.actor
    assert a.eps_clip == 0.2 and a.eps_clip_higher == 0.28
    assert a.overlong_reward_penalty and a.overlong_tokens == 512
    assert a.overlong_penalty_factor == 1.0
    assert a.max_new_tokens == cfg.gconfig.max_new_tokens  # penalty budget
    assert a.dynamic_sampling
    assert a.reward_norm.mean_level == "group"
    assert a.reward_norm.std_level == "group"
    assert a.kl_ctl == 0.0 and a.use_decoupled_loss


def test_drgrpo_yaml_drops_std_division():
    cfg = _load("dr_grpo")
    a = cfg.actor
    assert a.eps_clip == 0.4 and a.eps_clip_higher is None
    assert a.reward_norm.mean_level == "group"
    assert a.reward_norm.std_level is None  # the Dr. fix
    assert not a.dynamic_sampling and not a.overlong_reward_penalty


def test_liteppo_yaml_group_mean_batch_std():
    cfg = _load("lite_ppo")
    a = cfg.actor
    assert a.eps_clip == 0.4
    assert a.reward_norm.mean_level == "group"
    assert a.reward_norm.std_level == "batch"
    assert a.adv_norm.mean_level == "batch" and a.adv_norm.std_level == "batch"


def test_asymmetric_clip_changes_loss_where_expected():
    """DAPO clip-higher: a positive-advantage token whose ratio lands
    between 1+eps_clip and 1+eps_clip_higher is clipped by the symmetric
    rule but NOT by the asymmetric one; below 1-eps_clip both clip alike."""
    import jax.numpy as jnp

    from areal_tpu.ops.functional import ppo_actor_loss_fn

    old = jnp.zeros((1, 3))
    # ratios: 1.25 (inside the widened band), 0.7 (below), 1.5 (above both)
    new = jnp.log(jnp.array([[1.25, 0.7, 1.5]]))
    adv = jnp.array([[1.0, 1.0, 1.0]])
    mask = jnp.ones((1, 3))

    sym, _ = ppo_actor_loss_fn(new, old, adv, 0.2, mask)
    asym, stats = ppo_actor_loss_fn(
        new, old, adv, 0.2, mask, eps_clip_higher=0.28
    )
    # token 1: sym clips to 1.2, asym keeps 1.25 -> loss more negative
    assert float(asym) < float(sym)
    expected_sym = -(1.2 + 0.7 + 1.2)
    expected_asym = -(1.25 + 0.7 + 1.28)
    np.testing.assert_allclose(float(sym), expected_sym, rtol=1e-6)
    np.testing.assert_allclose(float(asym), expected_asym, rtol=1e-6)
    # negative advantages: the lower clip still applies identically
    sym_n, _ = ppo_actor_loss_fn(new, old, -adv, 0.2, mask)
    asym_n, _ = ppo_actor_loss_fn(
        new, old, -adv, 0.2, mask, eps_clip_higher=0.28
    )
    np.testing.assert_allclose(float(sym_n), float(asym_n), rtol=1e-6)


def _advantages_with(reward_norm, rewards):
    rng = np.random.default_rng(5)
    actor = _actor(adv_norm=None, reward_norm=reward_norm)
    batch = _rollout_batch(rng)
    batch["rewards"] = np.asarray(rewards, np.float32)
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    mask = batch["loss_mask"]
    # gamma=lam=1, values=0: every completion token carries the shaped
    # seq reward, so one scalar per sequence characterises the shaping
    return np.array([
        batch["advantages"][b][mask[b] > 0][0] for b in range(len(rewards))
    ])


def test_drgrpo_reward_shaping_keeps_group_scale():
    """Two groups with identical mean but different spread: GRPO's group
    std divides the spread away (both groups end up ±1); Dr.GRPO's
    std_level=null preserves the raw scale difference."""
    rewards = [2.0, 0.0, 2.0, 0.0, 1.25, 0.75, 1.25, 0.75]
    grpo = _advantages_with(
        NormConfig(mean_level="group", std_level="group"), rewards
    )
    dr = _advantages_with(
        NormConfig(mean_level="group", std_level=None), rewards
    )
    # GRPO: both groups normalised to the same magnitude
    np.testing.assert_allclose(np.abs(grpo), np.abs(grpo)[0], rtol=1e-3)
    # Dr.GRPO: centered only - the wide group keeps 4x the magnitude
    np.testing.assert_allclose(dr[:4], [1.0, -1.0, 1.0, -1.0], atol=1e-5)
    np.testing.assert_allclose(dr[4:], [0.25, -0.25, 0.25, -0.25], atol=1e-5)


def test_liteppo_reward_shaping_divides_by_batch_std():
    """LitePPO: (r - group_mean) / batch_std — group-centered like GRPO
    but one shared std across the batch."""
    rewards = [2.0, 0.0, 2.0, 0.0, 1.25, 0.75, 1.25, 0.75]
    lite = _advantages_with(
        NormConfig(mean_level="group", std_level="batch"), rewards
    )
    centered = np.array([1.0, -1.0, 1.0, -1.0, 0.25, -0.25, 0.25, -0.25])
    batch_std = np.std(rewards)
    np.testing.assert_allclose(lite, centered / (batch_std + 1e-5), rtol=1e-4)


@pytest.mark.slow
def test_dapo_entrypoint_end_to_end(tmp_path):
    """The shipped dapo yaml + entry script run the real loop under the
    local launcher (tiny ckpt, dot-list overrides for sizes/paths only —
    every recipe knob comes from the shipped yaml)."""
    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    data = make_gsm8k_jsonl(str(tmp_path / "train.jsonl"), n=16)
    fileroot = tmp_path / "exp"
    overrides = [
        f"tokenizer_path={ckpt}",
        f"cluster.fileroot={fileroot}",
        f"train_dataset.path={data}",
        "train_dataset.batch_size=4",
        "train_dataset.max_length=128",
        "total_train_steps=2",
        "gconfig.n_samples=2",
        "gconfig.max_new_tokens=16",
        f"gen_server.model_path={ckpt}",
        "gen_server.max_seqs=4",
        "gen_server.max_context_len=256",
        f"actor.path={ckpt}",
        "actor.dtype=float32",
        "actor.gradient_checkpointing=false",
        "actor.group_size=2",
        "actor.max_new_tokens=16",
        "actor.overlong_tokens=8",
        "actor.pack_length_quantum=64",
        "actor.max_pack_length=256",
        "actor.optimizer.lr=1e-4",
        "rollout.max_concurrent_rollouts=8",
        "rollout.consumer_batch_size=4",
        "rollout.request_timeout=120",
        "saver.freq_steps=null",
        "checkpointer.freq_steps=null",
        f"stats_logger.fileroot={fileroot}",
    ]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "areal_tpu.launcher.local",
         os.path.join(REPO, VARIANTS["dapo"] + ".py"),
         "--config", os.path.join(REPO, VARIANTS["dapo"] + ".yaml"),
         *overrides],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        out, _ = proc.communicate(timeout=540)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail(f"launcher timed out.\n{out[-4000:]}")
    log_dir = fileroot / "gsm8k-dapo" / "trial0" / "logs"
    trainer_log = ""
    if log_dir.exists():
        for f in sorted(log_dir.iterdir()):
            if f.name.startswith("trainer"):
                trainer_log += f.read_text()
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\n{out[-2000:]}\n{trainer_log[-4000:]}"
    )
    assert "Step 1/" in trainer_log and "done." in trainer_log, (
        trainer_log[-4000:]
    )
