"""Canonical name_resolve key layout (reference: areal/utils/names.py)."""

ROOT = "areal_tpu"


def _join(*parts: str) -> str:
    return "/".join([ROOT, *[p for p in parts if p]])


def trial_root(experiment_name: str, trial_name: str) -> str:
    return _join(experiment_name, trial_name)


def gen_servers(experiment_name: str, trial_name: str) -> str:
    return _join(experiment_name, trial_name, "gen_servers")


def gen_server(experiment_name: str, trial_name: str, server_idx: str) -> str:
    return _join(experiment_name, trial_name, "gen_servers", str(server_idx))


def update_weights_from_disk(
    experiment_name: str, trial_name: str, model_version: int
) -> str:
    return _join(
        experiment_name, trial_name, "update_weights_from_disk", str(model_version)
    )


def weight_version(experiment_name: str, trial_name: str) -> str:
    return _join(experiment_name, trial_name, "weight_version")


def trainer_master(experiment_name: str, trial_name: str) -> str:
    return _join(experiment_name, trial_name, "trainer_master")


def distributed_lock(experiment_name: str, trial_name: str, lock_name: str) -> str:
    return _join(experiment_name, trial_name, "locks", lock_name)


def worker(experiment_name: str, trial_name: str, worker_type: str, idx) -> str:
    return _join(experiment_name, trial_name, "workers", worker_type, str(idx))


def worker_root(experiment_name: str, trial_name: str, worker_type: str) -> str:
    return _join(experiment_name, trial_name, "workers", worker_type)


def experiment_status(experiment_name: str, trial_name: str) -> str:
    return _join(experiment_name, trial_name, "status")


def gen_router(experiment_name: str, trial_name: str) -> str:
    return _join(experiment_name, trial_name, "gen_router")
