"""C1 — lock discipline: guarded fields only touched under their lock.

The static race detector for the concurrent state machines in
`gen/engine.py`, `gen/server.py`, `gen/router.py`, `core/remote.py`, and
`core/runner.py` (scanned repo-wide: it activates on any class that
declares guarded fields).  The recurring failure class it encodes:
ADVICE r5 found `_holdback` mutated outside `self._lock` by hand; this
checker finds the next one mechanically.

A field is declared lock-protected either way:

    class Engine:
        _GUARDED_FIELDS = {"_holdback": "_lock", "_abort_gen": "_lock"}

or, next to the attribute's ``__init__`` assignment:

    self._holdback = []  # guarded-by: _lock

Every read/write of a guarded field (``self.<field>`` anywhere in the
class) must then sit lexically inside ``with self.<lock>:`` /
``async with self.<lock>:``, or in a method annotated ``# holds: <lock>``
(a documented only-called-with-lock-held contract — the annotation is what
the runtime assertion mode validates, see lockcheck.py).  ``__init__`` is
exempt: no other thread can hold a reference yet.

Accesses inside nested ``def``/``lambda`` bodies are NOT covered by an
enclosing ``with`` — a closure may run after the lock is released — so
they must carry their own ``# holds:`` annotation or take the lock.
"""

import ast
from typing import Dict, List, Optional, Set

from areal_tpu.analysis.core import Finding, SourceFile, apply_suppression

RULE = "unlocked-field"


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _parse_registry(node: ast.AST) -> Optional[Dict[str, str]]:
    """`_GUARDED_FIELDS = {...}` literal -> {field: lock}; None on a shape
    the checker cannot statically evaluate."""
    if isinstance(node, ast.Dict):
        out: Dict[str, str] = {}
        for k, v in zip(node.keys, node.values):
            ks, vs = _literal_str(k), _literal_str(v)
            if ks is None or vs is None:
                return None
            out[ks] = vs
        return out
    if isinstance(node, (ast.Set, ast.List, ast.Tuple)):
        out = {}
        for el in node.elts:
            s = _literal_str(el)
            if s is None:
                return None
            out[s] = "_lock"
        return out
    if isinstance(node, ast.Call):  # frozenset({...}) / dict(...)
        if node.args and not node.keywords:
            return _parse_registry(node.args[0])
    return None


def _guarded_fields(
    sf: SourceFile, cls: ast.ClassDef, findings: List[Finding]
) -> Dict[str, str]:
    guarded: Dict[str, str] = {}
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "_GUARDED_FIELDS":
                    reg = _parse_registry(stmt.value)
                    if reg is None:
                        findings.append(
                            apply_suppression(
                                sf,
                                Finding(
                                    "guard-syntax",
                                    sf.rel,
                                    stmt.lineno,
                                    "_GUARDED_FIELDS must be a literal dict "
                                    "{field: lock} or a literal set/list of "
                                    "field names",
                                ),
                            )
                        )
                    else:
                        guarded.update(reg)
    init = next(
        (
            s
            for s in cls.body
            if isinstance(s, ast.FunctionDef) and s.name == "__init__"
        ),
        None,
    )
    if init is not None:
        for node in ast.walk(init):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        lock = sf.guarded_by(node.lineno)
                        if lock:
                            guarded[tgt.attr] = lock
    return guarded


def _holds_of(sf: SourceFile, fn: ast.AST) -> Set[str]:
    """`# holds: <lock>` annotations attached to a def: on the def line,
    the line above it, or any decorator line."""
    start = fn.lineno
    if getattr(fn, "decorator_list", None):
        start = min(d.lineno for d in fn.decorator_list)
    return set(sf.holds_between(start - 1, fn.body[0].lineno - 1))


class _MethodChecker(ast.NodeVisitor):
    def __init__(
        self,
        sf: SourceFile,
        cls_name: str,
        guarded: Dict[str, str],
        held: Set[str],
        findings: List[Finding],
    ):
        self.sf = sf
        self.cls_name = cls_name
        self.guarded = guarded
        self.held = set(held)
        self.findings = findings

    def _lock_names(self, with_node: ast.AST) -> List[str]:
        out = []
        for item in with_node.items:
            e = item.context_expr
            if (
                isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self"
            ):
                out.append(e.attr)
        return out

    def visit_With(self, node: ast.With):
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith):
        self._visit_with(node)

    def _visit_with(self, node):
        added = [n for n in self._lock_names(node) if n not in self.held]
        self.held.update(added)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        self.held.difference_update(added)

    def _visit_nested(self, node):
        # a nested def's body runs whenever the closure is invoked — the
        # enclosing with-block guarantees nothing at that point
        inner = _MethodChecker(
            self.sf,
            self.cls_name,
            self.guarded,
            _holds_of(self.sf, node),
            self.findings,
        )
        for stmt in node.body:
            inner.visit(stmt)
        for d in getattr(node, "decorator_list", []):
            self.visit(d)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._visit_nested(node)

    def visit_Lambda(self, node: ast.Lambda):
        inner = _MethodChecker(
            self.sf, self.cls_name, self.guarded, set(), self.findings
        )
        inner.visit(node.body)

    def visit_Attribute(self, node: ast.Attribute):
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.guarded
        ):
            lock = self.guarded[node.attr]
            if lock not in self.held:
                mode = (
                    "written" if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read"
                )
                self.findings.append(
                    apply_suppression(
                        self.sf,
                        Finding(
                            RULE,
                            self.sf.rel,
                            node.lineno,
                            f"{self.cls_name}.{node.attr} {mode} without "
                            f"holding self.{lock} (declare `with self."
                            f"{lock}:` around it, or mark the method "
                            f"`# holds: {lock}`)",
                        ),
                    )
                )
        self.generic_visit(node)


def check_lock_discipline(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    if sf.tree is None:
        return findings
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded = _guarded_fields(sf, cls, findings)
        if not guarded:
            continue
        for meth in cls.body:
            if not isinstance(
                meth, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if meth.name == "__init__":
                continue
            checker = _MethodChecker(
                sf, cls.name, guarded, _holds_of(sf, meth), findings
            )
            for stmt in meth.body:
                checker.visit(stmt)
    return findings
