"""Slurm launcher tests: sbatch rendering + submit/babysit/cancel against
stub slurm binaries (no Slurm in this environment — same approach as the
reference's sbatch-generation tests)."""

import os
import stat
import textwrap

import pytest

from areal_tpu.launcher.slurm import (
    SlurmJobSpec,
    SlurmLauncher,
    render_sbatch,
)


def test_render_sbatch_contents(tmp_path):
    spec = SlurmJobSpec(
        job_name="exp-train",
        cmd="python entry.py --config c.yaml",
        n_tasks=4,
        cpus_per_task=8,
        mem_per_task_mb=65536,
        gres="tpu:4",
        partition="tpu-pod",
        time_limit="12:00:00",
        env={"AREAL_NUM_PROCESSES": "4", "AREAL_NAME_RESOLVE": "nfs:/shared/nr"},
        log_path="/logs/train_%j.log",
    )
    script = render_sbatch(spec)
    for expected in [
        "#SBATCH --job-name=exp-train",
        "#SBATCH --ntasks=4",
        "#SBATCH --gres=tpu:4",
        "#SBATCH --partition=tpu-pod",
        "#SBATCH --time=12:00:00",
        "#SBATCH --mem-per-cpu=8192M",
        "export AREAL_NUM_PROCESSES=4",
        "export AREAL_PROCESS_ID=$SLURM_PROCID",
        "srun --kill-on-bad-exit=1",
    ]:
        assert expected in script, f"missing {expected!r}\n{script}"
    # container wrapping
    spec.container = "/images/areal.sif"
    assert "apptainer exec" in render_sbatch(spec)


@pytest.fixture()
def stub_slurm(tmp_path):
    """Fake sbatch/squeue/scancel: sbatch records the script and prints an
    id; squeue reads a state file the test controls; scancel records."""
    state = tmp_path / "state"
    state.write_text("RUNNING")
    sbatch = tmp_path / "sbatch"
    sbatch.write_text(
        textwrap.dedent(
            f"""\
            #!/bin/bash
            echo "$@" >> {tmp_path}/sbatch.calls
            cp "${{@: -1}}" {tmp_path}/submitted_$(basename "${{@: -1}}")
            echo "$((1000 + $(wc -l < {tmp_path}/sbatch.calls)))"
            """
        )
    )
    squeue = tmp_path / "squeue"
    squeue.write_text(
        textwrap.dedent(
            f"""\
            #!/bin/bash
            cat {state}
            """
        )
    )
    scancel = tmp_path / "scancel"
    scancel.write_text(
        f"#!/bin/bash\necho \"$@\" >> {tmp_path}/scancel.calls\n"
    )
    # sacct consulted when squeue no longer lists the job
    acct_state = tmp_path / "acct_state"
    acct_state.write_text("COMPLETED")
    sacct = tmp_path / "sacct"
    sacct.write_text(f"#!/bin/bash\ncat {acct_state}\n")
    for p in (sbatch, squeue, scancel, sacct):
        p.chmod(p.stat().st_mode | stat.S_IEXEC)
    return tmp_path, state


def _launcher(stub_dir, tmp_path, n_gen=0, n_train=1):
    return SlurmLauncher(
        "entry.py",
        ["--config", _write_cfg(tmp_path)],
        n_gen_servers=n_gen,
        n_train_procs=n_train,
        sbatch_bin=str(stub_dir / "sbatch"),
        squeue_bin=str(stub_dir / "squeue"),
        scancel_bin=str(stub_dir / "scancel"),
        sacct_bin=str(stub_dir / "sacct"),
    )


def _write_cfg(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        textwrap.dedent(
            f"""\
            experiment_name: slurmtest
            trial_name: t0
            cluster:
              fileroot: {tmp_path}/runs
              name_resolve:
                type: nfs
                nfs_record_root: {tmp_path}/nr
            """
        )
    )
    return str(cfg)


def test_submit_babysit_cancel(stub_slurm, tmp_path):
    stub_dir, state = stub_slurm
    launcher = _launcher(stub_dir, tmp_path, n_gen=2, n_train=4)
    gen_id = launcher.submit(launcher.gen_server_spec())
    train_id = launcher.submit(launcher.trainer_spec())
    assert gen_id != train_id

    # both scripts hit sbatch and contain the wiring
    submitted = [f for f in os.listdir(stub_dir) if f.startswith("submitted_")]
    assert len(submitted) == 2
    train_script = (stub_dir / "submitted_slurmtest-train.sbatch").read_text()
    assert "AREAL_NUM_PROCESSES=4" in train_script
    assert "AREAL_COORDINATOR=" in train_script
    assert "AREAL_NAME_RESOLVE=" in train_script
    gen_script = (stub_dir / "submitted_slurmtest-gen.sbatch").read_text()
    assert "--server-idx $SLURM_PROCID" in gen_script
    assert "#SBATCH --ntasks=2" in gen_script

    assert launcher.job_state(train_id) == "RUNNING"
    state.write_text("COMPLETED")
    assert launcher.job_state(train_id) == "COMPLETED"

    launcher.cancel_all()
    calls = (stub_dir / "scancel.calls").read_text().splitlines()
    assert sorted(calls) == sorted([gen_id, train_id])


def test_run_returns_on_completion(stub_slurm, tmp_path):
    stub_dir, state = stub_slurm
    state.write_text("COMPLETED")
    assert _launcher(stub_dir, tmp_path).run(poll_interval=0.01) == 0

    state.write_text("FAILED")
    assert _launcher(stub_dir, tmp_path).run(poll_interval=0.01) == 1


def test_vanished_job_resolved_via_accounting(stub_slurm, tmp_path):
    """A job gone from squeue between polls is resolved through sacct — a
    FAILED job must not be reported as a successful run."""
    stub_dir, state = stub_slurm
    state.write_text("")  # squeue no longer lists the job
    (stub_dir / "acct_state").write_text("FAILED")
    assert _launcher(stub_dir, tmp_path).run(poll_interval=0.01) == 1

    (stub_dir / "acct_state").write_text("COMPLETED")
    assert _launcher(stub_dir, tmp_path).run(poll_interval=0.01) == 0


def test_requires_nfs_name_resolve(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("experiment_name: x\ntrial_name: y\n")
    with pytest.raises(ValueError, match="nfs"):
        SlurmLauncher("entry.py", ["--config", str(cfg)], 1, 1)
