"""C8 negative fixture: a producer/consumer pair in exact agreement with
the fixture registry (WIRE_DOC in test_lint.py).  Every request key the
client writes is read by the handler, every response key the handler
writes is declared, and the only `.get` with a default is either on an
optional key or computes its fallback (tolerant read)."""

from aiohttp import web


class PingServer:
    async def ping(self, request):
        body = await request.json()
        x = body["x"]
        opt = body.get("opt", str(x))  # tolerant: computed fallback
        return web.json_response({"y": x, "echo": opt})

    def make_app(self):
        app = web.Application()
        app.router.add_post("/ping", self.ping)
        return app


async def call_ping(session, addr):
    resp = await session.post(
        f"http://{addr}/ping", json={"x": 1, "opt": "o"}
    )
    data = await resp.json()
    return data["y"], data.get("echo", None)  # echo is optional
