"""JaxVLMEngine: vision-language training on the standard train engine.

Capability counterpart of the reference's VLM train path (lite loads
AutoModelForImageTextToText in BaseHFEngine and threads qwen2-VL mrope
position ids through packing, base_hf_engine.py:261-287).  TPU-first shape:

- the text stack, optimizer, sharding, checkpointing, and loss protocol are
  inherited unchanged from JaxTrainEngine; only `_call_model` changes — it
  runs the vision tower and scatters image embeddings before the decoder
  (models/vision.py forward_vlm_lm);
- batches stay PADDED (one sequence per row, original order) instead of
  FFD row-packed: image patches are matched to placeholder tokens by scan
  order, and repacking would permute sequences out from under their
  pixels.  Filler rows/patches pad the shapes up to shard divisibility, so
  everything remains static under jit.

Batch keys beyond the text ones:
  pixel_values     [N, patch_dim]  pre-patchified pixels, images in
                                   sequence order (AutoProcessor layout)
  patch_img_ids    [N]             image index per patch, -1 = padding
  mrope_positions  [B, L, 3]       optional per-token (t, h, w) positions
                                   (models/vision.py mrope_position_ids)
"""

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from areal_tpu.api.config import TrainEngineConfig
from areal_tpu.engine.jax_train import JaxTrainEngine
from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.models.vision import forward_vlm_lm, init_vision_params
from areal_tpu.utils.data import RowPackedBatch

VISION_KEYS = ("pixel_values", "patch_img_ids")


class JaxVLMEngine(JaxTrainEngine):
    def __init__(
        self,
        config: TrainEngineConfig,
        model_config: Optional[TransformerConfig] = None,
    ):
        if model_config is None or model_config.vision is None:
            raise ValueError("JaxVLMEngine needs a model_config with .vision")
        if model_config.image_token_id is None:
            raise ValueError("model_config.image_token_id is required")
        super().__init__(config, model_config)
        if max(1, config.mb_spec.n_mbs) != 1:
            raise NotImplementedError(
                "VLM engine v1 runs a single micro-batch per step (pixel "
                "tensors cannot be split across an mb scan); raise "
                "batch-level parallelism instead"
            )

    # ------------------------------------------------------------------

    def initialize(self, addr=None, ft_spec=None) -> None:
        super().initialize(addr=addr, ft_spec=ft_spec)
        if self.mesh.shape["sp"] != 1:
            raise NotImplementedError("VLM engine v1 requires sp=1")
        if "vision" not in self.params:
            # scratch init of the tower when the checkpoint is text-only
            import jax

            from areal_tpu.parallel import shard_pytree

            host = init_vision_params(
                self.model_config.vision,
                jax.random.PRNGKey(7),
                dtype=jnp.dtype(self.config.param_dtype),
            )
            # vision tower is small: replicate it across the mesh
            from jax.sharding import PartitionSpec as P

            specs = jax.tree_util.tree_map(lambda _: P(), host)
            self.params = dict(self.params)
            self.params["vision"] = shard_pytree(self.mesh, host, specs)
            # optimizer state was initialised from the text-only tree in
            # super().initialize(); rebuild so moments cover the tower
            if self._optimizer is not None:
                self._build_optimizer(ft_spec)

    # ------------------------------------------------------------------

    def _prepare_rows(
        self, batch: Dict[str, np.ndarray], n_mbs: int
    ) -> Tuple[RowPackedBatch, Dict[str, np.ndarray], int]:
        """Identity row-ification: sequence i -> row i (order preserved so
        patch order matches placeholder order), padded with filler rows and
        filler patches to shard divisibility."""
        mask = batch["attention_mask"].astype(bool)
        B, L = mask.shape
        mult = n_mbs * (
            self.mesh.shape["dp"]
            * self.mesh.shape["fsdp"]
            * self.mesh.shape.get("ep", 1)
        )
        R = ((B + mult - 1) // mult) * mult

        data: Dict[str, np.ndarray] = {}
        for k, v in batch.items():
            if k in VISION_KEYS or k == "attention_mask":
                continue
            if v.ndim >= 2 and v.shape[:2] == (B, L):
                buf = np.zeros((R, *v.shape[1:]), dtype=v.dtype)
                buf[:B] = v
                data[k] = buf
        seg = np.where(mask, 0, -1).astype(np.int32)
        data["segment_ids"] = np.full((R, L), -1, np.int32)
        data["segment_ids"][:B] = seg
        pos = np.maximum(mask.cumsum(-1) - 1, 0).astype(np.int32)
        data["positions"] = np.zeros((R, L), np.int32)
        data["positions"][:B] = pos
        data["input_ids"] = data["input_ids"].astype(np.int32)
        if "loss_mask" in data:
            data["loss_mask"] = data["loss_mask"] * (data["segment_ids"] >= 0)

        # vision: pad the patch dim to shard divisibility with -1-id patches
        # (their merged embeddings land past every real placeholder index)
        pv = batch["pixel_values"]
        ids = batch["patch_img_ids"]
        m2 = self.model_config.vision.spatial_merge_size ** 2
        quantum = mult * m2
        N = ((pv.shape[0] + quantum - 1) // quantum) * quantum
        pad_pv = np.zeros((N, pv.shape[1]), pv.dtype)
        pad_pv[: pv.shape[0]] = pv
        pad_ids = np.full((N,), -1, np.int32)
        pad_ids[: ids.shape[0]] = ids
        data["pixel_values"] = pad_pv
        data["patch_img_ids"] = pad_ids

        placements = [[(i, L)] for i in range(B)] + [[] for _ in range(R - B)]
        return (
            RowPackedBatch(data={}, placements=placements, row_len=L),
            data,
            L,
        )

    def _device_batch(self, data, stacked: bool):
        """Per-key sharding: token arrays use the standard batch spec;
        patch arrays shard the patch dim over the row axes (rank-1
        patch_img_ids cannot take the 2-axis token spec)."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from areal_tpu.parallel import batch_spec, distributed

        token_spec = batch_spec()
        row_axes = token_spec[0]
        specs = {}
        for k in data:
            s = P(row_axes) if k in VISION_KEYS else token_spec
            specs[k] = P(None, *s) if stacked else s
        if jax.process_count() > 1:
            return distributed.make_global_batch(self.mesh, specs, data)
        return {
            k: jax.device_put(v, NamedSharding(self.mesh, specs[k]))
            for k, v in data.items()
        }

    def _call_model(self, params, batch):
        mrope = batch.get("mrope_positions")
        if mrope is not None:
            mrope = jnp.moveaxis(mrope, -1, 0)  # [B, L, 3] -> [3, B, L]
        return forward_vlm_lm(
            params,
            self.model_config,
            batch["input_ids"],
            batch["positions"],
            batch["segment_ids"],
            batch["pixel_values"],
            batch["patch_img_ids"],
            mrope_positions=mrope,
            mesh=self.mesh,
        )


class VLMPPOActor:
    """GRPO actor for the VLM engine.

    Thin delegation instead of a PPOActor subclass: the generic minibatch
    split (select_rows over B) would slice pixel tensors — whose leading dim
    is patches, not sequences — so the update runs as ONE engine
    train_batch over the full batch (ppo_n_minibatches=1 enforced), with
    vision keys carried through intact.  Advantage/logp computation is
    inherited behavior via composition with the standard PPOActor.
    """

    def __init__(self, config, engine: JaxVLMEngine):
        from areal_tpu.engine.ppo.actor import PPOActor

        if config.ppo_n_minibatches != 1:
            raise NotImplementedError("VLM GRPO v1: set ppo_n_minibatches=1")
        if config.dynamic_sampling:
            raise NotImplementedError(
                "dynamic sampling reorders sequences away from their pixels"
            )
        self._ppo = PPOActor(config, engine)
        self.config = config
        self.engine = engine

    def compute_logp(self, batch):
        return self._ppo.compute_logp(batch)

    def compute_advantages(self, batch):
        self._ppo.compute_advantages(batch)

    def ppo_update(self, batch):
        keys = self._ppo.LOSS_KEYS + VISION_KEYS + ("mrope_positions",)
        view = {k: batch[k] for k in keys if k in batch}
        # loss construction, stat normalisation, and tracker commit are the
        # base actor's — one source, no drift
        return [self._ppo._train_one_mb(view)]


class JaxVLMPPOActor(JaxVLMEngine):
    """JaxVLMEngine + VLM GRPO surface (mirrors JaxPPOActor's wiring)."""

    def __init__(self, config, model_config=None):
        super().__init__(config, model_config)
        self.actor = VLMPPOActor(config, self)

    def compute_logp(self, batch):
        return self.actor.compute_logp(batch)

    def compute_advantages(self, batch):
        self.actor.compute_advantages(batch)

    def ppo_update(self, batch):
        return self.actor.ppo_update(batch)
