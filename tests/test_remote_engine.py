"""RemoteInfEngine + WorkflowExecutor against a fake HTTP generation server."""

import asyncio

import numpy as np
import pytest

from areal_tpu.api.config import GenerationHyperparameters, InferenceEngineConfig
from areal_tpu.api.io_struct import ModelRequest, WeightUpdateMeta
from areal_tpu.engine.jax_remote import RemoteJaxEngine
from areal_tpu.utils import name_resolve, names
from areal_tpu.workflow.rlvr import RLVRWorkflow

from tests.fake_server import FakeGenServer


@pytest.fixture
def server():
    s = FakeGenServer(completion=list(range(100, 110)), chunk_size=1024)
    addr = s.start()
    yield s, addr
    s.stop()


def _engine(addr, **cfg_kwargs) -> RemoteJaxEngine:
    cfg = InferenceEngineConfig(
        experiment_name="e", trial_name="t", consumer_batch_size=2,
        max_concurrent_rollouts=16, request_timeout=10, **cfg_kwargs,
    )
    eng = RemoteJaxEngine(cfg)
    eng.initialize(addr=addr)
    return eng


def _agen(eng, req):
    return asyncio.run(eng.agenerate(req))


def test_rid_affinity_survives_concurrent_first_lookup():
    """Regression (ISSUE 9 / C5 atomicity-split): _server_for_rid's
    lookup-miss -> choose -> insert sequence is one critical section.
    Split across lock releases, threads racing on the SAME rid could pin
    it to different servers and fracture its KV affinity."""
    import threading

    cfg = InferenceEngineConfig(
        experiment_name="e", trial_name="t", consumer_batch_size=2,
        max_concurrent_rollouts=16, request_timeout=10,
    )
    for attempt in range(20):
        eng = RemoteJaxEngine(cfg)
        eng.addresses = [f"10.0.0.{i}:80" for i in range(4)]
        n = 8
        barrier = threading.Barrier(n)
        got = [None] * n

        def probe(i):
            barrier.wait()
            # churn the round-robin counter AND resolve the shared group
            eng._server_for_rid(f"solo-{attempt}-{i}")
            got[i] = eng._server_for_rid("grp")

        threads = [
            threading.Thread(target=probe, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert len(set(got)) == 1, (
            f"rid 'grp' pinned to multiple servers: {set(got)}"
        )


def test_basic_generation(server):
    s, addr = server
    eng = _engine(addr)
    try:
        resp = _agen(eng, ModelRequest(
            input_ids=[1, 2, 3],
            gconfig=GenerationHyperparameters(max_new_tokens=32),
        ))
        assert resp.output_tokens == list(range(100, 110))
        assert resp.stop_reason == "stop"
        assert resp.output_versions == [0] * 10
        assert len(resp.output_logprobs) == 10
        assert resp.input_tokens == [1, 2, 3]
    finally:
        eng.destroy()


def test_length_cap(server):
    s, addr = server
    eng = _engine(addr)
    try:
        resp = _agen(eng, ModelRequest(
            input_ids=[1],
            gconfig=GenerationHyperparameters(max_new_tokens=4),
        ))
        assert resp.output_tokens == [100, 101, 102, 103]
        assert resp.stop_reason == "length"
    finally:
        eng.destroy()


def test_interruption_resumes_and_tracks_versions(server):
    """Mid-generation abort: client must resend accumulated tokens and tag
    later tokens with the new weight version (decoupled-PPO's raw signal)."""
    s, addr = server
    s.abort_once = True
    eng = _engine(addr)
    try:
        resp = _agen(eng, ModelRequest(
            input_ids=[7, 8],
            gconfig=GenerationHyperparameters(max_new_tokens=64),
        ))
        assert resp.output_tokens == list(range(100, 110))
        assert resp.stop_reason == "stop"
        # versions must switch from 0 to 1 mid-sequence
        assert resp.output_versions[0] == 0
        assert resp.output_versions[-1] == 1
        assert len(set(resp.output_versions)) == 2
        # at least two HTTP calls: the aborted chunk + the resumption
        assert len(s.requests) >= 2
        # the resumption request must carry the accumulated prompt
        assert s.requests[-1]["input_ids"][:2] == [7, 8]
        assert 100 in s.requests[-1]["input_ids"]
    finally:
        eng.destroy()


def test_chunked_generation(server):
    s, addr = server
    s.chunk_size = 3  # server yields 3 tokens per call ("abort" each chunk)
    eng = _engine(addr)
    try:
        resp = _agen(eng, ModelRequest(
            input_ids=[1],
            gconfig=GenerationHyperparameters(max_new_tokens=100),
        ))
        assert resp.output_tokens == list(range(100, 110))
        assert len(s.requests) == 4  # ceil(10/3)
    finally:
        eng.destroy()


def test_update_weights_and_version(server):
    s, addr = server
    eng = _engine(addr)
    try:
        meta = WeightUpdateMeta(type="disk", path="/tmp/fake_ckpt")
        eng.pause_generation()
        assert s.paused
        eng.update_weights(meta)
        eng.set_version(eng.get_version() + 1)
        eng.continue_generation()
        assert not s.paused
        assert s.weight_updates == [{"path": "/tmp/fake_ckpt"}]
        assert eng.get_version() == 1
        assert s.version == 1
    finally:
        eng.destroy()


def test_discovery_via_name_resolve(server):
    s, addr = server
    name_resolve.add(names.gen_server("e", "t", "0"), addr)
    cfg = InferenceEngineConfig(
        experiment_name="e", trial_name="t", consumer_batch_size=1,
        setup_timeout=5,
    )
    eng = RemoteJaxEngine(cfg)
    eng.initialize()  # no addr: discover
    try:
        assert eng.addresses == [addr]
    finally:
        eng.destroy()


def test_failover_resubmits_to_surviving_server():
    """ISSUE 11: kill one of two backends mid-chunked-generation.  The
    client must resubmit the accumulated tokens to the survivor (the same
    resume contract as interruption) and the trajectory completes — with a
    `resubmit` telemetry span joining the ORIGINAL trace_id, not a fresh
    submit."""
    import threading
    import time as _time

    from areal_tpu.utils import telemetry

    s0 = FakeGenServer(completion=list(range(100, 110)), chunk_size=3)
    s1 = FakeGenServer(completion=list(range(100, 110)), chunk_size=3)
    s0.delay_s = 0.05  # keep chunks in flight long enough to die mid-run
    addrs = [s0.start(), s1.start()]
    eng = _engine(addrs, request_retries=2)
    was = telemetry.is_enabled()
    telemetry.set_enabled(True)
    telemetry.EVENTS.clear()

    def _assassin():
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline and not s0.requests:
            _time.sleep(0.005)
        s0.stop()

    killer = threading.Thread(target=_assassin)
    killer.start()
    try:
        # round_robin places the first rid on s0
        resp = _agen(eng, ModelRequest(
            rid="victim", input_ids=[1, 2],
            gconfig=GenerationHyperparameters(max_new_tokens=64),
        ))
        killer.join(timeout=10)
        assert resp.output_tokens == list(range(100, 110))
        assert resp.stop_reason == "stop"
        # the survivor resumed from the accumulated prompt and finished
        assert s1.requests
        assert s1.requests[-1]["input_ids"][:2] == [1, 2]
        assert 100 in s1.requests[-1]["input_ids"]
        events = telemetry.EVENTS.snapshot()
        submit = next(e for e in events if e["event"] == "rollout_submit")
        resubmits = [e for e in events if e["event"] == "resubmit"]
        assert resubmits, "failover must emit a resubmit span"
        assert all(e["trace_id"] == submit["trace_id"] for e in resubmits)
        assert all(e["to_server"] == addrs[1] for e in resubmits)
    finally:
        telemetry.set_enabled(was)
        telemetry.EVENTS.clear()
        eng.destroy()
        s1.stop()


def test_trajectory_lost_after_failover_budget():
    """With every server dead and the failover budget exhausted, agenerate
    must raise TrajectoryLostError (the executor's expected fleet-failure
    outcome) rather than an opaque transport error."""
    from areal_tpu.core.executor import TrajectoryLostError

    s = FakeGenServer(completion=[100])
    addr = s.start()
    s.stop()  # dead before the first request: connection refused
    eng = _engine(addr, request_retries=1, failover_retries=2)
    try:
        with pytest.raises(TrajectoryLostError):
            _agen(eng, ModelRequest(
                rid="doomed", input_ids=[1],
                gconfig=GenerationHyperparameters(max_new_tokens=4),
            ))
    finally:
        eng.destroy()


def _reward_len(prompt, completion, prompt_ids, completion_ids, **kwargs):
    return float(len(completion_ids))


def test_rollout_batch_end_to_end(server):
    s, addr = server
    eng = _engine(addr)
    try:
        wf = RLVRWorkflow(
            reward_fn=_reward_len,
            gconfig=GenerationHyperparameters(max_new_tokens=16, n_samples=2),
        )
        batch = eng.rollout_batch(
            [{"input_ids": [1, 2]}, {"input_ids": [3, 4, 5]}], workflow=wf
        )
        # 2 prompts x 2 samples
        assert batch["input_ids"].shape[0] == 4
        assert batch["rewards"].tolist() == [10.0] * 4
        assert batch["attention_mask"].shape == batch["loss_mask"].shape
        # loss mask zero on prompt, one on completion
        lens = batch["attention_mask"].sum(-1)
        for i in range(4):
            n = int(lens[i])
            assert batch["loss_mask"][i, :n].sum() == 10
    finally:
        eng.destroy()


def test_prepare_batch_async(server):
    from areal_tpu.utils.dataloader import StatefulDataLoader

    s, addr = server
    eng = _engine(addr, max_head_offpolicyness=2)
    try:
        wf = RLVRWorkflow(
            reward_fn=_reward_len,
            gconfig=GenerationHyperparameters(max_new_tokens=8),
        )
        dl = StatefulDataLoader(
            [{"input_ids": [i]} for i in range(32)], batch_size=2
        )
        b1 = eng.prepare_batch(dl, workflow=wf)
        b2 = eng.prepare_batch(dl, workflow=wf)
        assert b1["input_ids"].shape[0] == 2
        assert b2["input_ids"].shape[0] == 2
        stats = eng.executor.staleness_manager.get_stats()
        # staleness gate must bound total submissions:
        # (eta + version + 1) * batch = (2+0+1)*2 = 6
        assert stats.submitted <= 6
    finally:
        eng.destroy()


def test_should_accept_filter(server):
    s, addr = server
    eng = _engine(addr)
    try:
        wf = RLVRWorkflow(
            reward_fn=_reward_len,
            gconfig=GenerationHyperparameters(max_new_tokens=4),
        )
        # reject everything once, then accept: executor must keep submitting
        calls = {"n": 0}

        def accept_second_half(traj):
            calls["n"] += 1
            return calls["n"] > 2

        eng.submit({"input_ids": [1]}, workflow=wf,
                   should_accept=accept_second_half)
        eng.submit({"input_ids": [2]}, workflow=wf,
                   should_accept=accept_second_half)
        eng.submit({"input_ids": [3]}, workflow=wf,
                   should_accept=accept_second_half)
        batch = eng.wait(1, timeout=10)
        assert batch["input_ids"].shape[0] == 1
    finally:
        eng.destroy()


def test_generation_payload_matches_server_contract():
    """Regression (ISSUE 18 / C8 payload-contract): the client must ship
    exactly the sampling keys gen/server.py::_req_from_body consumes —
    `frequency_penalty` rode the wire unread for 17 PRs, silently implying
    a sampler feature the JAX engine does not have."""
    from areal_tpu.engine.jax_remote import JaxBackend

    req = ModelRequest(
        rid="contract-0",
        input_ids=[1, 2, 3],
        gconfig=GenerationHyperparameters(max_new_tokens=4),
    )
    http = JaxBackend().build_generation_request(req)
    assert http.endpoint == "/generate"
    assert set(http.payload["sampling_params"]) == {
        "max_new_tokens", "min_new_tokens", "temperature",
        "top_p", "top_k", "stop_token_ids",
    }


def test_fake_server_speaks_full_wire_contract(server):
    """Regression (ISSUE 18 / C8): the fake must serve the real server's
    key-sets — it omitted `output_versions` from /generate and
    version/block/kv from /kv_export, hiding client drift from every
    fake-backed test."""
    import json
    import urllib.request

    from areal_tpu.gen import kv_pool

    s, addr = server

    def post(ep, payload):
        req = urllib.request.Request(
            f"http://{addr}{ep}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    r = post("/generate", {"rid": "wire-0", "input_ids": [1, 2, 3],
                           "sampling_params": {"max_new_tokens": 4}})
    assert r["output_tokens"]
    # every token is stamped with the version that produced it
    assert r["output_versions"] == [r["version"]] * len(r["output_tokens"])
    # /kv_export must round-trip through the REAL wire decoder the router
    # leg-2 import path uses
    entry = kv_pool.wire_decode_entry(
        post("/kv_export", {"input_ids": [1, 2, 3]})
    )
    assert entry["valid_len"] == 3
    assert entry["version"] == s.version
    assert list(entry["tokens"]) == [1, 2, 3]
