"""Tool-integrated reasoning (TIR) agent.

Capability counterpart of the reference's TIR example family
(examples/tir): the model interleaves reasoning with ```python blocks; the
agent executes each completed block in the code sandbox
(reward/code_verifier.py — rlimit'd isolated subprocess) and feeds stdout
back as an ```output block, then generation continues with the tool result
in context.  Tool-output tokens are injected, not sampled, so they carry
loss_mask 0 and logprob 0 — the policy is only trained on what it wrote.

The native generation engine has no server-side stop-strings; the agent
finds the earliest complete code block in each generation chunk by
incremental decode and discards the overshoot (the tokens the model
hallucinated past the block before the tool ran).
"""

import asyncio
import re
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.agent.api import Agent, register_agent
from areal_tpu.api.config import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest

# ```python / ```py only — deliberately narrower than code_verifier's
# extract_code (which also takes bare fences): the TIR transcript contains
# ```output blocks the agent itself injected, and a bare-fence match would
# "execute" those
_BLOCK_RE = re.compile(r"```(?:python|py)\s*\n(.*?)```", re.DOTALL)


def find_first_block(text: str):
    """(code, end_char_index) of the first complete ```python block."""
    m = _BLOCK_RE.search(text)
    return (m.group(1), m.end()) if m else (None, None)


@register_agent("tir-math")
class TIRMathAgent(Agent):
    def __init__(
        self,
        gconfig: GenerationHyperparameters,
        tokenizer=None,
        max_tool_calls: int = 4,
        tool_timeout: float = 6.0,
        tool_output_chars: int = 1024,
    ):
        self.gconfig = gconfig
        self.tokenizer = tokenizer
        self.max_tool_calls = max_tool_calls
        self.tool_timeout = tool_timeout
        self.tool_output_chars = tool_output_chars

    # ------------------------------------------------------------------
    # The generate→find-tool-call→execute→inject loop below is shared with
    # other tool agents (agent/search_agent.py) via these two hooks.

    def _find_call(self, text: str):
        """(payload, end_char_index) of the first complete tool call."""
        return find_first_block(text)

    def _tokens_until(self, tokens: List[int], end_char: int) -> int:
        """Smallest k with len(decode(tokens[:k])) >= end_char — the token
        boundary of a character position, found by bisection (decode is
        monotone in k)."""
        lo, hi = 1, len(tokens)
        while lo < hi:
            mid = (lo + hi) // 2
            if len(self.tokenizer.decode(tokens[:mid])) >= end_char:
                hi = mid
            else:
                lo = mid + 1
        return lo

    async def _run_tool(self, code: str, env=None) -> str:
        from areal_tpu.reward.code_verifier import _run_sandboxed

        res = await asyncio.get_running_loop().run_in_executor(
            None, lambda: _run_sandboxed(code, timeout=self.tool_timeout)
        )
        if res.passed:
            out = res.stdout
        else:
            # feed the traceback back — the loop's whole point is letting
            # the model read the failure and self-correct
            out = f"{res.reason}\n{res.stderr}".strip()
        out = out.strip()[: self.tool_output_chars]
        return f"\n```output\n{out}\n```\n"

    async def _one(self, engine, env, prompt_ids: List[int]):
        g = self.gconfig
        ids = list(prompt_ids)
        gen_mask: List[int] = []  # 1 = sampled by the policy, 0 = injected
        logprobs: List[float] = []
        versions: List[int] = []
        budget = g.max_new_tokens
        tool_calls = 0
        while budget > 0:
            resp = await engine.agenerate(
                ModelRequest(
                    rid=str(uuid.uuid4()),
                    input_ids=list(ids),
                    gconfig=g.new(n_samples=1, max_new_tokens=budget),
                    tokenizer=self.tokenizer,
                )
            )
            text = self.tokenizer.decode(resp.output_tokens)
            code, end_char = self._find_call(text)
            if code is not None and tool_calls >= self.max_tool_calls:
                code = None  # cap reached: keep the text, skip execution
            if code is None:
                ids += list(resp.output_tokens)
                gen_mask += [1] * len(resp.output_tokens)
                logprobs += list(resp.output_logprobs)
                versions += list(resp.output_versions)
                budget -= len(resp.output_tokens)
                break
            # keep tokens through the end of the block; overshoot past it
            # was generated without the tool result and is discarded
            k = self._tokens_until(list(resp.output_tokens), end_char)
            ids += list(resp.output_tokens[:k])
            gen_mask += [1] * k
            logprobs += list(resp.output_logprobs[:k])
            versions += list(resp.output_versions[:k])
            budget -= k
            tool_calls += 1
            tool_text = await self._run_tool(code, env)
            tool_ids = self.tokenizer.encode(tool_text, add_special_tokens=False)
            cur_version = versions[-1] if versions else 0
            ids += list(tool_ids)
            gen_mask += [0] * len(tool_ids)
            logprobs += [0.0] * len(tool_ids)
            versions += [cur_version] * len(tool_ids)
            budget -= len(tool_ids)

        completion = self.tokenizer.decode(ids[len(prompt_ids):])
        reward = 0.0
        if env is not None:
            _, reward, _ = await env.aexecute_tool(
                "verify_answer", {"completion": completion}
            )
        T = len(ids)
        n_prompt = len(prompt_ids)
        loss_mask = np.zeros(T, np.float32)
        loss_mask[n_prompt:] = np.asarray(gen_mask, np.float32)
        lp = np.zeros(T, np.float32)
        lp[n_prompt:] = np.asarray(logprobs, np.float32)
        ver = np.full(T, -1, np.int32)
        ver[n_prompt:] = np.asarray(versions, np.int32)
        return {
            "input_ids": np.asarray(ids, np.int32),
            "loss_mask": loss_mask,
            "logprobs": lp,
            "versions": ver,
            "rewards": float(reward),
        }

    async def collect_trajectory(self, engine, env, data: Dict[str, Any]):
        from areal_tpu.agent.math_agent import _prompt_ids

        prompt_ids = _prompt_ids(self.tokenizer, data)
        n = max(1, self.gconfig.n_samples)
        return list(
            await asyncio.gather(
                *[self._one(engine, env, prompt_ids) for _ in range(n)]
            )
        )
