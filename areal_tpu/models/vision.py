"""Vision tower + multimodal rope for VLM training.

Behavioral counterpart of the reference's VLM path (lite loads
AutoModelForImageTextToText and builds qwen2-VL mrope position ids,
areal/engine/base_hf_engine.py:261-287; vision episodes flow through
workflow/vision_rlvr.py).  TPU-first shape:

- the tower is a pure-function ViT over *pre-patchified* pixels
  [n_patches, C*tps*ps*ps] (the qwen2-VL wire format the AutoProcessor
  emits) — patch embedding is one matmul, blocks are bidirectional
  attention **within each image** (image ids double as attention segments),
  and a spatial-merge MLP emits embeddings at the text width;
- merged image embeddings are scattered into the text embedding stream at
  the image-placeholder token positions with a static-shape cumsum gather
  (no dynamic shapes under jit);
- mrope: 3-row (temporal, h, w) position ids drive rope, with the frequency
  bands split per `cfg.mrope_section`; attention masking keeps using the
  1-D text positions, so causality is untouched.
"""

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.models.model_config import TransformerConfig, VisionConfig
from areal_tpu.models.transformer import (
    LMOutput,
    _backbone,
    rms_norm,
)

Params = Dict


# ---------------------------------------------------------------------------
# Vision tower
# ---------------------------------------------------------------------------


def init_vision_params(cfg: VisionConfig, key, dtype=jnp.float32) -> Params:
    k = jax.random.split(key, 8)
    D, I = cfg.hidden_size, cfg.intermediate_size
    merged = D * cfg.spatial_merge_size**2

    def init(kk, *shape):
        # fan-in scaling; for stacked per-layer weights [L, in, out] the
        # fan-in is the second-to-last dim, not the layer-stack dim
        fan_in = shape[-2] if len(shape) >= 2 else shape[0]
        return (
            jax.random.normal(kk, shape, dtype) / np.sqrt(fan_in)
        ).astype(dtype)

    L = cfg.num_layers
    # layout mirrors Qwen2.5-VL's tower (RMSNorm blocks, biased qkv/proj and
    # gated mlp, biased 2-layer merger) so real checkpoints map 1:1
    return {
        "patch_embed": init(k[0], cfg.patch_dim, D),
        "layers": {
            "input_norm": jnp.ones((L, D), dtype),
            "wqkv": init(k[1], L, D, 3 * D) * np.sqrt(1.0 / 3),
            "b_qkv": jnp.zeros((L, 3 * D), dtype),
            "wo": init(k[2], L, D, D),
            "b_o": jnp.zeros((L, D), dtype),
            "post_attn_norm": jnp.ones((L, D), dtype),
            "w_up": init(k[3], L, D, I),
            "b_up": jnp.zeros((L, I), dtype),
            "w_gate": init(k[4], L, D, I),
            "b_gate": jnp.zeros((L, I), dtype),
            "w_down": init(k[5], L, I, D),
            "b_down": jnp.zeros((L, D), dtype),
        },
        "merger_norm": jnp.ones((D,), dtype),
        "merger_fc1": init(k[6], merged, merged),
        "merger_fc1_b": jnp.zeros((merged,), dtype),
        "merger_fc2": init(k[7], merged, cfg.out_hidden_size),
        "merger_fc2_b": jnp.zeros((cfg.out_hidden_size,), dtype),
    }


def vision_rot_pos_ids(
    image_grid_thw: np.ndarray,  # int [n_img, 3] (t, h, w) in patches
    spatial_merge_size: int = 2,
) -> np.ndarray:
    """Host-side per-patch (h, w) rotary coordinates [N, 2] in the
    processor's patch order (merge-window-major — Qwen2-VL's
    `rot_pos_emb` layout: h/w grids reshaped to (h/m, m, w/m, m) and
    transposed so each merge window's m*m patches are consecutive)."""
    out = []
    m = spatial_merge_size
    for t, h, w in np.asarray(image_grid_thw, np.int64):
        hpos = np.broadcast_to(np.arange(h)[:, None], (h, w))
        wpos = np.broadcast_to(np.arange(w)[None, :], (h, w))
        hpos = hpos.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3).reshape(-1)
        wpos = wpos.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3).reshape(-1)
        hw = np.stack([hpos, wpos], axis=-1)
        out.append(np.tile(hw, (int(t), 1)))
    if not out:
        return np.zeros((0, 2), np.int32)
    return np.concatenate(out).astype(np.int32)


def patch_arrays_for_rows(grids, spatial_merge_size: int = 2):
    """Per-row image grids -> the batch patch bookkeeping every consumer
    shares (workflow augmentation, SFT collate): globally-renumbered
    per-patch image ids [N], 2D rotary coords [N, 2], and per-row patch
    spans [R] (the metadata row-wise splitters carve patch arrays with)."""
    ids_parts, pos_parts, spans = [], [], []
    base = 0
    for grid in grids:
        grid = np.asarray(grid, np.int64).reshape(-1, 3)
        per_image = (grid[:, 0] * grid[:, 1] * grid[:, 2]).astype(np.int64)
        ids_parts.append(
            np.repeat(np.arange(len(grid)) + base, per_image).astype(np.int32)
        )
        pos_parts.append(vision_rot_pos_ids(grid, spatial_merge_size))
        base += len(grid)
        spans.append(int(per_image.sum()))
    if not ids_parts:
        return (
            np.zeros(0, np.int32),
            np.zeros((0, 2), np.int32),
            np.zeros(0, np.int64),
        )
    return (
        np.concatenate(ids_parts),
        np.concatenate(pos_parts),
        np.asarray(spans, np.int64),
    )


def _vision_rope_angles(cfg: VisionConfig, patch_pos_hw: jax.Array) -> jax.Array:
    """[N, 2] (h, w) coords -> rotary angles [N, head_dim/2]: the first
    half of the frequency bands rotate by the h coordinate, the second by
    w (Qwen2-VL VisionRotaryEmbedding: per-axis embeddings of dim hd/4
    concatenated)."""
    quarter = cfg.head_dim // 4
    inv_freq = 1.0 / (
        10000.0 ** (jnp.arange(0, quarter, dtype=jnp.float32) / quarter)
    )
    pos = patch_pos_hw.astype(jnp.float32)  # [N, 2]
    angles = pos[:, :, None] * inv_freq[None, None, :]  # [N, 2, hd/4]
    return angles.reshape(pos.shape[0], -1)  # [N, hd/2]


def _apply_vision_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [N, H, hd]; rotate_half convention with angles [N, hd/2]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, None, :]
    s = sin[:, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(
        x.dtype
    )


def _vit_layer(
    cfg: VisionConfig,
    lp: Params,
    x: jax.Array,
    mask: jax.Array,  # bool [N, N] attention partition (image or window)
    rope: Optional[Tuple[jax.Array, jax.Array]] = None,  # (cos, sin) [N, hd/2]
):
    """One bidirectional block over [N, D] patches; attention only where
    `mask` allows (same image, or same window for Qwen2.5-VL windowed
    blocks)."""
    N, D = x.shape
    H, hd = cfg.num_heads, cfg.head_dim
    h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
    qkv = h @ lp["wqkv"].astype(x.dtype)
    if "b_qkv" in lp:
        qkv = qkv + lp["b_qkv"].astype(x.dtype)
    qkv = qkv.reshape(N, 3, H, hd)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    if rope is not None:
        q = _apply_vision_rope(q, *rope)
        k = _apply_vision_rope(k, *rope)
    scores = jnp.einsum("nhd,mhd->hnm", q, k).astype(jnp.float32) / np.sqrt(hd)
    scores = jnp.where(mask[None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    attn = jnp.einsum("hnm,mhd->nhd", probs, v).reshape(N, D)
    proj = attn @ lp["wo"].astype(x.dtype)
    if "b_o" in lp:
        proj = proj + lp["b_o"].astype(x.dtype)
    x = x + proj
    h = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
    up = h @ lp["w_up"].astype(x.dtype)
    gate = h @ lp["w_gate"].astype(x.dtype)
    if "b_up" in lp:
        up = up + lp["b_up"].astype(x.dtype)
        gate = gate + lp["b_gate"].astype(x.dtype)
    out = (up * jax.nn.silu(gate)) @ lp["w_down"].astype(x.dtype)
    if "b_down" in lp:
        out = out + lp["b_down"].astype(x.dtype)
    return x + out


def vision_forward(
    params: Params,
    cfg: VisionConfig,
    pixel_values: jax.Array,  # [N, patch_dim] pre-patchified
    img_ids: jax.Array,  # int32 [N]: image index per patch, -1 padding
    patch_pos_hw: Optional[jax.Array] = None,  # int [N, 2] rotary coords
) -> jax.Array:
    """-> merged embeddings [N // merge^2, out_hidden_size].

    Patches must arrive row-major per image with h, w divisible by the
    merge size (the qwen2-VL processor guarantees this), so consecutive
    groups of merge^2 patches form one output embedding.

    `patch_pos_hw` (vision_rot_pos_ids) enables the 2D rotary embedding —
    without it the tower is permutation-blind to spatial layout within an
    image (legacy batches; spatial signal then comes only from merge
    grouping + decoder mrope).

    When `cfg.window_size > 0` (Qwen2.5-VL), blocks NOT in
    `cfg.fullatt_block_indexes` attend only within window_size-pixel tiles
    of their image: window membership is derived on device from
    `patch_pos_hw` (h//s, w//s with s the window side in patches), which
    partitions patches identically to HF's get_window_index reordering for
    still images (t=1).  For videos (t>1) the same (h, w) tile of
    different frames shares a window — a superset of HF, which windows
    per frame.  Without patch_pos_hw the tower falls back to full
    attention per image."""
    dtype = pixel_values.dtype
    x = pixel_values @ params["patch_embed"].astype(dtype)
    rope = None
    if patch_pos_hw is not None:
        angles = _vision_rope_angles(cfg, patch_pos_hw)
        rope = (jnp.cos(angles), jnp.sin(angles))

    img_mask = (img_ids[:, None] == img_ids[None, :]) & (img_ids[:, None] >= 0)
    # HF computes the window grid on merge units: side (in patches) is
    # (window // merge // patch) * merge so truncation matches exactly
    s = (
        cfg.window_size // cfg.spatial_merge_size // cfg.patch_size
    ) * cfg.spatial_merge_size
    if cfg.window_size > 0 and s > 0 and patch_pos_hw is not None:
        wh, ww = patch_pos_hw[:, 0] // s, patch_pos_hw[:, 1] // s
        win_mask = (
            img_mask & (wh[:, None] == wh[None, :]) & (ww[:, None] == ww[None, :])
        )
        L = params["layers"]["input_norm"].shape[0]
        is_full = jnp.asarray(
            [l in cfg.fullatt_block_indexes for l in range(L)], bool
        )

        def body(x, scanned):
            lp, full = scanned
            mask = jnp.where(full, img_mask, win_mask)
            return _vit_layer(cfg, lp, x, mask, rope=rope), None

        x, _ = jax.lax.scan(body, x, (params["layers"], is_full))
    else:

        def body(x, lp):
            return _vit_layer(cfg, lp, x, img_mask, rope=rope), None

        x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["merger_norm"], cfg.rms_norm_eps)
    m2 = cfg.spatial_merge_size**2
    x = x.reshape(x.shape[0] // m2, m2 * cfg.hidden_size)
    h1 = x @ params["merger_fc1"].astype(dtype)
    if "merger_fc1_b" in params:
        h1 = h1 + params["merger_fc1_b"].astype(dtype)
    # exact (erf) gelu: HF's nn.GELU default, not the tanh approximation
    out = jax.nn.gelu(h1, approximate=False) @ params["merger_fc2"].astype(dtype)
    if "merger_fc2_b" in params:
        out = out + params["merger_fc2_b"].astype(dtype)
    return out


# ---------------------------------------------------------------------------
# mrope
# ---------------------------------------------------------------------------


def mrope_position_ids(
    input_ids: np.ndarray,  # int [T] one sequence
    image_grid_thw: np.ndarray,  # int [n_img, 3] (t, h, w) in patches
    image_token_id: int,
    spatial_merge_size: int = 2,
) -> np.ndarray:
    """Host-side 3xT (temporal, h, w) position ids, qwen2-VL scheme
    (reference: base_hf_engine.py:261-287 position-id construction):
    text tokens advance all three rows together; each image's placeholder
    span gets (t, row, col) grid coordinates offset from the running
    position; text resumes at max(position)+1."""
    T = len(input_ids)
    out = np.zeros((3, T), np.int64)
    img_idx = 0
    pos = 0  # next position value for text
    t = 0
    while t < T:
        if input_ids[t] == image_token_id:
            if img_idx >= len(image_grid_thw):
                raise ValueError(
                    f"{img_idx + 1} image placeholder runs but only "
                    f"{len(image_grid_thw)} grids"
                )
            gt, gh, gw = (int(v) for v in image_grid_thw[img_idx])
            mh, mw = gh // spatial_merge_size, gw // spatial_merge_size
            n = gt * mh * mw
            if n <= 0:
                raise ValueError(
                    f"image grid {gt}x{gh}x{gw} with merge "
                    f"{spatial_merge_size} yields no embeddings"
                )
            tt, hh, ww = np.meshgrid(
                np.arange(gt), np.arange(mh), np.arange(mw), indexing="ij"
            )
            out[0, t : t + n] = pos + tt.reshape(-1)
            out[1, t : t + n] = pos + hh.reshape(-1)
            out[2, t : t + n] = pos + ww.reshape(-1)
            pos = pos + max(gt, mh, mw)
            t += n
            img_idx += 1
        else:
            out[:, t] = pos
            pos += 1
            t += 1
    return out


def mrope_cos_sin(
    positions3: jax.Array,  # int [3, B, T]
    head_dim: int,
    theta: float,
    section: Tuple[int, int, int],
):
    """cos/sin [B, T, hd/2] with frequency bands picked per mrope section:
    the first section[0] bands use the temporal row, the next section[1] the
    height row, the last section[2] the width row."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions3.astype(jnp.float32)[..., None] * inv_freq  # [3,B,T,hd/2]
    sec_id = jnp.repeat(
        jnp.arange(3), jnp.asarray(section), total_repeat_length=head_dim // 2
    )  # [hd/2] in {0,1,2}
    # advanced indexing at axes (0, 3) -> [hd/2, B, T]; restore [B, T, hd/2]
    picked = angles[sec_id, ..., jnp.arange(head_dim // 2)]
    picked = jnp.moveaxis(picked, 0, -1)
    return jnp.cos(picked), jnp.sin(picked)


# ---------------------------------------------------------------------------
# VLM forward
# ---------------------------------------------------------------------------


def merge_image_embeds(
    text_embeds: jax.Array,  # [B, T, D]
    input_ids: jax.Array,  # [B, T]
    image_embeds: jax.Array,  # [M, D] merged vision embeddings, in order
    image_token_id: int,
) -> jax.Array:
    """Replace placeholder-token embeddings with image embeddings, in
    scan order — static shapes throughout (cumsum gather, no boolean
    indexing)."""
    B, T, D = text_embeds.shape
    mask = (input_ids == image_token_id).reshape(-1)
    idx = jnp.cumsum(mask) - 1  # position among placeholder tokens
    M = image_embeds.shape[0]
    gathered = jnp.take(
        image_embeds, jnp.clip(idx, 0, M - 1), axis=0
    ).astype(text_embeds.dtype)
    flat = jnp.where(mask[:, None], gathered, text_embeds.reshape(-1, D))
    return flat.reshape(B, T, D)


def forward_vlm_lm(
    params: Params,
    cfg: TransformerConfig,
    input_ids: jax.Array,  # [B, T]
    positions: jax.Array,  # [B, T] text-index positions (masking/causality)
    segment_ids: jax.Array,  # [B, T]
    pixel_values: jax.Array,  # [N, patch_dim]
    patch_img_ids: jax.Array,  # [N] image index per patch (-1 pad)
    mrope_positions: Optional[jax.Array] = None,  # [3, B, T]
    patch_pos_hw: Optional[jax.Array] = None,  # [N, 2] 2D rotary coords
    mesh=None,
) -> LMOutput:
    """VLM forward with deferred LM head (mirrors transformer.forward_lm)."""
    assert cfg.vision is not None and cfg.image_token_id is not None
    dtype = jnp.dtype(cfg.dtype)
    text = jnp.take(params["embedding"].astype(dtype), input_ids, axis=0)
    vis = vision_forward(
        params["vision"], cfg.vision, pixel_values.astype(dtype),
        patch_img_ids, patch_pos_hw=patch_pos_hw,
    )
    x = merge_image_embeds(text, input_ids, vis, cfg.image_token_id)
    rope = None
    if mrope_positions is not None and cfg.mrope_section is not None:
        rope = mrope_cos_sin(
            mrope_positions, cfg.head_dim_, cfg.rope_theta, cfg.mrope_section
        )
    hidden, aux = _backbone(
        params, cfg, input_ids, positions, segment_ids,
        mesh=mesh, inputs_embeds=x, rope=rope,
    )
    head = params.get("lm_head")
    if head is None:
        head = params["embedding"].T
    return LMOutput(
        hidden=hidden,
        head=head.astype(dtype),
        aux_loss=aux * cfg.moe_aux_coef if cfg.num_experts > 0 else None,
    )
