"""Trace analytics & SLO harness (ISSUE 14): the lifecycle-JSONL
analyzer's stage state machine and accounting identity, the completeness
linter on truncated/orphaned fixture logs (tests/data/traces/), the SLO
report schema + CLI, the replay workload generators, and the
`scripts/check_slo.py` regression gate against the checked-in baseline."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from areal_tpu.obs import workload as wl
from areal_tpu.obs.slo import SCHEMA as SLO_SCHEMA
from areal_tpu.obs.slo import build_report, render_markdown
from areal_tpu.obs.slo import main as slo_main
from areal_tpu.obs.trace import analyze, check_accounting, dist_summary

DATA = os.path.join(os.path.dirname(__file__), "data")
TRACES = os.path.join(DATA, "traces")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECK_SLO = os.path.join(REPO, "scripts", "check_slo.py")


def _load_check_slo():
    spec = importlib.util.spec_from_file_location("check_slo", CHECK_SLO)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _clean_events():
    with open(os.path.join(TRACES, "clean.jsonl")) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# ---------------------------------------------------------------------------
# stage state machine + accounting identity
# ---------------------------------------------------------------------------


def test_stage_partition_clean_trace():
    rep = analyze(os.path.join(TRACES, "clean.jsonl"))
    assert rep.completeness.complete
    (rec,) = rep.records
    assert rec.closed and not rec.lost
    # the fixture's spans partition exactly: 0.1 queue + 0.18 prefill +
    # (0.22 + 0.2) decode + 0.05 delivery tail over a 0.75s event span
    assert rec.stages == pytest.approx({
        "admission_wait": 0.1, "prefill": 0.18,
        "decode": 0.42, "tail": 0.05,
    })
    assert rec.span_s == pytest.approx(0.75)
    assert rec.e2e_s == pytest.approx(0.74)
    assert rec.identity_rel == pytest.approx(0.01 / 0.74)
    assert rec.ttft_s == pytest.approx(0.5)
    # joined across the trajectory: reward after done, consume via key
    assert rec.reward == 1.0
    assert rec.reward_latency_s == pytest.approx(0.15)
    assert rec.staleness == 1.0
    assert rec.consume_latency_s == pytest.approx(0.75)
    acct = check_accounting(rep.records)
    assert acct.ok and acct.checked == 1 and acct.violations == 0


def test_accounting_identity_violation_detected():
    evs = _clean_events()
    done = next(e for e in evs if e["event"] == "gen_done")
    done["latency_s"] = 2.0  # client claims 2s; the spans sum to 0.75s
    rep = analyze(evs)
    acct = check_accounting(rep.records)
    assert not acct.ok and acct.violations == 1
    assert acct.max_rel_err > 0.5
    report = build_report(evs)
    assert report["accounting"]["ok"] is False
    assert report["complete"] is False  # identity failure taints the report


def test_sub_floor_jitter_is_not_a_violation():
    evs = _clean_events()
    next(e for e in evs if e["event"] == "gen_done")["latency_s"] = 0.73
    acct = check_accounting(analyze(evs).records)
    # 0.02s absolute error is under the floor even though 2.7% > nothing
    assert acct.ok


def test_monotonic_clock_used_when_single_pid():
    # wall clocks identical (an NTP step ate the deltas); mono carries
    # the real spacing — the partition must come from mono
    evs = [
        {"ts": 5.0, "mono": 10.0, "pid": 7, "event": "rollout_submit",
         "trace_id": "m1", "input_len": 4},
        {"ts": 5.0, "mono": 10.2, "pid": 7, "event": "admission",
         "trace_id": "m1", "kind": "fresh"},
        {"ts": 5.0, "mono": 10.5, "pid": 7, "event": "gen_done",
         "trace_id": "m1", "stop_reason": "stop", "output_len": 4,
         "latency_s": 0.5},
    ]
    (rec,) = analyze(evs).records
    assert rec.clock == "mono"
    assert rec.span_s == pytest.approx(0.5)
    assert rec.stages["admission_wait"] == pytest.approx(0.2)
    # two pids -> wall time is the only shared clock
    evs[1]["pid"] = 8
    (rec,) = analyze(evs).records
    assert rec.clock == "ts"


def test_client_only_log_is_opaque():
    evs = [
        {"ts": 1.0, "event": "rollout_submit", "trace_id": "c1",
         "input_len": 4},
        {"ts": 1.4, "event": "gen_done", "trace_id": "c1",
         "stop_reason": "stop", "output_len": 4, "latency_s": 0.4},
    ]
    rep = analyze(evs)
    (rec,) = rep.records
    # no server-side spans to decompose: one opaque stage, identity holds
    assert rec.stages == pytest.approx({"opaque": 0.4})
    assert check_accounting(rep.records).ok
    assert rep.completeness.complete


def test_dist_summary_interpolation():
    d = dist_summary(range(1, 101))
    assert d["count"] == 100 and d["min"] == 1 and d["max"] == 100
    assert d["p50"] == pytest.approx(50.5)
    assert d["p99"] == pytest.approx(99.01)
    assert dist_summary([]) is None
    assert dist_summary([float("inf"), float("nan")]) is None


# ---------------------------------------------------------------------------
# completeness linter on fixture logs
# ---------------------------------------------------------------------------


def test_truncated_log_flags_orphans():
    rep = analyze(os.path.join(TRACES, "truncated.jsonl"))
    assert not rep.completeness.complete
    assert rep.completeness.orphan_traces == ["tr-1"]


def test_unjoined_resubmit_flagged():
    rep = analyze(os.path.join(TRACES, "unjoined_resubmit.jsonl"))
    assert not rep.completeness.complete
    assert rep.completeness.unjoined_resubmits == 1
    # ...while a resubmit that follows its original submit joins fine
    rejoined = [
        {"ts": 1.0, "event": "rollout_submit", "trace_id": "r1",
         "input_len": 4},
        {"ts": 1.2, "event": "resubmit", "trace_id": "r1",
         "from_server": "s0", "to_server": "s1", "attempt": 2},
        {"ts": 1.6, "event": "gen_done", "trace_id": "r1",
         "stop_reason": "stop", "output_len": 4, "latency_s": 0.6},
    ]
    rep = analyze(rejoined)
    assert rep.completeness.complete
    assert rep.records[0].resubmits == 1


def test_meta_trailer_marks_log_lossy():
    rep = analyze(os.path.join(TRACES, "dropped.jsonl"))
    assert rep.completeness.dropped_events == 5
    assert not rep.completeness.complete
    report = build_report(os.path.join(TRACES, "dropped.jsonl"))
    assert report["complete"] is False
    assert report["completeness"]["dropped_events"] == 5


def test_open_traces_reported_not_failed_unless_strict():
    evs = [{"ts": 1.0, "event": "rollout_submit", "trace_id": "o1",
            "input_len": 4}]
    rep = analyze(evs)
    assert rep.completeness.complete and rep.completeness.open_traces == 1
    assert not analyze(evs, strict_open=True).completeness.complete


def test_incomplete_interrupt_on_closed_trace():
    evs = [
        {"ts": 1.0, "event": "rollout_submit", "trace_id": "i1",
         "input_len": 4},
        {"ts": 1.2, "event": "interrupt", "trace_id": "i1"},
        {"ts": 1.6, "event": "gen_done", "trace_id": "i1",
         "stop_reason": "stop", "output_len": 4, "latency_s": 0.6},
    ]
    rep = analyze(evs)
    assert rep.completeness.incomplete_interrupts == 1
    assert not rep.completeness.complete
    # a resume between them closes the window
    evs.insert(2, {"ts": 1.4, "event": "resume", "trace_id": "i1",
                   "attempt": 1, "generated": 2, "prompt_len": 4})
    assert analyze(evs).completeness.complete


# ---------------------------------------------------------------------------
# SLO report + CLI
# ---------------------------------------------------------------------------


def test_slo_report_schema_and_markdown():
    report = build_report(os.path.join(TRACES, "clean.jsonl"), run_id="t")
    assert report["schema"] == SLO_SCHEMA
    assert report["complete"] is True
    assert report["goodput"]["output_tokens"] == 16
    assert report["e2e_s"]["count"] == 1
    assert set(report["stages"]) == {"admission_wait", "prefill",
                                     "decode", "tail"}
    assert report["staleness"]["p50"] == 1.0
    md = render_markdown(report)
    assert "# SLO report t" in md
    assert "stage:decode" in md and "| end-to-end |" in md


def test_slo_cli_writes_artifacts_and_gates(tmp_path):
    out = tmp_path / "SLO_REPORT_t.json"
    md = tmp_path / "SLO_REPORT_t.md"
    rc = slo_main([os.path.join(TRACES, "clean.jsonl"), "--out", str(out),
                   "--md", str(md), "--run-id", "t", "--require-complete",
                   "--require-identity"])
    assert rc == 0
    assert json.loads(out.read_text())["schema"] == SLO_SCHEMA
    assert md.read_text().startswith("# SLO report t")
    # lossy log + --require-complete must gate
    rc = slo_main([os.path.join(TRACES, "dropped.jsonl"),
                   "--require-complete"])
    assert rc == 1


# ---------------------------------------------------------------------------
# replay workload generators
# ---------------------------------------------------------------------------


def test_synthetic_mixed_deterministic_and_mixed():
    kw = dict(seed=1, duration_s=12.0, base_rps=4.0,
              max_prompt_len=128, max_new_tokens=16)
    a = wl.synthetic_mixed(**kw)
    b = wl.synthetic_mixed(**kw)
    assert a == b  # same seed, same workload — curves comparable
    kinds = {x.kind for x in a}
    assert kinds == {"chat", "group", "straggler"}
    assert all(x.t >= 0 and x.prompt_len >= 1 for x in a)
    assert a != wl.synthetic_mixed(**{**kw, "seed": 2})


def test_group_siblings_share_prompts():
    arrivals = wl.synthetic_mixed(seed=1, duration_s=12.0, base_rps=4.0,
                                  max_prompt_len=128, max_new_tokens=16)
    groups = {}
    for a in arrivals:
        if a.group_id:
            groups.setdefault(a.group_id, []).append(
                wl.prompt_ids(a, vocab=512, seed=1))
    assert groups
    for ids in groups.values():
        assert len(ids) == 4  # group_n siblings
        assert all(x == ids[0] for x in ids)  # shared prefix material


def test_scale_compresses_clock_only():
    arrivals = wl.synthetic_mixed(seed=1, duration_s=12.0, base_rps=4.0)
    fast = wl.scale(arrivals, 4.0)
    assert [a.t / 4.0 for a in arrivals] == pytest.approx(
        [f.t for f in fast])
    assert [a.prompt_len for a in arrivals] == [f.prompt_len for f in fast]
    with pytest.raises(ValueError):
        wl.scale(arrivals, 0)


def test_arrivals_from_trace_roundtrip():
    arrivals = wl.arrivals_from_trace(os.path.join(TRACES, "clean.jsonl"))
    (a,) = arrivals
    assert a.t == 0.0 and a.prompt_len == 8
    assert a.max_new_tokens == 16  # budget from the recorded gen_done
    assert a.trace_id == "tr-1" and a.kind == "trace"


# ---------------------------------------------------------------------------
# check_slo regression gate
# ---------------------------------------------------------------------------


def test_check_slo_pass_and_regression():
    cs = _load_check_slo()
    report = build_report(os.path.join(TRACES, "clean.jsonl"), run_id="t")
    baseline = cs.write_baseline(report, None, tolerance=0.5)
    assert baseline["schema"] == cs.SCHEMA
    rc, text = cs.run_gate(report, baseline)
    assert rc == 0 and "PASS" in text

    # 2.5x p99 regression: hard fail, even in CI's --hard-only mode
    bad = json.loads(json.dumps(report))
    bad["e2e_s"]["p99"] *= 2.5
    rc, text = cs.run_gate(bad, baseline)
    assert rc == 1 and "HARD e2e_s.p99" in text
    rc, _ = cs.run_gate(bad, baseline, hard_only=True)
    assert rc == 1

    # 1.6x: outside the soft band (+50%) but under the 2x hard ratio
    mild = json.loads(json.dumps(report))
    mild["e2e_s"]["p99"] *= 1.6
    rc, text = cs.run_gate(mild, baseline)
    assert rc == 1 and "soft e2e_s.p99" in text
    rc, _ = cs.run_gate(mild, baseline, hard_only=True)
    assert rc == 0

    # an incomplete report can never pass, whatever the numbers say
    lossy = json.loads(json.dumps(report))
    lossy["completeness"]["complete"] = False
    rc, text = cs.run_gate(lossy, baseline, hard_only=True)
    assert rc == 1 and "HARD completeness" in text


def test_check_slo_lower_direction_guards_throughput():
    cs = _load_check_slo()
    report = {"completeness": {"complete": True}, "accounting": {"ok": True},
              "goodput": {"output_tokens_per_s": 100.0}}
    baseline = {"schema": cs.SCHEMA, "hard_fail_ratio": 2.0, "metrics": {
        "goodput.output_tokens_per_s": {
            "baseline": 100.0, "tolerance": 0.3, "direction": "lower"}}}
    assert cs.run_gate(report, baseline)[0] == 0
    report["goodput"]["output_tokens_per_s"] = 60.0  # -40%: soft band
    assert cs.run_gate(report, baseline)[0] == 1
    assert cs.run_gate(report, baseline, hard_only=True)[0] == 0
    report["goodput"]["output_tokens_per_s"] = 40.0  # <1/2x: hard
    assert cs.run_gate(report, baseline, hard_only=True)[0] == 1


def test_check_slo_cli_against_checked_in_baseline():
    """The committed baseline must accept the committed report it was
    written from (CI runs exactly this gate against fresh replay runs)."""
    report = os.path.join(DATA, "slo_replay_report.json")
    baseline = os.path.join(DATA, "slo_baseline.json")
    res = subprocess.run(
        [sys.executable, CHECK_SLO, "--report", report,
         "--baseline", baseline],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "PASS" in res.stdout

    res = subprocess.run(
        [sys.executable, CHECK_SLO, "--report", report,
         "--baseline", os.path.join(TRACES, "clean.jsonl")],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 2  # unusable baseline is its own failure


def test_resubmit_cache_hit_consumed_not_orphaned():
    """Regression (ISSUE 18 / C9 event-contract): core/remote.py emits
    `resubmit_cache_hit` when a failover resubmit warm-starts through the
    prefix cache; the trace parser dropped it on the floor, so the span
    survived only as an unparsed line."""
    evs = [
        {"ts": 1.0, "mono": 1.0, "pid": 7, "event": "rollout_submit",
         "trace_id": "rch", "input_len": 4},
        {"ts": 1.1, "mono": 1.1, "pid": 7, "event": "resubmit",
         "trace_id": "rch", "server": "b"},
        {"ts": 1.15, "mono": 1.15, "pid": 7, "event": "resubmit_cache_hit",
         "trace_id": "rch", "server": "b", "hit_tokens": 3},
        {"ts": 1.5, "mono": 1.5, "pid": 7, "event": "gen_done",
         "trace_id": "rch", "stop_reason": "stop", "output_len": 4,
         "latency_s": 0.5, "attempts": 2},
    ]
    rep = analyze(evs)
    assert rep.completeness.complete
    (rec,) = rep.records
    assert rec.resubmits == 1
    assert rec.resubmit_cache_hits == 1
    assert rec.resubmit_cache_hit_tokens == 3
