"""Continuous-batching generation engine on a fixed slot grid.

The TPU-native replacement for the SGLang/vLLM servers the reference wraps
(areal/launcher/sglang_server.py:117, realhf generation servers) and for the
legacy native decode loop (realhf/impl/model/nn/real_llm_generate.py).
Design for XLA's static shapes:

- `n_slots` concurrent sequences in a preallocated KV cache
  [L, S, M, Hkv, hd]; admission assigns a free slot, completion frees it —
  continuous batching without shape changes.
- TWO compiled programs: `forward_prefill` per prompt bucket (power-of-two
  padded) and ONE `forward_decode` step advancing every slot; idle slots
  decode garbage that is never read (cheaper than recompiling for occupancy).
- Cache and rng are donated; steady-state decode allocates nothing.
- Weight reload (`load_weights`) aborts in-flight requests with
  stop_reason="abort" — the client's interruption loop resubmits with
  accumulated tokens (reference behavior: remote_inf_engine.py:428-478) —
  then bumps `version`; per-token versions let decoupled PPO weight stale
  spans correctly.
"""

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.gen.sampling import sample_tokens
from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.models.transformer import (
    forward_decode,
    forward_prefill,
    init_kv_cache,
    init_params,
)
from areal_tpu.models.hf import load_hf_params
from areal_tpu.utils import logging
from areal_tpu.utils.datapack import round_up_to_bucket

logger = logging.getLogger("gen.engine")


@dataclass
class GenRequest:
    rid: str
    input_ids: List[int]
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    stop_token_ids: List[int] = field(default_factory=list)
    # filled by the engine
    output_tokens: List[int] = field(default_factory=list)
    output_logprobs: List[float] = field(default_factory=list)
    output_versions: List[int] = field(default_factory=list)
    stop_reason: str = ""
    on_done: Optional[Callable[["GenRequest"], None]] = None

    def finish(self, reason: str):
        self.stop_reason = reason
        if self.on_done is not None:
            self.on_done(self)


class GenEngine:
    def __init__(
        self,
        model_config: TransformerConfig,
        params=None,
        model_path: Optional[str] = None,
        n_slots: int = 8,
        max_seq_len: int = 2048,
        prompt_bucket: int = 128,
        kv_dtype: str = "bfloat16",
        seed: int = 0,
        decode_chunk: int = 8,
    ):
        self.model_config = model_config.replace(remat=False)
        if params is None:
            if model_path:
                host, mc = load_hf_params(model_path, model_config, dtype="bfloat16")
                self.model_config = mc.replace(
                    dtype=model_config.dtype, param_dtype="bfloat16", remat=False
                )
                params = host
            else:
                params = init_params(self.model_config, jax.random.PRNGKey(seed))
        self.params = jax.tree_util.tree_map(jnp.asarray, params)
        self.n_slots = n_slots
        self.max_seq_len = max_seq_len
        self.prompt_bucket = prompt_bucket
        self.cache = init_kv_cache(self.model_config, n_slots, max_seq_len, kv_dtype)
        self.rng = jax.random.PRNGKey(seed)
        self.version = 0

        # host-side slot state
        self.slot_req: List[Optional[GenRequest]] = [None] * n_slots
        self.lengths = np.zeros(n_slots, np.int32)
        self.last_tokens = np.zeros(n_slots, np.int32)
        self.temperature = np.ones(n_slots, np.float32)
        self.top_p = np.ones(n_slots, np.float32)
        self.top_k = np.zeros(n_slots, np.int32)
        self.pending: "queue.Queue[GenRequest]" = queue.Queue()
        self._lock = threading.Lock()

        # decode_chunk: tokens generated per host round-trip.  The decode scan
        # runs this many fused forward+sample steps on device before the host
        # sees anything — the host applies stop conditions in arrears and
        # discards overshoot (slots that stopped mid-chunk decode garbage that
        # is never delivered).  Chunking amortises host<->device latency,
        # which dominates when the chip is reached over a network tunnel.
        self.decode_chunk = max(1, decode_chunk)
        cfg = self.model_config

        def _prefill(params, cache, ids, plen, slot, rng, temp, tp, tk):
            logits, cache = forward_prefill(params, cfg, ids, plen, cache, slot)
            tok, logp = sample_tokens(logits, rng, temp, tk, tp)
            return tok, logp, cache

        def _decode_chunk(params, cache, tokens, lengths, rng, temp, tp, tk, n):
            def body(carry, _):
                cache, tokens, lengths, rng = carry
                logits, cache = forward_decode(params, cfg, tokens, lengths, cache)
                rng, sub = jax.random.split(rng)
                tok, logp = sample_tokens(
                    logits.astype(jnp.float32), sub, temp, tk, tp
                )
                return (cache, tok, lengths + 1, rng), (tok, logp)

            (cache, _, _, _), (toks, logps) = jax.lax.scan(
                body, (cache, tokens, lengths, rng), None, length=n
            )
            # one fused download: tokens are exactly representable in f32
            out = jnp.stack([toks.astype(jnp.float32), logps])  # [2, n, S]
            return out, cache

        self._prefill_fn = jax.jit(_prefill, donate_argnums=(1,))
        self._decode_fn = jax.jit(_decode_chunk, static_argnums=(8,),
                                  donate_argnums=(1,))

    # ------------------------------------------------------------------
    # submission / weights
    # ------------------------------------------------------------------

    def submit(self, req: GenRequest) -> None:
        if len(req.input_ids) + 1 >= self.max_seq_len:
            req.finish("length")
            return
        self.pending.put(req)

    def active_count(self) -> int:
        with self._lock:
            return sum(r is not None for r in self.slot_req) + self.pending.qsize()

    def abort_all(self, reason: str = "abort") -> int:
        """Finish every in-flight request immediately (weight update /
        shutdown). Returns how many were aborted."""
        n = 0
        with self._lock:
            for s, req in enumerate(self.slot_req):
                if req is not None:
                    req.finish(reason)
                    self.slot_req[s] = None
                    n += 1
            while True:
                try:
                    self.pending.get_nowait().finish(reason)
                    n += 1
                except queue.Empty:
                    break
        return n

    def load_weights(
        self, path: Optional[str] = None, params=None, version: Optional[int] = None
    ) -> int:
        """Swap weights; aborts in-flight generation first (interruptible
        generation: clients resubmit and the new prefill recomputes under the
        new policy). Returns the new version."""
        aborted = self.abort_all("abort")
        if aborted:
            logger.info(f"aborted {aborted} requests for weight update")
        if params is None:
            assert path is not None
            params, _ = load_hf_params(path, self.model_config, dtype="bfloat16")
        self.params = jax.tree_util.tree_map(jnp.asarray, params)
        self.version = version if version is not None else self.version + 1
        return self.version

    # ------------------------------------------------------------------
    # stepping
    # ------------------------------------------------------------------

    def _admit(self) -> None:
        for s in range(self.n_slots):
            if self.slot_req[s] is not None:
                continue
            try:
                req = self.pending.get_nowait()
            except queue.Empty:
                return
            plen = len(req.input_ids)
            bucket = round_up_to_bucket(
                max(plen, 1), self.prompt_bucket, self.max_seq_len
            )
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :plen] = req.input_ids
            self.rng, sub = jax.random.split(self.rng)
            tok, logp, self.cache = self._prefill_fn(
                self.params,
                self.cache,
                ids,
                jnp.asarray([plen], jnp.int32),
                s,
                sub,
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_p], jnp.float32),
                jnp.asarray([req.top_k], jnp.int32),
            )
            tok, logp = int(tok[0]), float(logp[0])
            with self._lock:
                self.slot_req[s] = req
                self.lengths[s] = plen
                self.last_tokens[s] = tok
                self.temperature[s] = req.temperature
                self.top_p[s] = req.top_p
                self.top_k[s] = req.top_k
            self._record_token(s, tok, logp)

    def _record_token(self, s: int, tok: int, logp: float) -> None:
        req = self.slot_req[s]
        if req is None:  # aborted between decode and delivery
            return
        req.output_tokens.append(tok)
        req.output_logprobs.append(logp)
        req.output_versions.append(self.version)
        n_out = len(req.output_tokens)
        stop_ids = req.stop_token_ids or (
            [self.model_config.eos_token_id]
            if self.model_config.eos_token_id is not None
            else []
        )
        hit_stop = tok in stop_ids and n_out >= req.min_new_tokens
        total_len = self.lengths[s] + 1  # prompt + generated so far
        if hit_stop:
            self._free(s, "stop")
        elif n_out >= req.max_new_tokens or total_len + 1 >= self.max_seq_len:
            self._free(s, "length")

    def _free(self, s: int, reason: str) -> None:
        req = self.slot_req[s]
        with self._lock:
            self.slot_req[s] = None
        if req is not None:
            req.finish(reason)

    def step(self, chunk: Optional[int] = None) -> int:
        """Admit pending prompts, then advance every active slot by up to
        `chunk` tokens in one device program.  Returns generated-token count
        actually delivered (overshoot past stop conditions excluded)."""
        self._admit()
        with self._lock:
            active = [s for s in range(self.n_slots) if self.slot_req[s] is not None]
        if not active:
            return 0
        n = chunk or self.decode_chunk
        # never decode past the cache: bound by the tightest active slot.
        # n is a static jit arg, so round the clamp DOWN to a power of two —
        # O(log decode_chunk) compiled programs instead of one per length.
        cap = max(1, int(self.max_seq_len - 1 - self.lengths[active].max()))
        n = min(n, cap)
        if n < (chunk or self.decode_chunk):
            n = 1 << (n.bit_length() - 1)
        self.rng, sub = jax.random.split(self.rng)
        out, self.cache = self._decode_fn(
            self.params,
            self.cache,
            jnp.asarray(self.last_tokens),
            jnp.asarray(self.lengths),
            sub,
            jnp.asarray(self.temperature),
            jnp.asarray(self.top_p),
            jnp.asarray(self.top_k),
            n,
        )
        out = np.asarray(out)  # [2, n, S]
        toks = out[0].astype(np.int32)
        logps = out[1]
        delivered = 0
        for s in active:
            for i in range(n):
                if self.slot_req[s] is None:
                    break  # stopped mid-chunk; remaining tokens are overshoot
                self.lengths[s] += 1  # K/V for this token is in the cache
                self.last_tokens[s] = toks[i, s]
                self._record_token(s, int(toks[i, s]), float(logps[i, s]))
                delivered += 1
        return delivered

    def generate_blocking(self, reqs: List[GenRequest]) -> List[GenRequest]:
        """Synchronous helper (tests / offline eval): run until all done."""
        for r in reqs:
            self.submit(r)
        while any(not r.stop_reason for r in reqs):
            if self.step() == 0 and self.pending.qsize() == 0:
                break
            time.sleep(0)
        return reqs
