"""Countdown GRPO — arithmetic-game agent RL.

Behavioral counterpart of the reference's `examples/countdown/train.py`:
the model writes an arithmetic expression over given numbers to hit a
target; `CountdownEnv` verifies the boxed formula (each number used at
most once, exact value match).

This entry point delegates to the shared GRPO loop
(examples/math/gsm8k_grpo.py) with `workflow: countdown` — the loop,
launcher wiring, weight sync, and recovery are identical across the
agentic examples; only the dataset + workflow branch differ.

Launch:  python examples/countdown/countdown_grpo.py --config examples/countdown/countdown_grpo.yaml
(or: python -m areal_tpu.launcher.local examples/countdown/countdown_grpo.py --config ...)
"""

import importlib.util
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_spec = importlib.util.spec_from_file_location(
    "gsm8k_grpo", os.path.join(_REPO, "examples", "math", "gsm8k_grpo.py")
)
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)


def main(argv):
    _mod.main(argv)


if __name__ == "__main__":
    main(sys.argv[1:])
