"""C9 positive fixture: unpinned metric, dynamic metric name outside an
allowlisted site, undeclared event emit, and a declared-but-never-parsed
event (METRIC_POS_DOC / METRIC_POS_SCHEMA in test_lint.py)."""

from areal_tpu.utils import telemetry

BAD = telemetry.GEN.counter("bad_total", "never pinned")  # VIOLATION


def dyn(name):
    return telemetry.GEN.counter(name)  # VIOLATION: dynamic, not allowlisted


def emit_all():
    telemetry.emit("ghost_ev")  # VIOLATION: not declared in the registry
    telemetry.emit("ev_unparsed")  # VIOLATION: trace.py never consumes it
