"""Countdown game env/reward tests (reference: examples/countdown)."""

import asyncio

import pytest

from areal_tpu.agent.countdown_env import (
    CountdownEnv,
    countdown_reward_fn,
    extract_expression,
    make_countdown_dataset,
    verify_countdown,
)


def test_extract_expression():
    assert extract_expression("so \\boxed{(1+2)*3}") == "(1+2)*3"
    assert extract_expression("<answer>4*5</answer>") == "4*5"
    assert extract_expression("nothing") is None
    # last answer wins
    assert extract_expression("\\boxed{1} then \\boxed{2+3}") == "2+3"


def test_verify_correct_and_wrong():
    assert verify_countdown("\\boxed{(25-5)*4}", [25, 5, 4, 7], 80) == 1.0
    assert verify_countdown("\\boxed{25+5}", [25, 5, 4, 7], 80) == 0.0
    assert verify_countdown("\\boxed{20*4/1}", [20, 4, 1], 80) == 1.0


def test_verify_number_constraints():
    # 5 used twice but provided once
    assert verify_countdown("\\boxed{5*5}", [5, 4], 25) == 0.0
    # number not in the pool
    assert verify_countdown("\\boxed{10*8}", [5, 4], 80) == 0.0
    # each number at most once is fine even when unused numbers remain
    assert verify_countdown("\\boxed{5*4}", [5, 4, 9], 20) == 1.0


def test_verify_rejects_unsafe_and_malformed():
    assert verify_countdown("\\boxed{__import__('os').getcwd()}", [1], 1) == 0.0
    assert verify_countdown("\\boxed{2**100}", [2, 100], 0) == 0.0  # ** banned
    assert verify_countdown("\\boxed{1/0}", [1, 0], 1) == 0.0
    assert verify_countdown("\\boxed{not valid (}", [1], 1) == 0.0


def test_reward_fn_and_env():
    r = countdown_reward_fn(
        "p", "\\boxed{3*7}", [], [], numbers=[3, 7, 2], target=21
    )
    assert r == 1.0

    async def go():
        async with CountdownEnv([3, 7, 2], 21) as env:
            _, reward, done = await env.aexecute_tool(
                "verify_answer", {"completion": "\\boxed{3*7}"}
            )
            return reward, done

    reward, done = asyncio.run(go())
    assert reward == 1.0 and done


def test_dataset_solvable_by_construction():
    ds = make_countdown_dataset(16, seed=1)
    assert len(ds) == 16
    for row in ds:
        assert 0 < row["target"] <= 10_000
        assert len(row["numbers"]) == 4
        assert str(row["numbers"]) in row["messages"][0]["content"]
