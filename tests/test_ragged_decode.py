"""Ragged paged-decode attention kernel (ISSUE 19) — bit-exactness and
collapse tests.

The contract under test: routing decode AND speculative verification
through the fused Pallas kernel (`ops/ragged_decode.py`, per-slot paged
KV gather + fused append + exact dense-order softmax) changes NOTHING
about the emitted streams — tokens and logprobs bit-identical to the
dense tiered path at any temperature — while the per-tier dispatch
fan-out collapses to ONE program per step.  Covers: kernel-vs-dense unit
parity (dtypes, softcap, tail page, verify tile with dropped positions),
engine-level stream parity (greedy + sampled x spec on/off), dispatch
collapse, mid-generation migration parity, a host-DRAM round trip, a
cross-engine disagg handoff,
rejected-draft KV hygiene through the kernel's fused writes, and the
compile-signature soak against the checked-in `ragged_decode` budget.

The dense references here are JITTED: XLA strength-reduces `x / const`
to `x * (1/const)` under jit (and the Pallas interpreter matches that),
so only jit-vs-jit comparison is meaningful — every engine path is
jitted anyway (docs/perf.md Round 13 forensics).
"""

import functools

import numpy as np
import pytest

from areal_tpu.gen.engine import GenEngine, GenRequest
from areal_tpu.models import init_params
from areal_tpu.models.model_config import tiny_config
from areal_tpu.ops.attention import naive_attention
from areal_tpu.ops.ragged_decode import ragged_paged_attention, ragged_supported
from tests.test_spec_decode import _rep_prompt
from tests.test_tiered_decode import _signature_budget


@pytest.fixture(scope="module")
def setup():
    import jax

    cfg = tiny_config(vocab_size=97, qkv_bias=True,
                      hf_architecture="Qwen2ForCausalLM", eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **kw):
    base = dict(n_slots=4, max_seq_len=256, prompt_bucket=16,
                kv_dtype="float32", reuse_min_tokens=4, seed=3)
    base.update(kw)
    return GenEngine(cfg, params=params, **base)


def _run(eng, reqs):
    eng.generate_blocking(reqs)
    return [(tuple(r.output_tokens), tuple(r.output_logprobs), r.stop_reason)
            for r in reqs]


def _mixed_reqs(rng, temperature, repetitive=False):
    """Mixed lengths/budgets; repetitive prompts when spec drafting should
    actually fire (prompt-lookup n-gram hits)."""
    specs = [(10, 6, 1.0), (24, 30, 0.9), (7, 12, 1.0), (40, 9, 1.0)]
    reqs = []
    for i, (n, m, tp) in enumerate(specs):
        ids = (_rep_prompt(rng, max(2, n // 4), n) if repetitive and i % 2
               else rng.integers(0, 97, n).tolist())
        reqs.append(GenRequest(rid=f"r{i}", input_ids=ids, max_new_tokens=m,
                               temperature=temperature, top_p=tp))
    return reqs


# ---------------------------------------------------------------------------
# kernel unit parity (vs the JITTED dense set->take->attention sequence)
# ---------------------------------------------------------------------------


def _oracle(q, k_new, v_new, ck, cv, rows, widx, mask, *, K, softcap):
    """The dense path's exact op order from forward_decode/forward_verify:
    scatter-append (drop at index M), row gather, bucketed
    naive_attention."""
    import jax.numpy as jnp

    ck = ck.at[rows[:, None], widx].set(k_new.astype(ck.dtype), mode="drop")
    cv = cv.at[rows[:, None], widx].set(v_new.astype(cv.dtype), mode="drop")
    ckr = jnp.take(ck, rows, axis=0)[:, :K].astype(q.dtype)
    cvr = jnp.take(cv, rows, axis=0)[:, :K].astype(q.dtype)
    out = naive_attention(q, ckr, cvr, mask[:, None], softcap)
    return out, ck, cv


def _case(seed, *, B=4, T=1, K=32, page=16, M=64, Hq=4, Hkv=2, hd=8,
          qdtype="float32", kvdtype="float32", softcap=None):
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    S = B + 1
    lengths = rng.integers(0, K - T, B).astype(np.int32)
    rows = rng.permutation(S)[:B].astype(np.int32)
    ck = rng.standard_normal((S, M, Hkv, hd)).astype(kvdtype)
    cv = rng.standard_normal((S, M, Hkv, hd)).astype(kvdtype)
    q = rng.standard_normal((B, T, Hq, hd)).astype(qdtype)
    # pre-cast through the cache dtype — the dense path's write-then-read
    # round trip, reproduced by the caller (models/transformer.py)
    k_new = rng.standard_normal((B, T, Hkv, hd)).astype(qdtype).astype(kvdtype)
    v_new = rng.standard_normal((B, T, Hkv, hd)).astype(qdtype).astype(kvdtype)
    # verify-style widx: position len+t, with the tile's tail positions
    # dropped for one slot (a short draft's padding) via the M sentinel
    widx = lengths[:, None] + np.arange(T, dtype=np.int32)[None, :]
    if T > 1:
        widx[0, -1] = M  # dropped padding position
    key_pos = np.arange(K, dtype=np.int32)
    mask = (key_pos[None, None, :]
            <= (lengths[:, None] + np.arange(T, dtype=np.int32)[None, :])[
                :, :, None])

    kern = jax.jit(functools.partial(
        ragged_paged_attention, key_window=K, page_size=page,
        logit_softcap=softcap,
    ))
    ref = jax.jit(functools.partial(_oracle, K=K, softcap=softcap))
    args = (jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
            jnp.asarray(ck), jnp.asarray(cv), jnp.asarray(rows),
            jnp.asarray(lengths), jnp.asarray(widx), jnp.asarray(mask))
    got = kern(*args)
    want = ref(args[0], args[1], args[2], args[3], args[4], args[5],
               args[7], args[8])
    return got, want


@pytest.mark.parametrize("case", [
    dict(),                                      # f32, page-aligned K
    dict(softcap=30.0),                          # softcapped logits
    dict(K=40, page=16),                         # static tail page
    dict(qdtype="bfloat16", kvdtype="bfloat16"),  # low-precision
    dict(qdtype="float32", kvdtype="bfloat16"),  # mixed compute/cache
    dict(T=4, K=48),                             # verify tile + dropped pos
])
def test_kernel_matches_dense_bitwise(case):
    """Kernel output AND in-place cache writes equal the dense sequence
    bit-for-bit — including the masked tail, the softcap, non-page-aligned
    K, low/mixed precision, and a wide verify tile with a scatter-dropped
    padding position."""
    got, want = _case(7, **case)
    for g, w, name in zip(got, want, ("out", "ck", "cv")):
        g, w = np.asarray(g), np.asarray(w)
        assert g.tobytes() == w.tobytes(), (
            f"{name} diverges: max|d|={np.abs(g.astype(np.float64) - w.astype(np.float64)).max()}"
        )


def test_ragged_supported_gate():
    """The VMEM gate: small windows fit, a window whose 2*K*Hkv*hd scratch
    exceeds the budget does not; tp shards the kv heads down."""
    assert ragged_supported(256, 2, 64, 4)
    assert not ragged_supported(1 << 20, 8, 128, 4)
    # tp=8 divides the per-shard scratch by 8 — the same window fits again
    assert ragged_supported(4096, 8, 128, 4, tp=8) or not ragged_supported(
        4096, 1, 128, 4
    )


# ---------------------------------------------------------------------------
# engine-level stream parity + dispatch collapse
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("temperature", [0.0, 1.0])
def test_ragged_matches_dense_streams(setup, temperature):
    """The same mixed-length workload through the dense tiered path and
    the collapsed ragged path emits identical token AND logprob streams —
    greedy and sampled, spec decode off and on — while the ragged engine
    issues strictly fewer decode+verify dispatches (the tier fan-out is
    gone)."""
    cfg, params = setup
    for spec in (False, True):
        outs, engs = [], []
        for ragged in (False, True):
            rng = np.random.default_rng(11)
            eng = _engine(cfg, params, decode_tiers=2, spec_decode=spec,
                          ragged_attn=ragged)
            outs.append(_run(eng, _mixed_reqs(rng, temperature, spec)))
            engs.append(eng)
        assert outs[0] == outs[1], f"stream diverged (spec={spec})"
        dense, ragged = engs
        assert ragged._ragged_ok
        assert ragged.stats["ragged_dispatches"] > 0
        assert ragged.stats["ragged_attended_pages"] > 0
        assert dense.stats["ragged_dispatches"] == 0
        # dispatch collapse: equal streams, strictly fewer programs run
        assert (ragged.stats["decode_calls"] + ragged.stats["verify_calls"]
                < dense.stats["decode_calls"] + dense.stats["verify_calls"])


def test_ragged_migration_parity(setup):
    """A mid-generation tier migration (device-side cache-row remap) under
    the ragged kernel still matches the untiered dense engine bit for bit
    — the kernel reads through the page table, so a remap is invisible to
    it."""
    cfg, params = setup

    def reqs_for(rng):
        blockers = [
            GenRequest(rid=f"b{i}",
                       input_ids=rng.integers(0, 97, 30).tolist(),
                       max_new_tokens=40, temperature=1.0)
            for i in range(2)
        ]
        mover = GenRequest(rid="mover",
                           input_ids=rng.integers(0, 97, 40).tolist(),
                           max_new_tokens=60, temperature=1.0)
        return blockers + [mover]

    ragged = _engine(cfg, params, decode_tier_lens=[64, 256],
                     decode_tier_slots=[2, 2], decode_chunk=4,
                     ragged_attn=True)
    rng = np.random.default_rng(21)
    r_out = _run(ragged, reqs_for(rng))
    assert ragged.stats["tier_migrations"] >= 1, ragged.stats
    assert ragged.stats["ragged_dispatches"] > 0

    dense = _engine(cfg, params, decode_tiers=1, decode_chunk=4)
    rng = np.random.default_rng(21)
    d_out = _run(dense, reqs_for(rng))
    assert r_out == d_out


def test_ragged_host_roundtrip_parity(setup):
    """A retained prefix spilled to host DRAM and swapped back continues
    its stream bit-identically under the ragged kernel — counter-keyed
    sampling depends on (stream, position), never on cache placement or
    the attention kernel."""
    cfg, params = setup
    rng = np.random.default_rng(25)
    turn1 = rng.integers(0, 97, 24).tolist()
    fills = [
        {"rid": f"fill-{i}",
         "ids": np.random.default_rng(26 + i).integers(0, 97, 20).tolist(),
         "n": 4}
        for i in range(2)
    ]

    outs = []
    for ragged in (False, True):
        eng = _engine(cfg, params, n_slots=2, max_seq_len=128,
                      host_offload=True, host_cache_mb=8,
                      host_min_tokens=8, ragged_attn=ragged)
        r1 = GenRequest(rid="t1", input_ids=list(turn1), max_new_tokens=6,
                        temperature=1.0, top_p=0.9)
        eng.generate_blocking([r1])
        transcript = turn1 + r1.output_tokens
        batches = [fills, [{"rid": "t2", "ids": transcript, "n": 6,
                            "temp": 1.0}]]
        done = []
        for batch in batches:
            rs = [GenRequest(rid=r["rid"], input_ids=list(r["ids"]),
                             max_new_tokens=r["n"],
                             temperature=r.get("temp", 0.0))
                  for r in batch]
            eng.generate_blocking(rs)
            done.extend(rs)
        assert eng.stats["prefix_cache_host_swaps"] >= 2, eng.stats
        outs.append((r1.output_tokens, done[-1].output_tokens,
                     done[-1].output_logprobs))
    assert outs[0] == outs[1]


def test_ragged_disagg_handoff_parity(setup):
    """A disagg handoff under the ragged kernel — leg 1 on a 'prefill'
    engine, wire export/import, leg 2 on a 'decode' engine — continues
    the stream bit-identically to the DENSE colocated control: the wire
    carries pages, the kernel reads through the page table, and counter-
    keyed sampling never sees the boundary (or the kernel swap)."""
    from areal_tpu.gen import kv_pool

    cfg, params = setup
    rng = np.random.default_rng(47)
    prompt = rng.integers(0, 97, 27).tolist()
    leg1_n, total, sid = 3, 9, 77

    def leg(eng, ids, n):
        r = GenRequest(rid=f"leg-{len(ids)}", input_ids=list(ids),
                       max_new_tokens=n, temperature=1.0, top_p=0.9,
                       stream_id=sid)
        eng.generate_blocking([r])
        return r

    # dense colocated control: both legs on one engine
    ctl = _engine(cfg, params, n_slots=2, max_seq_len=128)
    c1 = leg(ctl, prompt, leg1_n)
    c2 = leg(ctl, prompt + c1.output_tokens, total - leg1_n)

    # ragged disaggregated: leg 1 on A, wire transfer, leg 2 on B
    ea = _engine(cfg, params, n_slots=2, max_seq_len=128, ragged_attn=True)
    eb = _engine(cfg, params, n_slots=2, max_seq_len=128, ragged_attn=True,
                 host_offload=True, host_cache_mb=8, host_min_tokens=8)
    a1 = leg(ea, prompt, leg1_n)
    assert (a1.output_tokens, a1.output_logprobs) == (
        c1.output_tokens, c1.output_logprobs)
    full = prompt + a1.output_tokens
    doc = kv_pool.wire_encode_entry(ea.export_request_kv(full))
    assert eb.import_request_kv(kv_pool.wire_decode_entry(doc)) is True
    b2 = leg(eb, full, total - leg1_n)
    assert b2.cache_hit_tokens > 0  # warm continuation, not a cold prefill
    assert eb.stats["ragged_dispatches"] > 0
    assert (b2.output_tokens, b2.output_logprobs) == (
        c2.output_tokens, c2.output_logprobs)


def test_ragged_rejected_draft_kv_never_persists(setup):
    """KV hygiene through the kernel's FUSED writes: the verify dispatch
    appends draft K/V inside the kernel, and the engine's rejected-draft
    zeroing must still leave every cache row at or above a live slot's
    frontier all-zero at each step boundary."""
    cfg, params = setup
    eng = _engine(cfg, params, spec_decode=True, decode_chunk=4,
                  ragged_attn=True)
    rng = np.random.default_rng(5)
    req = GenRequest(rid="kv", input_ids=_rep_prompt(rng, 5, 16),
                     max_new_tokens=96, temperature=1.0)
    eng.submit(req)
    while not req.stop_reason:
        eng.step(chunk=4)
        s = next((i for i in range(eng.n_slots) if eng.slot_req[i] is req),
                 None)
        if s is None:
            continue
        row = eng.pool.row(s)
        frontier = int(eng.lengths[s])
        for name in ("k", "v"):
            tail = np.asarray(eng.cache[name])[:, row, frontier:]
            assert not np.any(tail), (
                f"{name}-cache rows >= frontier {frontier} nonzero after a "
                f"ragged verify dispatch (rejected draft KV leaked)"
            )
    assert eng.stats["spec_drafted"] > eng.stats["spec_accepted"]
    assert eng.stats["ragged_dispatches"] > 0


def test_ragged_compile_signature_soak(setup):
    """Steady-state ragged traffic stays on the (K bucket, D rung)
    lattice: ONE program family for the whole grid (no tier axis), zero
    mints after warmup, and the decode+verify program count within the
    checked-in `ragged_decode` budget."""
    cfg, params = setup
    eng = _engine(cfg, params, decode_tiers=2, decode_chunk=4,
                  spec_decode=True, ragged_attn=True)
    rng = np.random.default_rng(31)

    def wave(tag):
        reqs = []
        for i, (n, m) in enumerate([(8, 10), (20, 25), (40, 40), (60, 30)]):
            ids = (_rep_prompt(rng, max(2, n // 4), n) if i % 2 == 0
                   else rng.integers(0, 97, n).tolist())
            reqs.append(GenRequest(rid=f"{tag}{i}", input_ids=ids,
                                   max_new_tokens=m, temperature=1.0))
        eng.generate_blocking(reqs)

    # deterministic ladder sweep FIRST: the collapsed grid keys its K
    # bucket on the max span over ALL active slots, so which rung a
    # random wave first crosses is acceptance-dependent — saturate the
    # whole reachable (K bucket x {decode, D rung}) lattice up front by
    # walking one request per rung (random content = plain decode;
    # repetitive = drafting verify, whose span crosses every lower rung
    # as it grows), then mixed waves for the grid-packing interactions
    for L in (8, 24, 56, 120, 200):
        for rep in (False, True):
            ids = (_rep_prompt(rng, 4, L) if rep
                   else rng.integers(0, 97, L).tolist())
            eng.generate_blocking([GenRequest(
                rid=f"sweep{L}{'r' if rep else 'd'}", input_ids=ids,
                max_new_tokens=min(40, 250 - L), temperature=1.0,
            )])
    wave("warm0")
    wave("warm1")
    sizes = {
        "decode": eng._decode_fn._cache_size(),
        "verify": eng._verify_fn._cache_size(),
        "prefill": eng._prefill_fn._cache_size(),
    }
    for w in range(3):
        wave(f"soak{w}")
    assert eng._decode_fn._cache_size() == sizes["decode"]
    assert eng._prefill_fn._cache_size() == sizes["prefill"]
    assert eng.stats["ragged_dispatches"] > 0

    ref = _signature_budget("ragged_decode_soak")
    assert ref["config"] == {"n_slots": 4, "max_seq_len": 256,
                             "prompt_bucket": 16, "decode_tiers": 2,
                             "spec_rungs": 2, "ragged": 1}
    # the collapsed family: decode programs (one per K bucket) + verify
    # programs (one per K bucket x nonzero D rung), tier factor gone
    assert (eng._decode_fn._cache_size() + eng._verify_fn._cache_size()
            <= ref["budgets"]["ragged_decode"])
