from areal_tpu.scheduler.rpc_client import RPCEngineClient
from areal_tpu.scheduler.rpc_server import EngineRPCServer, serve_engine

__all__ = ["EngineRPCServer", "RPCEngineClient", "serve_engine"]
