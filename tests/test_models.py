"""Model numerics: parity vs HF transformers (torch CPU) and packed-vs-padded
consistency (ports the reference's test strategy:
areal/tests/test_packed_vs_padded_consistency.py and
realhf/tests/model/test_cpu_inference.py)."""

import json

import numpy as np
import pytest

from areal_tpu.models import TransformerConfig, forward, init_params
from areal_tpu.models.hf import load_hf_params, save_hf_checkpoint
from areal_tpu.models.model_config import tiny_config
from areal_tpu.utils.data import pack_tensor_dict


def _hf_tiny(arch: str, tmp_path, tie=False):
    import torch
    import transformers

    common = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        tie_word_embeddings=tie,
        torch_dtype="float32",
    )
    if arch == "qwen2":
        hf_cfg = transformers.Qwen2Config(**common)
        model = transformers.Qwen2ForCausalLM(hf_cfg)
    elif arch == "qwen3":
        hf_cfg = transformers.Qwen3Config(**common, head_dim=16)
        model = transformers.Qwen3ForCausalLM(hf_cfg)
    elif arch == "llama":
        hf_cfg = transformers.LlamaConfig(**common)
        model = transformers.LlamaForCausalLM(hf_cfg)
    elif arch == "gemma":
        hf_cfg = transformers.GemmaConfig(**common, head_dim=16)
        model = transformers.GemmaForCausalLM(hf_cfg)
    elif arch == "gpt2":
        hf_cfg = transformers.GPT2Config(
            vocab_size=256,
            n_embd=64,
            n_layer=2,
            n_head=4,
            n_positions=256,
            torch_dtype="float32",
        )
        model = transformers.GPT2LMHeadModel(hf_cfg)
    elif arch == "gemma2":
        # small sliding window so a 17-token input exercises the
        # alternating local/global layers; eager attn so torch actually
        # applies the logit softcaps (sdpa drops them)
        hf_cfg = transformers.Gemma2Config(
            **common,
            head_dim=16,
            query_pre_attn_scalar=16.0,
            attn_logit_softcapping=50.0,
            final_logit_softcapping=30.0,
            sliding_window=8,
            attn_implementation="eager",
        )
        model = transformers.Gemma2ForCausalLM(hf_cfg)
    else:
        raise ValueError(arch)
    model = model.eval().to(torch.float32)
    out_dir = tmp_path / arch
    model.save_pretrained(out_dir, safe_serialization=True)
    return model, str(out_dir)


@pytest.mark.parametrize(
    "arch", ["qwen2", "llama", "qwen3", "gemma", "gemma2", "gpt2"]
)
def test_hf_parity(arch, tmp_path):
    import torch

    model, ckpt = _hf_tiny(arch, tmp_path)
    params, cfg = load_hf_params(ckpt)
    cfg = cfg.replace(dtype="float32", remat=False)

    rng = np.random.default_rng(0)
    B, L = 2, 17
    ids = rng.integers(0, cfg.vocab_size, (B, L)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(ids).long()).logits.numpy()

    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (B, L))
    seg = np.broadcast_to(np.arange(B, dtype=np.int32)[:, None], (B, L))
    got = np.asarray(forward(params, cfg, ids, pos, seg))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_packed_vs_padded_consistency():
    import jax

    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    lens = [5, 9, 3]
    B, L = len(lens), max(lens)
    ids = np.zeros((B, L), np.int32)
    mask = np.zeros((B, L), bool)
    for i, n in enumerate(lens):
        ids[i, :n] = rng.integers(0, cfg.vocab_size, n)
        mask[i, :n] = True

    # padded forward
    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (B, L)).copy()
    seg = np.where(mask, np.arange(B, dtype=np.int32)[:, None], -1).astype(np.int32)
    padded_logits = np.asarray(forward(params, cfg, ids, pos, seg))

    # packed forward with bucket padding
    packed = pack_tensor_dict({"input_ids": ids, "attention_mask": mask}, pad_to=32)
    logits = np.asarray(
        forward(
            params,
            cfg,
            packed["input_ids"][None],
            packed["positions"][None],
            packed["segment_ids"][None],
        )
    )[0]
    cu = packed["cu_seqlens"]
    for i, n in enumerate(lens):
        np.testing.assert_allclose(
            logits[cu[i] : cu[i] + n], padded_logits[i, :n], rtol=1e-5, atol=1e-5
        )


def test_sequences_independent_in_pack():
    """A sequence's logits don't change based on what it is packed with."""
    import jax

    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(2)
    a = rng.integers(0, cfg.vocab_size, 6).astype(np.int32)
    b = rng.integers(0, cfg.vocab_size, 4).astype(np.int32)

    def run_packed(seqs, pad_to):
        ids = np.concatenate(seqs)
        seg = np.concatenate([np.full(len(s), i, np.int32) for i, s in enumerate(seqs)])
        pos = np.concatenate([np.arange(len(s), dtype=np.int32) for s in seqs])
        extra = pad_to - len(ids)
        ids = np.pad(ids, (0, extra))
        seg = np.pad(seg, (0, extra), constant_values=-1)
        pos = np.pad(pos, (0, extra))
        return np.asarray(forward(params, cfg, ids[None], pos[None], seg[None]))[0]

    both = run_packed([a, b], 16)
    alone = run_packed([a], 16)
    np.testing.assert_allclose(both[: len(a)], alone[: len(a)], rtol=1e-5, atol=1e-5)


def test_gemma2_roundtrip_and_transformers_reload(tmp_path):
    """gemma2's renamed sandwich norms + softcap fields survive
    save -> transformers reload with identical logits."""
    import torch
    import transformers

    model, ckpt = _hf_tiny("gemma2", tmp_path)
    params, cfg = load_hf_params(ckpt)
    cfg = cfg.replace(dtype="float32", remat=False)

    rt = tmp_path / "rt"
    save_hf_checkpoint(params, cfg, str(rt), save_dtype="float32")
    with open(rt / "config.json") as f:
        d = json.load(f)
    assert d["model_type"] == "gemma2"
    assert d["layer_types"] == ["sliding_attention", "full_attention"]

    reloaded = (
        transformers.Gemma2ForCausalLM.from_pretrained(
            str(rt), attn_implementation="eager"
        )
        .eval()
        .to(torch.float32)
    )
    rng = np.random.default_rng(5)
    B, L = 2, 17
    ids = rng.integers(0, cfg.vocab_size, (B, L)).astype(np.int32)
    with torch.no_grad():
        ref = reloaded(torch.from_numpy(ids).long()).logits.numpy()
    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (B, L))
    seg = np.broadcast_to(np.arange(B, dtype=np.int32)[:, None], (B, L))
    got = np.asarray(forward(params, cfg, ids, pos, seg))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_gpt2_roundtrip_and_transformers_reload(tmp_path):
    """gpt2's dialect (transformer.* names, fused c_attn, Conv1D layout,
    LayerNorm biases, learned positions) survives save -> transformers
    reload with identical logits."""
    import torch
    import transformers

    model, ckpt = _hf_tiny("gpt2", tmp_path)
    params, cfg = load_hf_params(ckpt)
    cfg = cfg.replace(dtype="float32", remat=False)
    assert cfg.norm_type == "layernorm" and cfg.pos_emb == "learned"

    rt = tmp_path / "rt"
    save_hf_checkpoint(params, cfg, str(rt), save_dtype="float32")
    with open(rt / "config.json") as f:
        assert json.load(f)["model_type"] == "gpt2"
    reloaded = (
        transformers.GPT2LMHeadModel.from_pretrained(str(rt))
        .eval()
        .to(torch.float32)
    )
    rng = np.random.default_rng(6)
    B, L = 2, 17
    ids = rng.integers(0, cfg.vocab_size, (B, L)).astype(np.int32)
    with torch.no_grad():
        ref = reloaded(torch.from_numpy(ids).long()).logits.numpy()
    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (B, L))
    seg = np.broadcast_to(np.arange(B, dtype=np.int32)[:, None], (B, L))
    got = np.asarray(forward(params, cfg, ids, pos, seg))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_save_roundtrip_and_transformers_reload(tmp_path):
    import jax
    import torch
    import transformers

    cfg = tiny_config(
        vocab_size=256, qkv_bias=True, hf_architecture="Qwen2ForCausalLM"
    )
    params = init_params(cfg, jax.random.PRNGKey(3))
    out = tmp_path / "ckpt"
    save_hf_checkpoint(params, cfg, str(out), save_dtype="float32")

    with open(out / "config.json") as f:
        d = json.load(f)
    assert d["architectures"] == ["Qwen2ForCausalLM"]

    # our loader roundtrip
    params2, cfg2 = load_hf_params(str(out))
    for p1, p2 in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params2)
    ):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), rtol=1e-6, atol=1e-6)

    # transformers can load it and agrees on logits
    model = transformers.AutoModelForCausalLM.from_pretrained(
        str(out), torch_dtype=torch.float32
    ).eval()
    ids = np.arange(10, dtype=np.int32)[None] % cfg.vocab_size
    with torch.no_grad():
        ref = model(torch.from_numpy(ids).long()).logits.numpy()
    pos = np.arange(10, dtype=np.int32)[None]
    seg = np.zeros((1, 10), np.int32)
    got = np.asarray(forward(params, cfg, ids, pos, seg))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_mistral_hf_parity(tmp_path):
    import torch
    import transformers

    hf_cfg = transformers.MistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, sliding_window=8,
        torch_dtype="float32",
    )
    model = transformers.MistralForCausalLM(hf_cfg).eval().to(torch.float32)
    out_dir = tmp_path / "mistral"
    model.save_pretrained(out_dir, safe_serialization=True)
    params, cfg = load_hf_params(str(out_dir))
    assert cfg.sliding_window == 8
    cfg = cfg.replace(dtype="float32", remat=False)
    rng = np.random.default_rng(3)
    B, L = 2, 17
    ids = rng.integers(0, cfg.vocab_size, (B, L)).astype(np.int32)
    import torch as _t

    with _t.no_grad():
        ref = model(_t.from_numpy(ids).long()).logits.numpy()
    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (B, L))
    seg = np.broadcast_to(np.arange(B, dtype=np.int32)[:, None], (B, L))
    got = np.asarray(forward(params, cfg, ids, pos, seg))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_qwen3_moe_hf_parity_and_roundtrip(tmp_path):
    """MoE checkpoints load from the REAL HF layout (mlp.experts.N.*_proj +
    mlp.gate router), match transformers numerically at the loader's
    DEFAULT impl (dropless — no capacity override needed, ADVICE r3), and
    round-trip through our saver."""
    import torch
    import transformers

    from areal_tpu.models.hf import save_hf_checkpoint

    hf_cfg = transformers.Qwen3MoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        norm_topk_prob=True, mlp_only_layers=[], decoder_sparse_step=1,
        torch_dtype="float32",
    )
    torch.manual_seed(0)
    model = transformers.Qwen3MoeForCausalLM(hf_cfg).eval().to(torch.float32)
    out_dir = tmp_path / "qwen3moe"
    model.save_pretrained(out_dir, safe_serialization=True)

    params, cfg = load_hf_params(str(out_dir))
    assert cfg.num_experts == 4 and cfg.moe_intermediate_size == 32
    assert params["layers"]["moe"]["w_gate"].shape == (2, 4, 64, 32)
    # HF checkpoints default to the dropless impl: parity holds at any
    # batch size with no capacity tuning
    assert cfg.moe_impl == "dropless"
    cfg = cfg.replace(dtype="float32", remat=False)

    rng = np.random.default_rng(4)
    B, L = 2, 17
    ids = rng.integers(0, cfg.vocab_size, (B, L)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(ids).long()).logits.numpy()
    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (B, L))
    seg = np.broadcast_to(np.arange(B, dtype=np.int32)[:, None], (B, L))
    got = np.asarray(forward(params, cfg, ids, pos, seg))
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)

    # round-trip: our saver emits the same HF names transformers reads
    rt = tmp_path / "rt"
    save_hf_checkpoint(params, cfg, str(rt), save_dtype="float32")
    params2, cfg2 = load_hf_params(str(rt))
    assert cfg2.num_experts == 4
    import jax

    flat1 = jax.tree_util.tree_leaves_with_path(params)
    flat2 = dict(jax.tree_util.tree_leaves_with_path(params2))
    for key, v1 in flat1:
        np.testing.assert_allclose(
            np.asarray(v1), np.asarray(flat2[key]), rtol=1e-6, err_msg=str(key)
        )
    reloaded = transformers.Qwen3MoeForCausalLM.from_pretrained(str(rt))
    with torch.no_grad():
        ref2 = reloaded(torch.from_numpy(ids).long()).logits.numpy()
    np.testing.assert_allclose(ref2, ref, rtol=2e-4, atol=2e-4)


def test_legacy_gemma_act_parity(tmp_path):
    """Legacy gemma-1 configs carry hidden_act='gelu' with no
    hidden_activation key. transformers>=4.57 GemmaMLP runs
    ACT2FN[config.hidden_act] verbatim (the old runtime tanh override is
    gone), so from_hf must NOT coerce — pin end-to-end forward parity on
    exactly that config shape so a future transformers flip fails loudly."""
    import torch
    import transformers

    model, ckpt = _hf_tiny("gemma", tmp_path)
    # rewrite config.json into the legacy gemma-1 shape
    p = ckpt + "/config.json"
    d = json.loads(open(p).read())
    d.pop("hidden_activation", None)
    d["hidden_act"] = "gelu"
    open(p, "w").write(json.dumps(d))

    # what does the installed transformers actually run for this config?
    reloaded = transformers.GemmaForCausalLM.from_pretrained(ckpt).eval()
    act_name = type(reloaded.model.layers[0].mlp.act_fn).__name__

    params, cfg = load_hf_params(ckpt)
    assert (cfg.hidden_act == "gelu") == (act_name == "GELUActivation"), (
        cfg.hidden_act, act_name,
    )
    cfg = cfg.replace(dtype="float32", remat=False)
    rng = np.random.default_rng(7)
    ids = rng.integers(0, cfg.vocab_size, (2, 17)).astype(np.int32)
    with torch.no_grad():
        ref = reloaded(torch.from_numpy(ids).long()).logits.numpy()
    pos = np.broadcast_to(np.arange(17, dtype=np.int32), (2, 17))
    seg = np.broadcast_to(np.arange(2, dtype=np.int32)[:, None], (2, 17))
    got = np.asarray(forward(params, cfg, ids, pos, seg))
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)

    # both-keys divergent case (HF's transitional gemma-1 config shape):
    # GemmaMLP ignores hidden_activation, so hidden_act must win
    d["hidden_activation"] = "gelu_pytorch_tanh"
    open(p, "w").write(json.dumps(d))
    reloaded = transformers.GemmaForCausalLM.from_pretrained(ckpt).eval()
    act_name = type(reloaded.model.layers[0].mlp.act_fn).__name__
    _, cfg = load_hf_params(ckpt)
    assert (cfg.hidden_act == "gelu") == (act_name == "GELUActivation"), (
        cfg.hidden_act, act_name,
    )

    # hidden_activation-only gemma-1: GemmaConfig leaves hidden_act at its
    # default, so HF runs tanh — hidden_activation must be ignored
    del d["hidden_act"]
    d["hidden_activation"] = "gelu"
    open(p, "w").write(json.dumps(d))
    reloaded = transformers.GemmaForCausalLM.from_pretrained(ckpt).eval()
    act_name = type(reloaded.model.layers[0].mlp.act_fn).__name__
    _, cfg = load_hf_params(ckpt)
    assert (cfg.hidden_act == "gelu_pytorch_tanh") == (act_name == "GELUTanh"), (
        cfg.hidden_act, act_name,
    )

    # inverse for gemma-2: Gemma2MLP reads config.hidden_activation, which
    # defaults to tanh even when a config carries only hidden_act
    cfg2 = TransformerConfig.from_hf(dict(
        architectures=["Gemma2ForCausalLM"], model_type="gemma2",
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, hidden_act="gelu",
    ))
    assert cfg2.hidden_act == "gelu_pytorch_tanh"
