"""Benchmark: trainer effective token throughput on one real TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

Workload: Qwen2.5-1.5B shapes (the reference's small benchmark model class,
BASELINE.md "1.5B R1-Distill"), bf16 params/optimizer, GRPO decoupled-loss
train step over packed rows — the same fused scan step the real training
loop runs, measured steady-state.

Baseline (vs_baseline denominator): the reference's *effective trainer
throughput per chip* derived from its published numbers (BASELINE.md):
1.5B async run, 1000 PPO steps in 14.8 h on 128 H800s, benchmark workload
512 prompts x 16 samples with ~8k mean tokens per trajectory
=> 512*16*8192 tokens / 53.3 s / 128 chips ~= 9.8k tokens/sec/chip.
This is an estimate (the reference publishes wall-clock, not tok/s/chip);
it is held fixed across rounds so the trend is comparable.
"""

import json
import time

import numpy as np

BASELINE_TOKENS_PER_SEC_PER_CHIP = 9800.0

MODEL = "qwen25_1p5b"
ROW_LEN = 2048
N_ROWS = 2
N_MBS = 1
WARMUP_STEPS = 2
MEASURE_STEPS = 5


def _make_batch(rng, n_rows, row_len, vocab):
    """Two packed sequences per row, loss on the latter 75% (completion)."""
    seqs_per_row = 2
    seq_len = row_len // seqs_per_row
    B = n_rows * seqs_per_row
    ids = rng.integers(0, vocab, (B, seq_len)).astype(np.int32)
    mask = np.ones((B, seq_len), bool)
    prompt = seq_len // 4
    loss_mask = np.zeros((B, seq_len), np.float32)
    loss_mask[:, prompt:] = 1.0
    return {
        "input_ids": ids,
        "attention_mask": mask,
        "loss_mask": loss_mask,
        "logprobs": rng.normal(-1.0, 0.1, (B, seq_len)).astype(np.float32),
        "rewards": rng.integers(0, 2, B).astype(np.float32),
        "versions": np.zeros((B, seq_len), np.int32),
    }


def _run(model_cfg, model_name, n_rows):
    import jax

    from areal_tpu.api.config import (
        MeshConfig,
        MicroBatchSpec,
        NormConfig,
        OptimizerConfig,
        PPOActorConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.ppo import JaxPPOActor

    cfg = PPOActorConfig(
        experiment_name="bench",
        trial_name="bench",
        init_from_scratch=True,
        dtype="bfloat16",
        # bf16 master+optimizer: a 1.5B fp32 AdamW state does not fit one
        # 16G chip; throughput is what's measured here
        param_dtype="bfloat16",
        gradient_checkpointing=True,
        mesh=MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=N_MBS),
        optimizer=OptimizerConfig(lr=1e-5, warmup_steps_proportion=0.0),
        pack_length_quantum=ROW_LEN,
        max_pack_length=ROW_LEN,
        group_size=2,
        ppo_n_minibatches=1,
        use_decoupled_loss=True,
        adv_norm=NormConfig(mean_level="group", std_level="group", group_size=2),
    )
    actor = JaxPPOActor(cfg, model_config=model_cfg)
    actor.initialize(ft_spec=FinetuneSpec(1, 1024, 8))

    rng = np.random.default_rng(0)
    batch = _make_batch(rng, n_rows, ROW_LEN, model_cfg.vocab_size)
    batch["prox_logp"] = batch["logprobs"].copy()
    actor.compute_advantages(batch)

    tokens_per_step = int(batch["attention_mask"].sum())
    for _ in range(WARMUP_STEPS):
        actor.ppo_update(batch)
    jax.block_until_ready(actor.params)
    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        actor.ppo_update(batch)
    jax.block_until_ready(actor.params)
    dt = (time.perf_counter() - t0) / MEASURE_STEPS

    tok_per_sec = tokens_per_step / dt
    return {
        "metric": f"grpo_train_step_throughput_{model_name}_bf16_ctx{ROW_LEN}",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tok_per_sec / BASELINE_TOKENS_PER_SEC_PER_CHIP, 3),
    }


def main():
    import sys

    from areal_tpu.models.model_config import qwen25_1p5b

    # largest workload that fits the local chip wins; HBM varies by TPU gen
    ladder = [
        (qwen25_1p5b(), "qwen25_1p5b", 2),
        (qwen25_1p5b(), "qwen25_1p5b", 1),
        (qwen25_1p5b().replace(num_layers=14), "qwen25_1p5b_half_depth", 1),
    ]
    last_err = None
    for model_cfg, name, n_rows in ladder:
        try:
            print(json.dumps(_run(model_cfg, name, n_rows)))
            return
        except Exception as e:  # noqa: BLE001 — fall through the ladder on OOM
            last_err = e
            if "RESOURCE_EXHAUSTED" not in str(e):
                raise
            print(f"bench: {name} x{n_rows} rows OOM, trying smaller", file=sys.stderr)
    raise last_err


if __name__ == "__main__":
    main()
