"""Unified radix/paged KV pool (ISSUE 16) — host-side unit tests.

Everything here is pure numpy/Python (no jax, no engine): the radix tree's
exact-lcp contract against a brute-force scan, edge splitting/pruning, the
LRU byte accounting of the host overflow tier, and the page-table
permutation invariant.  The engine-level behavior (paged decode parity,
host-swap round trips) lives in test_paged_cache.py.
"""

import numpy as np
import pytest

from areal_tpu.gen.kv_pool import (
    HostEntry,
    HostOverflowTier,
    KVPool,
    RadixIndex,
    lcp_ids,
)


# ------------------------------ lcp_ids --------------------------------


def test_lcp_ids_basics():
    assert lcp_ids([], []) == 0
    assert lcp_ids([1, 2, 3], []) == 0
    assert lcp_ids([1, 2, 3], [1, 2, 3]) == 3
    assert lcp_ids([1, 2, 3], [1, 2, 4]) == 2
    assert lcp_ids([1, 2], [1, 2, 9, 9]) == 2
    assert lcp_ids([5], [7]) == 0


# ----------------------------- RadixIndex ------------------------------


def _brute_match(entries, ids):
    return {k: lcp_ids(toks, ids) for k, toks in entries.items()}


def test_radix_match_is_exact_lcp_for_every_entry():
    idx = RadixIndex()
    entries = {
        "a": [1, 2, 3, 4],
        "b": [1, 2, 3, 9],
        "c": [1, 2],
        "d": [7, 8],
        "e": [1, 5, 6],
    }
    for k, t in entries.items():
        idx.insert(k, t)
    for query in (
        [1, 2, 3, 4, 5],
        [1, 2, 3],
        [1, 2, 9],
        [7, 8, 8],
        [9],
        [],
        [1],
        [1, 5, 6, 6],
    ):
        assert idx.match(query) == _brute_match(entries, query), query


def test_radix_match_randomized_against_brute_force():
    """The tree must reproduce the old vectorised seq_tokens scan bit for
    bit on adversarial shared-prefix families."""
    rng = np.random.default_rng(0)
    idx = RadixIndex()
    entries = {}
    # families of sequences sharing staggered prefixes (the GRPO/multi-turn
    # shape), over a tiny alphabet to force deep shared paths
    for i in range(60):
        base = rng.integers(0, 4, rng.integers(1, 12)).tolist()
        if entries and rng.random() < 0.6:
            donor = entries[rng.choice(list(entries))]
            cut = int(rng.integers(0, len(donor) + 1))
            base = list(donor[:cut]) + base
        entries[i] = base[:24]
        idx.insert(i, base[:24])
    # random churn: removals keep the tree consistent
    for i in list(entries)[::7]:
        idx.remove(i)
        del entries[i]
    assert len(idx) == len(entries)
    for _ in range(50):
        q = rng.integers(0, 4, rng.integers(0, 20)).tolist()
        assert idx.match(q) == _brute_match(entries, q)


def test_radix_insert_reinsert_and_remove():
    idx = RadixIndex()
    idx.insert("x", [1, 2, 3])
    assert "x" in idx and len(idx) == 1
    assert idx.tokens("x").tolist() == [1, 2, 3]
    # re-insert relocates rather than duplicating
    idx.insert("x", [4, 5])
    assert len(idx) == 1
    assert idx.match([4, 5]) == {"x": 2}
    assert idx.match([1, 2, 3]) == {"x": 0}
    got = idx.remove("x")
    assert got.tolist() == [4, 5]
    assert idx.remove("x") is None
    assert len(idx) == 0
    # fully pruned: the root has no leftover children
    assert not idx.root.children


def test_radix_edge_split_preserves_existing_entries():
    idx = RadixIndex()
    idx.insert("long", [1, 2, 3, 4, 5])
    idx.insert("mid", [1, 2, 3])  # lands mid-edge: forces a split
    idx.insert("fork", [1, 2, 9])  # diverges inside the compressed edge
    assert idx.match([1, 2, 3, 4, 5]) == {"long": 5, "mid": 3, "fork": 2}
    assert idx.match([1, 2, 9, 9]) == {"long": 2, "mid": 2, "fork": 3}
    idx.remove("mid")
    assert idx.match([1, 2, 3, 4, 5]) == {"long": 5, "fork": 2}


def test_radix_clear():
    idx = RadixIndex()
    for i in range(5):
        idx.insert(i, [i, i + 1])
    idx.clear()
    assert len(idx) == 0 and idx.match([0, 1]) == {}


# --------------------------- HostOverflowTier --------------------------


def _entry(n_tokens, nbytes_per_tok=8):
    kv = {"k": np.zeros((1, n_tokens, 1, nbytes_per_tok), np.uint8)}
    return HostEntry(
        tokens=np.arange(n_tokens, dtype=np.int64),
        valid_len=n_tokens,
        version=0,
        block=n_tokens,
        kv=kv,
    )


def test_host_tier_lru_evicts_by_bytes():
    tier = HostOverflowTier(capacity_bytes=3 * 8 * 8)  # fits three 8-token
    assert tier.put(0, _entry(8)) == []
    assert tier.put(1, _entry(8)) == []
    assert tier.put(2, _entry(8)) == []
    assert tier.used_bytes == 3 * 64
    # a fourth entry evicts the least recently used (hid 0)
    assert tier.put(3, _entry(8)) == [0]
    assert 0 not in tier and 1 in tier
    # touching 1 promotes it: the next eviction takes 2 instead
    tier.touch(1)
    assert tier.put(4, _entry(8)) == [2]
    assert 1 in tier
    assert tier.used_bytes == 3 * 64


def test_host_tier_refuses_oversized_entry():
    tier = HostOverflowTier(capacity_bytes=100)
    tier.put(0, _entry(4))  # 32 bytes, fits
    # an entry larger than the whole tier is its own eviction; the
    # resident entries are NOT flushed for nothing
    assert tier.put(1, _entry(32)) == [1]
    assert 0 in tier and 1 not in tier


def test_host_tier_take_and_clear():
    tier = HostOverflowTier(capacity_bytes=1 << 20)
    tier.put(0, _entry(8))
    ent = tier.take(0)
    assert ent is not None and ent.valid_len == 8
    assert tier.take(0) is None
    assert tier.used_bytes == 0
    tier.put(1, _entry(8))
    tier.put(2, _entry(8))
    assert tier.clear() == 2
    assert tier.used_bytes == 0 and len(tier) == 0


# -------------------------------- KVPool -------------------------------


def test_pool_page_table_swap_rehomes_radix_entries():
    pool = KVPool(n_slots=4)
    seq = np.arange(10, dtype=np.int64)
    pool.note_free(0, seq, 6)
    pool.note_free(2, seq + 50, 4)
    assert pool.match_device(seq[:6].tolist()) == {0: 6, 2: 0}
    r0, r2 = pool.row(0), pool.row(2)
    pool.swap(0, 2)
    # physical rows swapped, and the indexed prefixes moved WITH them
    assert pool.row(0) == r2 and pool.row(2) == r0
    assert pool.match_device(seq[:6].tolist()) == {2: 6, 0: 0}
    assert pool.device_tokens(2).tolist() == seq[:6].tolist()
    pool.check_page_table()
    # swap involving an entry-less slot keeps the tree consistent: slot 2's
    # entry moves to slot 1, slot 2 ends up entry-less
    pool.swap(1, 2)
    assert pool.match_device(seq[:6].tolist()) == {1: 6, 0: 0}
    assert pool.device_tokens(2) is None
    pool.check_page_table()


def test_pool_random_swaps_stay_a_permutation():
    rng = np.random.default_rng(1)
    pool = KVPool(n_slots=8)
    for _ in range(100):
        a, b = rng.integers(0, 8, 2)
        pool.swap(int(a), int(b))
        pool.check_page_table()
    # scratch row (index n_slots) is never remapped by slot swaps
    assert pool.row(8) == 8


def test_pool_note_free_and_drop_device():
    pool = KVPool(n_slots=2)
    seq = np.arange(16, dtype=np.int64)
    pool.note_free(0, seq, 8)
    assert pool.drop_device(0) == 8
    assert pool.drop_device(0) == 0  # already dropped
    pool.note_free(0, seq, 8)
    pool.note_free(0, seq, 0)  # zero retained removes the entry
    assert pool.device_tokens(0) is None


def test_pool_host_put_take_and_radix_visibility():
    pool = KVPool(n_slots=2, host_bytes=1 << 20)
    toks = np.arange(12, dtype=np.int64)
    kv = {"k": np.zeros((1, 16, 1, 4), np.float32)}
    assert pool.host_put(toks, 12, version=3, block=16, kv=kv) == 0
    m = pool.match_host(toks.tolist() + [99])
    assert list(m.values()) == [12]
    hid = next(iter(m))
    ent = pool.host_take(hid)
    assert ent.valid_len == 12 and ent.version == 3 and ent.block == 16
    assert ent.tokens.tolist() == toks.tolist()
    # taken for swap-in: gone from the host tier AND the radix
    assert pool.match_host(toks.tolist()) == {}
    assert pool.host_take(hid) is None


def test_pool_host_lru_eviction_counts_and_unindexes():
    # capacity for exactly two of these entries
    kv_bytes = int(
        np.zeros((1, 16, 1, 4), np.float32).nbytes
    )
    pool = KVPool(n_slots=2, host_bytes=2 * kv_bytes)
    def put(base):
        toks = np.arange(base, base + 12, dtype=np.int64)
        return pool.host_put(
            toks, 12, version=0, block=16,
            kv={"k": np.zeros((1, 16, 1, 4), np.float32)},
        )
    assert put(0) == 0
    assert put(100) == 0
    assert put(200) == 1  # LRU evicted the first spill
    assert pool.match_host(list(range(0, 12))) == {} or max(
        pool.match_host(list(range(0, 12))).values()
    ) == 0
    assert len(pool.host) == 2


def test_pool_clear_and_reset():
    pool = KVPool(n_slots=2, host_bytes=1 << 20)
    pool.note_free(0, np.arange(8, dtype=np.int64), 8)
    pool.host_put(
        np.arange(8, dtype=np.int64), 8, version=0, block=8,
        kv={"k": np.zeros((1, 8, 1, 2), np.float32)},
    )
    pool.swap(0, 1)
    pool.clear()
    assert pool.match_device(list(range(8))) == {}
    assert pool.match_host(list(range(8))) == {}
    # clear keeps the page table (cache rows still hold live K/V) ...
    assert pool.row(0) == 1
    # ... reset restores identity (cache reallocated)
    pool.reset()
    assert pool.row(0) == 0 and pool.row(1) == 1
    pool.check_page_table()


def test_pool_check_page_table_catches_corruption():
    pool = KVPool(n_slots=2)
    pool.page_table[0] = 1  # duplicate row: slots 0 and 1 alias
    with pytest.raises(AssertionError):
        pool.check_page_table()
