from areal_tpu.ops.functional import (
    dpo_loss_fn,
    gather_logprobs,
    gather_logprobs_entropy,
    grpo_loss_fn,
    kl_estimate,
    masked_mean,
    masked_normalize,
    pairwise_reward_loss_fn,
    ppo_actor_loss_fn,
    ppo_critic_loss_fn,
    sft_loss_fn,
)
from areal_tpu.ops.gae import gae_padded, gae_segments
from areal_tpu.ops.kv_copy import copy_kv_prefix

__all__ = [
    "gather_logprobs",
    "gather_logprobs_entropy",
    "grpo_loss_fn",
    "ppo_actor_loss_fn",
    "ppo_critic_loss_fn",
    "sft_loss_fn",
    "pairwise_reward_loss_fn",
    "dpo_loss_fn",
    "kl_estimate",
    "masked_mean",
    "masked_normalize",
    "gae_padded",
    "gae_segments",
    "copy_kv_prefix",
]
