"""Saver / Evaluator / RecoverHandler / FrequencyControl / datasets / math
parser (reference analogs: areal/tests test_utils + recover behavior)."""

import json
import os

import numpy as np
import pytest

from areal_tpu.api.config import (
    EvaluatorConfig,
    MeshConfig,
    MicroBatchSpec,
    OptimizerConfig,
    RecoverConfig,
    SaverConfig,
    TimerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo
from areal_tpu.engine.sft import JaxLMEngine
from areal_tpu.models.model_config import tiny_config
from areal_tpu.reward.math_parser import extract_answer, math_equal
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.utils.evaluator import Evaluator
from areal_tpu.utils.recover import RecoverHandler, RecoverInfo, check_if_recover
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.timer import FrequencyControl

MODEL_CFG = tiny_config(vocab_size=64, qkv_bias=True, hf_architecture="Qwen2ForCausalLM")


def _engine(lr=1e-2):
    cfg = TrainEngineConfig(
        experiment_name="t", trial_name="t", init_from_scratch=True,
        dtype="float32", gradient_checkpointing=False, mesh=MeshConfig(),
        mb_spec=MicroBatchSpec(), pack_length_quantum=16,
        optimizer=OptimizerConfig(lr=lr, warmup_steps_proportion=0.0),
    )
    eng = JaxLMEngine(cfg, model_config=MODEL_CFG)
    eng.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    return eng


def test_frequency_control():
    fc = FrequencyControl(TimerConfig(freq_steps=3))
    hits = [fc.check(0, s) for s in range(1, 10)]
    assert hits == [False, False, True, False, False, True, False, False, True]
    fc2 = FrequencyControl(TimerConfig())  # never triggers without force
    assert not fc2.check(5, 100)
    assert fc2.check(5, 100, force=True)
    state = fc.state_dict()
    fc3 = FrequencyControl(TimerConfig(freq_steps=3))
    fc3.load_state_dict(state)
    assert fc3._last_step == fc._last_step


def test_saver_paths_and_freq(tmp_path):
    eng = _engine()
    cfg = SaverConfig(experiment_name="e", trial_name="t",
                      fileroot=str(tmp_path), freq_steps=2)
    saver = Saver(cfg, FinetuneSpec(1, 64, 8))
    assert saver.save(eng, 0, 0, 1) is None  # freq not reached
    path = saver.save(eng, 0, 1, 2)
    assert path is not None and os.path.exists(os.path.join(path, "config.json"))
    assert "checkpoints" in path and "globalstep2" in path


def test_evaluator_freq():
    ev = Evaluator(EvaluatorConfig(freq_steps=2), None)
    calls = []
    out = ev.evaluate(lambda: calls.append(1) or {"x": 1.0}, 0, 0, 1)
    assert out is None and not calls
    out = ev.evaluate(lambda: calls.append(1) or {"x": 1.0}, 0, 1, 2)
    assert out == {"x": 1.0} and calls


def test_recover_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, 64, (4, 10)).astype(np.int32),
        "attention_mask": np.ones((4, 10), bool),
        "loss_mask": np.ones((4, 10), np.float32),
    }
    eng = _engine()
    for _ in range(3):
        eng.train_lm(batch)
    eng.set_version(3)

    cfg = RecoverConfig(mode="auto", experiment_name="e", trial_name="t",
                        fileroot=str(tmp_path))
    handler = RecoverHandler(cfg)
    dataloader = StatefulDataLoader(list(range(32)), batch_size=4, seed=0)
    it = iter(dataloader)
    next(it), next(it)
    step = StepInfo(epoch=0, epoch_step=2, global_step=2, steps_per_epoch=8)
    saver = Saver(SaverConfig(experiment_name="e", trial_name="t",
                              fileroot=str(tmp_path), freq_steps=2))
    handler.dump(eng, step, saver=saver, dataloader=dataloader)
    assert check_if_recover(cfg)
    logp_ref = eng.forward(batch)

    eng2 = _engine()
    dataloader2 = StatefulDataLoader(list(range(32)), batch_size=4, seed=0)
    info = handler.load(eng2, dataloader=dataloader2)
    assert info is not None
    assert info.recover_start.global_step == 3
    assert eng2.get_version() == 3
    assert eng2.step_count == eng.step_count
    assert dataloader2.state_dict() == dataloader.state_dict()
    np.testing.assert_allclose(eng2.forward(batch), logp_ref, rtol=1e-4, atol=1e-4)

    # both engines continue identically
    s1, s2 = eng.train_lm(batch), eng2.train_lm(batch)
    np.testing.assert_allclose(s1["loss"], s2["loss"], rtol=1e-4)


def test_recover_roundtrip_extra_engines(tmp_path):
    """extra_engines (the PPO critic pattern, examples/math/gsm8k_ppo.py)
    dump and restore beside the main engine."""
    rng = np.random.default_rng(1)
    batch = {
        "input_ids": rng.integers(0, 64, (4, 10)).astype(np.int32),
        "attention_mask": np.ones((4, 10), bool),
        "loss_mask": np.ones((4, 10), np.float32),
    }
    actor, second = _engine(), _engine()
    for _ in range(2):
        actor.train_lm(batch)
        second.train_lm(batch)

    cfg = RecoverConfig(mode="auto", experiment_name="e2", trial_name="t",
                        fileroot=str(tmp_path))
    handler = RecoverHandler(cfg)
    step = StepInfo(epoch=0, epoch_step=1, global_step=1, steps_per_epoch=8)
    handler.dump(actor, step, extra_engines={"second": second})
    ref = second.forward(batch)

    actor2, second2 = _engine(), _engine()
    info = handler.load(actor2, extra_engines={"second": second2})
    assert info is not None
    np.testing.assert_allclose(second2.forward(batch), ref, rtol=1e-4,
                               atol=1e-4)
    # a missing extra checkpoint degrades with a warning, not a crash
    info = handler.load(_engine(), extra_engines={"absent": _engine()})
    assert info is not None


def test_check_if_recover_modes(tmp_path):
    cfg = RecoverConfig(mode="disabled", experiment_name="e", trial_name="t",
                        fileroot=str(tmp_path))
    assert not check_if_recover(cfg)

    # `resume` on a MISSING checkpoint is an error, not a silent fresh
    # start — the user explicitly asked to continue a run
    cfg.mode = "resume"
    with pytest.raises(FileNotFoundError):
        check_if_recover(cfg)
    cfg.mode = "auto"
    assert not check_if_recover(cfg)

    # fabricate a completed generation: only gen-*/manifest.json counts
    root = os.path.join(tmp_path, "e", "t", "recover")
    gen = os.path.join(root, "gen-00000002")
    os.makedirs(gen)
    with open(os.path.join(gen, "manifest.json"), "w") as f:
        f.write("{}")
    cfg.mode = "fault"
    assert not check_if_recover(cfg, run_id=0)  # fresh submit
    assert check_if_recover(cfg, run_id=1)  # relaunch
    cfg.mode = "resume"
    assert check_if_recover(cfg, run_id=0)
    cfg.mode = "auto"
    assert check_if_recover(cfg)

    # a staging dir alone (crash mid-dump before the rename) is invisible
    import shutil
    shutil.rmtree(gen)
    os.makedirs(os.path.join(root, ".tmp-00000003"))
    assert not check_if_recover(cfg)


def test_dump_is_atomic_and_torn_dump_falls_back(tmp_path):
    """ISSUE 15 tentpole (a): a crash between staging and rename leaves
    only a .tmp-* dir; load() keeps serving the previous generation.  The
    in-process variant arms the `recover_mid_dump` fault point with
    action='raise' (the subprocess SIGKILL variant lives in
    tests/test_recover_e2e.py)."""
    from areal_tpu.utils.faults import (
        InjectedFault,
        arm_fault_point,
        reset_fault_points,
    )

    rng = np.random.default_rng(2)
    batch = {
        "input_ids": rng.integers(0, 64, (4, 10)).astype(np.int32),
        "attention_mask": np.ones((4, 10), bool),
        "loss_mask": np.ones((4, 10), np.float32),
    }
    eng = _engine()
    eng.train_lm(batch)
    cfg = RecoverConfig(mode="auto", experiment_name="torn", trial_name="t",
                        fileroot=str(tmp_path))
    handler = RecoverHandler(cfg)
    step1 = StepInfo(epoch=0, epoch_step=1, global_step=1, steps_per_epoch=8)
    handler.dump(eng, step1)
    ref = eng.forward(batch)

    eng.train_lm(batch)
    step2 = StepInfo(epoch=0, epoch_step=2, global_step=2, steps_per_epoch=8)
    try:
        arm_fault_point("recover_mid_dump", action="raise")
        with pytest.raises(InjectedFault):
            handler.dump(eng, step2)
    finally:
        reset_fault_points()
    # the torn attempt left a staging dir, never a gen-00000002
    root = handler.recover_root()
    assert os.path.isdir(os.path.join(root, ".tmp-00000002"))
    assert not os.path.isdir(os.path.join(root, "gen-00000002"))

    eng2 = _engine()
    info = handler.load(eng2)
    assert info is not None
    assert info.last_step_info.global_step == 1  # the intact generation
    assert eng2.get_version() == 2
    np.testing.assert_allclose(eng2.forward(batch), ref, rtol=1e-4, atol=1e-4)


def test_tampered_generation_rejected_and_falls_back(tmp_path):
    """Manifest digest validation: corrupting any file of the newest
    generation makes load() skip it and restore the previous one; a
    truncated manifest is an unreadable generation, same outcome."""
    rng = np.random.default_rng(3)
    batch = {
        "input_ids": rng.integers(0, 64, (4, 10)).astype(np.int32),
        "attention_mask": np.ones((4, 10), bool),
        "loss_mask": np.ones((4, 10), np.float32),
    }
    eng = _engine()
    eng.train_lm(batch)
    cfg = RecoverConfig(mode="auto", experiment_name="tamper", trial_name="t",
                        fileroot=str(tmp_path))
    handler = RecoverHandler(cfg)
    handler.dump(eng, StepInfo(epoch=0, epoch_step=1, global_step=1,
                               steps_per_epoch=8))
    ref = eng.forward(batch)
    eng.train_lm(batch)
    handler.dump(eng, StepInfo(epoch=0, epoch_step=2, global_step=2,
                               steps_per_epoch=8))

    # flip bytes in the newest generation's model weights
    gen2 = handler.generations()[-1]
    assert gen2.endswith("gen-00000002")
    victim = os.path.join(gen2, "recover_state.pkl")
    with open(victim, "r+b") as f:
        f.write(b"\xde\xad\xbe\xef")
    info = handler.load(_engine())
    assert info is not None
    assert info.last_step_info.global_step == 1

    # size-preserving tamper of a checkpoint file is caught by the digest
    eng3 = _engine()
    info = handler.load(eng3)
    np.testing.assert_allclose(eng3.forward(batch), ref, rtol=1e-4, atol=1e-4)


def test_config_fingerprint_mismatch_refused(tmp_path):
    """A checkpoint written under a different config fingerprint must be
    refused (raise), never silently resumed or fallen back from."""
    from areal_tpu.utils.recover import RecoverConfigMismatch, config_fingerprint

    rng = np.random.default_rng(4)
    batch = {
        "input_ids": rng.integers(0, 64, (4, 10)).astype(np.int32),
        "attention_mask": np.ones((4, 10), bool),
        "loss_mask": np.ones((4, 10), np.float32),
    }
    eng = _engine()
    eng.train_lm(batch)
    cfg = RecoverConfig(mode="auto", experiment_name="fp", trial_name="t",
                        fileroot=str(tmp_path))
    fp_a = config_fingerprint({"lr": 1e-2, "batch": 4})
    fp_b = config_fingerprint({"lr": 5e-3, "batch": 4})
    assert fp_a != fp_b
    handler = RecoverHandler(cfg, fingerprint=fp_a)
    handler.dump(eng, StepInfo(epoch=0, epoch_step=1, global_step=1,
                               steps_per_epoch=8))
    # same fingerprint loads fine
    assert handler.load(_engine()) is not None
    # a different one is refused
    other = RecoverHandler(cfg, fingerprint=fp_b)
    with pytest.raises(RecoverConfigMismatch):
        other.load(_engine())


def test_recover_sidecar_manifest_and_prune(tmp_path):
    """The recover_info.json sidecar carries the full human-readable
    manifest (step, version, run_id, timestamps, generation paths), and
    generations beyond the retention window are pruned."""
    rng = np.random.default_rng(5)
    batch = {
        "input_ids": rng.integers(0, 64, (4, 10)).astype(np.int32),
        "attention_mask": np.ones((4, 10), bool),
        "loss_mask": np.ones((4, 10), np.float32),
    }
    eng = _engine()
    cfg = RecoverConfig(mode="auto", experiment_name="side", trial_name="t",
                        fileroot=str(tmp_path))
    handler = RecoverHandler(cfg)
    for step in (1, 2, 3):
        eng.train_lm(batch)
        eng.set_version(step + 1)
        handler.dump(eng, StepInfo(epoch=0, epoch_step=step, global_step=step,
                                   steps_per_epoch=8))
    gens = handler.generations()
    assert [os.path.basename(g) for g in gens] == \
        ["gen-00000002", "gen-00000003"]  # gen-00000001 pruned
    with open(os.path.join(handler.recover_root(), "recover_info.json")) as f:
        side = json.load(f)
    assert side["last_step_info"]["global_step"] == 3
    assert side["weight_version"] == 4
    assert side["run_id"] == int(os.environ.get("AREAL_RUN_ID", 0))
    assert side["latest"].endswith("gen-00000003")
    assert side["updated_ts"] > 0
    assert [os.path.basename(g) for g in side["generations"]] == \
        ["gen-00000002", "gen-00000003"]
    # the per-generation manifest pins per-file digests + async state slots
    with open(os.path.join(gens[-1], "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["schema"] == "areal-recover/v1"
    assert manifest["files"]
    assert all({"size", "blake2b"} <= set(v) for v in manifest["files"].values())
    assert set(manifest["async_state"]) == \
        {"rollout_stat", "seed", "fleet_weight_version"}


def test_jsonl_dataset(tmp_path):
    from areal_tpu.dataset import get_custom_dataset

    p = tmp_path / "d.jsonl"
    with open(p, "w") as f:
        for i in range(5):
            f.write(json.dumps({"prompt": f"q{i}", "answer": str(i)}) + "\n")
    ds = get_custom_dataset(str(p), type="jsonl")
    assert len(ds) == 5 and ds[0]["query_id"] == "0"


def test_gsm8k_answer_extraction():
    from areal_tpu.dataset.gsm8k import gsm8k_answer

    assert gsm8k_answer("blah blah\n#### 1,234") == "1234"
    assert gsm8k_answer("#### -3.5") == "-3.5"


@pytest.mark.parametrize(
    "pred,target,equal",
    [
        ("42", "42", True),
        ("42.0", "42", True),
        ("1,234", "1234", True),
        ("\\frac{1}{2}", "0.5", True),
        ("\\frac{1}{2}", "1/2", True),
        ("0.333", "1/3", False),  # outside tolerance
        ("x+1", "1+x", True),  # sympy symbolic
        ("\\sqrt{4}", "2", True),
        ("50\\%", "50", True),
        ("$3.50", "3.5", True),
        ("7", "8", False),
        ("nonsense[", "42", False),
    ],
)
def test_math_equal(pred, target, equal):
    assert math_equal(pred, target) == equal


def test_extract_answer():
    assert extract_answer("stuff \\boxed{\\frac{1}{2}} end") == "\\frac{1}{2}"
    assert extract_answer("nested \\boxed{a{b}c}") == "a{b}c"
    assert extract_answer("The answer is 42.") == "42"
    assert extract_answer("compute... #### 17") == "17"
    # strict (reward) mode: bare numbers do not count as answers
    assert extract_answer("first 3 then 9 finally") is None
    assert extract_answer("first 3 then 9 finally", strict=False) == "9"
    assert extract_answer("no numbers here") is None


def test_profiling_flops_and_mfu():
    from areal_tpu.models.model_config import qwen25_1p5b, tiny_config
    from areal_tpu.utils import profiling

    cfg = qwen25_1p5b()
    P = profiling.param_count(cfg)
    assert 1.4e9 < P < 1.7e9  # qwen2.5-1.5b is ~1.54B params
    f = profiling.train_flops_per_token(cfg, ctx_len=2048)
    assert f > 6 * P  # attention adds on top of the 6P matmul estimate
    # MoE counts active experts only
    moe = tiny_config(num_experts=8, num_experts_per_tok=2)
    dense_like = tiny_config()
    assert profiling.train_flops_per_token(
        moe, 128
    ) < 8 / 2 * profiling.train_flops_per_token(dense_like, 128)
    # mfu is None on unknown devices instead of lying (CPU here)
    assert profiling.mfu(1e4, cfg, 2048) is None
    assert profiling.mfu(1e4, cfg, 2048, peak_tflops=197.0) > 0


def test_train_stats_report_mfu():
    import numpy as np

    from areal_tpu.api.config import (
        MeshConfig,
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.jax_train import JaxTrainEngine
    from areal_tpu.models.model_config import tiny_config
    from areal_tpu.ops import sft_loss_fn

    eng = JaxTrainEngine(
        TrainEngineConfig(
            experiment_name="prof", trial_name="t", init_from_scratch=True,
            dtype="float32", param_dtype="float32",
            gradient_checkpointing=False, mesh=MeshConfig(),
            mb_spec=MicroBatchSpec(n_mbs=1),
            optimizer=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
            pack_length_quantum=32, max_pack_length=64,
        ),
        model_config=tiny_config(),
    )
    eng.initialize(ft_spec=FinetuneSpec(1, 16, 4))
    rng = np.random.default_rng(0)
    B, L = 2, 24
    stats = eng.train_batch(
        {
            "input_ids": rng.integers(0, 512, (B, L)).astype(np.int32),
            "attention_mask": np.ones((B, L), bool),
            "loss_mask": np.ones((B, L), np.float32),
        },
        sft_loss_fn,
        lambda b: float(np.sum(b["loss_mask"])),
    )
    assert stats["tflops_per_chip"] > 0
