"""Abstract engine interfaces.

Capability counterpart of the reference's `areal/api/engine_api.py`
(`TrainEngine` :40, `InferenceEngine` :347).  TPU-first differences:

- `TrainEngine` owns a `jax.sharding.Mesh` instead of torch process groups;
  "process group creation" becomes mesh construction, and distributed state
  lives in sharded jax arrays.
- Batches are host-side `dict[str, np.ndarray]` (padded or packed layout from
  `areal_tpu.utils.data`), not torch TensorDicts.
- `train_batch/forward` take a loss function over (logits, batch) pytrees that
  is jit-compiled by the engine.
"""

import abc
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api.io_struct import (
    FinetuneSpec,
    ModelRequest,
    ModelResponse,
    SaveLoadMeta,
    WeightUpdateMeta,
)
from areal_tpu.api.workflow import RolloutWorkflow


@dataclass
class Scheduling:
    """Resource requirements of an engine worker (reference: engine_api.py:24)."""

    cpu: int = 4
    mem: int = 32768
    accelerator: int = 1
    env_vars: Dict[str, str] = field(default_factory=dict)


class TrainEngine(abc.ABC):
    """SPMD training backend over a device mesh."""

    def create_process_group(self, alloc_mode=None) -> None:
        """Build the device mesh / distributed runtime (idempotent)."""

    @abc.abstractmethod
    def initialize(
        self,
        addr: Optional[str] = None,
        ft_spec: Optional[FinetuneSpec] = None,
    ) -> None:
        """Load the model, build optimizer state, compile step functions."""

    def destroy(self) -> None:
        """Release device memory and host resources."""

    @property
    def data_parallel_rank(self) -> int:
        raise NotImplementedError

    @property
    def data_parallel_world_size(self) -> int:
        raise NotImplementedError

    def is_data_parallel_head(self) -> bool:
        raise NotImplementedError

    def current_data_parallel_head(self) -> int:
        raise NotImplementedError

    @abc.abstractmethod
    def train_batch(
        self,
        input_: Dict[str, np.ndarray],
        loss_fn: Callable,
        loss_weight_fn: Callable,
    ) -> Dict[str, float]:
        """One optimizer step over micro-batches with grad accumulation.

        `loss_weight_fn(batch) -> float` returns each micro-batch's weight
        (e.g. token count); losses are globally normalized by the total weight
        across all micro-batches and dp ranks (reference: fsdp_engine.py:499).
        """

    @abc.abstractmethod
    def forward(
        self,
        input_: Dict[str, np.ndarray],
        output_key: str = "logprobs",
        post_hook: Optional[Callable] = None,
        aggregate_fn: Callable = None,
    ) -> Any:
        """No-grad forward over micro-batches, outputs re-assembled to input
        order."""

    def eval_batch(
        self,
        input_: Dict[str, np.ndarray],
        loss_fn: Callable,
        loss_weight_fn: Callable,
    ) -> Dict[str, float]:
        raise NotImplementedError

    @abc.abstractmethod
    def update_weights(self, meta: WeightUpdateMeta) -> None:
        """Push current weights to inference servers (disk or transfer path)."""

    def stage_weights(self, meta: WeightUpdateMeta) -> None:
        """Optionally pre-run the expensive half of a weight publish while
        generation still runs (snapshot write / chunk streaming), so only
        the swap sits inside the pause window; update_weights() then skips
        the staged work.  Default: no-op (update_weights does everything)."""

    @abc.abstractmethod
    def save(self, meta: SaveLoadMeta) -> None: ...

    @abc.abstractmethod
    def load(self, meta: SaveLoadMeta) -> None: ...

    def step_lr_scheduler(self) -> None:
        """Advance the LR schedule one step (called once per train iteration)."""

    def get_scheduling_config(self) -> Scheduling:
        return Scheduling()

    def set_version(self, version: int) -> None:
        raise NotImplementedError

    def get_version(self) -> int:
        raise NotImplementedError


class InferenceEngine(abc.ABC):
    """Client of a fleet of streaming-LLM servers (reference: engine_api.py:347)."""

    def initialize(
        self,
        addr: Optional[str] = None,
        train_data_parallel_size: Optional[int] = None,
    ) -> None: ...

    def destroy(self) -> None: ...

    @abc.abstractmethod
    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        """Asynchronously generate one completion (n_samples == 1)."""

    # --- rollout submission surface ---
    @abc.abstractmethod
    def submit(
        self,
        data: Dict[str, Any],
        workflow: Optional[RolloutWorkflow] = None,
        workflow_builder: Optional[Callable] = None,
        should_accept: Optional[Callable] = None,
    ) -> None: ...

    @abc.abstractmethod
    def wait(self, count: int, timeout: Optional[float] = None) -> Dict[str, Any]: ...

    def rollout_batch(
        self,
        data: List[Dict[str, Any]],
        workflow: Optional[RolloutWorkflow] = None,
        workflow_builder: Optional[Callable] = None,
        should_accept: Optional[Callable] = None,
    ) -> Dict[str, Any]:
        raise NotImplementedError

    def prepare_batch(
        self,
        dataloader,
        workflow: Optional[RolloutWorkflow] = None,
        workflow_builder: Optional[Callable] = None,
        should_accept: Optional[Callable] = None,
    ) -> Dict[str, Any]:
        raise NotImplementedError

    # --- weight updates & versioning ---
    def init_weight_update_group(self, meta: WeightUpdateMeta) -> None: ...

    @abc.abstractmethod
    def update_weights(self, meta: WeightUpdateMeta) -> None: ...

    @abc.abstractmethod
    def set_version(self, version: int) -> None: ...

    @abc.abstractmethod
    def get_version(self) -> int: ...

    def pause(self) -> None:
        """Pause new request submission (during weight updates)."""

    def resume(self) -> None: ...
