"""Scoped, denominator-normalized statistics tracking.

Capability counterpart of the reference's `DistributedStatsTracker`
(areal/utils/stats_tracker.py:30-290) and `StatsLogger` (stats_logger.py).
torch-free: values are numpy arrays; cross-host reduction (multi-host TPU)
goes through an optional reduce hook instead of torch.distributed.
"""

import math
import time
from collections import defaultdict
from contextlib import contextmanager
from enum import Enum
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.utils import logging

logger = logging.getLogger("stats")


class ReduceType(Enum):
    AVG = "avg"
    SUM = "sum"
    MIN = "min"
    MAX = "max"


def _asarray(x) -> np.ndarray:
    if hasattr(x, "addressable_shards") or hasattr(x, "device_buffer"):
        x = np.asarray(x)  # jax array
    arr = np.asarray(x)
    return arr


class PendingTrainStats:
    """Train-step stats whose device→host fetch is deferred (Mapping-like).

    A per-step blocking stats fetch serialises the trainer on dispatch
    latency: the host cannot enqueue step N+1 until step N's scalars have
    crossed the wire (expensive on tunneled/remote TPU runtimes — measured
    ~150 ms/step on v5e behind a network hop).  Deferring the fetch lets XLA
    pipeline steps back-to-back; reading any key materialises the stats (one
    batched transfer) and runs the registered finalizers (normalisation +
    tracker commit), preserving the sync path's observable behavior, just
    later.
    """

    def __init__(self, device_stats: Dict[str, Any], fetch: Callable):
        # issue async copies now so the transfer overlaps device compute
        for v in device_stats.values():
            if hasattr(v, "copy_to_host_async"):
                try:
                    v.copy_to_host_async()
                except Exception:  # noqa: BLE001 — optional fast path
                    pass
        self._device_stats = device_stats
        self._fetch = fetch
        self._finalizers: List[Callable] = []
        self._result: Optional[Dict[str, float]] = None

    def then(self, fn: Callable) -> "PendingTrainStats":
        """Register `fn(stats_dict) -> stats_dict` to run at materialisation."""
        if self._result is not None:
            self._result = fn(self._result)
        else:
            self._finalizers.append(fn)
        return self

    def materialize(self) -> Dict[str, float]:
        if self._result is None:
            out = self._fetch(self._device_stats)
            self._device_stats = None
            for fn in self._finalizers:
                out = fn(out)
            self._finalizers = []
            self._result = out
        return self._result

    # Mapping surface — any read materialises
    def __getitem__(self, key):
        return self.materialize()[key]

    def __contains__(self, key):
        return key in self.materialize()

    def __iter__(self):
        return iter(self.materialize())

    def __len__(self):
        return len(self.materialize())

    def keys(self):
        return self.materialize().keys()

    def values(self):
        return self.materialize().values()

    def items(self):
        return self.materialize().items()

    def get(self, key, default=None):
        return self.materialize().get(key, default)

    def pop(self, key, *default):
        return self.materialize().pop(key, *default)

    def __setitem__(self, key, value):
        # callers annotate stats in place (e.g. sft/rw engines' ppl/acc);
        # writing forces materialisation so ordering stays deterministic
        self.materialize()[key] = value

    def __repr__(self):
        state = "pending" if self._result is None else repr(self._result)
        return f"PendingTrainStats({state})"


class StatsTracker:
    """Accumulates masked statistics under hierarchical scopes.

    - `denominator(name=mask)` registers boolean masks.
    - `stat(denominator="mask", key=value, ...)` records per-element values
      normalized by a mask at reduce time.
    - `scalar(key=value)` records plain scalars (averaged over records).
    - `scope(name)` nests key prefixes.
    - `export()` reduces everything to flat {key: float} and clears.
    """

    def __init__(self, name: str = ""):
        self.name = name
        self._scopes: List[str] = []
        self._denoms: Dict[str, List[np.ndarray]] = defaultdict(list)
        # each stat record carries the mask it was validated against, so
        # values and denominators can never be mis-paired positionally
        self._stats: Dict[str, List[tuple]] = defaultdict(list)
        self._reduce: Dict[str, ReduceType] = {}
        self._scalars: Dict[str, List[float]] = defaultdict(list)
        self._timing: Dict[str, List[float]] = defaultdict(list)

    # --- scoping ---
    @contextmanager
    def scope(self, name: str):
        self._scopes.append(name)
        try:
            yield self
        finally:
            self._scopes.pop()

    def _key(self, key: str) -> str:
        parts = [p for p in ([self.name] + self._scopes + [key]) if p]
        return "/".join(parts)

    # --- recording ---
    def denominator(self, **kwargs):
        for key, mask in kwargs.items():
            arr = _asarray(mask)
            if arr.dtype != np.bool_:
                raise ValueError(f"denominator {key!r} must be boolean, got {arr.dtype}")
            self._denoms[self._key(key)].append(arr.reshape(-1))

    def stat(
        self,
        denominator: str,
        reduce_type: ReduceType = ReduceType.AVG,
        **kwargs,
    ):
        denom_key = self._key(denominator)
        if denom_key not in self._denoms:
            raise ValueError(f"unknown denominator {denominator!r}")
        for key, value in kwargs.items():
            arr = _asarray(value).astype(np.float32).reshape(-1)
            full = self._key(key)
            mask = self._denoms[denom_key][-1]
            if arr.shape != mask.shape:
                raise ValueError(
                    f"stat {key!r} shape {arr.shape} != denominator shape {mask.shape}"
                )
            self._stats[full].append((arr, mask))
            self._reduce[full] = reduce_type

    def scalar(self, **kwargs):
        for key, value in kwargs.items():
            self._scalars[self._key(key)].append(float(value))

    @contextmanager
    def record_timing(self, key: str):
        tik = time.perf_counter()
        try:
            yield
        finally:
            self._timing[self._key(key)].append(time.perf_counter() - tik)

    # --- reduction ---
    def export(
        self,
        key: Optional[str] = None,
        reduce_hook: Optional[Callable[[Dict[str, Any]], Dict[str, Any]]] = None,
        reset: bool = True,
    ) -> Dict[str, float]:
        """Reduce to flat floats.  `reduce_hook` may implement cross-host
        aggregation: it receives {key: (num, denom)|value} partial sums."""
        out: Dict[str, float] = {}
        for full, records in self._stats.items():
            if key is not None and not full.startswith(key):
                continue
            vals = np.concatenate([v for v, _ in records])
            mask = np.concatenate([m for _, m in records])
            rt = self._reduce[full]
            if mask.sum() == 0:
                continue
            sel = vals[mask]
            if rt == ReduceType.AVG:
                out[full] = float(sel.mean())
            elif rt == ReduceType.SUM:
                out[full] = float(sel.sum())
            elif rt == ReduceType.MIN:
                out[full] = float(sel.min())
            elif rt == ReduceType.MAX:
                out[full] = float(sel.max())
        for full, masks in self._denoms.items():
            if key is not None and not full.startswith(key):
                continue
            tot = int(sum(m.sum() for m in masks))
            out.setdefault(f"{full}/count", float(tot))
        for full, vals in self._scalars.items():
            if key is not None and not full.startswith(key):
                continue
            out[full] = float(np.mean(vals))
        for full, vals in self._timing.items():
            if key is not None and not full.startswith(key):
                continue
            out[f"time_perf/{full}"] = float(np.sum(vals))
        if reduce_hook is not None:
            out = reduce_hook(out)
        if reset:
            if key is None:
                self._denoms.clear()
                self._stats.clear()
                self._scalars.clear()
                self._timing.clear()
                self._reduce.clear()
            else:
                for d in (self._denoms, self._stats, self._scalars, self._timing):
                    for k in [k for k in d if k.startswith(key)]:
                        del d[k]
        return {k: (0.0 if (isinstance(v, float) and math.isnan(v)) else v) for k, v in out.items()}


# Module-level default tracker, mirroring the reference's module-level API.
DEFAULT_TRACKER = StatsTracker()
denominator = DEFAULT_TRACKER.denominator
stat = DEFAULT_TRACKER.stat
scalar = DEFAULT_TRACKER.scalar
scope = DEFAULT_TRACKER.scope
record_timing = DEFAULT_TRACKER.record_timing
export = DEFAULT_TRACKER.export
