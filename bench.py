"""Benchmark: trainer effective token throughput on one real TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

Workload: Qwen2.5-1.5B shapes (the reference's small benchmark model class,
BASELINE.md "1.5B R1-Distill"), bf16 params/optimizer, GRPO decoupled-loss
train step over packed rows — the same fused scan step the real training
loop runs, measured steady-state.  Attention runs the Pallas splash kernel
(areal_tpu/ops/attention.py); the LM head is the chunked rematerialised
scan (ops/functional.py lm_logprobs_entropy), so the workload scales until
HBM is full instead of dying on a [tokens, vocab] fp32 materialisation.

Baseline (vs_baseline denominator): the reference's *effective trainer
throughput per chip* derived from its published numbers (BASELINE.md):
1.5B async run, 1000 PPO steps in 14.8 h on 128 H800s, benchmark workload
512 prompts x 16 samples with ~8k mean tokens per trajectory
=> 512*16*8192 tokens / 53.3 s / 128 chips ~= 9.8k tokens/sec/chip.
This is an estimate (the reference publishes wall-clock, not tok/s/chip);
it is held fixed across rounds so the trend is comparable.

Extra fields (informational): mfu (model-flops 6PT / peak), step_ms,
tokens_per_step, and a 16k-context variant result when it fits
(ctx-scaling evidence for the 32k-context workstream).

Env knobs: BENCH_PROFILE=/path -> writes a jax.profiler trace of 2 steps
(equivalent to --xla-profile-dir).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

BASELINE_TOKENS_PER_SEC_PER_CHIP = 9800.0

MODEL = "qwen25_1p5b"
WARMUP_STEPS = 4
MEASURE_STEPS = 5

def _peak_tflops():
    import jax

    from areal_tpu.utils.profiling import device_peak_tflops

    return device_peak_tflops(), jax.devices()[0].device_kind


def _make_batch(rng, n_rows, row_len, vocab, seqs_per_row=2):
    """`seqs_per_row` packed sequences per row, loss on the latter 75%."""
    seq_len = row_len // seqs_per_row
    B = n_rows * seqs_per_row
    ids = rng.integers(0, vocab, (B, seq_len)).astype(np.int32)
    mask = np.ones((B, seq_len), bool)
    prompt = seq_len // 4
    loss_mask = np.zeros((B, seq_len), np.float32)
    loss_mask[:, prompt:] = 1.0
    return {
        "input_ids": ids,
        "attention_mask": mask,
        "loss_mask": loss_mask,
        "logprobs": rng.normal(-1.0, 0.1, (B, seq_len)).astype(np.float32),
        "rewards": rng.integers(0, 2, B).astype(np.float32),
        "versions": np.zeros((B, seq_len), np.int32),
    }


def _run(model_cfg, model_name, n_rows, row_len, n_mbs=1, seqs_per_row=2,
         group_size=2, remat_policy="save_attn", layer_group_size=1,
         lm_head_chunk=0):
    import jax

    from areal_tpu.api.config import (
        MeshConfig,
        MicroBatchSpec,
        NormConfig,
        OptimizerConfig,
        PPOActorConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.ppo import JaxPPOActor

    cfg = PPOActorConfig(
        experiment_name="bench",
        trial_name="bench",
        init_from_scratch=True,
        dtype="bfloat16",
        # bf16 master+optimizer: a 1.5B fp32 AdamW state does not fit one
        # 16G chip; throughput is what's measured here
        param_dtype="bfloat16",
        gradient_checkpointing=True,
        # selective remat: keep attention outputs (the backward recomputes
        # projections/MLP but not the attention kernel) — fits v5e HBM and
        # buys ~1% over full remat; the ladder falls back to "full" if the
        # borderline fit flakes
        remat_policy=remat_policy,
        # two-level scan (ISSUE 20): >1 groups this many layers behind one
        # remat boundary per outer-scan step — the backward scan-transpose
        # carry shrinks ~G×; must divide the model depth
        layer_group_size=layer_group_size,
        # fused LM-head vocab chunk (0 = env default 8192); the sweep
        # below records the neighbouring widths
        lm_head_chunk=lm_head_chunk,
        # unroll 4 outer-scan steps per iteration: less per-step carry
        # traffic (~2% on v5e); 7+ runs out of HBM.  With grouping the
        # outer length is depth/G — non-divisors would loudly fall back
        # to 1, so grouped rungs pin unroll=1 instead
        scan_unroll=4 if layer_group_size == 1 else 1,
        mesh=MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=n_mbs),
        optimizer=OptimizerConfig(lr=1e-5, warmup_steps_proportion=0.0),
        pack_length_quantum=row_len,
        max_pack_length=row_len,
        group_size=group_size,
        ppo_n_minibatches=1,
        use_decoupled_loss=True,
        # deferred stats fetch: steps pipeline on the device instead of
        # serialising on per-step scalar readback (the real train loop runs
        # the same way and flushes at its logging boundary)
        async_stats=True,
        adv_norm=NormConfig(
            mean_level="group", std_level="group", group_size=group_size
        ),
    )
    actor = JaxPPOActor(cfg, model_config=model_cfg)
    try:
        return _run_on_actor(
            actor, model_cfg, model_name, n_rows, row_len, seqs_per_row
        )
    finally:
        # a failed attempt must free its params/optimizer, or every later
        # (smaller) ladder entry inherits a nearly-full chip and OOMs too
        actor.destroy()


def _run_on_actor(actor, model_cfg, model_name, n_rows, row_len, seqs_per_row):
    import jax

    from areal_tpu.api.io_struct import FinetuneSpec

    actor.initialize(ft_spec=FinetuneSpec(1, 1024, 8))

    rng = np.random.default_rng(0)
    batch = _make_batch(
        rng, n_rows, row_len, model_cfg.vocab_size, seqs_per_row=seqs_per_row
    )
    batch["prox_logp"] = batch["logprobs"].copy()
    actor.compute_advantages(batch)

    tokens_per_step = int(batch["attention_mask"].sum())
    for _ in range(WARMUP_STEPS):
        actor.ppo_update(batch)
    jax.block_until_ready(actor.params)

    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir:
        from areal_tpu.utils.profiling import profile_trace

        with profile_trace(profile_dir):
            actor.ppo_update(batch)
            actor.ppo_update(batch)
            jax.block_until_ready(actor.params)

    # two measurement windows, best wins: the tunneled chip's host-side
    # jitter (network hops per dispatch) biases single windows downward
    dt = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(MEASURE_STEPS):
            actor.ppo_update(batch)
        jax.block_until_ready(actor.params)
        dt = min(dt, (time.perf_counter() - t0) / MEASURE_STEPS)

    tok_per_sec = tokens_per_step / dt
    result = {
        "metric": f"grpo_train_step_throughput_{model_name}_bf16_ctx{row_len}",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tok_per_sec / BASELINE_TOKENS_PER_SEC_PER_CHIP, 3),
        "step_ms": round(dt * 1e3, 1),
        "tokens_per_step": tokens_per_step,
    }
    peak, kind = _peak_tflops()
    from areal_tpu.utils.profiling import param_count

    model_tflops = tokens_per_step * 6 * param_count(model_cfg) / dt / 1e12
    result["model_tflops_per_sec"] = round(model_tflops, 1)
    result["device_kind"] = kind
    if peak:
        result["mfu"] = round(model_tflops / peak, 3)
    # scan shape actually in effect (ISSUE 20 satellite: the silent unroll
    # fallback is now recorded, not guessed) — the engine computed these at
    # initialize() from the post-replace model config
    result["layer_group_size"] = int(
        max(1, actor.model_config.layer_group_size))
    result["effective_scan_unroll"] = int(
        getattr(actor, "_effective_scan_unroll", 1))
    result["lm_head_chunk"] = int(getattr(actor.config, "lm_head_chunk", 0))
    return result


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--xla-profile-dir",
        default=os.environ.get("BENCH_PROFILE", ""),
        help="write a jax.profiler trace of 2 warm steps here "
        "(utils/profiling.py profile_trace; BENCH_PROFILE env is the "
        "legacy spelling)",
    )
    args = p.parse_args()
    if args.xla_profile_dir:
        # _run_on_actor reads the env knob at its capture point
        os.environ["BENCH_PROFILE"] = args.xla_profile_dir

    from areal_tpu.models.model_config import qwen25_1p5b

    # best-throughput workload first (probed on v5e: 8 rows beats 12 —
    # larger batches hit HBM pressure); smaller fallbacks for smaller chips.
    # The two-level scan rungs (ISSUE 20) lead: 28 layers / G=4 = 7 outer
    # steps, one remat boundary per group, backward scan-transpose carry
    # ~G× smaller — the ROADMAP 3b plateau was carry-bound, so the grouped
    # rungs are the headline candidates and the proven G=1 rungs the net
    ladder = [
        # carry_offload parks the per-group saved activations in pinned
        # host DRAM between forward and backward — the HBM-relief rung
        (qwen25_1p5b(), "qwen25_1p5b", 8, 2048, 1, "carry_offload", 4),
        (qwen25_1p5b(), "qwen25_1p5b", 8, 2048, 1, "full", 4),
        (qwen25_1p5b(), "qwen25_1p5b", 8, 2048, 1, "full", 2),
        (qwen25_1p5b(), "qwen25_1p5b", 8, 2048, 1, "save_attn", 1),
        # ROADMAP 3b plateau probe: keep MLP intermediates instead of the
        # attention outputs — the intermediate memory/recompute rung
        # between save_attn and full, aimed at the backward-scan carry
        (qwen25_1p5b(), "qwen25_1p5b", 8, 2048, 1, "save_mlp", 1),
        (qwen25_1p5b(), "qwen25_1p5b", 8, 2048, 1, "full", 1),
        (qwen25_1p5b(), "qwen25_1p5b", 4, 2048, 1, "full", 1),
        (qwen25_1p5b(), "qwen25_1p5b", 2, 2048, 1, "full", 1),
        (qwen25_1p5b().replace(num_layers=14), "qwen25_1p5b_half_depth", 2,
         2048, 1, "full", 1),
    ]
    result = None
    last_err = None
    attempts = []  # self-describing bench (VERDICT r3 #10): which ladder
    # rung produced the headline, and what failed on the way there —
    # each attempt records its error TAIL (the HTTP status / exit code of
    # tunneled compile failures lives at the end of the message)
    for model_cfg, name, n_rows, row_len, n_mbs, policy, lgs in ladder:
        rung = f"{name} x{n_rows}x{row_len} remat={policy} G={lgs}"
        # transient remote_compile HTTP 500s used to forfeit the save_attn
        # rung for the whole round (BENCH_r05: one 500 -> full remat
        # headline); the upper rungs get ONE retry before falling back
        tries = 2 if policy in ("save_attn", "save_mlp", "carry_offload") \
            else 1
        for attempt in range(1, tries + 1):
            try:
                result = _run(model_cfg, name, n_rows, row_len, n_mbs,
                              remat_policy=policy, layer_group_size=lgs)
                attempts.append(
                    {"rung": rung, "attempt": attempt, "ok": True}
                )
                result["remat_policy"] = policy
                result["n_rows"] = n_rows
                headline_rung = (model_cfg, name, n_rows, row_len, n_mbs,
                                 policy, lgs)
                break
            except Exception as e:  # noqa: BLE001 — ladder fall-through
                last_err = e
                msg = str(e)
                # transient: the tunnel's compile service hiccuped (HTTP
                # 500 / compile-helper crash) — worth one retry at the
                # same rung.  OOM (RESOURCE_EXHAUSTED) is deterministic:
                # never retried, straight to the next (smaller) rung.
                transient = (
                    "remote_compile" in msg
                    or "HTTP 500" in msg
                    or "tpu_compile_helper" in msg
                )
                if "RESOURCE_EXHAUSTED" not in msg and not transient:
                    raise  # a real failure must surface, not degrade
                attempts.append({
                    "rung": rung,
                    "attempt": attempt,
                    "ok": False,
                    "error_tail": msg[-200:],
                })
                if transient and attempt < tries:
                    print(
                        f"bench: {rung} transient failure, retrying once",
                        file=sys.stderr,
                    )
                    continue
                print(
                    f"bench: {name} x{n_rows} rows failed, trying smaller",
                    file=sys.stderr,
                )
                break
        if result is not None:
            break
    if result is None:
        raise last_err
    result["attempts"] = attempts
    result["lm_head_impl"] = os.environ.get("AREAL_LM_HEAD_IMPL", "fused")

    # fused LM-head vocab-chunk sweep (ISSUE 20 satellite): the chunk width
    # was a buried env default (8192); now that it's a plumbed knob, record
    # the neighbouring widths on the headline workload so the default is
    # re-justified by data each round.  BENCH_CHUNK_SWEEP=0 skips.
    if os.environ.get("BENCH_CHUNK_SWEEP", "1") != "0":
        sweep = {}
        m_cfg, name, n_rows, row_len, n_mbs, policy, lgs = headline_rung
        for chunk in (4096, 16384):
            try:
                r = _run(m_cfg, name, n_rows, row_len, n_mbs,
                         remat_policy=policy, layer_group_size=lgs,
                         lm_head_chunk=chunk)
                sweep[str(chunk)] = {"tokens_per_sec": r["value"],
                                     "step_ms": r["step_ms"]}
            except Exception as e:  # noqa: BLE001 — informational extras
                print(f"bench: lm_head_chunk={chunk} sweep failed: "
                      f"{str(e)[:120]}", file=sys.stderr)
        if sweep:
            result["lm_head_chunk_sweep"] = sweep
    if args.xla_profile_dir:
        result["xla_profile_dir"] = args.xla_profile_dir

    # ctx-scaling variant: one 16k-token sequence per row — evidence the
    # splash path holds at long context (no O(T^2) mask materialisation)
    try:
        long_res = _run(
            qwen25_1p5b(), "qwen25_1p5b", 1, 16384, 1, seqs_per_row=1,
            group_size=1, remat_policy="full",
        )
        result["ctx16k_tokens_per_sec"] = long_res["value"]
        result["ctx16k_step_ms"] = long_res["step_ms"]
    except Exception as e:  # noqa: BLE001
        print(f"bench: 16k ctx variant failed: {str(e)[:120]}", file=sys.stderr)

    # 32k-context on-chip evidence (VERDICT r2 #8): the 1.5B state doesn't
    # leave room for 32k activations on 16G, so the Qwen2-class ~0.6B
    # (head_dim 128, splash-eligible) carries the long-context train step
    try:
        from areal_tpu.models.model_config import qwen2_0p6b_ctx

        long32 = _run(
            qwen2_0p6b_ctx(), "qwen2_0p6b", 1, 32768, 1, seqs_per_row=1,
            group_size=1, remat_policy="full",
        )
        result["ctx32k_0p6b_tokens_per_sec"] = long32["value"]
        result["ctx32k_0p6b_step_ms"] = long32["step_ms"]
    except Exception as e:  # noqa: BLE001
        print(f"bench: 32k ctx variant failed: {str(e)[:120]}", file=sys.stderr)

    # serving-side probe (VERDICT r3 #1): decode throughput with a busy
    # 64-slot grid + the multi-turn KV-prefix-reuse gain, on the same chip.
    # BENCH_SERVING=0 skips (the full curve lives in scripts/bench_serving.py
    # -> SERVING_BENCH_r{N}.json; the e2e async-vs-sync loop in
    # scripts/bench_e2e_grpo.py -> E2E_GRPO_BENCH_r{N}.json).
    if os.environ.get("BENCH_SERVING", "1") != "0":
        try:
            serving = _serving_probe()
            result.update(serving)
        except Exception as e:  # noqa: BLE001 — informational extras
            print(f"bench: serving probe failed: {str(e)[:120]}", file=sys.stderr)

    # primary-metric carry-over: the full async-vs-sync e2e loop takes
    # ~20 min on chip (scripts/bench_e2e_grpo.py), so its latest recorded
    # run rides along here instead of re-running inside the bench budget.
    # Every carried field is marked in result["stale_from"] with the round
    # it was actually measured in (VERDICT r6 #6): these numbers are NOT
    # re-measured by this bench run and must not read as current.
    try:
        import glob
        import re as _re

        runs = sorted(glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "E2E_GRPO_BENCH_r*.json")))
        if runs:
            with open(runs[-1]) as f:
                e2e = json.load(f)
            m = _re.search(r"_r(\d+)\.json$", runs[-1])
            stale_round = f"r{m.group(1)}" if m else os.path.basename(runs[-1])
            carried = result.setdefault("stale_from", {})
            # an e2e artifact may itself carry sections from an earlier
            # round (a CPU-only round keeps the on-chip sections verbatim
            # and lists them in its own stale_from) — the mark must name
            # the round the number was MEASURED in, not the latest file
            e2e_stale = e2e.get("stale_from", {})

            def _carry(key, value, section=""):
                result[key] = value
                carried[key] = e2e_stale.get(section, stale_round)
            # prefer the run BASELINE.json.published quotes: the
            # heterogeneous-length workload (its latest rerun), falling
            # back to the uniform-length live-swap run
            het = e2e.get("heterogeneous_length_live_swap", {})
            if het:
                src = "heterogeneous_length_live_swap"
                live = het.get("rerun_after_warm_signature_fix") or het
            elif e2e.get("publish_mode_live_swap"):
                src = "publish_mode_live_swap"
                live = e2e["publish_mode_live_swap"]
            else:
                src = ""
                live = e2e
            result["e2e_artifact"] = os.path.basename(runs[-1])
            _carry("e2e_async_trajs_per_sec_per_chip",
                   live["async"]["trajs_per_sec_per_chip"], src)
            _carry("e2e_async_over_sync",
                   live["async_over_sync_trajs_per_sec"], src)
            pause = live["async"].get("pause_window_s_mean")
            if pause is None:  # 0.0 is a real (sub-ms) measurement
                pause = het.get("async", {}).get("pause_window_s_mean")
            _carry("e2e_publish_pause_s", pause, src)
            mt = e2e.get("multi_turn_agentic")
            if mt:
                _carry("e2e_multiturn_async_over_sync",
                       mt["async_over_sync_trajs_per_sec"],
                       "multi_turn_agentic")
                _carry("e2e_multiturn_kv_reused_fraction",
                       mt["kv_reuse"]["reused_fraction"],
                       "multi_turn_agentic")
    except Exception as e:  # noqa: BLE001 — informational extras
        print(f"bench: e2e carry-over failed: {str(e)[:120]}",
              file=sys.stderr)

    print(json.dumps(result))


def _serving_probe():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    import bench_serving as bs

    cfg, params = bs.serving_model_setup()
    decode = bs.bench_decode(cfg, params, [64], max_seq_len=512,
                             gen_tokens=128, prompt_len=64)
    # prefill-dominated turns (the agentic shape where reuse matters) at
    # the SAME regime as BASELINE.json's multiturn_kv_reuse_speedup so the
    # probe tracks the published figure; tiny-turn workloads are
    # decode-bound and measure ~1.0x regardless
    mt = bs.bench_multi_turn(cfg, params, n_convs=8, turns=4,
                             turn_prompt=512, turn_gen=32, max_seq_len=4096)
    out = {}
    if "64" in decode and "tokens_per_sec" in decode["64"]:
        out["serving_decode_tok_s_64slots"] = decode["64"]["tokens_per_sec"]
        # ISSUE 5 window accounting: fraction of the cache width decode
        # actually attended (1.0 would mean the full ceiling is paid)
        out["serving_decode_attended_fraction"] = decode["64"].get(
            "decode_attended_fraction"
        )
        # latency distributions (ISSUE 14): BENCH carries p50/p99 curves,
        # not single-run means
        lat = decode["64"].get("latency") or {}
        for stat, key in (("ttft", "ttft_s"), ("e2e", "e2e_s"),
                          ("itl", "inter_token_s")):
            d = lat.get(key)
            if d:
                out[f"serving_decode_{stat}_p50_s"] = round(d["p50"], 4)
                out[f"serving_decode_{stat}_p99_s"] = round(d["p99"], 4)
    out["serving_multiturn_kv_reuse_speedup"] = mt["speedup"]
    out["serving_multiturn_prefill_tokens_saved_frac"] = round(
        mt["reuse"]["reused_tokens"]
        / max(1, mt["cold"]["prefill_tokens"]), 3,
    )
    # speculative decode (ISSUE 12): acceptance rate + on/off speedup on
    # the repetition-heavy workload, tracked alongside the decode curve
    spec = bs.bench_spec_decode_ab(cfg, params, n_slots=8, gen_tokens=128)
    out["serving_spec_acceptance_rate"] = spec["on"]["spec_acceptance_rate"]
    out["serving_spec_decode_speedup"] = spec["spec_over_plain_tok_s"]
    # ragged paged-decode kernel (ISSUE 19): dispatch collapse + tok/s
    # ratio on the mixed-length workload, with the stream-parity bit
    # riding along (False would mean the kernel broke bit-identity);
    # on CPU the kernel interprets, so the tok/s ratio carries the chip
    # caveat while the dispatch reduction transfers as-is
    ragged = bs.bench_ragged_ab(cfg, params, n_slots=8, gen_tokens=96)
    for regime in ("mixed", "repetition"):
        r = ragged[regime]
        out[f"serving_ragged_speedup_{regime}"] = r["ragged_over_dense_tok_s"]
        out[f"serving_ragged_dispatch_reduction_{regime}"] = (
            r["dispatch_reduction"]
        )
        out[f"serving_ragged_bit_identical_{regime}"] = (
            r["streams_bit_identical"]
        )
    return out


if __name__ == "__main__":
    main()
