"""LoRA adapters for the functional transformer.

Behavioral counterpart of the reference's PEFT integration
(areal/engine/fsdp_engine.py:270-296: get_peft_model over target_modules,
merged-weight push to inference).  TPU-first shape: adapters are extra
leaves inside the layer-stacked pytree (`{w}_lora_a` [L, in, r],
`{w}_lora_b` [L, r, out], B zero-initialised), so the layer scan, GSPMD
sharding and orbax checkpointing all see ordinary arrays; base weights are
frozen with stop_gradient (XLA then dead-code-eliminates their gradient
computation) and the optimizer is `optax.masked` onto adapter leaves only —
m/v state shrinks to adapter size, which is the memory point of LoRA.
"""

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.models.model_config import TransformerConfig

Params = Dict[str, Any]

# HF-style target names -> (subtree, leaf) in the layer pytree
TARGET_MAP = {
    "q_proj": ("attn", "wq"),
    "k_proj": ("attn", "wk"),
    "v_proj": ("attn", "wv"),
    "o_proj": ("attn", "wo"),
    "gate_proj": ("mlp", "w_gate"),
    "up_proj": ("mlp", "w_up"),
    "down_proj": ("mlp", "w_down"),
}


def lora_scale(cfg: TransformerConfig) -> float:
    return cfg.lora_alpha / max(cfg.lora_rank, 1)


def add_lora_params(
    params: Params, cfg: TransformerConfig, rng: jax.Array
) -> Params:
    """Attach adapter leaves next to each targeted base weight."""
    r = cfg.lora_rank
    pdt = jnp.dtype(cfg.param_dtype)
    layers = dict(params["layers"])
    for tgt in cfg.lora_targets:
        sub, leaf = TARGET_MAP[tgt]
        if sub not in layers:
            continue  # e.g. mlp targets on an MoE model
        tree = dict(layers[sub])
        base = tree[leaf]  # [L, in, out]
        L, d_in, d_out = base.shape
        rng, ka = jax.random.split(rng)
        tree[f"{leaf}_lora_a"] = (
            jax.random.normal(ka, (L, d_in, r), jnp.float32) / np.sqrt(d_in)
        ).astype(pdt)
        tree[f"{leaf}_lora_b"] = jnp.zeros((L, r, d_out), pdt)
        layers[sub] = tree
    out = dict(params)
    out["layers"] = layers
    return out


def lora_delta(lp_sub: Params, leaf: str, x: jax.Array, dtype, scale: float):
    """x @ A @ B * scale for one projection, or None if not adapted."""
    a = lp_sub.get(f"{leaf}_lora_a")
    if a is None:
        return None
    b = lp_sub[f"{leaf}_lora_b"]
    down = jnp.einsum("btd,dr->btr", x, a.astype(dtype))
    return jnp.einsum("btr,rh->bth", down, b.astype(dtype)) * dtype.type(scale)


def freeze_base(params: Params, enabled: bool) -> Params:
    """stop_gradient on every non-adapter leaf (no-op when LoRA is off):
    XLA prunes the whole base backward pass."""
    if not enabled:
        return params

    def _maybe(path, leaf):
        name = path[-1].key if path else ""
        return leaf if "_lora_" in str(name) else jax.lax.stop_gradient(leaf)

    return jax.tree_util.tree_map_with_path(_maybe, params)


def trainable_mask(params: Params) -> Params:
    """True for adapter leaves — the optax.masked mask."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: "_lora_" in str(path[-1].key if path else ""), params
    )


def merge_lora(host_params: Params, cfg: TransformerConfig) -> Params:
    """Fold adapters into the base weights (numpy, host side) and drop the
    adapter leaves — what gets pushed to inference servers / exported to HF
    (reference: merged-weight upload, fsdp_engine.py:270)."""
    if cfg.lora_rank <= 0:
        return host_params
    scale = lora_scale(cfg)
    layers = dict(host_params["layers"])
    for sub_name in list(layers):
        sub = layers[sub_name]
        if not isinstance(sub, dict):
            continue
        new_sub = {k: v for k, v in sub.items() if "_lora_" not in k}
        for leaf in list(new_sub):
            a = sub.get(f"{leaf}_lora_a")
            if a is None:
                continue
            b = sub[f"{leaf}_lora_b"]
            base = np.asarray(new_sub[leaf], np.float32)
            delta = np.einsum("ldr,lrh->ldh", np.asarray(a, np.float32),
                              np.asarray(b, np.float32)) * scale
            new_sub[leaf] = (base + delta).astype(np.asarray(sub[leaf]).dtype)
        layers[sub_name] = new_sub
    out = dict(host_params)
    out["layers"] = layers
    return out


def split_lora(params: Params) -> Tuple[Params, Params]:
    """(base-only tree, adapters-only flat dict) for separate persistence."""
    adapters = {}
    layers = dict(params["layers"])
    for sub_name, sub in list(layers.items()):
        if not isinstance(sub, dict):
            continue
        keep = {}
        for k, v in sub.items():
            if "_lora_" in k:
                adapters[f"{sub_name}.{k}"] = v
            else:
                keep[k] = v
        layers[sub_name] = keep
    base = dict(params)
    base["layers"] = layers
    return base, adapters
