"""Stateful batch dataloader over list-like datasets.

Replaces the reference's torchdata `StatefulDataLoader` dependency with a
minimal implementation carrying the same capabilities used by the framework:
deterministic per-epoch shuffling, drop_last batching, and checkpointable
iteration state (`state_dict`/`load_state_dict`) for recover-and-resume.
"""

import random
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence


def _default_collate(items: List[Any]) -> List[Any]:
    return items


class StatefulDataLoader:
    def __init__(
        self,
        dataset: Sequence,
        batch_size: int,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 0,
        collate_fn: Optional[Callable] = None,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.collate_fn = collate_fn or _default_collate
        self._epoch = 0
        self._batch_idx = 0  # next batch index within the epoch

    def _order(self, epoch: int) -> List[int]:
        idx = list(range(len(self.dataset)))
        if self.shuffle:
            random.Random((self.seed, epoch).__hash__()).shuffle(idx)
        return idx

    def __len__(self) -> int:
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def __iter__(self) -> Iterator[Any]:
        order = self._order(self._epoch)
        n_batches = len(self)
        while self._batch_idx < n_batches:
            s = self._batch_idx * self.batch_size
            batch_idx = order[s : s + self.batch_size]
            self._batch_idx += 1
            yield self.collate_fn([self.dataset[i] for i in batch_idx])
        self._epoch += 1
        self._batch_idx = 0

    def state_dict(self) -> Dict[str, int]:
        return {"epoch": self._epoch, "batch_idx": self._batch_idx}

    def load_state_dict(self, state: Dict[str, int]):
        self._epoch = state["epoch"]
        self._batch_idx = state["batch_idx"]


def cycle_dataloader(dataloader: StatefulDataLoader) -> Iterator[Any]:
    while True:
        yielded = False
        for batch in dataloader:
            yielded = True
            yield batch
        if not yielded:
            raise ValueError(
                "dataloader produced zero batches (dataset smaller than "
                "batch_size with drop_last?) — cycling would spin forever"
            )
