from areal_tpu.parallel import distributed
from areal_tpu.parallel.mesh import (
    MeshAxes,
    batch_spec,
    build_mesh,
    mesh_from_alloc,
    named_sharding,
    replicated,
    shard_pytree,
)

__all__ = [
    "MeshAxes",
    "build_mesh",
    "distributed",
    "mesh_from_alloc",
    "batch_spec",
    "named_sharding",
    "replicated",
    "shard_pytree",
]
