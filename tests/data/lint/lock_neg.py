"""C1 negative fixture: every guarded access holds its lock.

Zero findings expected.  The mutation test also consumes this file: it
rewrites `with self._lock:` to `if True:` and asserts the checker then
fires — the acceptance case "deleting a with-lock guard is caught".
"""

import asyncio
import threading


class Disciplined:
    _GUARDED_FIELDS = {"_queue": "_lock", "_counter": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self._counter = 0
        self._free = 0  # unguarded: not part of the contract

    def good_write(self):
        with self._lock:
            self._queue.append(1)
            self._counter += 1

    def good_swap(self):
        with self._lock:
            intake = self._queue
            self._queue = []
        return intake  # the alias is owned by this thread now

    def _drain(self):  # holds: _lock
        out = list(self._queue)
        self._queue = []
        return out

    def good_caller(self):
        with self._lock:
            return self._drain()

    def untracked(self):
        self._free += 1  # unguarded fields stay free


class AsyncDisciplined:
    _GUARDED_FIELDS = {"_running": "_lock"}

    def __init__(self):
        self._lock = asyncio.Lock()
        self._running = {}

    async def good_async(self, key):
        async with self._lock:
            self._running[key] = 1
