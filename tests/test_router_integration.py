"""Router integration: the rollout client pointed at the ROUTER (not the
servers) — requests proxy through to real generation engines, and a weight
update through the router flushes the whole fleet (the reference's
gserver-manager deployment shape: clients -> router -> SGLang fleet)."""

import asyncio
import threading

import numpy as np
import pytest
from aiohttp import web

from areal_tpu.api.config import (
    GenerationHyperparameters,
    InferenceEngineConfig,
)
from areal_tpu.core.remote import RemoteInfEngine
from areal_tpu.engine.jax_remote import JaxBackend
from areal_tpu.gen.engine import GenEngine
from areal_tpu.gen.router import Router, RouterConfig
from areal_tpu.gen.server import GenServer
from areal_tpu.models.model_config import tiny_config
from areal_tpu.workflow.rlvr import RLVRWorkflow


class _Tok:
    eos_token_id = None

    def decode(self, tokens):
        return " ".join(str(t) for t in tokens)


def _unit_reward(prompt, completion, prompt_ids, completion_ids, **kw):
    """Module-level: reward fns cross into the process pool by pickle."""
    return 1.0


def _serve(app_factory):
    holder = {}
    started = threading.Event()

    def _run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def go():
            runner = web.AppRunner(app_factory())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            holder["addr"] = f"127.0.0.1:{runner.addresses[0][1]}"
            started.set()

        loop.run_until_complete(go())
        loop.run_forever()

    threading.Thread(target=_run, daemon=True).start()
    assert started.wait(10)
    return holder["addr"]


@pytest.mark.slow
def test_client_through_router_to_real_servers(tmp_path):
    engines = [
        GenEngine(
            tiny_config(vocab_size=64, qkv_bias=True), n_slots=4,
            max_seq_len=96, seed=i,
        )
        for i in range(2)
    ]
    servers = [GenServer(e) for e in engines]
    for s in servers:
        s.start()
    server_addrs = [_serve(s.app) for s in servers]

    router = Router(
        RouterConfig(schedule_policy="round_robin"), addresses=server_addrs
    )
    router_addr = _serve(router.app)

    client = RemoteInfEngine(
        InferenceEngineConfig(
            experiment_name="ri", trial_name="t", consumer_batch_size=4
        ),
        JaxBackend(),
    )
    # the client sees ONE endpoint: the router
    client.initialize(addr=router_addr)
    workflow = RLVRWorkflow(
        reward_fn=_unit_reward,
        gconfig=GenerationHyperparameters(n_samples=2, max_new_tokens=6),
        tokenizer=_Tok(),
    )
    try:
        batch = client.rollout_batch(
            [{"query_id": str(i), "input_ids": [3, 4, 5]} for i in range(2)],
            workflow=workflow,
        )
        assert batch["input_ids"].shape[0] == 4
        assert (batch["rewards"] == 1.0).all()
        # both real engines served traffic (round-robin proxy)
        assert all(v > 0 for v in router._routed.values())

        # a weight update THROUGH the router flushes every real engine:
        # pause fleet-wide, load the checkpoint, resume, bump versions
        import json
        import urllib.request

        import jax

        from areal_tpu.models.hf import save_hf_checkpoint

        host = jax.tree_util.tree_map(np.asarray, engines[0].params)
        ckpt = tmp_path / "w"
        save_hf_checkpoint(
            host, engines[0].model_config, str(ckpt), save_dtype="float32"
        )
        req = urllib.request.Request(
            f"http://{router_addr}/update_weights",
            data=json.dumps({"path": str(ckpt), "version": 5}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            out = json.loads(resp.read())
        assert out["version"] == 5
        assert all(e.version == 5 for e in engines)  # whole fleet updated
        assert all(not s.paused.is_set() for s in servers)  # and resumed

        # generation still works on the new weights
        batch2 = client.rollout_batch(
            [{"query_id": "post", "input_ids": [9, 10]}], workflow=workflow
        )
        assert batch2["input_ids"].shape[0] == 2
        assert (batch2["versions"][batch2["loss_mask"] > 0] == 5).all()
    finally:
        client.destroy()
        for s in servers:
            s.shutdown.set()
