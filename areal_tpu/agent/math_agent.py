"""Math agents: single-step and retry-until-correct multi-turn.

Capability counterpart of the reference's math agents
(realhf/impl/agent/math_single_step_agent.py:23,
math_multi_turn_agent.py): generate answers for a math prompt, verify via
the environment's `verify_answer` tool, and (multi-turn) retry with
feedback, discounting earlier turns — the agent-layer expression of the
multi-turn workflow (workflow/multi_turn.py shares the convention).
"""

import asyncio
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.agent.api import Agent, register_agent
from areal_tpu.api.config import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest

FEEDBACK = (
    "\nYour answer is either wrong or not parsable. "
    "Please try to answer it again."
)


def _prompt_ids(tokenizer, data: Dict[str, Any]) -> List[int]:
    if "input_ids" in data:
        return list(data["input_ids"])
    if "messages" in data:
        return tokenizer.apply_chat_template(
            data["messages"], add_generation_prompt=True, tokenize=True
        )
    return tokenizer.encode(data["prompt"])


@register_agent("math-single-step")
class MathSingleStepAgent(Agent):
    """n_samples independent answers per prompt, each verified once."""

    def __init__(self, gconfig: GenerationHyperparameters, tokenizer=None):
        self.gconfig = gconfig
        self.tokenizer = tokenizer

    async def _one(self, engine, env, input_ids):
        resp = await engine.agenerate(
            ModelRequest(
                rid=str(uuid.uuid4()),
                input_ids=list(input_ids),
                gconfig=self.gconfig.new(n_samples=1),
                tokenizer=self.tokenizer,
            )
        )
        completion = (
            self.tokenizer.decode(resp.output_tokens) if self.tokenizer else ""
        )
        _, reward, _ = await env.aexecute_tool(
            "verify_answer", {"completion": completion}
        )
        n_in, n_out = resp.input_len, resp.output_len
        return dict(
            input_ids=np.array(resp.input_tokens + resp.output_tokens, np.int32),
            logprobs=np.array([0.0] * n_in + resp.output_logprobs, np.float32),
            loss_mask=np.array([0] * n_in + [1] * n_out, np.int32),
            versions=np.array([-1] * n_in + resp.output_versions, np.int32),
            rewards=np.float32(reward),
        )

    async def collect_trajectory(self, engine, env, data):
        input_ids = _prompt_ids(self.tokenizer, data)
        return list(
            await asyncio.gather(
                *[
                    self._one(engine, env, input_ids)
                    for _ in range(self.gconfig.n_samples)
                ]
            )
        )


@register_agent("math-multi-turn")
class MathMultiTurnAgent(Agent):
    """Retry with feedback until the env accepts or turns run out; the
    final reward is discounted by the number of retries."""

    def __init__(
        self,
        gconfig: GenerationHyperparameters,
        tokenizer=None,
        max_turns: int = 3,
        turn_discount: float = 0.9,
        feedback_text: str = FEEDBACK,
    ):
        self.gconfig = gconfig.new(n_samples=1)
        self.tokenizer = tokenizer
        self.max_turns = max_turns
        self.turn_discount = turn_discount
        self.feedback_text = feedback_text

    async def collect_trajectory(self, engine, env, data):
        seq = _prompt_ids(self.tokenizer, data)
        logprobs = [0.0] * len(seq)
        loss_mask = [0] * len(seq)
        versions = [-1] * len(seq)
        reward, discount = 0.0, 1.0
        for turn in range(self.max_turns):
            resp = await engine.agenerate(
                ModelRequest(
                    rid=str(uuid.uuid4()),
                    input_ids=seq,
                    gconfig=self.gconfig,
                    tokenizer=self.tokenizer,
                )
            )
            seq = seq + resp.output_tokens
            logprobs += resp.output_logprobs
            loss_mask += [1] * resp.output_len
            versions += resp.output_versions
            completion = (
                self.tokenizer.decode(resp.output_tokens)
                if self.tokenizer
                else ""
            )
            _, reward, done = await env.aexecute_tool(
                "verify_answer", {"completion": completion}
            )
            if done or turn == self.max_turns - 1:
                break
            fb = self.tokenizer.encode(
                self.feedback_text, add_special_tokens=False
            )
            seq += fb
            logprobs += [0.0] * len(fb)
            loss_mask += [0] * len(fb)
            versions += [-1] * len(fb)
            discount *= self.turn_discount
        return [
            dict(
                input_ids=np.array(seq, np.int32),
                logprobs=np.array(logprobs, np.float32),
                loss_mask=np.array(loss_mask, np.int32),
                versions=np.array(versions, np.int32),
                rewards=np.float32(reward * discount),
            )
        ]
