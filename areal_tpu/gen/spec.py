"""Self-speculative decoding: prompt-lookup drafting + per-tier draft-length
control (ISSUE 12).

Two host-side pieces, both deliberately model-free:

* :func:`propose_draft` — the prompt-lookup / n-gram drafter.  For a slot
  whose accumulated token history (prompt + generated, including the pending
  last token) ends in some n-gram, find the RIGHTMOST earlier occurrence of
  that n-gram and propose the tokens that followed it.  Math/code RLVR
  rollouts restate the prompt and loop over identifiers, so the continuation
  after a repeated n-gram is a strong guess — and drafting costs no model
  forward at all.

* :class:`SpecController` — picks the per-tier draft length D from a small
  static ladder (default ``(0, 3, 7)``) using a windowed acceptance rate.
  D must stay on a static ladder because each (tier, K, D) triple is a
  distinct jitted verify program; the checked-in signature budget in
  ``analysis/signature_budget.json`` assumes exactly the ladder values.

Correctness does NOT depend on the drafter or the controller: verification
samples every position under the same position-keyed PRNG that plain decode
would use (``sample_tokens_keyed`` with key = fold(decode_key, stream_id,
cache position)), so any draft — good, bad, or empty — yields the
bit-identical output stream.  These components only decide how much
verification work is worth dispatching.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Draft-length ladder: 0 = plain decode (reuses the existing decode program),
# nonzero entries each get their own verify program per (tier, K) bucket.
DEFAULT_SPEC_LADDER: Tuple[int, ...] = (0, 3, 7)

# Windowed-acceptance thresholds: rate >= HI -> top of ladder, >= LO ->
# middle rung, below -> drop to plain decode (with periodic probing).
DEFAULT_ACCEPT_HI = 0.5
DEFAULT_ACCEPT_LO = 0.2
# When a tier has fallen back to D=0, re-probe with a draft every N chunks
# so a workload that turns repetitive mid-stream is re-detected.
DEFAULT_PROBE_EVERY = 8
# Acceptance window: recent (drafted, accepted) pairs per tier.
DEFAULT_WINDOW = 16


def propose_draft(
    history: np.ndarray,
    max_draft: int,
    ngram_max: int = 3,
    ngram_min: int = 1,
) -> np.ndarray:
    """Prompt-lookup draft for one slot.

    ``history`` is the slot's full token history INCLUDING the pending last
    token (the one decode is about to attend from), as a 1-D int array.
    Tries suffix n-grams from ``ngram_max`` down to ``ngram_min``; for the
    first n with an earlier occurrence, returns up to ``max_draft`` tokens
    that followed the RIGHTMOST such occurrence with a full ``max_draft``
    continuation (falling back to the overall-rightmost occurrence when no
    match has that much follow-up).  Deterministic, and safe on empty/short
    histories (returns an empty draft).
    """
    h = np.asarray(history, dtype=np.int32).ravel()
    n_hist = h.shape[0]
    if max_draft <= 0 or n_hist < ngram_min + 1:
        return np.zeros((0,), dtype=np.int32)
    for n in range(min(ngram_max, n_hist - 1), ngram_min - 1, -1):
        suffix = h[n_hist - n:]
        # candidate start positions: occurrence must end before the suffix
        # itself AND leave at least one follow-up token to draft
        limit = n_hist - n  # exclusive upper bound on start index
        if limit <= 0:
            continue
        # vectorized rightmost-match scan over all windows of length n
        windows = np.lib.stride_tricks.sliding_window_view(h[:limit + n - 1], n)
        matches = np.nonzero((windows == suffix).all(axis=1))[0]
        if matches.size == 0:
            continue
        # prefer the rightmost occurrence whose continuation can fill the
        # whole draft: on a stream cycling with period < max_draft, the
        # overall-rightmost match sits so close to the end of history that
        # every draft gets truncated to one period, capping the tokens a
        # single verify dispatch can commit
        full = matches[matches + n + max_draft <= n_hist]
        i = int(full[-1] if full.size else matches[-1])
        draft = h[i + n: i + n + max_draft]
        if draft.size:
            return draft.astype(np.int32)
    return np.zeros((0,), dtype=np.int32)


class SpecController:
    """Per-tier draft-length selection from windowed acceptance rate.

    Tracks (drafted, accepted) for the last ``window`` verify dispatches of
    each tier and maps the rate onto the ladder: ``rate >= hi`` -> ladder
    max, ``rate >= lo`` -> middle rung, else 0.  A tier parked at D=0 emits
    a probe draft every ``probe_every`` chunks so it can climb back.  Starts
    optimistic (ladder max) — the first few chunks of a fresh tier have no
    signal, and a wasted probe costs one verify dispatch.

    This is a pure perf policy: the bit-identical-stream contract holds for
    ANY choice of D at every chunk (see module docstring), so tests may pin
    D while production adapts.
    """

    def __init__(
        self,
        ladder: Sequence[int] = DEFAULT_SPEC_LADDER,
        accept_hi: float = DEFAULT_ACCEPT_HI,
        accept_lo: float = DEFAULT_ACCEPT_LO,
        probe_every: int = DEFAULT_PROBE_EVERY,
        window: int = DEFAULT_WINDOW,
    ):
        lad = sorted(set(int(d) for d in ladder))
        if not lad or lad[0] < 0:
            raise ValueError(f"spec ladder must be non-negative: {ladder}")
        if lad[-1] == 0:
            raise ValueError("spec ladder needs at least one nonzero rung")
        self.ladder = tuple(lad)
        self.nonzero = tuple(d for d in lad if d > 0)
        self.accept_hi = float(accept_hi)
        self.accept_lo = float(accept_lo)
        self.probe_every = max(1, int(probe_every))
        self.window = max(1, int(window))
        # per-tier: list of (drafted, accepted) pairs, newest last
        self._hist: Dict[int, List[Tuple[int, int]]] = {}
        self._idle_chunks: Dict[int, int] = {}

    def draft_len(self, tier: int) -> int:
        """Pick D for this tier's next chunk."""
        hist = self._hist.get(tier)
        if not hist:
            return self.nonzero[-1]  # optimistic start
        drafted = sum(d for d, _ in hist)
        accepted = sum(a for _, a in hist)
        if drafted <= 0:
            return self.nonzero[-1]
        rate = accepted / drafted
        if rate >= self.accept_hi:
            return self.nonzero[-1]
        if rate >= self.accept_lo:
            return self.nonzero[0]
        # parked: probe periodically so a newly-repetitive stream re-climbs
        idle = self._idle_chunks.get(tier, 0)
        if idle + 1 >= self.probe_every:
            self._idle_chunks[tier] = 0
            return self.nonzero[0]
        self._idle_chunks[tier] = idle + 1
        return 0

    def record(self, tier: int, drafted: int, accepted: int) -> None:
        """Feed back one verify dispatch's totals for a tier."""
        if drafted <= 0:
            return
        hist = self._hist.setdefault(tier, [])
        hist.append((int(drafted), int(accepted)))
        if len(hist) > self.window:
            del hist[: len(hist) - self.window]

    def acceptance_rate(self, tier: int) -> Optional[float]:
        """Windowed acceptance rate for telemetry; None before any signal."""
        hist = self._hist.get(tier)
        if not hist:
            return None
        drafted = sum(d for d, _ in hist)
        if drafted <= 0:
            return None
        return sum(a for _, a in hist) / drafted
