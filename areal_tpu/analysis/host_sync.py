"""C2 — host-sync / recompile hazards on hot serving and training paths.

The static complement of the jit-cache-counting tests: files marked
``# areal-lint: hot-path`` (gen/engine.py, models/transformer.py,
engine/jax_train.py, ops/*) are scanned for the patterns that silently
serialise the device pipeline or mint new XLA programs mid-loop:

- `host-item`: any ``.item()`` call — a synchronous device->host readback
  per scalar, the classic decode-loop stall;
- `host-sync`: ``np.asarray``/``np.array``/``float()``/``int()`` applied
  to the result of a jitted callable (any callable named ``*_fn`` — the
  repo convention for jitted programs — or a direct ``jax.jit(...)(...)``
  call).  Each one is a device fence; intentional delivery points carry a
  suppression so the fence count stays visible and counted;
- `unbucketed-shape`: a ``len(...)``/``.shape``-derived int flowing into a
  jitted call site without passing through ``round_up_to_bucket`` or a
  power-of-two ``bit_length`` ladder — every distinct value compiles a new
  program (the recompile-storm class the bucket ladders exist to prevent);
- `host-upload`: ``jnp.asarray(self.<attr>)`` (or ``jnp.array`` /
  ``jax.device_put`` of an instance attribute) passed directly into a
  jitted call — persistent engine state re-uploaded host->device on every
  dispatch.  Per-batch locals are exempt (they are genuinely new data);
  instance attributes are standing state that belongs in a device-resident
  mirror synced only when host bookkeeping mutates it (the ISSUE 5 decode
  loop is the model: `_sync_device_state` on dirty, device->device chaining
  otherwise).

The tracking is per-function and source-ordered: a name assigned from a
jitted call is device-resident until reassigned from a host expression.
"""

import ast
from typing import List, Optional, Set

from areal_tpu.analysis.core import Finding, SourceFile, apply_suppression

_BUCKETING_MARKERS = ("round_up_to_bucket", "bit_length")
_HOST_CONVERTERS = {"float", "int"}
_NP_CONVERTERS = {("np", "asarray"), ("np", "array"), ("numpy", "asarray"),
                  ("numpy", "array")}
_UPLOADERS = {"jnp.asarray", "jnp.array", "jax.numpy.asarray",
              "jax.numpy.array", "jax.device_put"}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_jit_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr.endswith("_fn"):
        return True
    if isinstance(f, ast.Name) and f.id.endswith("_fn"):
        return True
    # jax.jit(fn, ...)(args): callee is itself a jax.jit call
    if isinstance(f, ast.Call) and _dotted(f.func) in ("jax.jit", "jit"):
        return True
    return False


def _is_np_converter(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return (f.value.id, f.attr) in _NP_CONVERTERS
    return False


def _is_host_converter(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id in _HOST_CONVERTERS:
        return True
    return _is_np_converter(call)


def _is_state_upload(arg: ast.AST) -> Optional[str]:
    """`jnp.asarray(self.<attr>, ...)`-shaped argument -> the attr path, or
    None.  Only instance attributes count: per-batch locals are new data,
    `self.*` is standing state that belongs in a device-resident mirror."""
    if not (isinstance(arg, ast.Call) and _dotted(arg.func) in _UPLOADERS):
        return None
    if not arg.args:
        return None
    src = arg.args[0]
    node = src
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and node is not src:
        return ast.unparse(src)
    return None


def _contains(node: ast.AST, pred) -> bool:
    return any(pred(n) for n in ast.walk(node))


def _is_shape_derived(expr: ast.AST) -> bool:
    """len(...) or .shape in the expression, with no bucketing marker."""
    def shapeish(n):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
            if n.func.id == "len":
                return True
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return True
        return False

    def bucketed(n):
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            return any(d.endswith(m) for m in _BUCKETING_MARKERS)
        return False

    return _contains(expr, shapeish) and not _contains(expr, bucketed)


def _assign_targets(node) -> List[str]:
    targets = (
        node.targets if isinstance(node, ast.Assign) else [node.target]
    )
    out: List[str] = []
    for tgt in targets:
        if isinstance(tgt, ast.Name):
            out.append(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            out.extend(
                el.id for el in tgt.elts if isinstance(el, ast.Name)
            )
    return out


def _walk_shallow(fn):
    """All descendants of `fn` WITHOUT descending into nested defs (nested
    functions get their own scan with fresh state)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _scan_function(sf: SourceFile, fn, findings: List[Finding]) -> None:
    device: Set[str] = set()
    shapeish: Set[str] = set()

    # events in source order; an assignment's effect lands AFTER the calls
    # inside its value expression are checked (so `x = np.asarray(x)` on a
    # device-resident x is flagged at the conversion, then x becomes host)
    events = []
    for node in _walk_shallow(fn):
        if isinstance(node, ast.Call):
            events.append((node.lineno, node.col_offset, 0, "call", node))
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            end = getattr(node, "end_lineno", node.lineno)
            events.append((end, node.col_offset, 1, "assign", node))
    events.sort(key=lambda e: (e[0], e[2], e[1]))

    for _, _, _, kind, node in events:
        if kind == "call":
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "item":
                findings.append(
                    apply_suppression(
                        sf,
                        Finding(
                            "host-item",
                            sf.rel,
                            node.lineno,
                            ".item() is a per-scalar device->host sync; "
                            "batch the readback (np.asarray once) or keep "
                            "the value on device",
                        ),
                    )
                )
            if _is_host_converter(node) and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name) and arg.id in device:
                    conv = _dotted(f)
                    findings.append(
                        apply_suppression(
                            sf,
                            Finding(
                                "host-sync",
                                sf.rel,
                                node.lineno,
                                f"{conv}({arg.id}) fences the device: "
                                f"`{arg.id}` is the result of a jitted "
                                "call — fetch once at a delivery point "
                                "(and suppress with the reason) or keep "
                                "it on device",
                            ),
                        )
                    )
            if _is_jit_call(node):
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    attr = _is_state_upload(arg)
                    if attr is not None:
                        findings.append(
                            apply_suppression(
                                sf,
                                Finding(
                                    "host-upload",
                                    sf.rel,
                                    arg.lineno,
                                    f"`{attr}` re-uploaded host->device on "
                                    "every dispatch — persistent engine "
                                    "state belongs in a device-resident "
                                    "mirror synced only when the host "
                                    "mutates it",
                                ),
                            )
                        )
                    hazard = None
                    if isinstance(arg, ast.Name) and arg.id in shapeish:
                        hazard = arg.id
                    elif isinstance(
                        arg, (ast.Call, ast.BinOp, ast.Subscript, ast.Attribute)
                    ) and _is_shape_derived(arg):
                        hazard = ast.unparse(arg)[:40]
                    if hazard is not None:
                        findings.append(
                            apply_suppression(
                                sf,
                                Finding(
                                    "unbucketed-shape",
                                    sf.rel,
                                    arg.lineno,
                                    f"shape-derived value `{hazard}` flows "
                                    "into a jitted call without bucketing "
                                    "— every distinct value compiles a new "
                                    "XLA program (use round_up_to_bucket / "
                                    "a pow2 ladder)",
                                ),
                            )
                        )
        else:  # assign
            targets = _assign_targets(node)
            if not targets or node.value is None:
                continue
            val = node.value
            if isinstance(val, ast.Call) and _is_jit_call(val):
                device.update(targets)
                shapeish.difference_update(targets)
            elif isinstance(val, ast.Call) and _is_host_converter(val):
                device.difference_update(targets)
                shapeish.difference_update(targets)
            elif _is_shape_derived(val):
                shapeish.update(targets)
                device.difference_update(targets)
            else:
                device.difference_update(targets)
                shapeish.difference_update(targets)


def check_host_sync(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    if sf.tree is None or not sf.hot:
        return findings
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_function(sf, node, findings)
    return findings
