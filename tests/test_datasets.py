"""Dataset loader tests (reference: areal/dataset/ — gsm8k/clevr covered
elsewhere; here hhrlhf preference pairs, geometry3k vision manifests, and
torl math rows + the registry dispatch)."""

import json
import os

import numpy as np
import pytest

from areal_tpu.dataset import get_custom_dataset
from tests.fixtures import make_tiny_tokenizer


@pytest.fixture(scope="module")
def tok(tmp_path_factory):
    d = tmp_path_factory.mktemp("tok")
    return make_tiny_tokenizer(str(d))


def test_hhrlhf_pairs(tok, tmp_path):
    rows = [
        {"chosen": "good answer number one", "rejected": "bad"},
        {"chosen": "ok", "rejected": "a much longer rejected response " * 10},
    ]
    p = tmp_path / "pairs.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    ds = get_custom_dataset(str(p), type="hhrlhf", tokenizer=tok)
    assert len(ds) == 2
    assert all(len(x["chosen_ids"]) > 0 and len(x["rejected_ids"]) > 0 for x in ds)

    # max_length filters out the row with the long rejected side
    n_tok_row0 = max(len(ds[0]["chosen_ids"]), len(ds[0]["rejected_ids"]))
    short = get_custom_dataset(
        str(p), type="hhrlhf", tokenizer=tok, max_length=n_tok_row0
    )
    assert len(short) == 1


def test_geometry3k_manifest(tmp_path):
    img = tmp_path / "diagram.png"
    try:
        from PIL import Image

        Image.new("RGB", (40, 20), (255, 0, 0)).save(img)
    except ImportError:
        pytest.skip("PIL unavailable")
    manifest = tmp_path / "train.jsonl"
    manifest.write_text(
        json.dumps(
            {"image": "diagram.png", "problem": "find angle x", "answer": "42"}
        )
    )
    ds = get_custom_dataset(str(tmp_path), type="geometry3k", split="train")
    assert len(ds) == 1
    sample = ds[0]
    assert os.path.isabs(sample["images"][0])
    assert sample["answer"] == "42"
    assert sample["messages"] == "find angle x"

    from areal_tpu.dataset.geometry3k import pad_to_square

    from PIL import Image

    sq = pad_to_square(Image.open(img))
    assert sq.size == (40, 40)


def test_torl_rows(tok, tmp_path):
    rows = [
        {
            "prompt": [{"role": "user", "content": "compute 2+2"}],
            "reward_model": {"ground_truth": "4"},
            "data_source": "torl",
            "ability": "math",
            "extra_info": {},
        }
    ]
    p = tmp_path / "torl.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    ds = get_custom_dataset(str(p), type="torl")
    assert len(ds) == 1
    assert ds[0]["answer"] == "\\boxed{4}"
    assert ds[0]["messages"][0]["content"] == "compute 2+2"

    # pre-converted shape works too
    p2 = tmp_path / "conv.jsonl"
    p2.write_text(json.dumps({"messages": "solve it", "answer": "7"}))
    ds2 = get_custom_dataset(str(p2), type="torl")
    assert ds2[0]["answer"] == "\\boxed{7}"


def test_registry_dispatch_unknown():
    with pytest.raises(ValueError, match="unknown dataset"):
        get_custom_dataset("nope", type="definitely-not-registered")
