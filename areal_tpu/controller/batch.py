"""Batch container for the single-controller RPC layer.

Behavioral counterpart of the reference's `DistributedBatchMemory`
(areal/controller/batch.py:16): a wire-serializable wrapper over a padded
tensor dict that a controller can split across data-parallel engine workers
(`chunk`), merge back (`concat`), and join column-wise (`union`).  Arrays are
numpy host-side; serialization is a single npz blob plus a JSON side-channel
for non-array metadata, so an RPC payload is one POST body with no pickle.
"""

import io
import json
from typing import Any, Dict, List, Sequence

import numpy as np

from areal_tpu.utils.data import batch_size, concat_padded_tensors, select_rows


class DistributedBatch:
    def __init__(self, data: Dict[str, Any]):
        self.arrays: Dict[str, np.ndarray] = {}
        self.meta: Dict[str, Any] = {}
        for k, v in data.items():
            if isinstance(v, np.ndarray):
                self.arrays[k] = v
            elif isinstance(v, (list, tuple)) and v and isinstance(v[0], (int, float)):
                self.arrays[k] = np.asarray(v)
            else:
                self.meta[k] = v

    # ------------------------------ dict-like ---------------------------

    def __getitem__(self, key: str):
        if key in self.arrays:
            return self.arrays[key]
        return self.meta[key]

    def __contains__(self, key: str) -> bool:
        return key in self.arrays or key in self.meta

    def keys(self):
        yield from self.arrays
        yield from self.meta

    def to_dict(self) -> Dict[str, Any]:
        return {**self.arrays, **self.meta}

    def __len__(self) -> int:
        if not self.arrays:
            return 0
        try:
            return batch_size(self.arrays)
        except ValueError:
            # no canonical keys (e.g. a bare result column): rows = dim 0
            return len(next(iter(self.arrays.values())))

    # ------------------------------ split/merge -------------------------

    def chunk(self, n: int, quantum: int = 1) -> List["DistributedBatch"]:
        """Split rows into n near-equal contiguous shards (dp fan-out).

        `quantum` keeps shard boundaries on multiples of a group size so
        grouped ops downstream (GRPO group normalization) never see a
        fractured group.  Rows must divide evenly into quantum blocks and
        there must be at least one block per shard."""
        total = len(self)
        has_vision = "pixel_values" in self.arrays or "patch_img_ids" in self.arrays
        if has_vision and "patches_per_row" not in self.arrays:
            # patch arrays are indexed by PATCH, not row: without per-row
            # patch spans (vision_rlvr emits "patches_per_row") slicing
            # them would desync images from their placeholder tokens
            raise ValueError(
                "vision batches need 'patches_per_row' to be chunked"
            )
        if quantum > 1 and total % quantum:
            raise ValueError(f"{total} rows not divisible by quantum {quantum}")
        blocks = total // quantum
        if blocks < n:
            raise ValueError(
                f"cannot chunk {total} rows ({blocks} blocks of {quantum}) "
                f"into {n} shards"
            )
        from areal_tpu.utils.data import VISION_PATCH_KEYS as patch_keys

        bounds = (np.linspace(0, blocks, n + 1).astype(int)) * quantum
        if has_vision:
            patch_bounds = np.concatenate(
                [[0], np.cumsum(self.arrays["patches_per_row"])]
            )
            for k in patch_keys:
                if k in self.arrays and (
                    self.arrays[k].shape[0] != int(patch_bounds[-1])
                ):
                    # spans must describe the patch arrays exactly, or the
                    # slices silently pair wrong images with rows
                    raise ValueError(
                        f"patches_per_row sums to {int(patch_bounds[-1])} "
                        f"but {k} has {self.arrays[k].shape[0]} patches"
                    )
        row_arrays = {
            k: v for k, v in self.arrays.items() if k not in patch_keys
        }
        out = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            shard = select_rows(row_arrays, list(range(lo, hi)))
            if has_vision:
                p_lo, p_hi = int(patch_bounds[lo]), int(patch_bounds[hi])
                for k in patch_keys:
                    if k in self.arrays:
                        shard[k] = self.arrays[k][p_lo:p_hi]
            b = DistributedBatch(shard)
            b.meta = dict(self.meta)
            out.append(b)
        return out

    @staticmethod
    def concat(batches: Sequence["DistributedBatch"]) -> "DistributedBatch":
        merged = concat_padded_tensors([b.arrays for b in batches])
        out = DistributedBatch(merged)
        for b in batches:
            out.meta.update(b.meta)
        return out

    def union(self, other: "DistributedBatch") -> "DistributedBatch":
        """Column-wise join: add the other batch's keys (same rows)."""
        if len(other) not in (0, len(self)):
            raise ValueError(f"union row mismatch: {len(self)} vs {len(other)}")
        data = {**self.arrays, **other.arrays}
        out = DistributedBatch(data)
        out.meta = {**self.meta, **other.meta}
        return out

    # ------------------------------ wire format -------------------------

    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        arrays = dict(self.arrays)
        arrays["__meta_json__"] = np.frombuffer(
            json.dumps(self.meta).encode(), dtype=np.uint8
        )
        np.savez(buf, **arrays)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, blob: bytes) -> "DistributedBatch":
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
        meta_raw = arrays.pop("__meta_json__", None)
        out = cls(arrays)
        if meta_raw is not None:
            out.meta = json.loads(bytes(meta_raw.tobytes()).decode())
        return out
