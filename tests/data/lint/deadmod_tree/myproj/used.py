"""Alive: imported by app.py (a non-test root)."""

from myproj.helper import add


def run():
    return add(1, 2)
