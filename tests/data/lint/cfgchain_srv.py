"""C10 fixture: the clean server side — every argparse flag reaches the
engine call as a kwarg."""

import argparse


class TinyEngine:  # stand-in so the fixture is self-contained
    pass


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--depth", type=int, default=1)
    p.add_argument("--width", type=int, default=2)
    args = p.parse_args()
    return TinyEngine(depth=args.depth, width=args.width)
