from areal_tpu.agent.api import Agent, AgentWorkflow, make_agent, register_agent
from areal_tpu.agent.math_agent import MathMultiTurnAgent, MathSingleStepAgent

__all__ = [
    "Agent",
    "AgentWorkflow",
    "make_agent",
    "register_agent",
    "MathMultiTurnAgent",
    "MathSingleStepAgent",
]
