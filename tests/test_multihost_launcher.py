"""Multi-host launcher test: fabricate a 2-"host" run on one machine.

The launcher's remote_shell is swapped for a local shell (the reference
fabricates clusters the same way, realhf/base/testing.py), everything else
is the real path: NFS name_resolve rendezvous, gen-server registration +
discovery, per-host trainer processes joining one jax.distributed runtime,
babysitting, and clean shutdown.
"""

import os
import sys
import textwrap

import yaml

from areal_tpu.launcher.multihost import MultiHostLauncher, local_shell

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENTRY = textwrap.dedent(
    """
    import os, sys, time, urllib.request

    sys.path.insert(0, {repo!r})
    from areal_tpu.api.config import GRPOConfig, load_expr_config
    from areal_tpu.parallel import distributed
    from areal_tpu.utils import name_resolve, names

    import jax

    jax.config.update("jax_platforms", "cpu")
    cfg, _ = load_expr_config(sys.argv[1:], GRPOConfig)
    distributed.init_distributed()
    assert jax.process_count() == 2, jax.process_count()

    # discover the generation server through the shared store and probe it
    key = names.gen_servers(cfg.experiment_name, cfg.trial_name)
    deadline = time.monotonic() + 60
    addrs = []
    while time.monotonic() < deadline and not addrs:
        addrs = sorted(name_resolve.get_subtree(key))
        time.sleep(0.25)
    assert addrs, "no gen servers registered"
    health = urllib.request.urlopen(
        f"http://{{addrs[0]}}/health", timeout=10
    ).read()
    print("TRAINER OK", jax.process_index(), addrs[0], flush=True)
    """
)


def test_two_host_launch(tmp_path):
    nr_root = str(tmp_path / "name_resolve")
    fileroot = str(tmp_path / "experiments")
    cfg_path = tmp_path / "cfg.yaml"
    cfg_path.write_text(
        yaml.safe_dump(
            {
                "experiment_name": "mh",
                "trial_name": "t0",
                "cluster": {
                    "fileroot": fileroot,
                    "name_resolve": {"type": "nfs", "nfs_record_root": nr_root},
                },
                "gen_server": {"max_seqs": 2, "max_context_len": 128},
                "recover": {"mode": "disabled", "retries": 1},
            }
        )
    )
    entry_path = tmp_path / "entry.py"
    entry_path.write_text(ENTRY.format(repo=REPO))

    def test_shell(host, cmd, env, workdir):
        env = {
            **env,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        }
        return local_shell(host, cmd, env, workdir)

    launcher = MultiHostLauncher(
        entry=str(entry_path),
        config_args=["--config", str(cfg_path)],
        gen_hosts=["hostA"],
        train_hosts=["hostA", "hostB"],
        remote_shell=test_shell,
        workdir=REPO,
        coordinator_host="127.0.0.1",
    )
    rc = launcher.run()
    assert rc == 0, rc

    log_dir = os.path.join(fileroot, "mh", "t0", "logs")
    logs = {f: open(os.path.join(log_dir, f)).read() for f in os.listdir(log_dir)}
    trainer_out = "".join(v for k, v in logs.items() if k.startswith("trainer"))
    assert "TRAINER OK 0" in trainer_out, logs
    assert "TRAINER OK 1" in trainer_out, logs
