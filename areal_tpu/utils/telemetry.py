"""Process-local telemetry: metrics registry + trajectory event log.

One lock-light module serves the whole fleet's observability needs
(ROADMAP item 4's evidence layer):

- **Metrics** — `Counter` / `Gauge` / `Histogram` behind named
  `Registry` objects, rendered in Prometheus text exposition format.
  Components that already keep their own counters (``engine.stats``,
  router dicts, `StalenessManager`) register a *collector* callback
  that samples them at scrape time, so the hot paths pay nothing.
- **Events** — a bounded in-memory log of timestamped trajectory
  lifecycle events (submit → admission → prefill → decode chunks →
  interrupt/resume → reward → train consumption), dumped to JSONL and
  exportable as a Chrome-trace (Perfetto-loadable) file.
- **Trace ids** — rollouts carry a ``trace_id`` string on the wire
  (ModelRequest → jax_remote → GenRequest → response meta); batches
  carry its stable int64 ``trace_key`` hash so trainer-side events can
  be joined back to the generation-side span stream.

Everything here is host-side Python: no JAX imports, no new XLA
signatures.  Event emission is disabled by default; call
:func:`set_enabled` (or set ``AREAL_TELEMETRY=1``) to turn it on.
Histogram observations at *cold* sites (weight-swap pause windows,
admission) are always live so the evidence histograms populate on any
scrape; per-decode-chunk timing is gated on the enabled flag.
"""

import hashlib
import json
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Global enable flag
# ---------------------------------------------------------------------------

_enabled = os.environ.get("AREAL_TELEMETRY", "") not in ("", "0", "false")

# Cached per process: every event record carries the emitting pid so the
# trace analyzer knows when two events share a perf_counter epoch (the
# monotonic clock is only comparable within one process).
_PID = os.getpid()


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


def trace_key(trace_id: str) -> int:
    """Stable non-negative int64 hash of a trace id.  Rides inside
    trajectory batches (plain int per row) so `train_batch` events can
    be joined to generation-side events without string plumbing."""
    h = hashlib.blake2b(trace_id.encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(h, "big") & 0x7FFFFFFFFFFFFFFF


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _escape_label(v: Any) -> str:
    s = str(v)
    for ch, rep in _LABEL_ESC.items():
        s = s.replace(ch, rep)
    return s


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
STALENESS_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 16.0, 32.0)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str):
        self.name = _sanitize(name)
        self.help = help
        self._lock = threading.Lock()

    def samples(self) -> List[Tuple[str, Dict[str, Any], float]]:
        """[(suffix, labels, value)] — suffix appended to the metric name
        ("" for plain counters/gauges, "_bucket"/"_sum"/"_count" for
        histograms)."""
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str):
        super().__init__(name, help)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    @staticmethod
    def _key(labels: Optional[Dict[str, Any]]) -> Tuple[Tuple[str, str], ...]:
        if not labels:
            return ()
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set_total(self, value: float, **labels: Any) -> None:
        """Scrape-time sampling of an externally maintained monotonic
        total (e.g. ``engine.stats`` counters) — the source guarantees
        monotonicity, the registry just mirrors it."""
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def samples(self):
        with self._lock:
            return [("", dict(k), v) for k, v in sorted(self._values.items())]


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # label-key -> [bucket counts..., +Inf count]; plus (sum, count)
        self._counts: Dict[Tuple[Tuple[str, str], ...], List[float]] = {}
        self._sums: Dict[Tuple[Tuple[str, str], ...], List[float]] = {}

    def observe(self, value: float, **labels: Any) -> None:
        key = Counter._key(labels)
        v = float(value)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0.0] * (len(self.buckets) + 1)
                self._sums[key] = [0.0, 0.0]
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1.0
                    break
            else:
                counts[-1] += 1.0
            s = self._sums[key]
            s[0] += v
            s[1] += 1.0

    def samples(self):
        out = []
        with self._lock:
            for key in sorted(self._counts):
                labels = dict(key)
                cum = 0.0
                for b, c in zip(self.buckets, self._counts[key][:-1]):
                    cum += c
                    out.append(("_bucket", {**labels, "le": _fmt(b)}, cum))
                cum += self._counts[key][-1]
                out.append(("_bucket", {**labels, "le": "+Inf"}, cum))
                out.append(("_sum", labels, self._sums[key][0]))
                out.append(("_count", labels, self._sums[key][1]))
        return out


class Registry:
    """A named collection of metrics plus scrape-time collectors.

    Collectors are zero-arg callables invoked before rendering; they
    sample external state (``engine.stats``, router dicts, staleness
    stats) into registered metrics, keeping the owning hot paths free
    of any telemetry bookkeeping.  A collector that raises is skipped
    (and counted) rather than failing the scrape."""

    def __init__(self, namespace: str):
        self.namespace = _sanitize(namespace)
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], None]] = []
        self.collector_errors = 0

    def _full(self, name: str) -> str:
        name = _sanitize(name)
        if name.startswith("areal_"):
            return name
        return f"areal_{self.namespace}_{name}"

    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Metric:
        full = self._full(name)
        with self._lock:
            m = self._metrics.get(full)
            if m is None:
                m = cls(full, help, **kw)
                self._metrics[full] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {full} already registered as {type(m).__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                self.collector_errors += 1

    def metric_names(self) -> List[str]:
        self.collect()
        with self._lock:
            return sorted(self._metrics)

    def render_prometheus(self) -> str:
        self.collect()
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for suffix, labels, value in m.samples():
                if labels:
                    lab = ",".join(
                        f'{_sanitize(k)}="{_escape_label(v)}"'
                        for k, v in sorted(labels.items())
                    )
                    lines.append(f"{m.name}{suffix}{{{lab}}} {_fmt(value)}")
                else:
                    lines.append(f"{m.name}{suffix} {_fmt(value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able {metric: value | {label_repr: value} | histogram}."""
        self.collect()
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        out: Dict[str, Any] = {}
        for m in metrics:
            if isinstance(m, Histogram):
                for suffix, labels, value in m.samples():
                    name = m.name + suffix
                    lab = {k: v for k, v in labels.items()}
                    key = json.dumps(lab, sort_keys=True) if lab else ""
                    out.setdefault(name, {})[key or "_"] = value
            else:
                samples = list(m.samples())
                if any(labels for _, labels, _ in samples):
                    # a family with any labeled series renders as a dict;
                    # its unlabeled series (legal in Prometheus — e.g. a
                    # fleet-wide rate next to per-tier rates) keys as ""
                    d = out.setdefault(m.name, {})
                    for _, labels, value in samples:
                        key = (json.dumps(labels, sort_keys=True)
                               if labels else "")
                        d[key] = value
                else:
                    for _, _, value in samples:
                        out[m.name] = value
        return out


_registries: Dict[str, Registry] = {}
_registries_lock = threading.Lock()


def registry(name: str) -> Registry:
    with _registries_lock:
        reg = _registries.get(name)
        if reg is None:
            reg = _registries[name] = Registry(name)
        return reg


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, float]]:
    """Minimal exposition-format parser (for tests / snapshot diffing):
    returns {metric_name: {label_block_or_'': value}}."""
    out: Dict[str, Dict[str, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$", line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labels, raw = m.groups()
        value = float("inf") if raw == "+Inf" else float(raw)
        out.setdefault(name, {})[labels or ""] = value
    return out


# ---------------------------------------------------------------------------
# Canonical evidence metrics (ISSUE 10 histograms, shared across modules)
# ---------------------------------------------------------------------------

GEN = registry("gen")
ROUTER = registry("router")
TRAIN = registry("train")

PAUSE_WINDOW = GEN.histogram(
    "pause_window_seconds",
    "Generation pause window at weight load/swap/commit (replaces the "
    "single overwritten last_pause_s)",
)
ADMISSION_WAIT = GEN.histogram(
    "admission_queue_wait_seconds",
    "submit() -> slot admission wait (holdback + group-hold + queue)",
)
DECODE_CHUNK = GEN.histogram(
    "decode_chunk_seconds",
    "Per-tier decode-chunk dispatch+fetch latency (label: tier)",
)
HANDOFF = GEN.histogram(
    "kv_handoff_seconds",
    "Prefill->decode KV handoff latency (label: op=export|import) — the "
    "worker-thread service time of one cross-server page-set transfer leg",
)
STALENESS_AT_CONSUMPTION = TRAIN.histogram(
    "staleness_at_consumption",
    "consumed_version - behavior_version per trajectory row at train_batch",
    buckets=STALENESS_BUCKETS,
)
# Fault-tolerance evidence (ISSUE 11).  Registered at module import so the
# pinned metric appears on the train /metrics surface (TYPE line) even
# before the first backend ever fails; the client-side failover loop in
# core/remote.py increments it.  The name is already fully qualified, so
# the registry serves it verbatim rather than namespacing it.
CLIENT_RESUBMISSIONS = TRAIN.counter(
    "areal_client_resubmissions_total",
    "Trajectories resubmitted to another server after a backend failure",
)

# A resubmit whose replacement server reported nonzero cache_hit_tokens:
# the retried trajectory warm-started through the radix/paged prefix cache
# (ISSUE 16) instead of cold-prefilling its accumulated tokens.
CLIENT_RESUBMIT_CACHE_HITS = TRAIN.counter(
    "areal_client_resubmit_cache_hits_total",
    "Failover resubmits that warm-started via a prefix-cache hit",
)

# Incremented once per successful RecoverHandler.load — a relaunched run
# resuming from a recover generation (utils/recover.py).  Registered at
# import for the same early-visibility reason as above.
TRAIN_RECOVER = TRAIN.counter(
    "areal_train_recover_total",
    "Trainer restarts that resumed from a recover checkpoint generation",
)

# Control-plane fanouts (update_weights / set_version / pause / continue)
# that missed at least one server.  Eager registration so the pinned name
# serves a TYPE line before the first partial failure; core/remote.py's
# fanout path increments it by the number of servers missed.
PUBLISH_PARTIAL_FAILURES = TRAIN.counter(
    "publish_partial_failures_total",
    "Servers missed by client control-plane fanouts",
)

# The silent-0 class made visible at runtime (ISSUE 18): the legacy
# /metrics JSON in gen/server.py reads engine.stats through a tolerant
# .get so a stats-key rename degrades the reported counter to 0 instead
# of 500ing the scrape — this counts every such degraded lookup so the
# drift shows up on the Prometheus surface instead of hiding in a zero.
GEN_STATS_KEY_MISSES = GEN.counter(
    "stats_key_misses_total",
    "Legacy /metrics JSON lookups of engine.stats keys that were absent",
)


# ---------------------------------------------------------------------------
# Event log
# ---------------------------------------------------------------------------


class EventLog:
    """Bounded in-memory trajectory event log.

    `emit` is a no-op unless telemetry is enabled; when the ring is
    full the oldest events fall off (counted in `dropped`).  Dumping
    (JSONL / Chrome trace) snapshots under the lock and writes outside
    it — call the dump methods from sync contexts only (benches,
    tests, executor threads), never on an event loop."""

    def __init__(self, capacity: int = 65536):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self.dropped = 0

    def emit(self, event: str, trace_id: Optional[str] = None,
             **fields: Any) -> None:
        if not _enabled:
            return
        # Paired clocks: wall `ts` joins events across processes, mono
        # `mono` (perf_counter) gives skew-free stage durations within
        # one process.  The analyzer prefers mono when pids match.
        rec: Dict[str, Any] = {
            "ts": time.time(),
            "mono": time.perf_counter(),
            "pid": _PID,
            "event": event,
        }
        if trace_id:
            rec["trace_id"] = trace_id
            rec.setdefault("trace_key", trace_key(trace_id))
        rec.update(fields)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(rec)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def dump_jsonl(self, path: str) -> int:
        events = self.snapshot()
        with self._lock:
            dropped = self.dropped
        if dropped:
            # Ring overflow is silent data loss to downstream analysis;
            # stamp it into the dump so the trace analyzer can refuse to
            # call a lossy log "complete" (see areal_tpu/obs/trace.py).
            events = events + [{
                "ts": time.time(),
                "mono": time.perf_counter(),
                "pid": _PID,
                "event": "telemetry_meta",
                "dropped_events": dropped,
                "capacity": self._events.maxlen,
            }]
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return len(events)

    def to_chrome_trace(
        self, events: Optional[Iterable[Dict[str, Any]]] = None
    ) -> Dict[str, Any]:
        """Chrome trace-event JSON (load in Perfetto / chrome://tracing).
        Events with a `latency_s`/`dur_s` field become complete ("X")
        slices; everything else becomes an instant event.  Each trace id
        gets its own track (tid = trace_key)."""
        evs = list(events) if events is not None else self.snapshot()
        trace_events: List[Dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "areal"}},
        ]
        if not evs:
            return {"traceEvents": trace_events, "displayTimeUnit": "ms"}
        t0 = min(e["ts"] for e in evs)
        for e in evs:
            ts_us = (e["ts"] - t0) * 1e6
            tid = int(e.get("trace_key") or 0) % (2**31)
            args = {k: v for k, v in e.items() if k not in ("ts", "event")}
            dur = e.get("latency_s") or e.get("dur_s")
            if dur:
                trace_events.append({
                    "name": e["event"], "ph": "X", "cat": "areal",
                    "pid": 1, "tid": tid,
                    "ts": max(0.0, ts_us - float(dur) * 1e6),
                    "dur": float(dur) * 1e6, "args": args,
                })
            else:
                trace_events.append({
                    "name": e["event"], "ph": "i", "s": "t", "cat": "areal",
                    "pid": 1, "tid": tid, "ts": ts_us, "args": args,
                })
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def dump_chrome_trace(self, path: str) -> int:
        trace = self.to_chrome_trace()
        with open(path, "w") as f:
            json.dump(trace, f)
        return len(trace["traceEvents"]) - 1  # minus metadata record


EVENTS = EventLog(
    capacity=int(os.environ.get("AREAL_TELEMETRY_EVENTS", "65536"))
)


def emit(event: str, trace_id: Optional[str] = None, **fields: Any) -> None:
    EVENTS.emit(event, trace_id=trace_id, **fields)


def _register_events_dropped(reg: Registry) -> None:
    c = reg.counter(
        "areal_telemetry_events_dropped_total",
        "Lifecycle events lost to EventLog ring overflow; any nonzero "
        "value marks downstream trace analysis incomplete",
    )
    reg.add_collector(lambda: c.set_total(float(EVENTS.dropped)))


# All three fleet surfaces (gen server, router, trainer endpoint) render
# these registries, so ring overflow is visible wherever /metrics is —
# the name is fully qualified and therefore served verbatim on each.
for _reg in (GEN, ROUTER, TRAIN):
    _register_events_dropped(_reg)
del _reg


# ---------------------------------------------------------------------------
# Trainer-side helpers
# ---------------------------------------------------------------------------


def publish_train_stats(stats: Dict[str, Any]) -> None:
    """Mirror one train step's scalar stats into the `train` registry
    (gauges per stat + a steps counter).  Called once per train step —
    cold relative to the step itself."""
    reg = TRAIN
    reg.counter("steps_total", "Optimizer steps taken").inc()
    for k, v in stats.items():
        try:
            f = float(v)
        except (TypeError, ValueError):
            continue
        reg.gauge(f"step_{k}", f"Last train step's {k}").set(f)
    if "step_time" in stats and "total_loss_weight" in stats:
        reg.counter("tokens_weighted_total",
                    "Cumulative loss-weight (token) count consumed").inc(
                        float(stats["total_loss_weight"]))


def register_staleness(reg: Registry, manager: Any) -> None:
    """Scrape-time collector exporting StalenessManager's RolloutStat
    (submitted / running / accepted) as gauges."""
    sub = reg.gauge("rollout_submitted", "Rollouts submitted (RolloutStat)")
    run = reg.gauge("rollout_running", "Rollouts in flight (RolloutStat)")
    acc = reg.gauge("rollout_accepted", "Rollouts accepted (RolloutStat)")
    rej = reg.gauge("rollout_rejected", "Rollouts rejected (RolloutStat)")

    def _collect():
        st = manager.get_stats()
        sub.set(st.submitted)
        run.set(st.running)
        acc.set(st.accepted)
        rej.set(getattr(st, "rejected", 0))

    reg.add_collector(_collect)


# ---------------------------------------------------------------------------
# Standalone metrics endpoint (trainer side)
# ---------------------------------------------------------------------------


def start_metrics_server(reg: Registry, host: str = "127.0.0.1",
                         port: int = 0):
    """Serve `reg` at ``/metrics`` (Prometheus text; ``?format=json``
    for the snapshot dict) on a daemon thread.  Returns
    ``(server, port)``; call ``server.shutdown()`` to stop.  This is
    the trainer's lightweight metrics surface — the gen server and
    router mount their registries on their existing aiohttp apps."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path.split("?")[0] not in ("/metrics", "/health"):
                self.send_error(404)
                return
            if self.path.startswith("/health"):
                body = b'{"status": "ok"}'
                ctype = "application/json"
            elif "format=json" in self.path:
                body = json.dumps(reg.snapshot()).encode()
                ctype = "application/json"
            else:
                body = reg.render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr lines
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="areal-metrics")
    t.start()
    return srv, srv.server_address[1]


def wants_prometheus(query_format: Optional[str], accept: str) -> bool:
    """Shared content negotiation for the gen server / router /metrics
    endpoints: explicit ``?format=prometheus`` wins; otherwise honor an
    Accept header asking for text/plain or openmetrics.  Default stays
    the legacy JSON dict."""
    if query_format:
        return query_format in ("prometheus", "text")
    accept = accept or ""
    return "text/plain" in accept or "openmetrics" in accept
