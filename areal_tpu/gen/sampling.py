"""Batched token sampling, shape-static for the decode jit.

Counterpart of the sampling the reference delegates to SGLang/vLLM servers
(temperature / top-k / top-p / greedy, areal/api/cli_args.py
GenerationHyperparameters).  Per-slot parameters are arrays so one compiled
step serves heterogeneous requests; top-k/top-p run inside a static
`TOPK_WINDOW`-wide candidate window (lax.top_k), which is exact whenever the
nucleus fits the window — 64 candidates at temperature ≤ 1 covers it in
practice.  Returned logprobs are exact full-vocab log-softmax values.
"""

from typing import Dict

import jax
import jax.numpy as jnp

TOPK_WINDOW = 64
NEG_INF = -1e30


def sample_tokens(
    logits: jax.Array,  # [S, V] fp32
    rng: jax.Array,
    temperature: jax.Array,  # [S]; 0 = greedy
    top_k: jax.Array,  # [S] int32; 0 = disabled
    top_p: jax.Array,  # [S]; 1.0 = disabled
):
    """Returns (tokens [S], logprobs [S]) — logprob of the sampled token
    under the *unmodified* (temperature-scaled) distribution, matching what
    inference servers report and what decoupled PPO consumes."""
    S, V = logits.shape
    logits = logits.astype(jnp.float32)
    greedy = temperature <= 0.0
    safe_temp = jnp.where(greedy, 1.0, temperature)
    scaled = logits / safe_temp[:, None]

    # candidate window
    win_logits, win_idx = jax.lax.top_k(scaled, TOPK_WINDOW)  # [S, W]
    ranks = jnp.arange(TOPK_WINDOW)[None, :]
    # top-k mask (0 = off)
    k = jnp.where(top_k <= 0, TOPK_WINDOW, jnp.minimum(top_k, TOPK_WINDOW))
    keep = ranks < k[:, None]
    # top-p mask over the window distribution
    win_probs = jax.nn.softmax(win_logits, axis=-1)
    cum = jnp.cumsum(win_probs, axis=-1)
    keep &= (cum - win_probs) < top_p[:, None]  # keep first token exceeding p
    keep |= ranks == 0  # top_p=0 must mean near-greedy, never mask everything
    masked = jnp.where(keep, win_logits, NEG_INF)

    choice = jax.random.categorical(rng, masked, axis=-1)  # [S] window index
    sampled = jnp.take_along_axis(win_idx, choice[:, None], axis=-1)[:, 0]
    tokens = jnp.where(greedy, win_idx[:, 0], sampled)

    logz = jax.nn.logsumexp(scaled, axis=-1)
    tok_logit = jnp.take_along_axis(scaled, tokens[:, None], axis=-1)[:, 0]
    return tokens, tok_logit - logz
