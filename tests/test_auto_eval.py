"""AutomaticEvaluator tests (reference: realhf/scheduler/evaluator.py:348
— watch ckpt dir, evaluate each new checkpoint once, persist results)."""

import json
import os

import pytest

from areal_tpu.utils.auto_eval import AutoEvalConfig, AutomaticEvaluator


def _make_ckpt(root, name):
    d = os.path.join(root, name)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "config.json"), "w") as f:
        f.write("{}")
    return d


def test_evaluates_new_checkpoints_in_order(tmp_path):
    root = str(tmp_path / "ckpts")
    _make_ckpt(root, "globalstep10")
    _make_ckpt(root, "globalstep2")
    os.makedirs(os.path.join(root, "not_a_ckpt"))  # no model files: skipped

    log = tmp_path / "evals.txt"
    ev = AutomaticEvaluator(
        AutoEvalConfig(
            ckpt_root=root,
            eval_cmd=(
                f"echo {{name}} >> {log} && "
                "echo '{\"accuracy\": 0.5, \"ckpt\": \"{name}\"}'"
            ),
        )
    )
    results = ev.step()
    assert [r["name"] for r in results] == ["globalstep2", "globalstep10"]
    assert all(r["rc"] == 0 for r in results)
    assert results[0]["metrics"]["accuracy"] == 0.5
    assert log.read_text().split() == ["globalstep2", "globalstep10"]

    # second sweep: nothing new -> no re-evaluation
    assert ev.step() == []

    # new checkpoint appears -> only it runs
    _make_ckpt(root, "globalstep20")
    results = ev.step()
    assert [r["name"] for r in results] == ["globalstep20"]


def test_results_persist_across_restart(tmp_path):
    root = str(tmp_path / "ckpts")
    _make_ckpt(root, "globalstep1")
    cfg = AutoEvalConfig(ckpt_root=root, eval_cmd="echo '{\"ok\": 1}'")
    AutomaticEvaluator(cfg).step()

    # a fresh instance (restart) reads the jsonl and skips finished work
    ev2 = AutomaticEvaluator(cfg)
    assert ev2.step() == []
    lines = open(os.path.join(root, "autoeval.jsonl")).read().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["metrics"] == {"ok": 1}


def test_failed_eval_recorded_with_stderr(tmp_path):
    root = str(tmp_path / "ckpts")
    _make_ckpt(root, "globalstep1")
    ev = AutomaticEvaluator(
        AutoEvalConfig(ckpt_root=root, eval_cmd="echo doom >&2; exit 3")
    )
    (r,) = ev.step()
    assert r["rc"] == 3 and "doom" in r["stderr_tail"]
    assert r["metrics"] is None


def test_config_validation():
    with pytest.raises(ValueError):
        AutomaticEvaluator(AutoEvalConfig())
