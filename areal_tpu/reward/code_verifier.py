"""Sandboxed code-execution reward verification.

Behavioral counterpart of the reference's `functioncall/code` service
(functioncall/code/local_verify.py, functioncall/code/function/
testing_util.py): model-generated code is executed against problem test
cases in an isolated subprocess and the reward is the pass verdict.  The
TPU repo keeps the local path only (the reference's FaaS remote path is a
deployment concern, not an algorithm one) and hardens it:

- each case runs in a fresh `python -I` (isolated mode) subprocess, its own
  session (os.setsid), an empty environment, and a throwaway cwd;
- resource limits via preexec: CPU seconds, address space, process count,
  file size — so a fork bomb, allocation bomb, or busy loop in generated
  code cannot take the host down;
- wall-clock timeout kills the whole process group.

Two problem styles, mirroring the reference's dataset coverage:
- "stdio": run the program with `input` on stdin, compare stdout to
  `expected_output` (whitespace-normalised, numeric-tolerant);
- "assert": append the problem's assertion snippet(s) to the submission and
  pass iff the process exits 0.
"""

import os
import re
import signal
import subprocess
import sys
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from areal_tpu.utils import logging

logger = logging.getLogger("code_verifier")

DEFAULT_TIMEOUT = 6.0  # reference SINGLE_CASE_EXEC_TIMEOUT (local_verify.py)
DEFAULT_MEMORY_MB = 512


@dataclass
class CaseResult:
    passed: bool
    reason: str = ""
    stdout: str = ""
    stderr: str = ""


_FENCE_RE = re.compile(r"```(?:python|py)?\s*\n(.*?)```", re.DOTALL)


def extract_code(text: str) -> str:
    """Last fenced code block wins (the reference evaluates the final
    answer block); fall back to the raw text when there is no fence."""
    blocks = _FENCE_RE.findall(text)
    return blocks[-1].strip() if blocks else text.strip()


def _limit_resources(memory_mb: int, cpu_seconds: int):
    def apply():
        import resource

        os.setsid()
        mem = memory_mb * 1024 * 1024
        resource.setrlimit(resource.RLIMIT_AS, (mem, mem))
        resource.setrlimit(resource.RLIMIT_CPU, (cpu_seconds, cpu_seconds))
        resource.setrlimit(resource.RLIMIT_FSIZE, (16 * 1024 * 1024,) * 2)
        try:
            resource.setrlimit(resource.RLIMIT_NPROC, (64, 64))
        except (ValueError, OSError):
            pass  # already lower than 64 in this environment

    return apply


def _run_sandboxed(
    code: str,
    stdin: str = "",
    timeout: float = DEFAULT_TIMEOUT,
    memory_mb: int = DEFAULT_MEMORY_MB,
) -> CaseResult:
    with tempfile.TemporaryDirectory(prefix="codeverify-") as tmp:
        path = os.path.join(tmp, "main.py")
        with open(path, "w") as f:
            f.write(code)
        try:
            proc = subprocess.Popen(
                [sys.executable, "-I", path],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                cwd=tmp,
                env={"PATH": "/usr/bin:/bin", "HOME": tmp},
                preexec_fn=_limit_resources(memory_mb, int(timeout) + 1),
                text=True,
            )
        except OSError as e:
            return CaseResult(False, f"spawn failed: {e}")
        try:
            out, err = proc.communicate(input=stdin, timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait()
            return CaseResult(False, "timeout")
        if proc.returncode != 0:
            return CaseResult(
                False, f"exit {proc.returncode}", stdout=out, stderr=err[-2000:]
            )
        return CaseResult(True, stdout=out, stderr=err[-2000:])


def _outputs_match(got: str, expected: str) -> bool:
    """Line-by-line comparison, whitespace-normalised; numeric lines compare
    with a small tolerance (the reference's testing_util accepts float
    answers printed at different precisions)."""
    got_lines = [l.strip() for l in got.strip().splitlines() if l.strip()]
    exp_lines = [l.strip() for l in expected.strip().splitlines() if l.strip()]
    if len(got_lines) != len(exp_lines):
        return False
    for g, e in zip(got_lines, exp_lines):
        if g == e:
            continue
        g_tok, e_tok = g.split(), e.split()
        if len(g_tok) != len(e_tok):
            return False
        for gt, et in zip(g_tok, e_tok):
            if gt == et:
                continue
            try:
                if abs(float(gt) - float(et)) > 1e-6 * max(1.0, abs(float(et))):
                    return False
            except ValueError:
                return False
    return True


def verify_code(
    generation: str,
    problem: Dict[str, Any],
    timeout: float = DEFAULT_TIMEOUT,
    memory_mb: int = DEFAULT_MEMORY_MB,
    max_cases: Optional[int] = None,
) -> List[CaseResult]:
    """Run one submission against a problem's test cases.

    Problem dict formats:
      {"inputs": [...], "outputs": [...]}            stdio style
      {"test_cases": [{"input":..., "output":...}]}  stdio style
      {"asserts": ["assert f(2)==4", ...]}           assertion style
    """
    code = extract_code(generation)
    results: List[CaseResult] = []
    if "asserts" in problem:
        cases = problem["asserts"]
        if max_cases:
            cases = cases[:max_cases]
        for snippet in cases:
            full = f"{code}\n\n{snippet}\n"
            results.append(_run_sandboxed(full, timeout=timeout, memory_mb=memory_mb))
        return results

    if "test_cases" in problem:
        pairs = [(c["input"], c["output"]) for c in problem["test_cases"]]
    elif "inputs" in problem:
        pairs = list(zip(problem["inputs"], problem["outputs"]))
    else:
        raise ValueError(
            "problem needs 'asserts', 'test_cases', or 'inputs'/'outputs'"
        )
    if max_cases:
        pairs = pairs[:max_cases]
    for stdin, expected in pairs:
        r = _run_sandboxed(code, stdin=stdin, timeout=timeout, memory_mb=memory_mb)
        if r.passed and not _outputs_match(r.stdout, expected):
            r = CaseResult(
                False,
                f"wrong answer: got {r.stdout.strip()[:200]!r} "
                f"expected {str(expected).strip()[:200]!r}",
                stdout=r.stdout,
            )
        results.append(r)
    return results


def code_reward_fn(
    prompt, completions, prompt_ids, completion_ids, **data
) -> float:
    """Reward-API-compatible entry (same signature family as
    reward/math_parser.py gsm8k_reward_fn): 1.0 iff every test case of the
    sample's problem passes.  The problem spec rides in the dataset row
    under 'problem' (dict or JSON string).

    With AREAL_CODE_VERIFIER_ADDR set, verification is delegated to the
    remote service (reward/code_verifier_service.py — the reference's FaaS
    deployment shape, functioncall/) so untrusted code never runs on the
    rollout host; the local rlimit sandbox remains the fallback."""
    problem = data.get("problem")
    if problem is None:
        raise ValueError("code_reward_fn needs a 'problem' field in data")
    if isinstance(problem, str):
        import json

        problem = json.loads(problem)
    timeout = float(data.get("case_timeout", DEFAULT_TIMEOUT))
    max_cases = data.get("max_cases")
    addr = os.environ.get("AREAL_CODE_VERIFIER_ADDR")
    if addr:
        from areal_tpu.reward.code_verifier_service import remote_verify_reward

        try:
            return remote_verify_reward(
                addr, completions, problem, timeout=timeout, max_cases=max_cases
            )
        except Exception as e:  # noqa: BLE001
            if os.environ.get("AREAL_CODE_VERIFIER_STRICT"):
                # isolation deployments: NEVER run untrusted code on this
                # host — a verifier outage fails the reward closed (0.0)
                logger.error(
                    f"code verifier service {addr} unreachable ({e}); "
                    "strict mode returns reward 0 (no local execution)"
                )
                return 0.0
            logger.warning(
                f"code verifier service {addr} unreachable ({e}); "
                "falling back to the LOCAL rlimit sandbox — set "
                "AREAL_CODE_VERIFIER_STRICT=1 to fail closed instead"
            )
    results = verify_code(
        completions, problem, timeout=timeout, max_cases=max_cases
    )
    return 1.0 if results and all(r.passed for r in results) else 0.0
