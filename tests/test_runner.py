import asyncio
import time

import pytest

from areal_tpu.core.runner import AsyncTaskRunner, TaskError, TaskQueueFullError


@pytest.fixture
def runner():
    r = AsyncTaskRunner(max_queue_size=64)
    r.start()
    yield r
    r.stop()


def test_basic_submit_wait(runner):
    async def task():
        await asyncio.sleep(0.01)
        return 42

    for _ in range(5):
        runner.submit(task)
    out = runner.wait(5, timeout=5)
    assert out == [42] * 5


def test_results_in_completion_order(runner):
    async def slow():
        await asyncio.sleep(0.3)
        return "slow"

    async def fast():
        return "fast"

    runner.submit(slow)
    runner.submit(fast)
    out = runner.wait(2, timeout=5)
    assert out == ["fast", "slow"]


def test_wait_timeout_preserves_results(runner):
    async def task():
        return 1

    runner.submit(task)
    with pytest.raises(TimeoutError):
        runner.wait(3, timeout=0.3)
    # the one completed result is still collectable
    assert runner.wait(1, timeout=2) == [1]


def test_exception_becomes_task_error(runner):
    async def boom():
        raise ValueError("nope")

    runner.submit(boom)
    (out,) = runner.wait(1, timeout=5)
    assert isinstance(out, TaskError)
    assert isinstance(out.exc, ValueError)


def test_pause_blocks_new_tasks(runner):
    runner.pause()

    async def task():
        return "ran"

    runner.submit(task)
    time.sleep(0.2)
    with pytest.raises(TimeoutError):
        runner.wait(1, timeout=0.2)
    runner.resume()
    assert runner.wait(1, timeout=2) == ["ran"]


def test_queue_full():
    r = AsyncTaskRunner(max_queue_size=2)
    r.start()
    r.pause()  # prevent dequeue

    async def task():
        return None

    try:
        r.submit(task)
        r.submit(task)
        with pytest.raises(TaskQueueFullError):
            r.submit(task)
    finally:
        r.stop()


def test_many_concurrent_tasks(runner):
    async def task(i):
        await asyncio.sleep(0.001 * (i % 7))
        return i

    for i in range(50):
        runner.submit(lambda i=i: task(i))
    out = runner.wait(50, timeout=10)
    assert sorted(out) == list(range(50))
