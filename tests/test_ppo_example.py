"""PPO-with-critic example smoke: the gsm8k_ppo.py entry point runs a full
tiny experiment under the local launcher (actor + critic + GAE baseline),
mirroring test_launcher_example.py for the GRPO path."""

import os
import subprocess
import sys

import pytest

from tests.fixtures import make_gsm8k_jsonl, make_tiny_ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_ppo_critic_example_end_to_end(tmp_path):
    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    data = make_gsm8k_jsonl(str(tmp_path / "train.jsonl"), n=16)
    fileroot = tmp_path / "exp"

    cfg = f"""
experiment_name: ppo-smoke
trial_name: t0
seed: 1
total_train_epochs: 1
total_train_steps: 2
async_training: true
tokenizer_path: {ckpt}
cluster:
  fileroot: {fileroot}
allocation_mode: "jax:d1+jax:d1"
train_dataset:
  path: {data}
  type: gsm8k
  batch_size: 4
  max_length: 128
gconfig:
  n_samples: 2
  max_new_tokens: 16
  temperature: 1.0
rollout:
  max_concurrent_rollouts: 8
  consumer_batch_size: 4
  max_head_offpolicyness: 2
  request_timeout: 120
gen_server:
  model_path: {ckpt}
  max_seqs: 4
  max_context_len: 256
actor:
  path: {ckpt}
  dtype: float32
  gradient_checkpointing: false
  group_size: 2
  ppo_n_minibatches: 1
  pack_length_quantum: 64
  max_pack_length: 256
  adv_norm:
    mean_level: batch
    std_level: batch
  optimizer:
    lr: 1.0e-4
    warmup_steps_proportion: 0.0
critic:
  path: {ckpt}
  dtype: float32
  gradient_checkpointing: false
  ppo_n_minibatches: 1
  pack_length_quantum: 64
  max_pack_length: 256
  optimizer:
    lr: 1.0e-4
    warmup_steps_proportion: 0.0
saver:
  freq_steps: null
checkpointer:
  freq_steps: null
evaluator:
  freq_steps: null
recover:
  mode: disabled
stats_logger:
  fileroot: {fileroot}
"""
    cfg_path = tmp_path / "cfg.yaml"
    cfg_path.write_text(cfg)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "areal_tpu.launcher.local",
         os.path.join(REPO, "examples/math/gsm8k_ppo.py"),
         "--config", str(cfg_path)],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        out, _ = proc.communicate(timeout=540)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail(f"launcher timed out.\n{out[-4000:]}")

    log_dir = fileroot / "ppo-smoke" / "t0" / "logs"
    trainer_log = ""
    if log_dir.exists():
        for f in sorted(log_dir.iterdir()):
            if f.name.startswith("trainer"):
                trainer_log += f.read_text()
    assert proc.returncode == 0, (
        f"launcher rc={proc.returncode}\n{out[-2000:]}\n{trainer_log[-4000:]}"
    )
    assert "Step 1/" in trainer_log and "done." in trainer_log, trainer_log[-4000:]
    assert "Step 2/" in trainer_log, trainer_log[-4000:]
    # the critic actually trained: its clipped-value-loss stats were
    # committed (ppo_critic_loss_fn's value_clip_ratio key reaches the
    # stats logger line)
    assert "critic/value_clip_ratio" in trainer_log, trainer_log[-4000:]
