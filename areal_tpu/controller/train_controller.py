"""Single-controller training driver over RPC engine workers.

Behavioral counterpart of the reference's `TrainController`
(areal/api/controller_api.py:207) with `DistributedBatchMemory` fan-out
(areal/controller/batch.py): algorithm code runs here, in one process; each
batch-consuming call is chunked row-wise across the worker fleet, issued
concurrently, and the results are merged — stats averaged weighted by shard rows, arrays
concatenated in row order.
"""

import concurrent.futures
from typing import TYPE_CHECKING, Any, Dict, List, Sequence

import numpy as np

from areal_tpu.controller.batch import DistributedBatch

if TYPE_CHECKING:  # import-time would cycle: scheduler.rpc_client pulls
    # controller.batch, whose package __init__ pulls this module — the
    # name is only an annotation here
    from areal_tpu.scheduler.rpc_client import RPCEngineClient


def _merge_stats(
    per_worker: Sequence[List[Dict[str, float]]],
    weights: Sequence[float],
) -> List[Dict[str, float]]:
    """Average each minibatch-step's stats dict across workers.  Engine
    stats are per-token means (actor.py normalizes by n_valid_tokens and
    reports it as 'n_tokens'), so weight by the step's token count when
    present; fall back to the worker's shard rows otherwise."""
    n_steps = max(len(w) for w in per_worker)
    out = []
    for i in range(n_steps):
        acc: Dict[str, List[tuple]] = {}
        for w, rows in zip(per_worker, weights):
            if i < len(w):
                wt = float(w[i].get("n_tokens", rows))
                for k, v in w[i].items():
                    if isinstance(v, (int, float)):
                        acc.setdefault(k, []).append((float(v), wt))
        out.append(
            {
                k: float(
                    sum(v * wt for v, wt in vs) / max(sum(wt for _, wt in vs), 1e-8)
                )
                for k, vs in acc.items()
            }
        )
    return out


class TrainController:
    def __init__(self, clients: List["RPCEngineClient"], chunk_quantum: int = 1):
        """`chunk_quantum` aligns dp shard boundaries to a group size
        (GRPO group_size) so group-normalized ops never straddle shards."""
        if not clients:
            raise ValueError("need at least one engine worker")
        self.clients = clients
        self.chunk_quantum = chunk_quantum
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=len(clients)
        )

    @property
    def dp_size(self) -> int:
        return len(self.clients)

    def _fan(self, fn_name: str, batch: Dict[str, Any], **kw):
        shards = DistributedBatch(batch).chunk(
            self.dp_size, quantum=self.chunk_quantum
        )
        futs = [
            self._pool.submit(getattr(c, "call"), fn_name, s.to_dict(), **kw)
            for c, s in zip(self.clients, shards)
        ]
        return [f.result() for f in futs], [len(s) for s in shards]

    # ---------------------------- algorithm ops -------------------------

    def compute_logp(self, batch: Dict[str, Any]) -> np.ndarray:
        parts, _ = self._fan("compute_logp", batch)
        return np.concatenate(parts, axis=0)

    def compute_advantages(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        # advantage math is host-side reward/logp arithmetic: the pixel
        # tensors are dead weight on this RPC — strip them from the fan-out
        # so the echoed batches don't double the largest transfer
        from areal_tpu.utils.data import VISION_BATCH_KEYS

        view = {k: v for k, v in batch.items() if k not in VISION_BATCH_KEYS}
        parts, _ = self._fan("compute_advantages", view, return_batch=True)
        merged = DistributedBatch.concat(
            [DistributedBatch(p) for p in parts]
        ).to_dict()
        batch.update(merged)
        return batch

    def ppo_update(self, batch: Dict[str, Any]) -> List[Dict[str, float]]:
        results, sizes = self._fan("ppo_update", batch)
        return _merge_stats(results, sizes)

    # ---------------------------- control plane -------------------------

    def _all(self, method: str, **kw):
        futs = [
            self._pool.submit(c.call, method, **kw) for c in self.clients
        ]
        return [f.result() for f in futs]

    def set_version(self, version: int):
        self._all("set_version", version=version)

    def get_version(self) -> int:
        return self.clients[0].get_version()

    def step_lr_scheduler(self):
        self._all("step_lr_scheduler")

    def update_weights(self, meta):
        """Weight publishing is a head-worker action (every worker holds the
        same replicated/sharded state; one snapshot suffices)."""
        return self.clients[0].update_weights(meta)

    def save(self, meta):
        return self.clients[0].save(meta)

    def load(self, meta):
        return self._all("load", meta=meta)

    def health(self) -> List[Dict[str, Any]]:
        return [c.health() for c in self.clients]
