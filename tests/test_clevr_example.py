"""CLEVR VLM example smoke: `python -m areal_tpu.launcher.local
examples/vlm/clevr_grpo.py --config <tiny yaml>` runs a tiny vision GRPO
experiment end-to-end (the reference's test_examples.py pattern applied to
examples/vlm/clevr_count_70k_grpo.py)."""

import os
import subprocess
import sys

import pytest

from tests.fixtures import make_clevr_jsonl, make_tiny_vlm_ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_clevr_example_end_to_end(tmp_path):
    ckpt = tmp_path / "model"
    cfg_model = make_tiny_vlm_ckpt(str(ckpt))
    data_dir = tmp_path / "clevr"
    data_dir.mkdir()
    make_clevr_jsonl(str(data_dir / "train.jsonl"), cfg_model, n=8)
    fileroot = tmp_path / "exp"

    cfg = f"""
experiment_name: clevr-smoke
trial_name: t0
seed: 1
total_train_epochs: 1
total_train_steps: 2
async_training: true
tokenizer_path: {ckpt}
cluster:
  fileroot: {fileroot}
allocation_mode: "jax:d1+jax:d1"
train_dataset:
  path: {data_dir}
  type: clevr
  batch_size: 4
  max_length: 128
gconfig:
  n_samples: 2
  max_new_tokens: 8
  temperature: 1.0
rollout:
  max_concurrent_rollouts: 8
  consumer_batch_size: 4
  max_head_offpolicyness: 2
  request_timeout: 120
gen_server:
  model_path: {ckpt}
  max_seqs: 4
  max_context_len: 256
actor:
  path: {ckpt}
  dtype: float32
  gradient_checkpointing: false
  group_size: 2
  ppo_n_minibatches: 2
  pack_length_quantum: 64
  max_pack_length: 256
  adv_norm:
    mean_level: group
    std_level: group
  optimizer:
    lr: 1.0e-4
    warmup_steps_proportion: 0.0
saver:
  freq_steps: null
checkpointer:
  freq_steps: null
evaluator:
  freq_steps: null
recover:
  mode: disabled
stats_logger:
  fileroot: {fileroot}
"""
    cfg_path = tmp_path / "cfg.yaml"
    cfg_path.write_text(cfg)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "areal_tpu.launcher.local",
         os.path.join(REPO, "examples/vlm/clevr_grpo.py"),
         "--config", str(cfg_path)],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        out, _ = proc.communicate(timeout=540)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail(f"launcher timed out.\n{out[-4000:]}")

    log_dir = fileroot / "clevr-smoke" / "t0" / "logs"
    trainer_log = ""
    if log_dir.exists():
        for f in sorted(log_dir.iterdir()):
            if f.name.startswith("trainer"):
                trainer_log += f.read_text()
    assert proc.returncode == 0, (
        f"launcher rc={proc.returncode}\n{out[-2000:]}\n{trainer_log[-4000:]}"
    )
    assert "Step 1/" in trainer_log and "done." in trainer_log, trainer_log[-4000:]
    assert "Step 2/" in trainer_log, trainer_log[-4000:]


@pytest.mark.slow
def test_clevr_sft_example_end_to_end(tmp_path):
    """VLM SFT entry point (reference: examples/vlm/clevr_count_70k_sft.py)
    runs on a tiny VLM checkpoint with pre-patchified rows."""
    ckpt = tmp_path / "model"
    cfg_model = make_tiny_vlm_ckpt(str(ckpt))
    data_dir = tmp_path / "clevr"
    data_dir.mkdir()
    make_clevr_jsonl(str(data_dir / "train.jsonl"), cfg_model, n=8)
    fileroot = tmp_path / "exp"
    cfg = f"""
experiment_name: clevr-sft-smoke
trial_name: t0
seed: 1
total_train_epochs: 1
total_train_steps: 2
tokenizer_path: {ckpt}
cluster:
  fileroot: {fileroot}
train_dataset:
  path: {data_dir}
  type: clevr
  batch_size: 4
  max_length: 64
model:
  path: {ckpt}
  dtype: float32
  gradient_checkpointing: false
  pack_length_quantum: 32
  max_pack_length: 64
  optimizer:
    lr: 1.0e-4
    warmup_steps_proportion: 0.0
saver:
  freq_steps: null
stats_logger:
  fileroot: {fileroot}
"""
    cfg_path = tmp_path / "cfg.yaml"
    cfg_path.write_text(cfg)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples/vlm/clevr_sft.py"),
         "--config", str(cfg_path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-3000:]
    assert "Step 2/" in proc.stderr + proc.stdout, proc.stderr[-2000:]
