"""Functional decoder-only transformer, TPU-first.

Capability counterpart of the reference's model runtimes (lite: HF
AutoModelForCausalLM under FSDP2, areal/engine/base_hf_engine.py:46; legacy:
ReaLModel, realhf/impl/model/nn/real_llm_api.py:100 with flash-attn varlen
attention, realhf/impl/model/modules/attn.py:307).  Design differences:

- Pure functions over a parameter pytree; no module system.  `jax.jit`
  closes over the static `TransformerConfig`.
- **Layer stacking + `lax.scan`**: all layers' weights live in single leaves
  with a leading `num_layers` axis.  One layer is traced/compiled once
  regardless of depth, and `jax.checkpoint` gives per-layer rematerialisation
  (the HBM/FLOPs trade the reference gets from torch activation ckpt).
- **Packed sequences via segment ids**: variable-length batches arrive as a
  flat token buffer `[B, T]` (usually B=1) with `segment_ids`; attention
  masks `seg_i == seg_j & causal`, replacing flash-attn varlen cu_seqlens.
  Padding tokens carry segment_id -1 and attend to nothing.
- Compute in bf16 on the MXU, master params fp32; softmax and norms in fp32.
- Sharding is expressed once in `param_partition_specs` and applied by the
  engine via NamedSharding; GSPMD inserts the collectives.
"""
# areal-lint: hot-path

import functools
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.ops.attention import (  # noqa: F401 — re-exported for gen paths
    make_attention_mask,
    naive_attention as attention,
    segment_attention,
    splash_supported,
)
from areal_tpu.ops.ragged_decode import ragged_paged_attention

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, unit_offset: bool = False
) -> jax.Array:
    """`unit_offset` reads the weight as zero-centered (effective scale
    1 + w) — the gemma-family convention."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if unit_offset:
        w = 1.0 + w
    return (x * w).astype(dtype)


def layer_norm(
    x: jax.Array, weight: jax.Array, bias: jax.Array, eps: float
) -> jax.Array:
    """Mean-centred LayerNorm with bias (gpt2 family), fp32 numerics."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    x = (x - mu) * jax.lax.rsqrt(var + eps)
    return (
        x * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    ).astype(dtype)


def _norm(
    cfg: TransformerConfig, x: jax.Array, tree: Params, name: str
) -> jax.Array:
    """Normalise with the config's norm flavour; `tree[name]` is the weight,
    `tree[name + "_b"]` the LayerNorm bias."""
    if cfg.norm_type == "layernorm":
        return layer_norm(x, tree[name], tree[name + "_b"], cfg.rms_norm_eps)
    return rms_norm(x, tree[name], cfg.rms_norm_eps, cfg.norm_unit_offset)


def _act(cfg: TransformerConfig):
    if cfg.hidden_act == "silu":
        return jax.nn.silu
    if cfg.hidden_act in ("gelu_pytorch_tanh", "gelu_tanh"):
        return functools.partial(jax.nn.gelu, approximate=True)
    if cfg.hidden_act == "gelu":
        return functools.partial(jax.nn.gelu, approximate=False)
    raise ValueError(f"unsupported hidden_act {cfg.hidden_act!r}")


def _embed(
    params: Params,
    cfg: TransformerConfig,
    ids: jax.Array,
    dtype,
    positions: Optional[jax.Array] = None,
):
    x = jnp.take(params["embedding"].astype(dtype), ids, axis=0)
    if cfg.scale_embeddings:
        # gemma multiplies by sqrt(D) rounded in the compute dtype
        x = x * jnp.asarray(cfg.hidden_size**0.5, dtype)
    if cfg.pos_emb == "learned":
        x = x + jnp.take(
            params["pos_embedding"].astype(dtype), positions, axis=0
        )
    return x


def _layer_sliding_flags(cfg: TransformerConfig) -> jax.Array:
    """bool [L]: whether each layer uses the sliding window (gemma2
    alternation); all-False when windows are uniform/absent."""
    if cfg.sliding_window is not None and cfg.layer_is_sliding is not None:
        return jnp.asarray(cfg.layer_is_sliding, bool)
    return jnp.zeros((cfg.num_layers,), bool)


def _head_logits(params: Params, cfg: TransformerConfig, x: jax.Array, dtype):
    head = params.get("lm_head")
    if head is None:
        head = params["embedding"].T
    eq = "btd,dv->btv" if x.ndim == 3 else "bd,dv->bv"
    logits = jnp.einsum(eq, x, head.astype(dtype))
    if cfg.final_logit_softcap:
        cap = cfg.final_logit_softcap
        logits = jnp.tanh(logits / cap) * cap
    return logits


def rope_cos_sin(positions: jax.Array, head_dim: int, theta: float):
    """positions [B, T] -> cos/sin [B, T, head_dim//2] in fp32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B,T,hd/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [B, T, H, hd]; HF 'half rotation' convention (rotate_half)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[:, :, None, :]  # [B,T,1,hd/2]
    sin = sin[:, :, None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


# ---------------------------------------------------------------------------
# Layer / model forward
# ---------------------------------------------------------------------------


def _ffn(cfg: TransformerConfig, lp: Params, h: jax.Array, dtype):
    """Dense MLP or MoE block; returns (out, aux-loss scalar fp32)."""
    if cfg.num_experts > 0:
        from areal_tpu.models.moe import moe_ffn

        return moe_ffn(cfg, lp["moe"], h, dtype)
    return _mlp(lp, h, dtype, cfg), jnp.zeros((), jnp.float32)


def _layer_forward(
    cfg: TransformerConfig,
    mesh: Optional[Mesh],
    lp: Params,  # this layer's params (no leading L axis)
    x: jax.Array,  # [B, T, D]
    cos: jax.Array,
    sin: jax.Array,
    seg: jax.Array,  # [B, T] segment ids
    pos: jax.Array,  # [B, T] positions
    mask: Optional[jax.Array],  # [B, 1, T, T] — naive path only
):
    """One decoder block (cache-free; the generation paths below thread
    their own cache through the same _qkv/_ffn primitives)."""
    B, T, _ = x.shape
    dtype = x.dtype
    h = _norm(cfg, x, lp, "input_norm")
    q, k, v = _qkv(cfg, lp, h, dtype)
    if cfg.pos_emb == "rope":
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
    if mask is not None:
        attn_out = attention(q, k, v, mask, cfg.attn_logit_softcap)
    else:
        attn_out = segment_attention(
            q,
            k,
            v,
            seg,
            pos,
            sliding_window=cfg.sliding_window,
            logit_softcap=cfg.attn_logit_softcap,
            impl="ring" if cfg.attn_impl == "ring" else "splash",
            mesh=mesh,
        )
    attn_out = jax.ad_checkpoint.checkpoint_name(attn_out, "attn_out")
    attn_out = attn_out.reshape(B, T, cfg.q_size)
    attn_delta = _proj(cfg, lp["attn"], "wo", attn_out, dtype, bias="bo")
    if cfg.sandwich_norms:
        attn_delta = _norm(cfg, attn_delta, lp, "sandwich_attn_norm")
    x = x + attn_delta
    h = _norm(cfg, x, lp, "post_attn_norm")
    ffn_out, aux = _ffn(cfg, lp, h, dtype)
    ffn_out = jax.ad_checkpoint.checkpoint_name(ffn_out, "mlp_out")
    if cfg.sandwich_norms:
        ffn_out = _norm(cfg, ffn_out, lp, "sandwich_ffn_norm")
    return x + ffn_out, aux


def _remat_checkpoint_kwargs(cfg: TransformerConfig) -> dict:
    """jax.checkpoint kwargs for the config's remat rung.  Applied around
    one layer (layer_group_size == 1) or one unrolled group of layers — the
    policy composes per checkpoint boundary either way."""
    if cfg.remat_policy == "dots":
        return dict(
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    if cfg.remat_policy == "save_attn":
        # keep the tagged attention outputs (checkpoint_name in
        # _layer_forward): the backward pass recomputes projections and
        # MLP but not the attention kernel — ~50 MB/layer at 16k tokens,
        # the selective policy that still fits 16G v5e
        return dict(
            policy=jax.checkpoint_policies.save_only_these_names("attn_out")
        )
    if cfg.remat_policy == "save_mlp":
        # keep the tagged MLP outputs instead (ROADMAP 3b probe): the
        # backward pass recomputes attention but not the MLP — the rung
        # between save_attn and full on the memory/recompute ladder
        return dict(
            policy=jax.checkpoint_policies.save_only_these_names("mlp_out")
        )
    if cfg.remat_policy == "carry_offload":
        # keep BOTH tagged outputs but park them in pinned host memory:
        # the residuals leave HBM entirely, trading the pressure that
        # kills save_attn compiles for PCIe traffic the backward can
        # overlap with recompute.  Requires a runtime with host memory
        # spaces (TPU); CPU test rigs may fail to lower — the bench
        # ladder records the per-rung compile outcome either way.
        return dict(
            policy=jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=["attn_out", "mlp_out"],
                offload_src="device",
                offload_dst="pinned_host",
            )
        )
    if cfg.remat_policy == "full":
        return {}
    raise ValueError(
        f"unknown remat_policy {cfg.remat_policy!r}; use 'full', "
        "'save_attn', 'save_mlp', 'carry_offload', or 'dots'"
    )


def effective_scan_unroll(cfg: TransformerConfig) -> int:
    """The unroll factor the layer scan will actually use.

    `scan_unroll` must divide the OUTER scan length (num_layers /
    layer_group_size).  Non-divisors fall back to 1 — loudly: the silent
    fallback this replaces let a mistuned config quietly forfeit the
    unrolling win for whole rounds.  Engines record this value in train
    stats / bench JSON so the regression is visible in artifacts too."""
    u = max(1, cfg.scan_unroll)
    n = cfg.num_layers // max(1, cfg.layer_group_size)
    if n % u:
        import warnings

        warnings.warn(
            f"scan_unroll={cfg.scan_unroll} does not divide the outer layer-"
            f"scan length {n} (num_layers={cfg.num_layers}, "
            f"layer_group_size={cfg.layer_group_size}); falling back to "
            "unroll=1 — pick a divisor to get the requested unrolling",
            stacklevel=2,
        )
        return 1
    return u


def _backbone(
    params: Params,
    cfg: TransformerConfig,
    input_ids: jax.Array,
    positions: jax.Array,
    segment_ids: jax.Array,
    mesh: Optional[Mesh] = None,
    inputs_embeds: Optional[jax.Array] = None,  # [B, T, D] (VLM merge)
    rope: Optional[tuple] = None,  # (cos, sin) override (mrope)
):
    """Layer scan -> (final-norm hidden [B, T, D], summed MoE aux loss)."""
    if cfg.lora_rank:
        # freeze everything but the adapters: XLA prunes the base bwd pass
        from areal_tpu.models.lora import freeze_base

        params = freeze_base(params, True)
    dtype = jnp.dtype(cfg.dtype)
    if inputs_embeds is not None:
        x = inputs_embeds.astype(dtype)
    else:
        x = _embed(params, cfg, input_ids, dtype, positions=positions)
    cos, sin = rope if rope is not None else rope_cos_sin(
        positions, cfg.head_dim_, cfg.rope_theta
    )

    B, T = input_ids.shape
    sp = mesh.shape["sp"] if mesh is not None else 1
    per_layer_window = (
        cfg.sliding_window is not None and cfg.layer_is_sliding is not None
    )
    # ring attention: K/V sequence-sharded over sp with rotating blocks —
    # the context-parallel regime (ops/attention.py ring_attention)
    use_ring = (
        cfg.attn_impl == "ring"
        and not per_layer_window
        and mesh is not None
        and mesh.shape.get("sp", 1) > 1
    )
    if cfg.attn_impl == "ring" and not use_ring:
        # requesting ring implies the O(T/sp) memory regime was wanted —
        # falling back silently would surprise at long context (trace-time
        # warning: fires once per compiled shape)
        import warnings

        reason = (
            "per-layer sliding windows (gemma2) are mask-based"
            if per_layer_window
            else "the mesh has no sp>1 axis"
        )
        warnings.warn(
            f"attn_impl='ring' requested but unused: {reason}; falling "
            "back to the splash/naive ladder",
            stacklevel=2,
        )
    use_splash = (
        cfg.attn_impl != "naive"
        and not use_ring
        and not per_layer_window  # splash masks are static per kernel
        and splash_supported(
            T, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_, sp=sp
        )
    )
    # the splash/ring paths never materialise a mask; naive builds
    # [B,1,T,T] once.  With per-layer windows (gemma2) both variants are
    # built once and each scan step selects by the layer's flag.
    mask_win = None
    if per_layer_window:
        mask = make_attention_mask(segment_ids, positions, None)
        mask_win = make_attention_mask(
            segment_ids, positions, cfg.sliding_window
        )
    elif use_splash or use_ring:
        mask = None
    else:
        mask = make_attention_mask(segment_ids, positions, cfg.sliding_window)

    layer_fn = functools.partial(_layer_forward, cfg, mesh)
    ckpt_kwargs = _remat_checkpoint_kwargs(cfg) if cfg.remat else None

    G = max(1, cfg.layer_group_size)
    if cfg.num_layers % G:
        raise ValueError(
            f"layer_group_size={cfg.layer_group_size} must divide "
            f"num_layers={cfg.num_layers}: a trailing partial group would "
            "silently change the remat boundary — pick a divisor"
        )
    n_groups = cfg.num_layers // G

    def one_layer(lp, sliding, x):
        m = mask
        if mask_win is not None:
            m = jnp.where(sliding, mask_win, mask)
        return layer_fn(lp, x, cos, sin, segment_ids, positions, m)

    if G == 1:
        # classic single-level scan; the remat policy wraps each layer
        if ckpt_kwargs is not None:
            layer_fn = jax.checkpoint(layer_fn, **ckpt_kwargs)

        def scan_body(carry, xs):
            lp, sliding = xs
            x, aux_sum = carry
            x, aux = one_layer(lp, sliding, x)
            return (x, aux_sum + aux), None

        xs = (params["layers"], _layer_sliding_flags(cfg))
    else:
        # two-level scan: the outer scan runs n_groups steps, each an
        # unrolled chain of G layers behind ONE checkpoint at the group
        # boundary.  Only the inter-group activation is saved (everything
        # inside the group is recomputed under `full`, or kept per the
        # selective policy), so the backward scan-transpose carry holds
        # n_groups entries instead of num_layers — ~G× fewer
        # dynamic-update-slice carry writes.
        def group_fn(gp, gflags, x):
            aux = jnp.zeros((), jnp.float32)
            for i in range(G):
                lp = jax.tree_util.tree_map(lambda a, i=i: a[i], gp)
                x, a = one_layer(lp, gflags[i], x)
                aux = aux + a
            return x, aux

        if ckpt_kwargs is not None:
            group_fn = jax.checkpoint(group_fn, **ckpt_kwargs)

        def scan_body(carry, xs):
            gp, gflags = xs
            x, aux_sum = carry
            x, aux = group_fn(gp, gflags, x)
            return (x, aux_sum + aux), None

        xs = (
            jax.tree_util.tree_map(
                lambda a: a.reshape((n_groups, G) + a.shape[1:]),
                params["layers"],
            ),
            _layer_sliding_flags(cfg).reshape(n_groups, G),
        )

    (x, aux), _ = jax.lax.scan(
        scan_body,
        (x, jnp.zeros((), jnp.float32)),
        xs,
        unroll=effective_scan_unroll(cfg),
        _split_transpose=cfg.scan_split_transpose,
    )
    return _norm(cfg, x, params, "final_norm"), aux


def forward_hidden(
    params: Params,
    cfg: TransformerConfig,
    input_ids: jax.Array,  # int32 [B, T]
    positions: jax.Array,  # int32 [B, T]
    segment_ids: jax.Array,  # int32 [B, T], -1 = padding
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Backbone forward -> final-norm hidden states [B, T, D] (for value /
    reward heads, the role of the reference's critic models)."""
    x, _ = _backbone(params, cfg, input_ids, positions, segment_ids, mesh=mesh)
    return x


def forward(
    params: Params,
    cfg: TransformerConfig,
    input_ids: jax.Array,  # int32 [B, T]
    positions: jax.Array,  # int32 [B, T]
    segment_ids: jax.Array,  # int32 [B, T], -1 = padding
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Full forward -> logits [B, T, V] (in cfg.dtype; softmax-sensitive
    consumers should upcast)."""
    dtype = jnp.dtype(cfg.dtype)
    x = forward_hidden(params, cfg, input_ids, positions, segment_ids, mesh=mesh)
    return _head_logits(params, cfg, x, dtype)


class LMOutput(NamedTuple):
    """Deferred language-model head: final-norm hidden states + head matrix.

    Train-path losses consume this instead of materialised logits so the
    [tokens, vocab] matrix (2.4 GB bf16 / 4.9 GB fp32 at 8k tokens on a 151k
    vocab — the round-1 OOM wall) only ever exists one chunk at a time inside
    `ops.functional.lm_logprobs_entropy`'s rematerialised scan.

    `aux_loss` carries the MoE load-balancing penalty (already scaled by
    cfg.moe_aux_coef; 0 for dense models) — losses fold it in per token.
    """

    hidden: jax.Array  # [B, T, D] in compute dtype
    head: jax.Array  # [D, V] in compute dtype
    aux_loss: Optional[jax.Array] = None  # scalar fp32
    # gemma2 final-logit tanh cap; consumers (ops.functional) must apply it
    # to every logits chunk.  Static python float, never a traced leaf.
    logit_softcap: Optional[float] = None


def forward_lm(
    params: Params,
    cfg: TransformerConfig,
    input_ids: jax.Array,
    positions: jax.Array,
    segment_ids: jax.Array,
    mesh: Optional[Mesh] = None,
) -> LMOutput:
    """Backbone forward with a *deferred* LM head (see LMOutput)."""
    dtype = jnp.dtype(cfg.dtype)
    x, aux = _backbone(params, cfg, input_ids, positions, segment_ids, mesh=mesh)
    head = params.get("lm_head")
    if head is None:
        head = params["embedding"].T
    if cfg.lora_rank:
        head = jax.lax.stop_gradient(head)
    return LMOutput(
        hidden=x,
        head=head.astype(dtype),
        aux_loss=aux * cfg.moe_aux_coef if cfg.num_experts > 0 else None,
        logit_softcap=cfg.final_logit_softcap,
    )


def forward_packed(params: Params, cfg: TransformerConfig, packed: Dict[str, jax.Array]):
    """Convenience wrapper over a packed dict (flat [T] buffers)."""
    ids = packed["input_ids"][None, :]
    pos = packed["positions"][None, :]
    seg = packed["segment_ids"][None, :]
    return forward(params, cfg, ids, pos, seg)[0]


# ---------------------------------------------------------------------------
# KV-cache forward paths (generation engine)
# ---------------------------------------------------------------------------
#
# The decode-time counterpart of the reference's native generation runtime
# (realhf/impl/model/nn/real_llm_generate.py KV-cache decode loop) and of the
# SGLang servers it normally delegates to.  Cache layout is layer-stacked to
# match the scan parameter layout:
#     k, v: [L, S, M, Hkv, hd]   (S = batch slots, M = max seq len)
# Both entry points are shape-static: prefill takes a padded prompt bucket,
# decode advances every slot by exactly one token.


def _proj(
    cfg: TransformerConfig,
    sub: Params,
    leaf: str,
    x: jax.Array,
    dtype,
    bias: Optional[str] = None,
):
    """x @ W (+ bias leaf if present, + LoRA delta when adapted)."""
    out = jnp.einsum("btd,dh->bth", x, sub[leaf].astype(dtype))
    if bias is not None and bias in sub:
        out = out + sub[bias].astype(dtype)
    if cfg.lora_rank:
        from areal_tpu.models.lora import lora_delta, lora_scale

        d = lora_delta(sub, leaf, x, dtype, lora_scale(cfg))
        if d is not None:
            out = out + d
    return out


def _qkv(cfg: TransformerConfig, lp: Params, h: jax.Array, dtype):
    q = _proj(cfg, lp["attn"], "wq", h, dtype)
    k = _proj(cfg, lp["attn"], "wk", h, dtype)
    v = _proj(cfg, lp["attn"], "wv", h, dtype)
    if cfg.qkv_bias:
        q = q + lp["attn"]["bq"].astype(dtype)
        k = k + lp["attn"]["bk"].astype(dtype)
        v = v + lp["attn"]["bv"].astype(dtype)
    B, T = h.shape[:2]
    q = q.reshape(B, T, cfg.num_heads, cfg.head_dim_)
    k = k.reshape(B, T, cfg.num_kv_heads, cfg.head_dim_)
    v = v.reshape(B, T, cfg.num_kv_heads, cfg.head_dim_)
    if cfg.qk_norm:
        q = _norm(cfg, q, lp["attn"], "q_norm")
        k = _norm(cfg, k, lp["attn"], "k_norm")
    if cfg.query_pre_attn_scalar is not None:
        # attention kernels scale scores by head_dim^-0.5; pre-scaling q
        # makes the net softmax scale query_pre_attn_scalar^-0.5 (gemma2)
        q = q * jnp.asarray(
            cfg.head_dim_**0.5 / cfg.query_pre_attn_scalar**0.5, q.dtype
        )
    return q, k, v


def _mlp(lp: Params, h: jax.Array, dtype, cfg: Optional[TransformerConfig] = None):
    act = jax.nn.silu if cfg is None else _act(cfg)
    if cfg is not None and not cfg.mlp_gated:
        # gpt2-style: up-project, activate, down-project.  _proj applies
        # bias leaves when present and LoRA deltas when adapted.
        up = _proj(cfg, lp["mlp"], "w_up", h, dtype, bias="b_up")
        return _proj(cfg, lp["mlp"], "w_down", act(up), dtype, bias="b_down")
    if cfg is not None and cfg.lora_rank:
        gate = _proj(cfg, lp["mlp"], "w_gate", h, dtype)
        up = _proj(cfg, lp["mlp"], "w_up", h, dtype)
        return _proj(cfg, lp["mlp"], "w_down", act(gate) * up, dtype)
    gate = jnp.einsum("btd,df->btf", h, lp["mlp"]["w_gate"].astype(dtype))
    up = jnp.einsum("btd,df->btf", h, lp["mlp"]["w_up"].astype(dtype))
    return jnp.einsum(
        "btf,fd->btd", act(gate) * up, lp["mlp"]["w_down"].astype(dtype)
    )


def init_kv_cache(
    cfg: TransformerConfig, n_slots: int, max_len: int, dtype: str = "bfloat16"
) -> Dict[str, jax.Array]:
    shape = (cfg.num_layers, n_slots, max_len, cfg.num_kv_heads, cfg.head_dim_)
    return {
        "k": jnp.zeros(shape, jnp.dtype(dtype)),
        "v": jnp.zeros(shape, jnp.dtype(dtype)),
    }


def forward_prefill(
    params: Params,
    cfg: TransformerConfig,
    input_ids: jax.Array,  # [S, P] padded prompt bucket (may be 1 row)
    prompt_lens: jax.Array,  # [S]
    cache: Dict[str, jax.Array],
    slot_ids: jax.Array,  # int32 [S]: cache slot each row occupies
    inputs_embeds: Optional[jax.Array] = None,  # [S, P, D] (VLM merge)
    rope: Optional[tuple] = None,  # (cos, sin) override (mrope)
):
    """Prefill `input_ids` into cache slots `slot_ids` (arbitrary, possibly
    non-contiguous — batched admission fills whichever slots are free);
    returns (last-token logits [S, V], updated cache)."""
    S, P = input_ids.shape
    dtype = jnp.dtype(cfg.dtype)
    positions = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (S, P))
    valid = positions < prompt_lens[:, None]
    seg = jnp.where(valid, 0, -1)
    per_layer_window = (
        cfg.sliding_window is not None and cfg.layer_is_sliding is not None
    )
    mask = make_attention_mask(
        seg, positions, None if per_layer_window else cfg.sliding_window
    )
    mask_win = (
        make_attention_mask(seg, positions, cfg.sliding_window)
        if per_layer_window
        else None
    )
    if rope is not None:
        cos, sin = rope
    else:
        cos, sin = rope_cos_sin(positions, cfg.head_dim_, cfg.rope_theta)
    if inputs_embeds is not None:
        x = inputs_embeds.astype(dtype)
    else:
        x = _embed(params, cfg, input_ids, dtype, positions=positions)

    def layer(x, xs):
        lp, sliding, ck, cv = xs  # ck/cv: [S_total, M, Hkv, hd] per layer
        m = mask if mask_win is None else jnp.where(sliding, mask_win, mask)
        h = _norm(cfg, x, lp, "input_norm")
        q, k, v = _qkv(cfg, lp, h, dtype)
        if cfg.pos_emb == "rope":
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        ck = ck.at[slot_ids, :P].set(k.astype(ck.dtype))
        cv = cv.at[slot_ids, :P].set(v.astype(cv.dtype))
        attn = attention(q, k, v, m, cfg.attn_logit_softcap)
        delta = _proj(
            cfg, lp["attn"], "wo", attn.reshape(S, P, cfg.q_size), dtype,
            bias="bo",
        )
        if cfg.sandwich_norms:
            delta = _norm(cfg, delta, lp, "sandwich_attn_norm")
        x = x + delta
        h = _norm(cfg, x, lp, "post_attn_norm")
        ffn_out = _ffn(cfg, lp, h, dtype)[0]
        if cfg.sandwich_norms:
            ffn_out = _norm(cfg, ffn_out, lp, "sandwich_ffn_norm")
        x = x + ffn_out
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        layer,
        x,
        (params["layers"], _layer_sliding_flags(cfg), cache["k"], cache["v"]),
    )
    x = _norm(cfg, x, params, "final_norm")
    # logits only at each row's final real token
    idx = jnp.maximum(prompt_lens - 1, 0)
    last = jnp.take_along_axis(x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = _head_logits(params, cfg, last, dtype)
    return logits, {"k": new_k, "v": new_v}


def forward_prefill_cached(
    params: Params,
    cfg: TransformerConfig,
    input_ids: jax.Array,  # [S, P] padded SUFFIX tokens
    starts: jax.Array,  # int32 [S]: cache position where the suffix begins
    suffix_lens: jax.Array,  # int32 [S]: real suffix tokens per row
    cache: Dict[str, jax.Array],
    slot_ids: jax.Array,  # int32 [S]
    copy_src: Optional[jax.Array] = None,  # int32 [S]: prefix-KV source row
    copy_block: int = 0,  # STATIC bucketed copy length (0 = no fan-out)
    key_window: Optional[int] = None,  # STATIC bucketed attended span
):
    """Prefill only a SUFFIX of each row, attending over the slot's retained
    KV prefix [0, starts) plus the causal suffix — the engine's KV prefix
    reuse (VERDICT r3 #3: the counterpart of the radix-cache reuse the
    reference gets from SGLang, areal/core/remote_inf_engine.py:404-413).
    Returns (last-token logits [S, V], updated cache).

    Group fan-out (ISSUE 2): with `copy_src`/`copy_block`, each row's
    prefix K/V [0, copy_block) is first copied from `copy_src[row]` into
    its own slot — ONE batched gather/scatter over the cache pytree
    (ops/kv_copy.py) fused into the same program, so GRPO siblings ride
    their representative's prefix without an extra dispatch.  Rows that
    reuse their OWN retained prefix pass copy_src == slot_ids (an identity
    self-copy); copy_block rides the prompt-bucket ladder so the program
    count stays bounded.  The caller guarantees every source row's
    [0, starts[row]) span is valid BEFORE this call (fresh representatives
    prefill first; retained representatives cap the share at their lcp).

    Cost is O(P * K) attention over the attended span K (`key_window`, a
    bucketed bound on the deepest row's start + suffix — M when omitted)
    instead of O(P^2) within the prompt — the right trade when P (new
    tokens) << the retained prefix, and the window keeps short sequences
    in a large cache from paying O(M).  Fresh admissions keep using
    `forward_prefill`."""
    S, P = input_ids.shape
    M = cache["k"].shape[2]
    if copy_block and copy_src is not None:
        from areal_tpu.ops.kv_copy import copy_kv_prefix

        cache = copy_kv_prefix(cache, copy_src, slot_ids, copy_block)
    K = min(key_window, M) if key_window else M
    dtype = jnp.dtype(cfg.dtype)
    offs = jnp.arange(P, dtype=jnp.int32)
    positions = starts[:, None] + offs[None, :]  # [S, P] global positions
    cos, sin = rope_cos_sin(positions, cfg.head_dim_, cfg.rope_theta)
    x = _embed(params, cfg, input_ids, dtype, positions=positions)
    key_pos = jnp.arange(K, dtype=jnp.int32)
    # q at global position g attends cache positions <= g; padding rows
    # (offs >= suffix_lens) produce garbage that is never read
    per_layer_window = (
        cfg.sliding_window is not None and cfg.layer_is_sliding is not None
    )
    mask = (key_pos[None, None, :] <= positions[:, :, None])[:, None]  # [S,1,P,M]
    mask_win = None
    if cfg.sliding_window is not None:
        win = mask & (
            key_pos[None, None, :] > positions[:, :, None] - cfg.sliding_window
        )[:, None]
        if per_layer_window:
            mask_win = win
        else:
            mask = win

    def layer(x, xs):
        lp, sliding, ck, cv = xs  # [S_total, M, Hkv, hd]
        m = mask if mask_win is None else jnp.where(sliding, mask_win, mask)
        h = _norm(cfg, x, lp, "input_norm")
        q, k, v = _qkv(cfg, lp, h, dtype)
        if cfg.pos_emb == "rope":
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        ck = ck.at[slot_ids[:, None], positions].set(k.astype(ck.dtype))
        cv = cv.at[slot_ids[:, None], positions].set(v.astype(cv.dtype))
        # gather only the attended span [0, K) of each row — the cache
        # write above stays full-range, but attention never reads past the
        # window the caller bounded
        ckr = jnp.take(ck, slot_ids, axis=0)[:, :K].astype(dtype)
        cvr = jnp.take(cv, slot_ids, axis=0)[:, :K].astype(dtype)
        attn = attention(q, ckr, cvr, m, cfg.attn_logit_softcap)
        delta = _proj(
            cfg, lp["attn"], "wo", attn.reshape(S, P, cfg.q_size), dtype,
            bias="bo",
        )
        if cfg.sandwich_norms:
            delta = _norm(cfg, delta, lp, "sandwich_attn_norm")
        x = x + delta
        h = _norm(cfg, x, lp, "post_attn_norm")
        ffn_out = _ffn(cfg, lp, h, dtype)[0]
        if cfg.sandwich_norms:
            ffn_out = _norm(cfg, ffn_out, lp, "sandwich_ffn_norm")
        x = x + ffn_out
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        layer,
        x,
        (params["layers"], _layer_sliding_flags(cfg), cache["k"], cache["v"]),
    )
    x = _norm(cfg, x, params, "final_norm")
    idx = jnp.maximum(suffix_lens - 1, 0)
    last = jnp.take_along_axis(x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = _head_logits(params, cfg, last, dtype)
    return logits, {"k": new_k, "v": new_v}


def forward_decode(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B] last generated token per slot in the block
    lengths: jax.Array,  # [B] current sequence length (cache fill) per slot
    cache: Dict[str, jax.Array],
    rope_positions: Optional[jax.Array] = None,  # [B] logical rope position
    key_window: Optional[int] = None,  # STATIC bucketed attended span
    slot_base: int = 0,  # STATIC first cache row of the dispatched block
    active: Optional[jax.Array] = None,  # bool [B]; False drops the KV write
    rows: Optional[jax.Array] = None,  # int32 [B] physical rows (page table)
    ragged: bool = False,  # STATIC: fused ragged paged-attention kernel
    page_size: int = 0,  # STATIC page granularity for the ragged path
    mesh: Optional[Mesh] = None,  # tp>1 shard_map wrap for the kernel
):
    """One decode step for a block of `B` slots; returns (logits [B, V],
    new cache).  The new token's K/V is written at cache position
    `lengths[s]`.  Rows are contiguous from `slot_base` by default; when
    `rows` is given (ISSUE 16 paged pool) each logical slot reads and
    writes THROUGH its page-table row instead — same program shape (rows
    is traced data), so remapping a slot's physical row costs zero new
    compilations and, with an identity table, zero numeric difference.

    `key_window` bounds attention, masks, and the cache write to the first
    K cache columns: decode FLOPs and HBM reads then track the occupied
    span, not the configured `max_seq_len` ceiling (ISSUE 5 — the decode
    analogue of `forward_prefill_cached`'s bucketed window).  K is STATIC
    and must come from a bucket ladder; the caller guarantees
    K >= max(lengths of active slots) + steps for the whole fused chunk.
    `slot_base`/`B` carve a length-cohort tier out of the slot grid — one
    dispatch per tier keeps a long outlier from inflating K for everyone.

    `active` masks the cache write per slot (out-of-window scatter drop):
    idle slots riding a tier dispatch would otherwise clamp their garbage
    write into column K-1, which may sit INSIDE a freed slot's retained
    prefix when K is windowed (full-width decode never had the hazard —
    the M-1 clamp was always past any retained frontier).

    `rope_positions` separates the rotary position from the cache index:
    VLM slots compress an image's placeholder run into a small mrope extent,
    so post-image text continues at a logical position < cache length (for
    equal (t,h,w) text positions, sectioned mrope equals standard rope, so
    decode needs only the scalar)."""
    B = tokens.shape[0]
    M = cache["k"].shape[2]
    K = min(key_window, M) if key_window else M
    dtype = jnp.dtype(cfg.dtype)
    rp = lengths if rope_positions is None else rope_positions
    positions = rp[:, None].astype(jnp.int32)  # [B, 1]
    cos, sin = rope_cos_sin(positions, cfg.head_dim_, cfg.rope_theta)
    x = _embed(params, cfg, tokens[:, None], dtype, positions=positions)
    # attend to cache positions 0..lengths (inclusive: self just written)
    key_pos = jnp.arange(K, dtype=jnp.int32)[None, :]
    per_layer_window = (
        cfg.sliding_window is not None and cfg.layer_is_sliding is not None
    )
    attn_mask = (key_pos <= lengths[:, None])[:, None, None, :]  # [B,1,1,K]
    mask_win = None
    if cfg.sliding_window is not None:
        # window over CACHE indices, not rope positions (they diverge on
        # VLM slots)
        win = attn_mask & (
            key_pos > lengths[:, None] - cfg.sliding_window
        )[:, None, None, :]
        if per_layer_window:
            mask_win = win
        else:
            attn_mask = win
    slots = rows if rows is not None else slot_base + jnp.arange(B)
    # clamp: a slot past its cache end (freed host-side mid-chunk, still
    # advancing in the fused decode scan) overwrites the window's last
    # column with garbage instead of stalling the whole grid (VERDICT r3
    # weak #3); inactive slots drop the write entirely (index M is
    # out-of-bounds -> scatter mode="drop")
    widx = jnp.minimum(lengths, K - 1)
    if active is not None:
        widx = jnp.where(active, widx, M)

    def layer(x, xs):
        lp, sliding, ck, cv = xs
        m = attn_mask if mask_win is None else jnp.where(
            sliding, mask_win, attn_mask
        )
        h = _norm(cfg, x, lp, "input_norm")
        q, k, v = _qkv(cfg, lp, h, dtype)
        if cfg.pos_emb == "rope":
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        if ragged and rows is not None:
            # fused ragged kernel: append write + per-slot paged read +
            # exact dense-order softmax in ONE program over the grid
            # (bit-identical to the set/take/attention sequence below —
            # ops/ragged_decode.py pins the exactness argument)
            attn, ck, cv = ragged_paged_attention(
                q, k.astype(ck.dtype), v.astype(cv.dtype), ck, cv,
                rows, lengths, widx[:, None], m[:, 0],
                key_window=K, page_size=page_size,
                logit_softcap=cfg.attn_logit_softcap, mesh=mesh,
            )
        else:
            ck = ck.at[slots, widx].set(
                k[:, 0].astype(ck.dtype), mode="drop"
            )
            cv = cv.at[slots, widx].set(
                v[:, 0].astype(cv.dtype), mode="drop"
            )
            # read only the block's rows and the attended window [0, K):
            # the cache keeps its full [S_total, M] shape, attention never
            # touches rows outside the tier or columns past the window
            if rows is None:
                ckr = jax.lax.slice_in_dim(
                    ck, slot_base, slot_base + B, axis=0
                )
                cvr = jax.lax.slice_in_dim(
                    cv, slot_base, slot_base + B, axis=0
                )
            else:
                ckr = jnp.take(ck, rows, axis=0)
                cvr = jnp.take(cv, rows, axis=0)
            attn = attention(
                q, ckr[:, :K].astype(dtype), cvr[:, :K].astype(dtype), m,
                cfg.attn_logit_softcap,
            )
        delta = _proj(
            cfg, lp["attn"], "wo", attn.reshape(B, 1, cfg.q_size), dtype,
            bias="bo",
        )
        if cfg.sandwich_norms:
            delta = _norm(cfg, delta, lp, "sandwich_attn_norm")
        x = x + delta
        h = _norm(cfg, x, lp, "post_attn_norm")
        ffn_out = _ffn(cfg, lp, h, dtype)[0]
        if cfg.sandwich_norms:
            ffn_out = _norm(cfg, ffn_out, lp, "sandwich_ffn_norm")
        x = x + ffn_out
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        layer,
        x,
        (params["layers"], _layer_sliding_flags(cfg), cache["k"], cache["v"]),
    )
    x = _norm(cfg, x, params, "final_norm")
    logits = _head_logits(params, cfg, x[:, 0], dtype)
    return logits, {"k": new_k, "v": new_v}


def forward_verify(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B, T] committed last token + T-1 draft tokens
    lengths: jax.Array,  # [B] current sequence length (cache fill) per slot
    cache: Dict[str, jax.Array],
    rope_positions: Optional[jax.Array] = None,  # [B] logical rope position
    key_window: Optional[int] = None,  # STATIC bucketed attended span
    slot_base: int = 0,  # STATIC first cache row of the dispatched block
    active: Optional[jax.Array] = None,  # bool [B]; False drops ALL KV writes
    n_write: Optional[jax.Array] = None,  # int32 [B] valid input positions
    rows: Optional[jax.Array] = None,  # int32 [B] physical rows (page table)
    ragged: bool = False,  # STATIC: fused ragged paged-attention kernel
    page_size: int = 0,  # STATIC page granularity for the ragged path
    mesh: Optional[Mesh] = None,  # tp>1 shard_map wrap for the kernel
):
    """Speculative-decode verification: score T input positions per slot of
    a contiguous tier block in ONE dispatch — the decode analogue of
    `forward_prefill_cached` (ISSUE 12).  Row b's inputs are its committed
    pending token followed by T-1 prompt-lookup draft tokens; their K/V
    land at cache positions lengths[b] .. lengths[b]+T-1 and the returned
    logits [B, T, V] give, at each position j, the model's distribution for
    the token at sequence position lengths[b]+j+1 — exactly what T
    sequential `forward_decode` steps would have computed had every draft
    been the sampled token.  The caller samples each position under the
    counter-keyed PRNG and accepts the leading run of agreeing drafts.

    Write-side hazard (same class as decode's idle-slot clamp): position j
    of row b scatter-drops its K/V write (index M, mode="drop") unless the
    row is `active` AND j < n_write[b] — padding positions of a short draft
    and idle slots riding the tier dispatch must never write, because a
    clamped write at K-1 can land inside a freed slot's retained prefix
    when K is windowed.  Writes for positions the caller later REJECTS do
    land here (acceptance needs these very logits) but sit strictly above
    the accepted frontier; the engine zeroes them post-acceptance
    (`_verify_chunk`) so no rejected draft's K/V outlives its dispatch.

    The caller guarantees K >= max(lengths of active slots) + T so no
    active in-budget slot ever clamps."""
    B, T = tokens.shape
    M = cache["k"].shape[2]
    K = min(key_window, M) if key_window else M
    dtype = jnp.dtype(cfg.dtype)
    rp = lengths if rope_positions is None else rope_positions
    offs = jnp.arange(T, dtype=jnp.int32)
    rope_pos = rp[:, None].astype(jnp.int32) + offs[None, :]  # [B, T]
    positions = lengths[:, None].astype(jnp.int32) + offs[None, :]  # cache idx
    cos, sin = rope_cos_sin(rope_pos, cfg.head_dim_, cfg.rope_theta)
    x = _embed(params, cfg, tokens, dtype, positions=rope_pos)
    key_pos = jnp.arange(K, dtype=jnp.int32)
    per_layer_window = (
        cfg.sliding_window is not None and cfg.layer_is_sliding is not None
    )
    # q at cache position g attends cache positions <= g (inclusive: its
    # own K/V was just written) — same mask family as forward_prefill_cached
    attn_mask = (key_pos[None, None, :] <= positions[:, :, None])[:, None]
    mask_win = None
    if cfg.sliding_window is not None:
        # window over CACHE indices, not rope positions (VLM divergence)
        win = attn_mask & (
            key_pos[None, None, :] > positions[:, :, None] - cfg.sliding_window
        )[:, None]
        if per_layer_window:
            mask_win = win
        else:
            attn_mask = win
    slots = rows if rows is not None else slot_base + jnp.arange(B)
    widx = jnp.minimum(positions, K - 1)
    keep = offs[None, :] < (
        jnp.full((B,), T, jnp.int32) if n_write is None else n_write
    )[:, None]
    if active is not None:
        keep = keep & active[:, None]
    widx = jnp.where(keep, widx, M)  # out-of-bounds -> scatter drop

    def layer(x, xs):
        lp, sliding, ck, cv = xs
        m = attn_mask if mask_win is None else jnp.where(
            sliding, mask_win, attn_mask
        )
        h = _norm(cfg, x, lp, "input_norm")
        q, k, v = _qkv(cfg, lp, h, dtype)
        if cfg.pos_emb == "rope":
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        if ragged and rows is not None:
            # same fused kernel as decode with a T-wide query tile: draft
            # verification rides the paged read for free (ISSUE 19)
            attn, ck, cv = ragged_paged_attention(
                q, k.astype(ck.dtype), v.astype(cv.dtype), ck, cv,
                rows, lengths, widx, m[:, 0],
                key_window=K, page_size=page_size,
                logit_softcap=cfg.attn_logit_softcap, mesh=mesh,
            )
        else:
            ck = ck.at[slots[:, None], widx].set(
                k.astype(ck.dtype), mode="drop"
            )
            cv = cv.at[slots[:, None], widx].set(
                v.astype(cv.dtype), mode="drop"
            )
            if rows is None:
                ckr = jax.lax.slice_in_dim(
                    ck, slot_base, slot_base + B, axis=0
                )
                cvr = jax.lax.slice_in_dim(
                    cv, slot_base, slot_base + B, axis=0
                )
            else:
                ckr = jnp.take(ck, rows, axis=0)
                cvr = jnp.take(cv, rows, axis=0)
            attn = attention(
                q, ckr[:, :K].astype(dtype), cvr[:, :K].astype(dtype), m,
                cfg.attn_logit_softcap,
            )
        delta = _proj(
            cfg, lp["attn"], "wo", attn.reshape(B, T, cfg.q_size), dtype,
            bias="bo",
        )
        if cfg.sandwich_norms:
            delta = _norm(cfg, delta, lp, "sandwich_attn_norm")
        x = x + delta
        h = _norm(cfg, x, lp, "post_attn_norm")
        ffn_out = _ffn(cfg, lp, h, dtype)[0]
        if cfg.sandwich_norms:
            ffn_out = _norm(cfg, ffn_out, lp, "sandwich_ffn_norm")
        x = x + ffn_out
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        layer,
        x,
        (params["layers"], _layer_sliding_flags(cfg), cache["k"], cache["v"]),
    )
    x = _norm(cfg, x, params, "final_norm")
    logits = _head_logits(params, cfg, x, dtype)  # [B, T, V]
    return logits, {"k": new_k, "v": new_v}


# ---------------------------------------------------------------------------
# Init & partitioning
# ---------------------------------------------------------------------------


def init_params(cfg: TransformerConfig, rng: jax.Array) -> Params:
    """Random init (fan-in scaled normal), master dtype cfg.param_dtype."""
    pdt = jnp.dtype(cfg.param_dtype)
    D, F, V, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.num_layers
    Hq, Hkv = cfg.q_size, cfg.kv_size
    keys = jax.random.split(rng, 8)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / np.sqrt(fan_in)).astype(pdt)

    # unit-offset (gemma) norms store zero-centered weights: zeros==identity
    norm_one = jnp.zeros if cfg.norm_unit_offset else jnp.ones
    layers = {
        "attn": {
            "wq": dense(keys[0], (L, D, Hq), D),
            "wk": dense(keys[1], (L, D, Hkv), D),
            "wv": dense(keys[2], (L, D, Hkv), D),
            "wo": dense(keys[3], (L, Hq, D), Hq),
        },
        "input_norm": norm_one((L, D), pdt),
        "post_attn_norm": norm_one((L, D), pdt),
    }
    if cfg.sandwich_norms:
        layers["sandwich_attn_norm"] = norm_one((L, D), pdt)
        layers["sandwich_ffn_norm"] = norm_one((L, D), pdt)
    if cfg.num_experts > 0:
        E = cfg.num_experts
        Fm = cfg.moe_intermediate_size or F
        layers["moe"] = {
            "router": dense(jax.random.fold_in(keys[4], 7), (L, D, E), D),
            "w_gate": dense(keys[4], (L, E, D, Fm), D),
            "w_up": dense(keys[5], (L, E, D, Fm), D),
            "w_down": dense(keys[6], (L, E, Fm, D), Fm),
        }
    elif not cfg.mlp_gated:
        layers["mlp"] = {
            "w_up": dense(keys[5], (L, D, F), D),
            "w_down": dense(keys[6], (L, F, D), F),
        }
        if cfg.mlp_bias:
            layers["mlp"]["b_up"] = jnp.zeros((L, F), pdt)
            layers["mlp"]["b_down"] = jnp.zeros((L, D), pdt)
    else:
        layers["mlp"] = {
            "w_gate": dense(keys[4], (L, D, F), D),
            "w_up": dense(keys[5], (L, D, F), D),
            "w_down": dense(keys[6], (L, F, D), F),
        }
    if cfg.attn_output_bias:
        layers["attn"]["bo"] = jnp.zeros((L, D), pdt)
    if cfg.norm_type == "layernorm":
        for nm in list(layers):
            if nm.endswith("_norm"):
                layers[nm + "_b"] = jnp.zeros((L, D), pdt)
    if cfg.qkv_bias:
        layers["attn"]["bq"] = jnp.zeros((L, Hq), pdt)
        layers["attn"]["bk"] = jnp.zeros((L, Hkv), pdt)
        layers["attn"]["bv"] = jnp.zeros((L, Hkv), pdt)
    if cfg.qk_norm:
        layers["attn"]["q_norm"] = norm_one((L, cfg.head_dim_), pdt)
        layers["attn"]["k_norm"] = norm_one((L, cfg.head_dim_), pdt)
    params: Params = {
        "embedding": dense(keys[7], (V, D), D),
        "layers": layers,
        "final_norm": norm_one((D,), pdt),
    }
    if cfg.norm_type == "layernorm":
        params["final_norm_b"] = jnp.zeros((D,), pdt)
    if cfg.pos_emb == "learned":
        params["pos_embedding"] = dense(
            jax.random.fold_in(keys[7], 2),
            (cfg.max_position_embeddings, D),
            D,
        )
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense(jax.random.fold_in(keys[7], 1), (D, V), D)
    return params


def param_partition_specs(cfg: TransformerConfig, tp: int = 0) -> Params:
    """PartitionSpecs over mesh axes ("fsdp", "tp").

    Layout follows the megatron/GSPMD convention the reference realises with
    DTensor TP plans (areal/utils/fsdp/parallel.py:10-18) and in-repo
    Column/RowParallelLinear (realhf .../tensor_parallel/modules.py:737,885):
    qkv & mlp-in column-split over tp, attn-out & mlp-down row-split; the
    other axis is ZeRO-sharded over fsdp.  Vocab-parallel embedding/head.

    Pass the mesh's `tp` size to drop the vocab sharding when the vocab
    is not divisible (odd test vocabs; real vocabs are multiples of 128).
    """
    vocab_axis = "tp" if (tp == 0 or cfg.vocab_size % max(tp, 1) == 0) else None
    attn = {
        "wq": P(None, "fsdp", "tp"),
        "wk": P(None, "fsdp", "tp"),
        "wv": P(None, "fsdp", "tp"),
        "wo": P(None, "tp", "fsdp"),
    }
    if cfg.qkv_bias:
        attn.update(bq=P(None, "tp"), bk=P(None, "tp"), bv=P(None, "tp"))
    if cfg.attn_output_bias:
        attn["bo"] = P(None, "fsdp")
    if cfg.qk_norm:
        attn.update(q_norm=P(None, None), k_norm=P(None, None))
    if cfg.num_experts > 0:
        # experts over ep, megatron column/row split inside each expert —
        # the reference's EP x ETP layout (alloc_mode.py:80-117)
        ffn = {
            "moe": {
                "router": P(None, "fsdp", None),
                "w_gate": P(None, "ep", "fsdp", "tp"),
                "w_up": P(None, "ep", "fsdp", "tp"),
                "w_down": P(None, "ep", "tp", "fsdp"),
            }
        }
    elif not cfg.mlp_gated:
        ffn = {
            "mlp": {
                "w_up": P(None, "fsdp", "tp"),
                "w_down": P(None, "tp", "fsdp"),
            }
        }
        if cfg.mlp_bias:
            ffn["mlp"]["b_up"] = P(None, "tp")
            ffn["mlp"]["b_down"] = P(None, "fsdp")
    else:
        ffn = {
            "mlp": {
                "w_gate": P(None, "fsdp", "tp"),
                "w_up": P(None, "fsdp", "tp"),
                "w_down": P(None, "tp", "fsdp"),
            }
        }
    if cfg.lora_rank:
        # adapters: A follows the base weight's input sharding, B its
        # output (column/row) split; the rank dim stays whole
        from areal_tpu.models.lora import TARGET_MAP

        row_split = {"wo", "w_down"}
        for tgt in cfg.lora_targets:
            sub_name, leaf = TARGET_MAP[tgt]
            sub = attn if sub_name == "attn" else ffn.get("mlp")
            if sub is None or leaf not in sub:
                continue
            if leaf in row_split:
                sub[f"{leaf}_lora_a"] = P(None, "tp", None)
                sub[f"{leaf}_lora_b"] = P(None, None, "fsdp")
            else:
                sub[f"{leaf}_lora_a"] = P(None, "fsdp", None)
                sub[f"{leaf}_lora_b"] = P(None, None, "tp")
    layer_specs = {
        "attn": attn,
        **ffn,
        "input_norm": P(None, "fsdp"),
        "post_attn_norm": P(None, "fsdp"),
    }
    if cfg.sandwich_norms:
        layer_specs["sandwich_attn_norm"] = P(None, "fsdp")
        layer_specs["sandwich_ffn_norm"] = P(None, "fsdp")
    if cfg.norm_type == "layernorm":
        for nm in [n for n in layer_specs if n.endswith("_norm")]:
            layer_specs[nm + "_b"] = P(None, "fsdp")
    specs: Params = {
        "embedding": P(vocab_axis, "fsdp"),
        "layers": layer_specs,
        "final_norm": P("fsdp"),
    }
    if cfg.norm_type == "layernorm":
        specs["final_norm_b"] = P("fsdp")
    if cfg.pos_emb == "learned":
        specs["pos_embedding"] = P(None, "fsdp")
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P("fsdp", vocab_axis)
    return specs


def param_count(params: Params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
