"""C1 positive fixture: guarded fields touched OUTSIDE their lock.

Each violation below is an expected `unlocked-field` finding; the test
asserts the checker reports exactly these lines.
"""

import threading


class RegistryStyle:
    _GUARDED_FIELDS = {"_queue": "_lock", "_counter": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self._counter = 0

    def bad_write(self):
        self._queue.append(1)  # VIOLATION: read outside the lock

    def bad_mixed(self):
        with self._lock:
            self._counter += 1
        self._counter += 1  # VIOLATION: second touch after release

    def bad_closure(self):
        with self._lock:
            def later():
                return self._queue  # VIOLATION: closure may outlive the lock

            return later


class CommentStyle:
    def __init__(self):
        self._lock = threading.Lock()
        self._holdback = []  # guarded-by: _lock

    def bad_swap(self):
        intake = self._holdback  # VIOLATION: unlocked alias grab
        self._holdback = []  # VIOLATION: unlocked reset
        return intake
