"""Frequency control for periodic actions (reference: realhf/base/timeutil.py
FrequencyControl, used by lite's Saver/Evaluator via SaverConfig/TimerConfig
cli_args.py:850-905)."""

import time
from typing import Optional

from areal_tpu.api.config import TimerConfig


class FrequencyControl:
    """Triggers when ANY configured budget (epochs, steps, seconds) elapses
    since the last trigger; all-None means never trigger (except on
    explicit `force`)."""

    def __init__(self, config: TimerConfig):
        self.freq_epochs = config.freq_epochs
        self.freq_steps = config.freq_steps
        self.freq_secs = config.freq_secs
        self._last_epoch = 0
        self._last_step = 0
        self._last_time = time.monotonic()

    def check(self, epoch: int, step: int, force: bool = False) -> bool:
        now = time.monotonic()
        hit = force
        if self.freq_epochs is not None and epoch - self._last_epoch >= self.freq_epochs:
            hit = True
        if self.freq_steps is not None and step - self._last_step >= self.freq_steps:
            hit = True
        if self.freq_secs is not None and now - self._last_time >= self.freq_secs:
            hit = True
        if hit:
            self._last_epoch, self._last_step, self._last_time = epoch, step, now
        return hit

    def state_dict(self):
        return {
            "last_epoch": self._last_epoch,
            "last_step": self._last_step,
            "elapsed": time.monotonic() - self._last_time,
        }

    def load_state_dict(self, state):
        self._last_epoch = state["last_epoch"]
        self._last_step = state["last_step"]
        self._last_time = time.monotonic() - state.get("elapsed", 0.0)
